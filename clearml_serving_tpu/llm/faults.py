"""Fault-injection seam for request-lifecycle chaos testing.

Production code calls :func:`fire` at a handful of well-known points; with no
faults configured the call is a single attribute read (``active()`` short
circuit), so the seam costs nothing on the hot path. Tests (and operators,
via the ``TPUSERVE_FAULTS`` env var) arm :class:`FaultSpec` entries that make
a point delay, raise, or surface a fake gRPC status — which is how the chaos
suite proves the deadline, shedding, watchdog-recovery, and retry paths
without real hardware failures.

Known points (ctx carried with each):

- ``engine.prefill``   — inside the admission worker, before device prefill
                         (``request``); ``delay`` = slow prefill,
                         ``raise`` = failed admission.
- ``engine.decode``    — inside the decode-chunk dispatch worker, before the
                         device step (``requests`` = active GenRequests);
                         ``match_token`` poisons only the request whose
                         prompt contains that token; ``delay`` = stuck loop.
- ``engine.decode.stall`` — at the top of a speculative decode dispatch
                         (``requests``), before any page over-allocation;
                         ``delay`` models a slow spec round wedging the
                         loop (the watchdog's view of a stuck spec scan),
                         ``raise`` fails the dispatch before it touches
                         the pool.
- ``engine.decode.retire`` — on the loop thread at chunk retirement, after
                         the device->host sync and before emission
                         (``requests``); ``match_token`` fails only the
                         matched request (the rest of the chunk still
                         emits), an unmatched raise is a batch-wide retire
                         failure. Younger chunks may still be in flight.
- ``engine.admit``     — inside check_admission (``request``); a raise is
                         converted to a load-shed (429).
- ``engine.admit.class`` — inside check_admission's class-aware admission
                         path (``request``); a raise forces a class-policy
                         shed (429 with the request's priority class in the
                         payload) regardless of queue state.
- ``engine.admit.budget`` — ragged scheduler (docs/ragged_attention.md): on
                         the loop thread as one prefill job's chunk is
                         admitted into a step's token budget (``request``);
                         a raise sheds that admission (structured 429) —
                         decode rows and the other jobs ride the step
                         untouched.
- ``engine.pool``      — inside check_admission's KV-pool headroom check; a
                         raise simulates pool exhaustion.
- ``engine.preempt``   — on the loop thread mid-preemption, AFTER the
                         victim's generated-so-far KV was committed into the
                         radix prefix cache and BEFORE its slot is freed /
                         the request requeued (``request``); a raise aborts
                         the preemption — the armed KV sanitizer must stay
                         green (the store alone is a normal admission-commit
                         store, so nothing may leak).
- ``engine.release``   — at paged-slot teardown, before the slot's pages are
                         freed (``request``); a raise simulates a teardown
                         bug that LEAKS the slot's pages — the KV sanitizer
                         (llm/kv_sanitizer.py, TPUSERVE_SANITIZE=1) must
                         catch it at drain.
- ``engine.kv.demote`` — in the radix prefix cache as device-budget eviction
                         is about to demote a cached run's pages to the
                         host-RAM tier (``pages``; docs/kv_tiering.md); a
                         raise aborts the demotion — the node drops for
                         real (legacy eviction), leak-free under the armed
                         sanitizer.
- ``engine.compile.bucket`` — inside the engine's prefill bucket picker
                         (``_bucket_for``); a raise makes the picker return
                         the RAW request length instead of a bucket — the
                         seeded shape-drift defect of the compile-surface
                         discipline (docs/static_analysis.md TPU6xx): every
                         novel prompt length then mints a fresh XLA program,
                         which the armed compile sentry
                         (llm/compile_sentry.py) must count post-fence and,
                         in strict mode, raise on. Proven caught by the
                         sentry self-test in tests/test_compile_sentry.py.
- ``engine.shard.drift`` — inside the engine's sharding-sentry audit-entry
                         builder (``_shard_audit_entries``); a raise swaps a
                         HOST-MATERIALIZED numpy copy in for the chained
                         decode row — the seeded implicit-transfer defect of
                         the sharding discipline (docs/static_analysis.md
                         TPU8xx): the armed sharding sentry
                         (llm/sharding_sentry.py) must count it as an
                         implicit device->host transfer and, in strict mode,
                         raise naming the array path and declared-vs-actual
                         spec. Proven caught by the sentry self-test in
                         tests/test_sharding_sentry.py.
- ``engine.kv.promote`` — as a lookup on a demoted run is about to allocate
                         device pages and enqueue the host→device re-online
                         DMA (``pages``); a raise aborts the promotion — the
                         demoted suffix drops, the hit shortens to the
                         resident prefix, and the tail falls back to
                         recompute with zero page leaks.
- ``engine.kv.ship``   — on the prefill replica's loop thread at commit,
                         BEFORE the finished admission's prefix pages are
                         exported into a KV-transport shipment
                         (``request``; docs/disaggregation.md); a raise
                         aborts the ship leak-free — nothing reaches the
                         transport, and the decode replica falls back to
                         recomputing the prefix.
- ``kv.ship.partial``  — on the prefill replica's loop thread as a
                         DRAFT-AHEAD partial shipment (storable pages of a
                         still-running prefill, docs/spec_decode_trees.md)
                         is about to export at a chunk boundary
                         (``request``); a raise aborts the job's entire
                         draft-ahead stream AND the commit-time seal — the
                         receiver's unsealed assembly is never consumable,
                         so the decode replica falls back to recompute
                         with zero page leaks on either side.
- ``engine.spec.tree`` — on the loop thread in the ragged scheduler's
                         step planner, after spec-verify eligibility is
                         decided and BEFORE drafts are proposed or any
                         row laid out (``requests`` = the eligible
                         slots' GenRequests); ``match_token`` demotes
                         only the matched request's row to PLAIN DECODE
                         in the same launch (an unmatched raise demotes
                         every verify row that step). Nothing was
                         allocated yet, so the fallback is leak-free by
                         construction and the stream stays byte-identical
                         — the row just decodes without drafts.
- ``engine.kv.receive`` — on the decode replica as a popped shipment is
                         about to import (fresh device pages + the fenced
                         host→device scatter + radix-cache attach;
                         ``request`` carries the prompt ids); a raise
                         drops the shipment with zero page leaks and the
                         replica group re-routes the stream to a
                         hybrid-capable sibling (recompute there).
- ``engine.ledger.leak`` — at the preemption resume-pin teardown
                         (``_release_resume_pin``), AFTER the handle is
                         detached from the request and BEFORE the
                         underlying unpin runs (``request``); a raise
                         models a lost free — the handle drops, the unpin
                         never fires, and the armed ownership ledger
                         (llm/lifecycle_ledger.py, TPUSERVE_LEDGER) must
                         name the leaked ``prefix.resume_pin`` and its
                         acquire site at the drain audit. Node pins are
                         invisible to page refcount accounting, so this
                         leak class is the ledger's alone.
- ``engine.dispatch.prepare`` — on the loop thread at the end of
                         ``_prepare_dispatch`` (``requests``): the shared
                         host state is snapshotted, the worker-thread device
                         call has not started. The boundary where the PR-4
                         host-buffer aliasing window sat; the interleaving
                         explorer (llm/schedule_explorer.py) permutes thread
                         orderings at exactly this class of seam.
- ``engine.watchdog``  — at the top of a watchdog trip, before the epoch
                         bump and in-flight request failure (``requests``);
                         ``delay`` = slow trip, ``raise`` = the watchdog
                         task dies until the next request restarts it.
- ``engine.drain``     — on the loop thread at the drained boundary, before
                         the drained sanitizer audit; a raise fails the loop
                         through the structured step-failure path.
- ``transport.wire.send`` — in the socket KV-transport backend
                         (llm/kv_wire.py) before a shipment is framed and
                         written to the destination replica's listener; a
                         raise drops the shipment sender-side (counted wire
                         send failure, ``send`` returns False) and the
                         decode replica recomputes — the same
                         drop-to-recompute contract as a full receive slab.
- ``transport.wire.recv`` — on the receiving endpoint's listener thread
                         before a received frame is decoded/validated; a
                         raise drops the frame leak-free (nothing was
                         attached — the slabs are views into the frame
                         buffer), nacks the sender, and the stream falls
                         back to recompute. The same path truncated or
                         geometry-lying frames take via WireFormatError.
- ``replica.proc.crash`` — in the process-replica supervisor's heartbeat
                         (serving/process_replica.py) with the replica
                         INDEX as the shim's ``prompt_ids`` (the
                         ``router.eject`` convention); ``match_token:
                         <index>`` SIGKILLs exactly that worker process —
                         the chaos suite's handle for a real worker death
                         (EOF mid-stream -> history-as-prompt failover,
                         ejection, bounded restart-with-rewarm).
- ``router.pick``      — in the replica router as a route decision is
                         about to return its pick (``request``;
                         serving/replica_router.py, docs/replication.md);
                         a raise makes the router fall to the next ring
                         member (counted as a ``rebalance``) instead of
                         failing the request — the structured-fallback
                         contract of the routing path.
- ``router.eject``     — fired per replica during each ring sweep; the
                         carried shim's ``prompt_ids`` holds the replica
                         INDEX, so ``match_token: <index>`` force-ejects
                         exactly that replica from the ring while the
                         spec stays armed. Used by the chaos suite to
                         prove ejection drains traffic to siblings and
                         re-admission re-warms through the warmup gate.
- ``grpc.call``        — before each gRPC attempt (``attempt``); set
                         ``grpc_code`` ("UNAVAILABLE"/"DEADLINE_EXCEEDED")
                         to exercise the transient-retry path.

The three ``engine.dispatch.prepare``/``engine.watchdog``/``engine.drain``
points double as the engine's YIELD-POINT SEAMS for the deterministic
interleaving explorer (llm/schedule_explorer.py): together with the
existing dispatch/retire/preempt points they mark every thread-ownership
boundary of the pipelined loop, and the explorer's scenario seam labels
must stay a subset of this registry (test_schedule_explorer pins that).

Every point a production call site fires MUST be listed in
:data:`KNOWN_POINTS`: the static analyzer (``tpuserve-analyze`` TPU403)
checks call-site literals against it, and :func:`configure` rejects specs
targeting unknown points — a typo'd point would otherwise arm a fault that
never fires and silently prove nothing.

Env format (``TPUSERVE_FAULTS``): a JSON list of spec dicts, e.g.::

    TPUSERVE_FAULTS='[{"point": "engine.decode", "action": "raise",
                       "match_token": 300, "times": 1}]'
"""

from __future__ import annotations

import json
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Any, List, Optional


# the registry of production fault seams (module docstring documents each).
# tpuserve-analyze parses this assignment from source (stdlib ast, no import)
# — keep it a literal.
KNOWN_POINTS = frozenset({
    "engine.prefill",
    "engine.decode",
    "engine.decode.stall",
    "engine.decode.retire",
    "engine.dispatch.prepare",
    "engine.watchdog",
    "engine.drain",
    "engine.admit",
    "engine.admit.class",
    "engine.admit.budget",
    "engine.pool",
    "engine.preempt",
    "engine.release",
    "engine.kv.demote",
    "engine.kv.promote",
    "engine.kv.ship",
    "kv.ship.partial",
    "engine.kv.receive",
    "engine.spec.tree",
    "engine.ledger.leak",
    "engine.compile.bucket",
    "engine.shard.drift",
    "transport.wire.send",
    "transport.wire.recv",
    "replica.proc.crash",
    "router.pick",
    "router.eject",
    "grpc.call",
})


@dataclass
class FaultSpec:
    point: str
    action: str = "raise"          # "raise" | "delay"
    times: int = -1                # firings before the spec disarms (-1 = inf)
    delay: float = 0.0             # seconds slept before acting
    match_token: Optional[int] = None  # only fire when a request's prompt has it
    grpc_code: Optional[str] = None    # fake upstream status for grpc points
    message: str = "injected fault"
    fired: int = field(default=0, compare=False)

    def exhausted(self) -> bool:
        return 0 <= self.times <= self.fired


class InjectedFault(Exception):
    """Raised by an armed ``action="raise"`` spec. Carries the spec and the
    matched request (when ``match_token`` selected one) so the engine can
    fail ONLY that request instead of the whole batch."""

    def __init__(self, spec: FaultSpec, request: Any = None):
        super().__init__("{} [{}]".format(spec.message, spec.point))
        self.spec = spec
        self.request = request

    @property
    def grpc_code(self) -> Optional[str]:
        return self.spec.grpc_code


class FaultInjector:
    def __init__(self):
        self._specs: List[FaultSpec] = []
        self._lock = threading.Lock()
        self.load_env()

    # -- configuration ----------------------------------------------------

    def configure(self, specs) -> None:
        """Arm the given specs (list of FaultSpec or dicts). Replaces any
        previously armed set. Unknown points are rejected loudly — a spec
        that can never fire reads as "chaos test passed"."""
        armed = []
        for s in specs or []:
            spec = s if isinstance(s, FaultSpec) else FaultSpec(**s)
            if spec.point not in KNOWN_POINTS:
                raise ValueError(
                    "unknown fault point {!r} (known: {})".format(
                        spec.point, ", ".join(sorted(KNOWN_POINTS))
                    )
                )
            armed.append(spec)
        with self._lock:
            self._specs = armed

    def clear(self) -> None:
        with self._lock:
            self._specs = []

    def load_env(self) -> None:
        raw = os.environ.get("TPUSERVE_FAULTS")
        if not raw:
            return
        try:
            specs = json.loads(raw)
        except ValueError as ex:
            raise ValueError("unparseable TPUSERVE_FAULTS: {}".format(ex))
        # configure() raises its own precise error for valid-JSON specs with
        # an unknown point/field — don't relabel that as a parse failure
        self.configure(specs)

    def active(self) -> bool:
        return bool(self._specs)

    # -- firing -----------------------------------------------------------

    @staticmethod
    def _match(spec: FaultSpec, request, requests) -> Any:
        """The request a spec applies to, or None when match_token filters
        everything out. Specs without match_token apply unconditionally."""
        if spec.match_token is None:
            return request
        candidates = list(requests or [])
        if request is not None:
            candidates.append(request)
        for r in candidates:
            if spec.match_token in (getattr(r, "prompt_ids", None) or []):
                return r
        return None

    def fire(self, point: str, request: Any = None, requests=None, **ctx) -> None:
        """Run every armed spec for ``point``: sleep for ``delay`` actions,
        raise :class:`InjectedFault` for ``raise`` actions. No-op when
        nothing matches."""
        with self._lock:
            specs = [s for s in self._specs if s.point == point]
        for spec in specs:
            target = self._match(spec, request, requests)
            if spec.match_token is not None and target is None:
                continue
            with self._lock:
                # check-and-claim one firing atomically: the loop thread and
                # dispatch workers race here, and a times-bounded spec must
                # never fire more than its limit
                if spec.exhausted():
                    continue
                spec.fired += 1
            if spec.delay:
                time.sleep(spec.delay)
            if spec.action == "raise":
                raise InjectedFault(spec, target)


# module singleton: production call sites and tests share it
injector = FaultInjector()


def active() -> bool:
    return injector.active()


def fire(point: str, request: Any = None, requests=None, **ctx) -> None:
    if injector.active():
        injector.fire(point, request=request, requests=requests, **ctx)


def configure(specs) -> None:
    injector.configure(specs)


def clear() -> None:
    injector.clear()
