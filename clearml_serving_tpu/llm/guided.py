"""Grammar-constrained (guided) decoding, TPU-native.

The reference serves vLLM's guided decoding (``response_format`` /
``guided_json`` / ``guided_regex`` pass through preprocess_service.py's
completion bodies into the vLLM engine, which masks logits per step with an
Outlines/xgrammar FSM on the host). A host-side per-step mask is the wrong
shape for this engine: decode steps run fused in a `lax.scan` chunk
(llm/engine.py), so the constraint must live ON DEVICE.

Design: compile the constraint once on the host into a token-level DFA
transition table ``T[state, token] -> next_state | -1`` (int16). The table
uploads to HBM once; inside the decode scan each step is two gathers:

    rows    = T[state]            # [B, V]   allowed = rows >= 0
    logits  = where(allowed, logits, -inf)
    sampled ~ logits
    state   = rows[sampled]

No host round-trip, no per-step recompile, works under any sampling mode
(the mask composes with temperature/top-k/top-p/penalties upstream of the
sampler). EOS is part of the table: accepting states transition on
``eos_id`` (to a terminal self-loop), non-accepting states forbid it — so
generation can only stop on a complete match.

Pipeline: regex subset --Thompson--> byte NFA --subset construction over
byte equivalence classes--> byte DFA --per-token byte walk (vectorized
numpy)--> token table. JSON schemas lower to regexes (Outlines-style);
``json_object`` mode uses a bounded-nesting JSON value regex.

Supported regex subset: literals (UTF-8), ``.`` ``|`` ``( )`` ``* + ?``
``{m}`` ``{m,n}``, classes ``[a-z^...]``, escapes ``\\d \\w \\s \\D \\W
\\S \\n \\r \\t \\f \\v`` and escaped metacharacters. Anchoring is
implicit (whole-string match), as is standard for constrained
generation; a leading ``^`` / trailing ``$`` are accepted as no-ops.
Anything outside the subset (``\\b`` ``\\B`` ``\\A`` ``\\Z``,
backreferences, mid-pattern anchors, lookaround) raises RegexError so
unsupported patterns fail the 4xx pre-flight instead of mis-compiling
into a grammar that forces literal characters (Outlines/xgrammar treat
these as anchors/classes; silently diverging would corrupt output).
"""

from __future__ import annotations

import json
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

DEAD = -1  # dead/forbidden marker in transition tables


# --------------------------------------------------------------- regex AST

class _Node:
    pass


@dataclass
class _Lit(_Node):
    bytes_: frozenset  # allowed byte values for this single position


@dataclass
class _Concat(_Node):
    parts: List[_Node]


@dataclass
class _Alt(_Node):
    options: List[_Node]


@dataclass
class _Repeat(_Node):
    node: _Node
    min: int
    max: Optional[int]  # None = unbounded


_ANY = frozenset(range(256)) - {0x0A}  # '.' = any byte except newline
_DIGIT = frozenset(range(0x30, 0x3A))
_WORD = (
    frozenset(range(0x30, 0x3A))
    | frozenset(range(0x41, 0x5B))
    | frozenset(range(0x61, 0x7B))
    | {0x5F}
)
_SPACE = frozenset(b" \t\n\r\f\v")
_ALL = frozenset(range(256))


class RegexError(ValueError):
    pass


# named escape -> byte set, shared by _escape (pattern level) and
# _class_atom (inside [...]) so the two can never drift apart
_ESCAPE_SETS = {"d": _DIGIT, "w": _WORD, "s": _SPACE,
                "D": _ALL - _DIGIT, "W": _ALL - _WORD, "S": _ALL - _SPACE,
                "n": frozenset(b"\n"), "r": frozenset(b"\r"),
                "t": frozenset(b"\t"), "f": frozenset(b"\f"),
                "v": frozenset(b"\v")}


class _Parser:
    """Recursive-descent parser over the regex subset, operating on the
    pattern's UTF-8 bytes (multi-byte literals become byte concats)."""

    def __init__(self, pattern: str):
        self.p = pattern
        self.i = 0
        self.n = len(pattern)

    def parse(self) -> _Node:
        node = self._alt()
        if self.i != self.n:
            raise RegexError(
                "unexpected {!r} at {}".format(self.p[self.i], self.i)
            )
        return node

    def _alt(self) -> _Node:
        options = [self._concat()]
        while self.i < self.n and self.p[self.i] == "|":
            self.i += 1
            options.append(self._concat())
        return options[0] if len(options) == 1 else _Alt(options)

    def _concat(self) -> _Node:
        parts: List[_Node] = []
        while self.i < self.n and self.p[self.i] not in "|)":
            parts.append(self._repeat())
        return _Concat(parts)

    def _repeat(self) -> _Node:
        node = self._atom()
        while self.i < self.n and self.p[self.i] in "*+?{":
            ch = self.p[self.i]
            if ch == "*":
                node, self.i = _Repeat(node, 0, None), self.i + 1
            elif ch == "+":
                node, self.i = _Repeat(node, 1, None), self.i + 1
            elif ch == "?":
                node, self.i = _Repeat(node, 0, 1), self.i + 1
            else:  # {m} / {m,} / {m,n}
                j = self.p.find("}", self.i)
                if j < 0:
                    raise RegexError("unterminated {} quantifier")
                body = self.p[self.i + 1 : j]
                if "," in body:
                    lo, hi = body.split(",", 1)
                    node = _Repeat(
                        node, int(lo or 0), int(hi) if hi.strip() else None
                    )
                else:
                    node = _Repeat(node, int(body), int(body))
                self.i = j + 1
        return node

    def _atom(self) -> _Node:
        ch = self.p[self.i]
        if ch == "(":
            self.i += 1
            if self.p.startswith("?:", self.i):  # non-capturing marker
                self.i += 2
            node = self._alt()
            if self.i >= self.n or self.p[self.i] != ")":
                raise RegexError("unbalanced parenthesis")
            self.i += 1
            return node
        if ch == "[":
            return self._char_class()
        if ch == ".":
            self.i += 1
            return _Lit(_ANY)
        if ch == "\\":
            return self._escape()
        if ch in "*+?{":
            raise RegexError("dangling quantifier at {}".format(self.i))
        if ch == "^":
            if self.i == 0:  # leading anchor: no-op, matching is anchored
                self.i += 1
                return _Concat([])
            raise RegexError(
                "'^' mid-pattern unsupported (matching is whole-string)"
            )
        if ch == "$":
            if self.i == self.n - 1:  # trailing anchor: no-op
                self.i += 1
                return _Concat([])
            raise RegexError(
                "'$' mid-pattern unsupported (matching is whole-string)"
            )
        self.i += 1
        data = ch.encode("utf-8")
        if len(data) == 1:
            return _Lit(frozenset(data))
        return _Concat([_Lit(frozenset([b])) for b in data])

    def _escape(self) -> _Node:
        self.i += 1
        if self.i >= self.n:
            raise RegexError("trailing backslash")
        ch = self.p[self.i]
        self.i += 1
        if ch in _ESCAPE_SETS:
            return _Lit(_ESCAPE_SETS[ch])
        if ch == "x":  # \xNN byte escape
            hexpair = self.p[self.i : self.i + 2]
            if len(hexpair) != 2:
                raise RegexError("truncated \\x escape")
            self.i += 2
            return _Lit(frozenset([int(hexpair, 16)]))
        if ch.isalnum():  # \b \B \A \Z, backrefs, \p{..}: NOT literals
            raise RegexError(
                "unsupported escape \\{} (outside the guided-regex "
                "subset)".format(ch)
            )
        return _Lit(frozenset(ch.encode("utf-8")[:1]))

    def _class_atom(self):
        """One class member: a byte value, or a named set (returns a set)."""
        if self.p[self.i] == "\\":
            self.i += 1
            ch = self.p[self.i]
            self.i += 1
            if ch in _ESCAPE_SETS:
                return _ESCAPE_SETS[ch]
            if ch == "x":
                hexpair = self.p[self.i : self.i + 2]
                if len(hexpair) != 2:
                    raise RegexError("truncated \\x escape in class")
                self.i += 2
                return int(hexpair, 16)
            if ch.isalnum():
                raise RegexError(
                    "unsupported escape \\{} in character class".format(ch)
                )
            return ch.encode("utf-8")[0]
        enc = self.p[self.i].encode("utf-8")
        if len(enc) != 1:
            raise RegexError("non-ASCII in char class unsupported")
        self.i += 1
        return enc[0]

    def _char_class(self) -> _Node:
        self.i += 1  # past '['
        negate = self.i < self.n and self.p[self.i] == "^"
        if negate:
            self.i += 1
        members: set = set()
        first = True
        while self.i < self.n and (self.p[self.i] != "]" or first):
            first = False
            atom = self._class_atom()
            if isinstance(atom, frozenset):
                members |= atom
                continue
            if (
                self.i + 1 < self.n
                and self.p[self.i] == "-"
                and self.p[self.i + 1] != "]"
            ):
                self.i += 1
                hi = self._class_atom()
                if isinstance(hi, frozenset):
                    raise RegexError("named set cannot end a range")
                members |= set(range(atom, hi + 1))
            else:
                members.add(atom)
        if self.i >= self.n:
            raise RegexError("unterminated character class")
        self.i += 1  # past ']'
        if negate:
            members = set(range(256)) - members
        return _Lit(frozenset(members))


# ------------------------------------------------------------ NFA -> DFA

class _NFA:
    """Thompson NFA: states are ints; eps[s] = set of states;
    edges[s] = list of (byteset, target)."""

    def __init__(self):
        self.eps: List[set] = []
        self.edges: List[List[Tuple[frozenset, int]]] = []

    def new_state(self) -> int:
        self.eps.append(set())
        self.edges.append([])
        return len(self.eps) - 1

    def build(self, node: _Node) -> Tuple[int, int]:
        """Returns (start, accept) fragment for `node`."""
        if isinstance(node, _Lit):
            s, a = self.new_state(), self.new_state()
            self.edges[s].append((node.bytes_, a))
            return s, a
        if isinstance(node, _Concat):
            s = a = self.new_state()
            for part in node.parts:
                ps, pa = self.build(part)
                self.eps[a].add(ps)
                a = pa
            return s, a
        if isinstance(node, _Alt):
            s, a = self.new_state(), self.new_state()
            for opt in node.options:
                os_, oa = self.build(opt)
                self.eps[s].add(os_)
                self.eps[oa].add(a)
            return s, a
        if isinstance(node, _Repeat):
            lo, hi = node.min, node.max
            s = a = self.new_state()
            for _ in range(lo):  # mandatory copies
                ps, pa = self.build(node.node)
                self.eps[a].add(ps)
                a = pa
            if hi is None:  # Kleene tail
                ps, pa = self.build(node.node)
                self.eps[a].add(ps)
                self.eps[pa].add(a)
            else:
                end = self.new_state()
                self.eps[a].add(end)
                for _ in range(hi - lo):  # optional copies
                    ps, pa = self.build(node.node)
                    self.eps[a].add(ps)
                    self.eps[pa].add(end)
                    a = pa
                a = end
            return s, a


def _eps_closure(nfa: _NFA, states: frozenset) -> frozenset:
    stack, seen = list(states), set(states)
    while stack:
        s = stack.pop()
        for t in nfa.eps[s]:
            if t not in seen:
                seen.add(t)
                stack.append(t)
    return frozenset(seen)


@dataclass
class ByteDFA:
    """Dense byte-level DFA: trans [S, 256] int32 (DEAD = -1), accepting
    [S] bool, start = 0."""

    trans: np.ndarray
    accepting: np.ndarray

    @property
    def n_states(self) -> int:
        return self.trans.shape[0]

    @classmethod
    def from_regex(
        cls,
        pattern: str,
        max_states: int = 4096,
        allow_leading_space: bool = False,
    ) -> "ByteDFA":
        """``allow_leading_space`` prepends an optional ' ' at the AST
        level (SPM detokenization strips it) — string-level wrapping would
        push a user's no-op leading '^' / trailing '$' into mid-pattern
        position and fail patterns the pre-flight already accepted."""
        ast = _Parser(pattern).parse()
        if allow_leading_space:
            ast = _Concat([_Repeat(_Lit(frozenset([0x20])), 0, 1), ast])
        nfa = _NFA()
        start, accept = nfa.build(ast)

        # byte equivalence classes: partition bytes by NFA-edge signature so
        # the subset construction touches ~tens of classes, not 256 bytes
        sig = {}
        for b in range(256):
            key = []
            for s, edges in enumerate(nfa.edges):
                for ei, (bs, _t) in enumerate(edges):
                    if b in bs:
                        key.append((s, ei))
            sig.setdefault(tuple(key), []).append(b)
        classes = list(sig.values())

        d0 = _eps_closure(nfa, frozenset([start]))
        index: Dict[frozenset, int] = {d0: 0}
        rows: List[np.ndarray] = [np.full(256, DEAD, np.int32)]
        work = [d0]
        while work:
            cur = work.pop()
            ci = index[cur]
            for cls_bytes in classes:
                rep = cls_bytes[0]
                nxt = set()
                for s in cur:
                    for bs, t in nfa.edges[s]:
                        if rep in bs:
                            nxt.add(t)
                if not nxt:
                    continue
                closed = _eps_closure(nfa, frozenset(nxt))
                if closed not in index:
                    if len(index) >= max_states:
                        raise RegexError(
                            "DFA exceeds {} states; simplify the "
                            "pattern/schema".format(max_states)
                        )
                    index[closed] = len(rows)
                    rows.append(np.full(256, DEAD, np.int32))
                    work.append(closed)
                ti = index[closed]
                row = rows[ci]
                for b in cls_bytes:
                    row[b] = ti
        trans = np.stack(rows)
        accepting = np.zeros(len(rows), bool)
        for states, i in index.items():
            if accept in states:
                accepting[i] = True
        return cls(trans=trans, accepting=accepting)

    def matches(self, data: bytes) -> bool:
        s = 0
        for b in data:
            s = int(self.trans[s, b])
            if s == DEAD:
                return False
        return bool(self.accepting[s])


# ------------------------------------------------------- token-level table

@dataclass
class TokenDFA:
    """Token-level transition table over a model vocabulary.

    table [S+1, V] int16: table[s, t] = state after emitting token t from s
    (DEAD if t's byte path dies, or if it ends the match without reaching
    an accepting byte-state mid-token — partial progress through a token is
    fine, the BYTES must stay alive). Row S (the last row) is the terminal
    post-EOS self-loop state. EOS column: accepting states -> terminal,
    others DEAD. Terminal row: everything DEAD except EOS (self-loop).
    """

    table: np.ndarray
    start: int = 0

    @property
    def n_states(self) -> int:
        return self.table.shape[0]

    @classmethod
    def build(
        cls,
        dfa: ByteDFA,
        token_bytes: Sequence[Optional[bytes]],
        eos_id: int,
    ) -> "TokenDFA":
        S = dfa.n_states
        V = len(token_bytes)
        if S + 1 > np.iinfo(np.int16).max:
            raise RegexError("token DFA too large for int16 states")
        # vectorized byte walk: state_mat [S, V] starts at each DFA state,
        # consumes every token's bytes in lockstep (grouped by position)
        max_len = max((len(t) for t in token_bytes if t), default=0)
        lens = np.array(
            [len(t) if t else 0 for t in token_bytes], np.int32
        )
        state_mat = np.repeat(
            np.arange(S, dtype=np.int32)[:, None], V, axis=1
        )  # [S, V]
        trans_pad = np.vstack([dfa.trans, np.full((1, 256), DEAD, np.int32)])
        for pos in range(max_len):
            live_tok = lens > pos
            if not live_tok.any():
                break
            byte_at = np.zeros(V, np.int64)
            for t in np.nonzero(live_tok)[0]:
                byte_at[t] = token_bytes[t][pos]
            cur = state_mat[:, live_tok]
            nxt = trans_pad[np.where(cur == DEAD, S, cur), byte_at[live_tok]]
            state_mat[:, live_tok] = nxt
        # zero-length / special tokens are never allowed
        state_mat[:, lens == 0] = DEAD
        table = np.vstack([state_mat, np.full((1, V), DEAD, np.int32)])
        terminal = S
        if 0 <= eos_id < V:
            table[:S, eos_id] = np.where(dfa.accepting, terminal, DEAD)
            table[terminal, eos_id] = terminal
        # Fixpoint-prune token-level dead ends: a byte-state can be alive at
        # byte granularity yet unreachable-forward at TOKEN granularity (no
        # whole vocab token survives from it). Without pruning the engine
        # could sample into such a state and find every next token masked.
        for _ in range(S + 1):
            alive = (table != DEAD).any(axis=1)
            into_dead = (table != DEAD) & ~alive[np.clip(table, 0, None)]
            if not into_dead.any():
                break
            table[into_dead] = DEAD
        if not (table[0] != DEAD).any():
            raise RegexError(
                "no vocabulary token can begin a match of this grammar"
            )
        return cls(table=table.astype(np.int16))


_BYTE_DECODER: Optional[Dict[str, int]] = None


def _gpt2_byte_decoder() -> Dict[str, int]:
    """Inverse of the byte-level-BPE bytes->unicode table (GPT-2 alphabet,
    used by Llama-3/Qwen/GPT-style HF fast tokenizers): printable bytes map
    to themselves, the rest to U+0100+n. Public, well-known construction."""
    global _BYTE_DECODER
    if _BYTE_DECODER is None:
        bs = (
            list(range(0x21, 0x7F))
            + list(range(0xA1, 0xAD))
            + list(range(0xAE, 0x100))
        )
        cs = bs[:]
        n = 0
        for b in range(256):
            if b not in bs:
                bs.append(b)
                cs.append(0x100 + n)
                n += 1
        _BYTE_DECODER = {chr(c): b for b, c in zip(bs, cs)}
    return _BYTE_DECODER


def token_byte_table(tokenizer, vocab_size: int) -> List[Optional[bytes]]:
    """Bytes each vocab id contributes to the output text (None for
    specials/unused ids — those are never allowed by a guided mask).

    Per-id ``decode([i])`` is NOT used: HF decode strips SentencePiece word
    markers (so '▁world' would lose its space) and renders partial-UTF-8
    byte-level pieces as U+FFFD. Instead the raw vocab pieces are mapped:
    SentencePiece '▁'->space and '<0xNN>' byte pieces; byte-level BPE via
    the inverse GPT-2 byte-unicode alphabet. The two conventions are
    disambiguated by probing the vocab for '▁' pieces."""
    specials = {
        getattr(tokenizer, name, None)
        for name in ("bos_token_id", "eos_token_id", "pad_token_id")
    }
    hf = getattr(tokenizer, "_tok", None)
    out: List[Optional[bytes]] = []
    if hf is None:  # ByteTokenizer: ids 0..255 ARE bytes
        for i in range(vocab_size):
            if i in specials or i >= 256:
                out.append(None)
            else:
                out.append(bytes([i]))
        return out

    specials |= set(getattr(hf, "all_special_ids", None) or [])
    pieces = hf.convert_ids_to_tokens(list(range(vocab_size)))
    spm = any(p is not None and "▁" in p for p in pieces)
    try:  # share the probe with _is_spm_tokenizer: one O(V) walk, one truth
        tokenizer._spm_convention = spm
    except Exception:
        pass
    bd = _gpt2_byte_decoder()
    for i, p in enumerate(pieces):
        if i in specials or p is None:
            out.append(None)
            continue
        try:
            if spm:
                if p.startswith("<0x") and p.endswith(">") and len(p) == 6:
                    out.append(bytes([int(p[3:5], 16)]))  # sp byte fallback
                else:
                    out.append(p.replace("▁", " ").encode("utf-8"))
            elif all(ch in bd for ch in p):
                out.append(bytes(bd[ch] for ch in p))  # byte-level BPE
            else:
                out.append(p.encode("utf-8"))
        except Exception:
            out.append(None)
    return out


# ------------------------------------------------------- JSON -> regex

# control bytes excluded and \u forced to 4 hex digits: strict JSON parsers
# (json.loads) reject raw 0x00-0x1f inside strings and partial \u escapes
_JSON_STRING = r'"([^"\\\x00-\x1f]|\\(["\\/bfnrt]|u[0-9a-fA-F]{4}))*"'
_JSON_INT = r"-?(0|[1-9][0-9]*)"
_JSON_NUM = r"-?(0|[1-9][0-9]*)(\.[0-9]+)?([eE][-+]?[0-9]+)?"
_WS = r"[ ]?"


def _regex_escape_literal(text: str) -> str:
    out = []
    for ch in text:
        if ch in r"\.[]{}()*+?|^$/":
            out.append("\\" + ch)
        else:
            out.append(ch)
    return "".join(out)


def json_schema_to_regex(schema: dict, depth: int = 4) -> str:
    """Lower a JSON-schema subset to a whole-string regex (Outlines-style).

    Supported: type object (properties + required, in declaration order),
    string (incl. enum/const), integer, number, boolean, null, array
    (items, minItems/maxItems, default 0..8), anyOf, $-less nesting.
    """
    if depth < 0:
        raise RegexError("schema nesting too deep for guided decoding")
    if not isinstance(schema, dict):
        raise RegexError("schema must be an object")
    if "enum" in schema:
        return "({})".format(
            "|".join(
                _regex_escape_literal(json.dumps(v)) for v in schema["enum"]
            )
        )
    if "const" in schema:
        return _regex_escape_literal(json.dumps(schema["const"]))
    if "anyOf" in schema:
        return "({})".format(
            "|".join(json_schema_to_regex(s, depth - 1) for s in schema["anyOf"])
        )
    t = schema.get("type")
    if t == "string":
        return _JSON_STRING
    if t == "integer":
        return _JSON_INT
    if t == "number":
        return _JSON_NUM
    if t == "boolean":
        return "(true|false)"
    if t == "null":
        return "null"
    if t == "array":
        item = json_schema_to_regex(schema.get("items", {}), depth - 1)
        lo = int(schema.get("minItems", 0))
        hi = int(schema.get("maxItems", 8))
        if lo == 0:
            body = "({i}(,{w}{i}){{0,{n}}})?".format(i=item, w=_WS, n=max(hi - 1, 0))
        else:
            body = "{i}(,{w}{i}){{{m},{n}}}".format(
                i=item, w=_WS, m=lo - 1, n=max(hi - 1, lo - 1)
            )
        return r"\[" + _WS + body + _WS + r"\]"
    if (
        t == "object"
        and not schema.get("properties")
        and not schema.get("required")
        and schema.get("additionalProperties") is not False
    ):
        # no declared properties = ANY object (JSON Schema), not the empty
        # object: lower to a bounded any-object like json_object mode.
        # With an explicit `additionalProperties: false` the schema instead
        # falls through to the declared-properties branch, whose empty
        # member list lowers to exactly `{}` — the closed-object semantics
        # OpenAI strict tool calling pins (llm/tools.tool_call_schema).
        # (additionalProperties is otherwise not modeled — documented
        # subset limitation; a declared-properties object is already
        # closed over its declared members by construction.)
        _arr, obj = _json_container_regexes(json_value_regex(min(depth, 2)))
        return obj
    if t == "object" or "properties" in schema:
        props = schema.get("properties") or {}
        if not props and schema.get("required"):
            # required-only object: presence of the required members IS the
            # constraint — enforce them (any JSON value), declaration order
            props = {str(r): {} for r in schema["required"]}
        # JSON Schema semantics (and Outlines): absent `required` means NO
        # property is required, not all of them (ADVICE r3)
        required = set(schema.get("required") or [])
        pieces = [
            (
                '"{}":{}{}'.format(
                    _regex_escape_literal(name), _WS,
                    json_schema_to_regex(sub, depth - 1),
                ),
                name in required,
            )
            for name, sub in props.items()
        ]
        comma = "," + _WS
        req_idx = [i for i, (_p, r) in enumerate(pieces) if r]
        if req_idx:
            # anchor commas on the first REQUIRED property: optionals before
            # it carry a trailing comma, everything after a leading one —
            # separators stay correct for any subset of optionals
            first = req_idx[0]
            out = []
            for i, (p, r) in enumerate(pieces):
                if i < first:
                    out.append("({}{})?".format(p, comma))
                elif i == first:
                    out.append(p)
                elif r:
                    out.append(comma + p)
                else:
                    out.append("({}{})?".format(comma, p))
            body = "".join(out)
        elif pieces:
            # all optional: alternation over the FIRST present property;
            # every later property then optionally follows with a leading
            # comma. Quadratic pattern size (sum of suffix lengths) — the
            # previous suffix-recursion duplicated the tail twice per
            # property, i.e. exponential, and a ~28-optional-property
            # schema could OOM the pre-flight (r4 code review)
            alts = []
            for i, (p, _r) in enumerate(pieces):
                rest = "".join(
                    "({}{})?".format(comma, q) for q, _r2 in pieces[i + 1 :]
                )
                alts.append(p + rest)
            body = "({})?".format("|".join(alts))
        else:
            body = ""
        return r"\{" + _WS + body + _WS + r"\}"
    # untyped: any bounded JSON value
    return json_value_regex(min(depth, 2))


def _json_container_regexes(value: str) -> Tuple[str, str]:
    # Kleene stars, not bounded repeats: {0,N} COPIES the whole nested
    # fragment N times in the NFA (exponential across depths); a star is
    # a loop edge and keeps the automaton linear in the regex size
    arr = r"\[" + _WS + "({v}(,{w}{v})*)?".format(v=value, w=_WS) + _WS + r"\]"
    obj = (
        r"\{" + _WS
        + "({k}:{w}{v}(,{w}{k}:{w}{v})*)?".format(
            k=_JSON_STRING, w=_WS, v=value
        )
        + _WS + r"\}"
    )
    return arr, obj


def json_value_regex(depth: int = 3) -> str:
    """Any JSON value with nesting bounded to `depth` (regular languages
    can't count braces; bounded depth is the standard trade)."""
    scalar = "({}|{}|true|false|null)".format(_JSON_STRING, _JSON_NUM)
    value = scalar
    for _ in range(depth):
        arr, obj = _json_container_regexes(value)
        value = "({}|{}|{})".format(scalar, arr, obj)
    return value


def json_object_regex(depth: int = 3) -> str:
    """A JSON OBJECT at top level (OpenAI json_object semantics: "the model
    must output a JSON object", not any JSON value), members nested to
    `depth`."""
    _arr, obj = _json_container_regexes(json_value_regex(max(depth - 1, 0)))
    return obj


# ------------------------------------------------------------ public entry

@dataclass(frozen=True)
class GuidedSpec:
    """What the API layer hands the engine. kind: 'regex' | 'json_schema' |
    'json_object'; payload: pattern string / schema-JSON string / ''."""

    kind: str
    payload: str = ""

    def cache_key(self) -> str:
        return "{}:{}".format(self.kind, self.payload)


@dataclass
class CompiledGrammar:
    """Device-friendly compiled form. A dense [S, V] token table costs
    S*V*2 bytes (770 MB for json_object over a 128k vocab) — instead:

    - mask_bits [S+1, ceil(V/8)] uint8: bitpacked allowed-token sets
      (little bit order: token v -> byte v//8, bit v%8). 16x smaller; the
      decode scan gathers a state's row and bit-expands on device. Row S is
      the post-EOS terminal (only the EOS bit set).
    - byte_trans [S+1, 256] int16: the BYTE DFA (+ all-DEAD terminal row).
      State advance re-walks the sampled token's bytes on device — a
      [B, Lmax] fori_loop of tiny gathers instead of a V-wide row.

    Token-level pruning already happened on the full table, so any token
    admitted by mask_bits byte-walks to a token-live state; mask and walk
    agree by construction.
    """

    mask_bits: np.ndarray
    byte_trans: np.ndarray
    start: int
    terminal: int

    @property
    def n_states(self) -> int:
        return self.mask_bits.shape[0]


def pack_token_mask(table: np.ndarray) -> np.ndarray:
    """[S, V] transition table -> [S, ceil(V/8)] little-order bitmask."""
    return np.packbits(table != DEAD, axis=1, bitorder="little")


def build_token_byte_arrays(
    token_bytes: Sequence[Optional[bytes]], max_len: int = 16
) -> Tuple[np.ndarray, np.ndarray]:
    """(tok_bytes [V, max_len] uint8, tok_len [V] int32) for the on-device
    byte walk. Tokens longer than max_len get len 0 — compile_guided
    forbids them in every grammar so the walk never sees one."""
    V = len(token_bytes)
    tb = np.zeros((V, max_len), np.uint8)
    tl = np.zeros((V,), np.int32)
    for i, t in enumerate(token_bytes):
        if t and len(t) <= max_len:
            tb[i, : len(t)] = np.frombuffer(t, np.uint8)
            tl[i] = len(t)
    return tb, tl


def _is_spm_tokenizer(tokenizer, vocab_size: int) -> bool:
    """True for SentencePiece-convention tokenizers (pieces use '▁' word
    markers and decode strips the sequence-leading space). Byte-level BPE
    (GPT-2/Llama-3 alphabet) returns False: there decode PRESERVES the
    leading space, so the grammar must not admit one.

    Uses the SAME vocab probe as token_byte_table (any '▁' piece) so the
    grammar's leading-space branch and the byte table can never disagree;
    the O(V) walk is cached on the tokenizer wrapper."""
    hf = getattr(tokenizer, "_tok", None)
    if hf is None:
        return False
    flag = getattr(tokenizer, "_spm_convention", None)
    if flag is None:
        try:
            pieces = hf.convert_ids_to_tokens(list(range(vocab_size)))
            flag = any(p is not None and "▁" in p for p in pieces)
        except Exception:
            flag = False
        try:
            tokenizer._spm_convention = flag
        except Exception:
            pass
    return flag


def compile_guided(
    spec: GuidedSpec, tokenizer, vocab_size: int, eos_id: int,
    max_states: int = 8192, max_token_bytes: int = 16,
    token_bytes: Optional[Sequence[Optional[bytes]]] = None,
) -> CompiledGrammar:
    """``token_bytes``: pass a cached token_byte_table() to skip the O(V)
    vocab walk per grammar (the engine caches one per tokenizer)."""
    if spec.kind == "regex":
        pattern = spec.payload
    elif spec.kind == "json_schema":
        pattern = json_schema_to_regex(json.loads(spec.payload))
    elif spec.kind == "json_object":
        pattern = json_object_regex(3)
    else:
        raise RegexError("unknown guided kind {!r}".format(spec.kind))
    # SentencePiece detokenization strips one leading space ('▁word' at
    # sequence start decodes to "word"), so the natural word-start pieces
    # contribute " word" bytes and a grammar anchored at string start
    # would steer the model away from its highest-probability tokenization
    # (ADVICE r3). Allow exactly one optional leading space: it vanishes
    # in decode, so emitted text still matches the original pattern.
    dfa = ByteDFA.from_regex(
        pattern,
        max_states=max_states,
        allow_leading_space=_is_spm_tokenizer(tokenizer, vocab_size),
    )
    if token_bytes is None:
        token_bytes = token_byte_table(tokenizer, vocab_size)
    tokens = list(token_bytes)
    for i, t in enumerate(tokens):  # over-long tokens can't be walked
        if t is not None and len(t) > max_token_bytes:
            tokens[i] = None
    tdfa = TokenDFA.build(dfa, tokens, eos_id)
    byte_trans = np.vstack(
        [dfa.trans, np.full((1, 256), DEAD, np.int32)]
    ).astype(np.int16)
    return CompiledGrammar(
        mask_bits=pack_token_mask(tdfa.table),
        byte_trans=byte_trans,
        start=0,
        terminal=dfa.n_states,
    )
