"""Paged KV cache: page pool + per-sequence page tables.

vLLM's PagedAttention memory model rebuilt for TPU/HBM (SURVEY.md §2.9 row 2):
the cache is a fixed pool of fixed-size pages per layer; sequences own page
lists, so HBM holds only the tokens that exist and slots never reserve
max_seq_len. Allocation is host-side (cheap integer bookkeeping); the device
side sees dense pools + int32 page tables, which feed
ops/paged_attention.paged_attention.

Device layout per layer:   k_pool/v_pool [Hkv, num_pages, page_size, D]
(head-major — the layout ops/paged_attention.py's kernel tiles over)
Host bookkeeping:          free-page stack + per-slot page lists
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np


class PagePool:
    """Host-side page allocator for a fixed pool.

    Page 0 is RESERVED as the null page: unused page-table entries point at it
    and inactive batch slots write their garbage KV there — it is never
    allocated to a sequence."""

    def __init__(self, num_pages: int, page_size: int, max_slots: int):
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        self.max_slots = int(max_slots)
        self._free: List[int] = list(range(num_pages - 1, 0, -1))
        self._slot_pages: List[List[int]] = [[] for _ in range(max_slots)]
        self._slot_len: List[int] = [0] * max_slots

    @property
    def free_pages(self) -> int:
        return len(self._free)

    def pages_needed(self, tokens: int) -> int:
        return -(-tokens // self.page_size)

    def can_allocate(self, tokens: int) -> bool:
        return self.pages_needed(tokens) <= len(self._free)

    def allocate(self, slot: int, tokens: int) -> List[int]:
        """Give `slot` enough pages for `tokens` total; returns new page ids."""
        have = len(self._slot_pages[slot])
        need = self.pages_needed(tokens) - have
        if need > len(self._free):
            raise MemoryError(
                "page pool exhausted: need {} pages, {} free".format(need, len(self._free))
            )
        new = [self._free.pop() for _ in range(max(0, need))]
        self._slot_pages[slot].extend(new)
        self._slot_len[slot] = tokens
        return new

    def extend(self, slot: int, extra_tokens: int = 1) -> List[int]:
        """Grow a sequence; returns ALL newly allocated page ids (possibly
        several when `extra_tokens` spans page boundaries; empty if none)."""
        return self.allocate(slot, self._slot_len[slot] + extra_tokens)

    def free(self, slot: int) -> None:
        self._free.extend(reversed(self._slot_pages[slot]))
        self._slot_pages[slot] = []
        self._slot_len[slot] = 0

    def truncate(self, slot: int, tokens: int) -> None:
        """Shrink a sequence to `tokens`, returning surplus pages to the
        pool (speculative chunks over-allocate for the worst-case accepted
        length, then roll back to what was actually emitted)."""
        if tokens > self._slot_len[slot]:
            raise ValueError(
                "truncate({}) past current length {}".format(
                    tokens, self._slot_len[slot]
                )
            )
        keep = self.pages_needed(tokens)
        surplus = self._slot_pages[slot][keep:]
        self._slot_pages[slot] = self._slot_pages[slot][:keep]
        self._free.extend(reversed(surplus))
        self._slot_len[slot] = tokens

    def slot_length(self, slot: int) -> int:
        return self._slot_len[slot]

    def token_coords(self, slot: int, start: int, count: int):
        """(page_id, offset) for token positions [start, start+count) of a
        slot. The single source of the page//offset math for engine, cache,
        and tests."""
        pages = self._slot_pages[slot]
        out = []
        for pos in range(start, start + count):
            out.append((pages[pos // self.page_size], pos % self.page_size))
        return out

    def page_table(self, pages_per_seq: int) -> np.ndarray:
        """Dense [max_slots, pages_per_seq] table (unused entries point at
        page 0 — they are masked by lengths on the device side). Raises if any
        slot owns more pages than the table can express — silently truncating
        would drop the newest tokens from attention."""
        table = np.zeros((self.max_slots, pages_per_seq), np.int32)
        for slot, pages in enumerate(self._slot_pages):
            if len(pages) > pages_per_seq:
                raise ValueError(
                    "slot {} holds {} pages > table width {}".format(
                        slot, len(pages), pages_per_seq
                    )
                )
            table[slot, : len(pages)] = pages
        return table

    def lengths(self) -> np.ndarray:
        return np.asarray(self._slot_len, np.int32)


class PagedKVCache:
    """Device pools for all layers + the shared host-side PagePool.

    Pools are ONE stacked array per side — ``k``/``v`` [L, Hkv, N, P, D] — and
    every write goes through a jitted, buffer-donating scatter: the pool is
    updated in place in HBM, never copied (an eager ``.at[].set`` would copy
    the whole multi-GB pool per token)."""

    def __init__(
        self,
        n_layers: int,
        n_kv_heads: int,
        head_dim: int,
        *,
        num_pages: int,
        page_size: int = 16,
        max_slots: int = 8,
        dtype="bfloat16",
    ):
        import jax
        import jax.numpy as jnp

        self.pool = PagePool(num_pages, page_size, max_slots)
        self.n_layers = n_layers
        shape = (n_layers, n_kv_heads, num_pages, page_size, head_dim)
        self.k = jnp.zeros(shape, jnp.dtype(dtype))
        self.v = jnp.zeros(shape, jnp.dtype(dtype))

        def _write_pages(pool, chunks, pages):
            # chunks [NP, L, Hkv, P, D], pages [NP] -> scatter all pages in ONE
            # dispatch (a per-page Python loop would put O(prompt/page_size)
            # host->device roundtrips on the TTFT-critical prefill path)
            chunks = jnp.moveaxis(chunks, 0, 2)          # [L, Hkv, NP, P, D]
            return pool.at[:, :, pages].set(chunks)

        def _write_token(pool, kv, page, offset):
            # kv [L, Hkv, D] -> pool[:, :, page, offset]
            return jax.lax.dynamic_update_slice(
                pool, kv[:, :, None, None], (0, 0, page, offset, 0)
            )

        self._write_pages = jax.jit(_write_pages, donate_argnums=(0,))
        self._write_token = jax.jit(_write_token, donate_argnums=(0,))

    def layer(self, li: int):
        """Per-layer head-major views for ops.paged_attention."""
        return self.k[li], self.v[li]

    def max_pages_per_seq(self, max_seq_len: int) -> int:
        return self.pool.pages_needed(max_seq_len)

    def write_prompt(self, slot: int, k_stack, v_stack, length: int) -> None:
        """Scatter a prefilled prompt's KV (stacked [L, S, Hkv, D]) into this
        slot's pages via donated jitted writes."""
        import jax.numpy as jnp

        self.pool.free(slot)
        self.pool.allocate(slot, length)
        pages = self.pool._slot_pages[slot]
        page_size = self.pool.page_size
        n_pages = len(pages)
        k_hm = jnp.moveaxis(jnp.asarray(k_stack), 2, 1)  # [L, Hkv, S, D]
        v_hm = jnp.moveaxis(jnp.asarray(v_stack), 2, 1)
        pad_to = n_pages * page_size
        k_hm = jnp.pad(k_hm, ((0, 0), (0, 0), (0, pad_to - k_hm.shape[2]), (0, 0)))
        v_hm = jnp.pad(v_hm, ((0, 0), (0, 0), (0, pad_to - v_hm.shape[2]), (0, 0)))
        l, hkv, _, d = k_hm.shape
        # [L,Hkv,NP*P,D] -> [NP, L, Hkv, P, D]
        k_chunks = k_hm.reshape(l, hkv, n_pages, page_size, d).transpose(2, 0, 1, 3, 4)
        v_chunks = v_hm.reshape(l, hkv, n_pages, page_size, d).transpose(2, 0, 1, 3, 4)
        page_ids = jnp.asarray(pages, jnp.int32)
        self.k = self._write_pages(self.k, k_chunks, page_ids)
        self.v = self._write_pages(self.v, v_chunks, page_ids)

    def append_token(self, slot: int, k_token, v_token) -> None:
        """Append one token's KV (stacked [L, Hkv, D]) to the slot."""
        import jax.numpy as jnp

        length = self.pool.slot_length(slot)
        self.pool.extend(slot, 1)
        ((page, offset),) = self.pool.token_coords(slot, length, 1)
        self.k = self._write_token(self.k, jnp.asarray(k_token), page, offset)
        self.v = self._write_token(self.v, jnp.asarray(v_token), page, offset)
