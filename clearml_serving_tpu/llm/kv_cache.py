"""Paged KV cache: refcounted page pool + per-sequence page tables.

vLLM's PagedAttention memory model rebuilt for TPU/HBM (SURVEY.md §2.9 row 2):
the cache is a fixed pool of fixed-size pages per layer; sequences own page
lists, so HBM holds only the tokens that exist and slots never reserve
max_seq_len. Allocation is host-side (cheap integer bookkeeping); the device
side sees dense pools + int32 page tables, which feed
ops/paged_attention.paged_attention.

Pages are REFCOUNTED so they can be shared between live slots and the radix
prefix cache (llm/prefix_cache.py): the cache stores a prompt prefix by
taking a reference on the admitting slot's pages, and a later admission
sharing that prefix maps the same pages into its own page table — zero HBM
copies either way. A page returns to the free list only when its last
reference (slot or cache) drops. A slot that must WRITE into a shared page
(its tail page is referenced elsewhere) gets a private replacement first —
copy-on-write: the pool swaps the page id host-side and records a
(src, dst) pair; PagedKVCache.apply_pending_cow() performs the device copy
before the next write lands.

Device layout per layer:   k_pool/v_pool [Hkv, num_pages, page_size, D]
(head-major — the layout ops/paged_attention.py's kernel tiles over)
Host bookkeeping:          free-page stack + per-slot page lists + refcounts

int8 paged KV (``kv_quant="int8"``, docs/paged_kv_quant.md): the K/V pools
store int8 and each side gains a SCALE pool ``[L, Hkv, num_pages, P]`` f32
holding the per-(token, head) symmetric dequant scales (the same
quantization as models/llama._kv_store on the dense path). A page id
indexes BOTH its data plane and its scale row — one lifecycle: every write
(prompt scatter, per-token append), every copy-on-write duplication, and
every free/share/refcount operation covers the scale row by construction,
because the scale pools are addressed by the same page ids the PagePool
hands out. Pool HBM per token-head drops from 2·D bytes (bf16) to D + 4
(int8 + f32 scale) — 1.94x at D=128 — which doubles the page budget the
radix prefix cache can hold.

Host-RAM tier (docs/kv_tiering.md): ``enable_host_tier`` preallocates a
:class:`HostKVTier` — page-major host buffers addressed by HOST-tier page
ids, a separate id space from the device pool's. The radix prefix cache
(llm/prefix_cache.py) demotes cold cached pages into the tier instead of
dropping them (``demote_pages``: device→host readback of int8 pages AND
their scale rows, 2x cheaper than bf16 to hold and transfer) and re-onlines
them on a hit (``promote_pages``: async host→device DMA enqueued under the
dispatch lock, so every later consumer program is ordered after the copy by
data dependency on the pool handles — the "tier fence";
llm/schedule_explorer.py's ``tier_promotion`` scenario models losing it).
Promotion completion is observed at the engine's retire boundaries
(``reap_promotions``), which is where the DMA-overlap metric comes from.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from . import lifecycle_ledger as _ledger

from .shapes import pad_pages, pow2_bucket


class PagePool:
    """Host-side refcounted page allocator for a fixed pool.

    Page 0 is RESERVED as the null page: unused page-table entries point at it
    and inactive batch slots write their garbage KV there — it is never
    allocated to a sequence and never refcounted.

    A single re-entrant lock guards all bookkeeping: the engine loop thread,
    decode worker threads, and admission workers (prefix-cache pins) all
    mutate refcounts concurrently."""

    # lock-discipline registry (tpuserve-analyze TPU301): every mutation of
    # these attributes must sit inside `with self._lock:`; helpers called
    # with the lock already held annotate their def line
    __guarded_by__ = {
        "_lock": ("_free", "_slot_pages", "_slot_len", "_refs",
                  "_pending_cow", "_pins"),
    }

    # ownership-discipline registry (tpuserve-analyze TPU7xx,
    # docs/static_analysis.md): every declared acquire must reach a
    # matching release / drop-to-recompute handler on ALL paths (exception
    # edges included). Mirrored in analyze/rules_lifecycle.py
    # LIFECYCLE_REGISTRY (consistency-tested); "static": False entries are
    # cross-function protocols the runtime ownership ledger
    # (llm/lifecycle_ledger.py) audits instead.
    __acquires__ = {
        "allocate": {"resource": "pages.slot",
                     "releases": ("free", "truncate"),
                     "drops": ("_free_slot_pages",),
                     "receivers": ("pool", "_pool", "page_pool", "pages")},
        "extend": {"resource": "pages.slot",
                   "releases": ("free", "truncate"),
                   "drops": ("_free_slot_pages",),
                   "receivers": ("pool", "_pool", "page_pool")},
        "map_shared": {"resource": "pages.slot", "releases": ("free",),
                       "drops": ("_free_slot_pages",),
                       "receivers": ("pool", "_pool", "page_pool")},
        "allocate_cache_pages": {"resource": "pages.ref",
                                 "releases": ("unref_pages",),
                                 "mint": True},
        "ref_pages": {"resource": "pages.ref", "releases": ("unref_pages",),
                      "static": False},
        "pin_pages": {"resource": "pages.pin",
                      "releases": ("unpin_pages",)},
    }

    def __init__(self, num_pages: int, page_size: int, max_slots: int):
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        self.max_slots = int(max_slots)
        self._free: List[int] = list(range(num_pages - 1, 0, -1))
        self._slot_pages: List[List[int]] = [[] for _ in range(max_slots)]
        self._slot_len: List[int] = [0] * max_slots
        self._refs: List[int] = [0] * num_pages
        self._lock = threading.RLock()
        # copy-on-write bookkeeping: host-side id swaps whose device copy is
        # still pending (drained by PagedKVCache.apply_pending_cow)
        self._pending_cow: List[Tuple[int, int]] = []
        self.cow_events = 0
        # transient out-of-structure references (prefix-cache lookup pins):
        # page -> count of refs held by in-flight admissions. Tracked apart
        # from _refs so the KV sanitizer (llm/kv_sanitizer.py) can prove
        # refcount CONSERVATION: refs == slot-table + cache-node + pin refs.
        self._pins: Dict[int, int] = {}

    @property
    def free_pages(self) -> int:
        with self._lock:
            return len(self._free)

    def pages_needed(self, tokens: int) -> int:
        return -(-tokens // self.page_size)

    def can_allocate(self, tokens: int) -> bool:
        with self._lock:
            return self.pages_needed(tokens) <= len(self._free)

    def _pop_free(self) -> int:  # tpuserve: ignore[TPU301] lock held by caller
        page = self._free.pop()
        self._refs[page] = 1
        return page

    def _unref(self, page: int) -> bool:  # tpuserve: ignore[TPU301] lock held by caller
        """Drop one reference; True when the page returned to the free list."""
        self._refs[page] -= 1
        if self._refs[page] == 0:
            self._free.append(page)
            return True
        if self._refs[page] < 0:
            raise RuntimeError("page {} refcount went negative".format(page))
        return False

    def allocate(self, slot: int, tokens: int) -> List[int]:
        """Give `slot` enough pages for `tokens` total; returns new page ids."""
        with self._lock:
            have = len(self._slot_pages[slot])
            need = self.pages_needed(tokens) - have
            if need > len(self._free):
                raise MemoryError(
                    "page pool exhausted: need {} pages, {} free".format(
                        need, len(self._free)
                    )
                )
            new = [self._pop_free() for _ in range(max(0, need))]
            self._slot_pages[slot].extend(new)
            self._slot_len[slot] = tokens
            if new and _ledger.armed():
                _ledger.acquire("pages.slot", key=slot, n=len(new),
                                domain=self)
            return new

    def extend(self, slot: int, extra_tokens: int = 1) -> List[int]:
        """Grow a sequence; returns ALL newly allocated page ids (possibly
        several when `extra_tokens` spans page boundaries; empty if none).

        Copy-on-write: if the slot's write position falls inside a page that
        is ALSO referenced elsewhere (prefix cache or another slot), the page
        is replaced with a private copy first — writing in place would
        corrupt every other reader. The device copy is deferred to
        PagedKVCache.apply_pending_cow()."""
        with self._lock:
            length = self._slot_len[slot]
            if extra_tokens > 0 and length % self.page_size:
                idx = length // self.page_size
                page = self._slot_pages[slot][idx]
                if self._refs[page] > 1:
                    if not self._free:
                        raise MemoryError(
                            "page pool exhausted (copy-on-write of shared "
                            "page {})".format(page)
                        )
                    fresh = self._pop_free()
                    self._slot_pages[slot][idx] = fresh
                    self._refs[page] -= 1  # > 1, so never frees here
                    self._pending_cow.append((page, fresh))
                    self.cow_events += 1
            return self.allocate(slot, length + extra_tokens)

    def free(self, slot: int) -> None:
        """Release the slot's references; pages still referenced by the
        prefix cache (or another slot) stay allocated."""
        with self._lock:
            for page in reversed(self._slot_pages[slot]):
                self._unref(page)
            self._slot_pages[slot] = []
            self._slot_len[slot] = 0
            if _ledger.armed():
                _ledger.release("pages.slot", key=slot, domain=self,
                                all_of_key=True)

    def truncate(self, slot: int, tokens: int) -> None:
        """Shrink a sequence to `tokens`, dropping this slot's references to
        the surplus pages (speculative chunks over-allocate for the
        worst-case accepted length, then roll back to what was actually
        emitted). Surplus pages shared with the cache stay allocated."""
        with self._lock:
            if tokens > self._slot_len[slot]:
                raise ValueError(
                    "truncate({}) past current length {}".format(
                        tokens, self._slot_len[slot]
                    )
                )
            keep = self.pages_needed(tokens)
            surplus = self._slot_pages[slot][keep:]
            self._slot_pages[slot] = self._slot_pages[slot][:keep]
            for page in reversed(surplus):
                self._unref(page)
            self._slot_len[slot] = tokens
            if surplus and _ledger.armed():
                _ledger.release("pages.slot", key=slot, n=len(surplus),
                                domain=self)

    # -- sharing (prefix cache) --------------------------------------------

    def ref_pages(self, pages: List[int]) -> None:
        """Take one reference on each page (cache store / lookup pin).
        Validates the whole batch before mutating anything: a mid-loop
        raise must not leave earlier pages referenced (the failure fires
        exactly when accounting is already suspect — don't compound it)."""
        with self._lock:
            for page in pages:
                if self._refs[page] <= 0:
                    raise RuntimeError(
                        "ref_pages on unallocated page {}".format(page)
                    )
            for page in pages:
                self._refs[page] += 1
            if pages and _ledger.armed():
                _ledger.acquire("pages.ref", n=len(pages), domain=self)

    def unref_pages(self, pages: List[int]) -> int:
        """Drop one reference per page; returns how many were freed."""
        freed = 0
        with self._lock:
            for page in pages:
                if self._unref(page):
                    freed += 1
            if pages and _ledger.armed():
                _ledger.release("pages.ref", n=len(pages), domain=self)
        return freed

    def pin_pages(self, pages: List[int]) -> None:
        """Take one TRANSIENT reference per page (prefix-cache lookup pin,
        held by an in-flight admission). Same refcount semantics as
        ref_pages, but accounted separately so the sanitizer can attribute
        every reference to a holder."""
        with self._lock:
            # validate-then-mutate: no partial pins on error
            for page in pages:
                if self._refs[page] <= 0:
                    raise RuntimeError(
                        "pin_pages on unallocated page {}".format(page)
                    )
            for page in pages:
                self._refs[page] += 1
                self._pins[page] = self._pins.get(page, 0) + 1
            if pages and _ledger.armed():
                # keyed by the exact page run: concurrent admissions' pins
                # must not discharge each other's entries
                _ledger.acquire("pages.pin", key=tuple(pages),
                                n=len(pages), domain=self)

    def unpin_pages(self, pages: List[int]) -> int:
        """Drop one transient reference per page; returns pages freed."""
        freed = 0
        with self._lock:
            # validate-then-mutate: no partial unpins on error
            counted: Dict[int, int] = {}
            for page in pages:
                counted[page] = counted.get(page, 0) + 1
                if self._pins.get(page, 0) < counted[page]:
                    raise RuntimeError(
                        "unpin_pages on unpinned page {}".format(page)
                    )
            for page in pages:
                count = self._pins[page]
                if count == 1:
                    self._pins.pop(page)
                else:
                    self._pins[page] = count - 1
                if self._unref(page):
                    freed += 1
            if pages and _ledger.armed():
                _ledger.release("pages.pin", key=tuple(pages),
                                n=len(pages), domain=self)
        return freed

    def snapshot(self) -> Dict[str, object]:
        """Consistent copy of all bookkeeping (one lock hold) for the KV
        sanitizer: refcounts, free list, slot tables/lengths, transient
        pins, and pending copy-on-write pairs."""
        with self._lock:
            return {
                "refs": list(self._refs),
                "free": list(self._free),
                "slot_pages": [list(p) for p in self._slot_pages],
                "slot_len": list(self._slot_len),
                "pins": dict(self._pins),
                "pending_cow": list(self._pending_cow),
            }

    def map_shared(self, slot: int, pages: List[int], tokens: int) -> None:
        """Map already-allocated (shared) pages as the slot's first pages —
        the zero-copy half of a prefix-cache hit. The slot takes its own
        reference on each page; ``tokens`` must cover the pages exactly
        (page-aligned prefix)."""
        with self._lock:
            if self._slot_pages[slot]:
                raise RuntimeError(
                    "map_shared into non-empty slot {}".format(slot)
                )
            if tokens != len(pages) * self.page_size:
                raise ValueError(
                    "shared prefix of {} tokens does not fill {} pages".format(
                        tokens, len(pages)
                    )
                )
            for page in pages:
                if self._refs[page] <= 0:
                    raise RuntimeError(
                        "map_shared of unallocated page {}".format(page)
                    )
            for page in pages:
                self._refs[page] += 1
            self._slot_pages[slot] = list(pages)
            self._slot_len[slot] = tokens
            if pages and _ledger.armed():
                _ledger.acquire("pages.slot", key=slot, n=len(pages),
                                domain=self)

    def allocate_cache_pages(self, n: int) -> List[int]:
        """Pop ``n`` free pages with one reference each, to be owned by the
        radix prefix cache (promotion targets for host-tier re-onlining,
        docs/kv_tiering.md). The caller MUST attach them to cache nodes (or
        unref them on failure) inside the same tree-lock window it called
        from — the KV sanitizer's conservation audit snapshots under that
        lock, so no intermediate owner-less state is ever observable."""
        with self._lock:
            if n > len(self._free):
                raise MemoryError(
                    "page pool exhausted: promotion needs {} pages, {} "
                    "free".format(n, len(self._free))
                )
            fresh = [self._pop_free() for _ in range(n)]
            if fresh and _ledger.armed():
                _ledger.acquire("pages.ref", n=len(fresh), domain=self)
            return fresh

    def drain_pending_cow(self) -> List[Tuple[int, int]]:
        with self._lock:
            out, self._pending_cow = self._pending_cow, []
            return out

    def page_refcount(self, page: int) -> int:
        with self._lock:
            return self._refs[page]

    def slot_pages(self, slot: int) -> List[int]:
        with self._lock:
            return list(self._slot_pages[slot])

    @property
    def shared_pages(self) -> int:
        """Pages with more than one reference (slot+cache or slot+slot)."""
        with self._lock:
            return sum(1 for r in self._refs[1:] if r > 1)

    def slot_length(self, slot: int) -> int:
        return self._slot_len[slot]

    def token_coords(self, slot: int, start: int, count: int):
        """(page_id, offset) for token positions [start, start+count) of a
        slot. The single source of the page//offset math for engine, cache,
        and tests."""
        with self._lock:
            pages = list(self._slot_pages[slot])
        out = []
        for pos in range(start, start + count):
            out.append((pages[pos // self.page_size], pos % self.page_size))
        return out

    def page_table(self, pages_per_seq: int) -> np.ndarray:
        """Dense [max_slots, pages_per_seq] table (unused entries point at
        page 0 — they are masked by lengths on the device side). Raises if any
        slot owns more pages than the table can express — silently truncating
        would drop the newest tokens from attention."""
        with self._lock:
            table = np.zeros((self.max_slots, pages_per_seq), np.int32)
            for slot, pages in enumerate(self._slot_pages):
                if len(pages) > pages_per_seq:
                    raise ValueError(
                        "slot {} holds {} pages > table width {}".format(
                            slot, len(pages), pages_per_seq
                        )
                    )
                table[slot, : len(pages)] = pages
            return table

    def lengths(self) -> np.ndarray:
        with self._lock:
            return np.asarray(self._slot_len, np.int32)


def available_host_memory_bytes(path: str = "/proc/meminfo") -> int:
    """``MemAvailable`` from /proc/meminfo, in bytes — the input to the
    host-tier auto-sizer (aux ``engine.prefix_cache_host_mb: "auto"``,
    docs/kv_tiering.md). Raises :class:`errors.HostTierAutoSizeError`
    (named, construction-time) on platforms without the file or without
    the field: silently guessing a size would hide that the knob did
    nothing."""
    from ..errors import HostTierAutoSizeError

    try:
        with open(path) as fh:
            for line in fh:
                if line.startswith("MemAvailable:"):
                    return int(line.split()[1]) * 1024
    except OSError as ex:
        raise HostTierAutoSizeError(
            "prefix_cache_host_mb='auto' needs {} (Linux); probe failed on "
            "this platform: {}".format(path, ex)
        )
    raise HostTierAutoSizeError(
        "prefix_cache_host_mb='auto': {} has no MemAvailable field on this "
        "platform; set an explicit engine.prefix_cache_host_mb".format(path)
    )


def cohosted_worker_processes() -> int:
    """How many engine worker processes share this host's RAM — the
    divisor for ``prefix_cache_host_mb: "auto"``. Each process sizes its
    tier independently from the same ``MemAvailable`` reading, so without
    the divide a 2-worker fleet claims half of host memory TWICE
    (over-commit the OOM killer settles later, not the sizer). The
    process-fleet builder (serving/process_replica.py) exports the fleet
    width as ``TPUSERVE_COHOSTED_PROCS`` into every worker; unset or
    malformed reads as 1 (the single-process in-heap backend)."""
    raw = os.environ.get("TPUSERVE_COHOSTED_PROCS", "")
    try:
        n = int(raw)
    except ValueError:
        return 1
    return max(1, n)


class HostKVTier:
    """Preallocated host-RAM page tier behind the HBM pools
    (docs/kv_tiering.md).

    Layout is PAGE-MAJOR — ``hk``/``hv`` [Nh, L, Hkv, P, D] (+ [Nh, L, Hkv,
    P] f32 scale rows on int8 pools) — so one host page's bytes are
    contiguous: a demotion writes one slab, a promotion stages one slab, and
    the host→device upload presents the runtime a single contiguous source
    per page run instead of a strided gather. Buffers are allocated ONCE at
    construction (numpy keeps them resident; on TPU runtimes jax's transfer
    path stages through its own pinned buffers, and preallocating here
    avoids allocator churn on the demote/promote paths).

    Host page ids are a SEPARATE id space from the device pool's: a cached
    node references either a device page id or a host-tier page id, never
    both (the KV sanitizer's two-tier invariant). Ownership is single-holder
    by construction — only the radix prefix cache allocates host pages, one
    node per id — so the tier needs an allocator, not refcounts."""

    # lock-discipline registry (tpuserve-analyze TPU301): id bookkeeping is
    # mutated only under self._lock. The data slabs themselves need no lock:
    # a freshly allocated id is exclusive to its allocator until freed, and
    # promotion stages a COPY of the rows before the id returns to the free
    # list (the PR-4 aliasing rule).
    __guarded_by__ = {"_lock": ("_free", "_used")}

    # ownership-discipline registry (tpuserve-analyze TPU7xx): host ids
    # pair allocate/free; the radix cache owns them at steady state
    __acquires__ = {
        "allocate": {"resource": "host.pages", "releases": ("free",),
                     "receivers": ("host_tier", "_host", "tier", "host")},
    }

    def __init__(self, num_pages: int, page_size: int, n_layers: int,
                 n_kv_heads: int, head_dim: int, dtype, quantized: bool):
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        if self.num_pages <= 0:
            raise ValueError("host tier needs at least one page")
        shape = (self.num_pages, n_layers, n_kv_heads, page_size, head_dim)
        self.hk = np.zeros(shape, np.dtype(dtype))
        self.hv = np.zeros(shape, np.dtype(dtype))
        if quantized:
            self.hk_scale = np.zeros(shape[:-1], np.float32)
            self.hv_scale = np.zeros(shape[:-1], np.float32)
        else:
            self.hk_scale = None
            self.hv_scale = None
        self._free: List[int] = list(range(self.num_pages - 1, -1, -1))
        self._used: set = set()
        self._lock = threading.Lock()

    @property
    def quantized(self) -> bool:
        return self.hk_scale is not None

    @property
    def page_bytes(self) -> int:
        """True host bytes per page: K+V slabs plus scale rows."""
        per = int(self.hk[0].nbytes) + int(self.hv[0].nbytes)
        if self.hk_scale is not None:
            per += int(self.hk_scale[0].nbytes) + int(self.hv_scale[0].nbytes)
        return per

    @property
    def free_pages(self) -> int:
        with self._lock:
            return len(self._free)

    @property
    def used_pages(self) -> int:
        with self._lock:
            return len(self._used)

    def allocate(self, n: int) -> List[int]:
        with self._lock:
            if n > len(self._free):
                raise MemoryError(
                    "host KV tier exhausted: need {} pages, {} free".format(
                        n, len(self._free)
                    )
                )
            ids = [self._free.pop() for _ in range(n)]
            self._used.update(ids)
            if ids and _ledger.armed():
                _ledger.acquire("host.pages", n=len(ids), domain=self)
            return ids

    def free(self, ids: List[int]) -> None:
        with self._lock:
            for hid in ids:
                if hid not in self._used:
                    raise RuntimeError(
                        "free of unallocated host page {}".format(hid)
                    )
                self._used.discard(hid)
                self._free.append(hid)
            if ids and _ledger.armed():
                _ledger.release("host.pages", n=len(ids), domain=self)

    def snapshot(self) -> Dict[str, object]:
        """Consistent copy of the id bookkeeping for the KV sanitizer."""
        with self._lock:
            return {
                "free": list(self._free),
                "used": set(self._used),
                "num_pages": self.num_pages,
            }


class PagedKVCache:
    """Device pools for all layers + the shared host-side PagePool.

    Pools are ONE stacked array per side — ``k``/``v`` [L, Hkv, N, P, D] — and
    every write goes through a jitted, buffer-donating scatter: the pool is
    updated in place in HBM, never copied (an eager ``.at[].set`` would copy
    the whole multi-GB pool per token).

    ``dispatch_lock`` serializes DISPATCH of device programs that touch the
    pools: the decode/spec chunks donate k/v while admission workers
    concurrently enqueue prefix-KV gathers and commit writes — without the
    lock a gather could grab a pool reference that a racing donating dispatch
    has already invalidated. Execution still overlaps; only the (cheap,
    host-side) enqueue is serialized.

    Donation ordering under the pipelined decode loop
    (docs/pipelined_decode.md): chained chunk dispatches rebind ``k``/``v``
    to the PENDING outputs of the in-flight chunk, and every later program
    (the next chunk, CoW copies, commit scatters, prefix gathers) consumes
    those handles — device-side ordering holds by data dependency, never by
    host-side waiting. Page FREES are the one thing data flow cannot order:
    the engine defers a freed slot's ``pool.free`` to the retirement of the
    newest chunk still writing it (the quarantine barrier), so a page is
    never re-allocated under an in-flight write. The barrier protocol is
    modelled and explored across seeded interleavings by
    llm/schedule_explorer.py's ``quarantine_barrier`` scenario
    (``--mutate drop_quarantine`` demonstrates the corruption a missing
    barrier causes); the thread-ownership side is machine-checked by
    tpuserve-analyze TPU501 via the engine's ``__affine_to__``."""

    # pool-handle rebinds happen only under the dispatch lock (a donating
    # dispatch invalidates the old handle; tpuserve-analyze TPU301). The
    # in-flight promotion records ride the same lock: they are appended at
    # copy-enqueue time (dispatch path) and drained at retire boundaries.
    __guarded_by__ = {
        "dispatch_lock": ("k", "v", "k_scale", "v_scale", "_promotions"),
    }

    def __init__(
        self,
        n_layers: int,
        n_kv_heads: int,
        head_dim: int,
        *,
        num_pages: int,
        page_size: int = 16,
        max_slots: int = 8,
        dtype="bfloat16",
        kv_quant: str = "",
    ):
        import jax
        import jax.numpy as jnp

        if kv_quant not in ("", "int8"):
            raise ValueError(
                "kv_quant must be '' or 'int8' (got {!r})".format(kv_quant)
            )
        self.kv_quant = kv_quant
        self.pool = PagePool(num_pages, page_size, max_slots)
        self.n_layers = n_layers
        shape = (n_layers, n_kv_heads, num_pages, page_size, head_dim)
        pool_dtype = jnp.int8 if kv_quant else jnp.dtype(dtype)
        self.k = jnp.zeros(shape, pool_dtype)
        self.v = jnp.zeros(shape, pool_dtype)
        # int8: per-(token, head) f32 dequant scales, page-id addressed so
        # a page and its scale row share one lifecycle (module docstring)
        if kv_quant:
            self.k_scale = jnp.zeros(shape[:-1], jnp.float32)
            self.v_scale = jnp.zeros(shape[:-1], jnp.float32)
        else:
            self.k_scale = None
            self.v_scale = None
        self.dispatch_lock = threading.Lock()
        # host-RAM tier (docs/kv_tiering.md): None until enable_host_tier;
        # the radix prefix cache demotes into / promotes out of it
        self.host_tier: Optional[HostKVTier] = None
        self._promotions: List[dict] = []   # in-flight promotion DMAs
        # tier counters (pages moved; GIL-atomic int bumps): observability
        # for engine_kv_demotions_total / engine_kv_promotions_total
        self.demoted_pages = 0
        self.promoted_pages = 0
        self.promo_reaped = 0       # promotion DMAs observed complete
        self.promo_wait_ms = 0.0    # exposed (un-hidden) wait at the reap
        self.promo_total_ms = 0.0   # issue -> observed-complete wall time

        def _write_pages(pool, chunks, pages):
            # chunks [NP, L, Hkv, P, D] (or [NP, L, Hkv, P] for scale pools),
            # pages [NP] -> scatter all pages in ONE dispatch (a per-page
            # Python loop would put O(prompt/page_size) host->device
            # roundtrips on the TTFT-critical prefill path)
            chunks = jnp.moveaxis(chunks, 0, 2)          # [L, Hkv, NP, P(, D)]
            return pool.at[:, :, pages].set(chunks)

        def _write_token(pool, kv, page, offset):
            # kv [L, Hkv, D] -> pool[:, :, page, offset]; scale pools drop
            # the trailing D (kv [L, Hkv] -> [L, Hkv, N, P] pool)
            idx = (0, 0, page, offset) + (0,) * (pool.ndim - 4)
            return jax.lax.dynamic_update_slice(
                pool, kv[:, :, None, None], idx
            )

        def _copy_page(pool, src, dst):
            # copy-on-write: duplicate one page inside the pool (src read,
            # dst written, one fused donated program — no host round trip)
            page = jax.lax.dynamic_slice(
                pool, (0, 0, src, 0, 0),
                (pool.shape[0], pool.shape[1], 1, pool.shape[3], pool.shape[4]),
            )
            return jax.lax.dynamic_update_slice(pool, page, (0, 0, dst, 0, 0))

        def _copy_pages(pool, srcs, dsts):
            # batched CoW: all pending (src, dst) pairs in ONE donated
            # gather/scatter — the pipelined decode loop applies CoW on the
            # dispatch path, so per-pair dispatches would put 4 host->device
            # program launches per shared-tail slot between chunks. Pair
            # lists pad to (0, 0): writing the reserved null page onto
            # itself is a no-op by construction.
            return pool.at[:, :, dsts].set(pool[:, :, srcs])

        self._write_pages = jax.jit(_write_pages, donate_argnums=(0,))
        self._write_token = jax.jit(_write_token, donate_argnums=(0,))
        self._copy_page = jax.jit(_copy_page, donate_argnums=(0,))
        self._copy_pages = jax.jit(_copy_pages, donate_argnums=(0,))

    def layer(self, li: int):
        """Per-layer head-major views for ops.paged_attention."""
        return self.k[li], self.v[li]

    @property
    def has_scales(self) -> bool:
        return self.k_scale is not None

    def pool_bytes(self) -> Dict[str, int]:
        """Device HBM held by the pools, split by kind (observability:
        statistics/metrics.py exports these as engine_kv_pool_bytes)."""
        scale = 0
        if self.k_scale is not None:
            scale = int(self.k_scale.nbytes) + int(self.v_scale.nbytes)
        return {"kv": int(self.k.nbytes) + int(self.v.nbytes), "scale": scale}

    @property
    def pool_dtype(self) -> str:
        return str(self.k.dtype)

    def max_pages_per_seq(self, max_seq_len: int) -> int:
        return self.pool.pages_needed(max_seq_len)

    def apply_pending_cow(self) -> int:
        """Perform the device copies for any host-side copy-on-write page
        swaps (PagePool.extend). MUST run after extending slots and before
        the writes of the extension land — with pipelined decode this sits
        on the dispatch path between chained chunks, and ordering holds by
        data dependency: the copy consumes the in-flight chunk's output
        pool handle, so it reads post-chunk page contents. Returns the
        number of pages copied.

        All pending pairs are applied in ONE donated program per pool side
        (pair count padded to a power-of-two bucket with null-page no-ops,
        so traces stay bounded)."""
        import jax.numpy as jnp

        pairs = self.pool.drain_pending_cow()
        if not pairs:
            return 0
        bucket = pow2_bucket(len(pairs))
        padded = pairs + [(0, 0)] * (bucket - len(pairs))
        srcs = jnp.asarray([s for s, _ in padded], jnp.int32)
        dsts = jnp.asarray([d for _, d in padded], jnp.int32)
        with self.dispatch_lock:
            self.k = self._copy_pages(self.k, srcs, dsts)
            self.v = self._copy_pages(self.v, srcs, dsts)
            if self.k_scale is not None:
                # scale rows share the page lifecycle: a CoW'd page carries
                # its dequant scales to the private copy in the same batch
                self.k_scale = self._copy_pages(self.k_scale, srcs, dsts)
                self.v_scale = self._copy_pages(self.v_scale, srcs, dsts)
        return len(pairs)

    # -- host-RAM tier (docs/kv_tiering.md) --------------------------------

    def enable_host_tier(self, num_pages: int) -> "HostKVTier":
        """Preallocate a host-RAM page tier matching this pool's geometry.
        Returns the tier (also kept as ``self.host_tier``)."""
        _l, hkv, _n, p, d = self.k.shape
        self.host_tier = HostKVTier(
            num_pages, p, self.n_layers, hkv, d,
            dtype=self.k.dtype, quantized=bool(self.kv_quant),
        )
        return self.host_tier

    def export_pages(self, pages: List[int]) -> Dict[str, np.ndarray]:
        """Synchronous device→host readback of ``pages`` (and, on int8
        pools, their scale rows) into PAGE-MAJOR numpy slabs — ``hk``/``hv``
        ``[n, L, Hkv, P, D]`` (+ ``hk_scale``/``hv_scale`` ``[n, L, Hkv,
        P]``): the host-tier demote layout, which is also the KV-transport
        shipment payload (llm/kv_transport.py, docs/disaggregation.md).

        The gather consumes the CURRENT pool handles under the dispatch
        lock, so it is ordered after every enqueued write by data
        dependency; the readback itself is synchronous (the host copy is
        complete before the caller releases or re-uses the device pages —
        a later re-allocation can never overwrite bytes the caller still
        needs). The victim list pads to a power of two with null-page
        entries (llm/shapes.py) so the gather compiles once per power of
        two, not once per count (tpuserve-analyze TPU601)."""
        import jax.numpy as jnp

        n = len(pages)
        idx = jnp.asarray(pad_pages(pages), jnp.int32)
        with self.dispatch_lock:
            k_slab = self.k[:, :, idx]          # [L, Hkv, n_pad, P, D]
            v_slab = self.v[:, :, idx]
            if self.kv_quant:
                ks_slab = self.k_scale[:, :, idx]   # [L, Hkv, n_pad, P]
                vs_slab = self.v_scale[:, :, idx]
        # device->host readback OUTSIDE the dispatch lock: the gather
        # outputs are immutable device arrays; only the (cheap) enqueue
        # needed serializing against donating dispatches. Rows past the
        # real count gathered the null page and are dropped here.
        out = {
            "hk": np.moveaxis(np.asarray(k_slab), 2, 0)[:n],
            "hv": np.moveaxis(np.asarray(v_slab), 2, 0)[:n],
        }
        if self.kv_quant:
            out["hk_scale"] = np.moveaxis(np.asarray(ks_slab), 2, 0)[:n]
            out["hv_scale"] = np.moveaxis(np.asarray(vs_slab), 2, 0)[:n]
        return out

    def demote_pages(self, pages: List[int]) -> List[int]:
        """Copy device pages (and, on int8 pools, their scale rows) into
        freshly allocated host-tier pages; returns the host-tier page ids.

        The gather/readback contract is :meth:`export_pages` (same slabs,
        same fence). Raises MemoryError when the tier is full; the caller
        (radix cache eviction) then drops the run for real."""
        tier = self.host_tier
        if tier is None:
            raise RuntimeError("demote_pages without an enabled host tier")
        host_ids = tier.allocate(len(pages))
        try:
            slabs = self.export_pages(pages)
            tier.hk[host_ids] = slabs["hk"]
            tier.hv[host_ids] = slabs["hv"]
            if self.kv_quant:
                tier.hk_scale[host_ids] = slabs["hk_scale"]
                tier.hv_scale[host_ids] = slabs["hv_scale"]
        except BaseException:
            tier.free(host_ids)
            raise
        self.demoted_pages += len(pages)
        return host_ids

    def promote_pages(self, host_ids: List[int], pages: List[int]) -> None:
        """Re-online host-tier pages into freshly allocated device pages
        (``pages``, from PagePool.allocate_cache_pages) via an ASYNC
        host→device DMA: the donated page scatter is only ENQUEUED here —
        dispatch returns in microseconds and the copy itself proceeds in
        the background, hidden behind whatever the engine enqueues next
        (the prefix hit's tail-chunk prefill). Ordering for every later
        consumer holds by data dependency on the rebound pool handles (the
        tier fence). Frees the host ids: the rows are STAGED into fresh
        arrays first, so the upload never aliases tier memory a later
        demotion may overwrite (the PR-4 zero-copy race class)."""
        tier = self.host_tier
        if tier is None:
            raise RuntimeError("promote_pages without an enabled host tier")
        if len(host_ids) != len(pages):
            raise ValueError(
                "promotion of {} host pages into {} device pages".format(
                    len(host_ids), len(pages)
                )
            )
        # stage into POWER-OF-TWO-bucketed private slabs (llm/shapes.py):
        # fancy indexing COPIES the real rows, rows beyond the count stay
        # zero and scatter into the dead null page 0 — so the upload and
        # the donated page scatter compile once per power of two, not once
        # per promotion size (tpuserve-analyze TPU601), and never alias
        # tier memory a later demotion may overwrite (the PR-4 race class)
        n = len(pages)
        padded = pad_pages(pages)
        k_rows = np.zeros((len(padded),) + tier.hk.shape[1:], tier.hk.dtype)
        v_rows = np.zeros_like(k_rows)
        k_rows[:n] = tier.hk[host_ids]        # [n_pad, L, Hkv, P, D]
        v_rows[:n] = tier.hv[host_ids]
        if self.kv_quant:
            ks_rows = np.zeros(
                (len(padded),) + tier.hk_scale.shape[1:], tier.hk_scale.dtype
            )
            vs_rows = np.zeros_like(ks_rows)
            ks_rows[:n] = tier.hk_scale[host_ids]
            vs_rows[:n] = tier.hv_scale[host_ids]
        tier.free(host_ids)
        self._upload_pages(
            k_rows, v_rows,
            ks_rows if self.kv_quant else None,
            vs_rows if self.kv_quant else None,
            padded, len(pages),
        )
        self.promoted_pages += len(pages)

    def _upload_pages(self, k_rows, v_rows, ks_rows, vs_rows,
                      padded: List[int], n: int) -> None:
        """Enqueue the async host→device page scatter shared by the tier
        promotion and the KV-transport import (docs/kv_tiering.md,
        docs/disaggregation.md): the donated write is only ENQUEUED under
        the dispatch lock — dispatch returns in microseconds, the copy
        proceeds in the background, and ordering for every later consumer
        holds by data dependency on the rebound pool handles (the tier
        fence). Rows must be PRIVATE staged copies padded to ``padded``'s
        power-of-two length (rows past ``n`` scatter into dead page 0)."""
        import jax.numpy as jnp

        page_ids = jnp.asarray(padded, jnp.int32)
        t_issue = time.perf_counter()
        with self.dispatch_lock:
            # the fence holds the UPLOADED chunk arrays (not the pool
            # handles — a later donating dispatch deletes those): their
            # readiness marks the host→device transfer complete, and the
            # scatter that consumes them is ordered for every later reader
            # by data dependency on the rebound pools
            k_dev = jnp.asarray(k_rows)
            v_dev = jnp.asarray(v_rows)
            self.k = self._write_pages(self.k, k_dev, page_ids)
            self.v = self._write_pages(self.v, v_dev, page_ids)
            fence = [k_dev, v_dev]
            if self.kv_quant:
                ks_dev = jnp.asarray(ks_rows)
                vs_dev = jnp.asarray(vs_rows)
                self.k_scale = self._write_pages(self.k_scale, ks_dev, page_ids)
                self.v_scale = self._write_pages(self.v_scale, vs_dev, page_ids)
                fence += [ks_dev, vs_dev]
            self._promotions.append({
                "pages": n,
                "t_issue": t_issue,
                "fence": fence,
            })
            if _ledger.armed():
                _ledger.acquire("kv.promotion", domain=self)

    def import_pages(self, hk, hv, pages: List[int],
                     hk_scale=None, hv_scale=None) -> None:
        """Re-online SHIPPED page slabs (llm/kv_transport.py KVShipment
        rows, ``[n, L, Hkv, P, D]`` page-major + scale rows on int8 pools)
        into freshly allocated device pages via the same async
        enqueue-before-publish fence as a host-tier promotion
        (docs/disaggregation.md). The rows are staged into PRIVATE
        power-of-two-padded buffers first — the upload never aliases the
        transport slab, which the sender's mailbox may recycle (the PR-4
        zero-copy race class) — and completion is observed at the engine's
        retire boundaries (``reap_promotions``)."""
        if len(pages) != int(hk.shape[0]):
            raise ValueError(
                "import of {} slab rows into {} device pages".format(
                    hk.shape[0], len(pages)
                )
            )
        self._require_scales(hk_scale, hv_scale)
        n = len(pages)
        padded = pad_pages(pages)
        k_rows = np.zeros((len(padded),) + tuple(hk.shape[1:]), self.k.dtype)
        v_rows = np.zeros_like(k_rows)
        k_rows[:n] = hk
        v_rows[:n] = hv
        ks_rows = vs_rows = None
        if self.kv_quant:
            ks_rows = np.zeros(
                (len(padded),) + tuple(hk_scale.shape[1:]), np.float32
            )
            vs_rows = np.zeros_like(ks_rows)
            ks_rows[:n] = hk_scale
            vs_rows[:n] = hv_scale
        self._upload_pages(k_rows, v_rows, ks_rows, vs_rows, padded, n)

    def reap_promotions(self, force: bool = False) -> int:
        """Account promotion DMAs that completed (engine retire-stage
        event): a record whose fence arrays are ready cost the serving loop
        nothing — the copy hid behind the in-flight prefill/decode work.
        ``force`` blocks on stragglers (drain/stop paths and the A/B bench's
        end-of-run accounting). Returns how many records were reaped."""
        import jax

        with self.dispatch_lock:
            if not self._promotions:
                return 0
            if force:
                records, self._promotions = self._promotions, []
            else:
                records = [
                    r for r in self._promotions
                    if all(
                        getattr(f, "is_ready", lambda: True)()
                        for f in r["fence"]
                    )
                ]
                for r in records:
                    self._promotions.remove(r)
            if records and _ledger.armed():
                _ledger.release("kv.promotion", n=len(records), domain=self)
        reaped = 0
        for rec in records:
            t_reap = time.perf_counter()
            try:
                for f in rec["fence"]:
                    jax.block_until_ready(f)
            except Exception:
                # a poisoned fence surfaces at its consumer; the record is
                # still retired so the list cannot grow without bound
                pass
            t_done = time.perf_counter()
            self.promo_wait_ms += (t_done - t_reap) * 1e3
            self.promo_total_ms += (t_done - rec["t_issue"]) * 1e3
            self.promo_reaped += 1
            reaped += 1
        return reaped

    def tier_stats(self) -> Optional[Dict[str, object]]:
        """Host-tier movement/occupancy counters for lifecycle_stats()
        (None when no tier is enabled). ``overlap_ratio`` = share of the
        promotion DMA wall time hidden behind other device work, observed
        at the reap points."""
        tier = self.host_tier
        if tier is None:
            return None
        total = self.promo_total_ms
        hidden = max(0.0, total - self.promo_wait_ms)
        return {
            "host_pages_used": tier.used_pages,
            "host_pages_capacity": tier.num_pages,
            "host_page_bytes": tier.page_bytes,
            "demoted_pages_total": self.demoted_pages,
            "promoted_pages_total": self.promoted_pages,
            "promotions_reaped": self.promo_reaped,
            "promo_wait_ms": round(self.promo_wait_ms, 3),
            "promo_total_ms": round(self.promo_total_ms, 3),
            "overlap_ratio": (
                round(hidden / total, 4) if total > 0 else None
            ),
        }

    def _require_scales(self, k_scales, v_scales) -> None:
        """Fail fast when the caller's scale operands disagree with the
        pool layout: an int8 pool written without scales would silently
        dequantize with stale rows; scales against a bf16 pool mean the
        caller quantized for the wrong backend."""
        if self.kv_quant and (k_scales is None or v_scales is None):
            raise ValueError(
                "int8 KV pools need k_scales/v_scales alongside every write"
            )
        if not self.kv_quant and (k_scales is not None or v_scales is not None):
            raise ValueError("scale operands given but the pools are not int8")

    def _scatter_pages(self, pages: List[int], k_stack, v_stack,
                       k_scales=None, v_scales=None) -> None:
        """Scatter token KV (stacked [L, S, Hkv, D], S <= len(pages)*P) into
        the given pages via the donated jitted page write. int8 pools also
        take the per-token scales ([L, S, Hkv]) for the same positions."""
        import jax.numpy as jnp

        self._require_scales(k_scales, v_scales)
        page_size = self.pool.page_size
        n_pages = len(pages)
        pad_to = n_pages * page_size

        def to_chunks(stack, ndim5):
            # [L, S, Hkv(, D)] -> [NP, L, Hkv, P(, D)]
            hm = jnp.moveaxis(jnp.asarray(stack), 2, 1)   # [L, Hkv, S(, D)]
            pad = ((0, 0), (0, 0), (0, pad_to - hm.shape[2]))
            if ndim5:
                pad = pad + ((0, 0),)
            hm = jnp.pad(hm, pad)
            shape = hm.shape[:2] + (n_pages, page_size) + hm.shape[3:]
            perm = (2, 0, 1, 3, 4) if ndim5 else (2, 0, 1, 3)
            return hm.reshape(shape).transpose(perm)

        k_chunks = to_chunks(k_stack, True)
        v_chunks = to_chunks(v_stack, True)
        # page-multiple key space: one trace per page COUNT (the commit
        # path already rounds through pool.pages_needed, and llm/warmup.py
        # compiles counts 1..N before the serve fence)
        page_ids = jnp.asarray(pages, jnp.int32)  # tpuserve: ignore[TPU601] page-count-keyed, warmup-covered
        with self.dispatch_lock:
            self.k = self._write_pages(self.k, k_chunks, page_ids)
            self.v = self._write_pages(self.v, v_chunks, page_ids)
            if self.kv_quant:
                self.k_scale = self._write_pages(
                    self.k_scale, to_chunks(k_scales, False), page_ids
                )
                self.v_scale = self._write_pages(
                    self.v_scale, to_chunks(v_scales, False), page_ids
                )

    def write_prompt(self, slot: int, k_stack, v_stack, length: int,
                     k_scales=None, v_scales=None) -> None:
        """Scatter a prefilled prompt's KV (stacked [L, S, Hkv, D]) into this
        slot's pages via donated jitted writes (plus [L, S, Hkv] scales on
        int8 pools)."""
        self.pool.free(slot)
        # the pages ride the slot's table from here; a failed admission
        # frees the slot in the engine (cross-function pairing the
        # ownership ledger audits at drain)
        self.pool.allocate(slot, length)  # tpuserve: ignore[TPU701] pages ride the slot table
        self._scatter_pages(
            self.pool.slot_pages(slot), k_stack, v_stack, k_scales, v_scales
        )

    def write_prompt_shared(
        self, slot: int, shared_pages: List[int], prefix_len: int,
        k_tail, v_tail, length: int,
        k_scales_tail=None, v_scales_tail=None,
    ) -> None:
        """Prefix-cache hit admission: map ``shared_pages`` (holding the
        first ``prefix_len`` tokens, page-aligned) into the slot's page table
        BY REFERENCE — zero KV copies for the shared run (on int8 pools the
        shared pages' scale rows come along for free: same page ids) — then
        scatter only the tail's KV ([L, length - prefix_len, Hkv, D], plus
        tail scales on int8 pools) into freshly allocated pages."""
        if prefix_len % self.pool.page_size:
            raise ValueError(
                "shared prefix length {} is not page-aligned".format(prefix_len)
            )
        self.pool.free(slot)
        self.pool.map_shared(slot, shared_pages, prefix_len)  # tpuserve: ignore[TPU701] pages ride the slot table
        tail_pages = self.pool.allocate(slot, length)  # tpuserve: ignore[TPU701] pages ride the slot table
        if tail_pages:
            self._scatter_pages(
                tail_pages, k_tail, v_tail, k_scales_tail, v_scales_tail
            )

    def append_token(self, slot: int, k_token, v_token,
                     k_scale=None, v_scale=None) -> None:
        """Append one token's KV (stacked [L, Hkv, D]; [L, Hkv] scales on
        int8 pools) to the slot."""
        import jax.numpy as jnp

        self._require_scales(k_scale, v_scale)
        length = self.pool.slot_length(slot)
        self.pool.extend(slot, 1)  # tpuserve: ignore[TPU701] pages ride the slot table
        self.apply_pending_cow()
        ((page, offset),) = self.pool.token_coords(slot, length, 1)
        with self.dispatch_lock:
            self.k = self._write_token(self.k, jnp.asarray(k_token), page, offset)
            self.v = self._write_token(self.v, jnp.asarray(v_token), page, offset)
            if self.kv_quant:
                self.k_scale = self._write_token(
                    self.k_scale, jnp.asarray(k_scale), page, offset
                )
                self.v_scale = self._write_token(
                    self.v_scale, jnp.asarray(v_scale), page, offset
                )
