"""Runtime KV/refcount sanitizer: prove page-accounting invariants, don't
assume them.

The paged KV tier shares physical pages between live slots, the radix prefix
cache, in-flight admission pins, and pending copy-on-write swaps — four
holders, one refcount. The chaos suite (watchdog recovery, poison isolation,
shed paths) exercises exactly the code that reclaims those references under
failure; "the test passed" only means the TOKENS came out right. With
``TPUSERVE_SANITIZE=1`` (or programmatic arming) the engine additionally
asserts, after every decode step and at drain:

1. **Refcount conservation** — for every page, ``refcount == slot-table
   references + radix-cache node references + admission pins``. A page the
   books can't explain is a leak (never reclaimable) or a time bomb (freed
   while someone still reads it).
2. **Free-list integrity** — no duplicates, no referenced page on the free
   list, every zero-ref page on it, and the reserved null page (0) neither
   free nor referenced.
3. **Slot-table shape** — each slot's page count matches its token length
   (``pages_needed``), so device page tables never index garbage.
4. **Pending-CoW sanity** — every recorded (src, dst) swap still has a live
   src (the sharers that forced the copy) and a dst owned by some slot.
5. **At drain** (no active requests, no admissions in flight) — no slot
   holds pages, no pins remain, and every surviving reference belongs to
   the prefix cache. Anything else is a leaked page, reported BY ID.
6. **Scale-row lifecycle** (int8 paged KV, docs/paged_kv_quant.md) — the
   per-(token, head) scale pools must address exactly the allocator's
   pages (one scale row per page id per side), so every page operation
   (write, CoW, share, free) covers its scale rows by construction; drain
   leak reports name the stranded scale rows beside the pages.
7. **Two-tier exclusivity** (host-RAM tier, docs/kv_tiering.md) — a page
   lives in exactly one tier: no cache node holds both a device and a
   host payload; every allocated host-tier id is referenced by exactly
   one node and every node-referenced id is allocated; the host free
   list has no duplicates, overlaps the used set nowhere, and together
   with it covers the tier exactly; a quantized pool's host tier must
   carry scale slabs of the matching geometry (demoted scale rows track
   their pages).

Failures raise :class:`KVSanitizerError` (an AssertionError subclass: armed
test suites fail closed) with a diagnostic naming the offending pages.

The checks are host-side integer audits over a locked snapshot —
O(num_pages + cached nodes), no device work — cheap enough for every test
step but off by default in production (arm via env to debug a live leak).
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional

__all__ = ["KVSanitizerError", "KVSanitizer", "enabled"]


def enabled() -> bool:
    """Armed via ``TPUSERVE_SANITIZE`` (1/true/yes; 0/empty disarms)."""
    return os.environ.get("TPUSERVE_SANITIZE", "").lower() in ("1", "true", "yes")


class KVSanitizerError(AssertionError):
    """A KV page-accounting invariant failed. Carries the offending page ids
    (``pages``) and the check site (``where``) for programmatic triage."""

    def __init__(self, message: str, *, where: str, pages: Optional[List[int]] = None):
        super().__init__(message)
        self.where = where
        self.pages = list(pages or [])


class KVSanitizer:
    """Audits one PagedKVCache's PagePool (and the radix prefix cache that
    shares it) against the conservation invariants above.

    ``check()`` is thread-safe: the snapshot is taken under the cache's tree
    lock and the pool's bookkeeping lock (same order as every mutating cache
    path), so a concurrent admission can interleave only BETWEEN atomic pool
    operations — each of which preserves the invariants — never inside one.
    """

    def __init__(self, pool, prefix_cache=None, paged_cache=None):
        self.pool = pool
        self.prefix = prefix_cache
        # the PagedKVCache (optional): int8 pools carry per-page scale rows
        # whose lifecycle is the page id itself — the audit verifies the
        # scale pools stay shape-consistent with the page allocator (a
        # drifted page axis would dequantize every page with the wrong
        # rows), and leak reports name the scale rows leaked alongside.
        self.paged_cache = paged_cache
        self.checks = 0     # observability: how many audits ran
        self.failures = 0

    # -- snapshot ----------------------------------------------------------

    def _snapshot(self):
        if self.prefix is not None and getattr(self.prefix, "_pool", None) is self.pool:
            cache_refs, snap = self.prefix.page_refs(self.pool)
        else:
            cache_refs = {}
            snap = self.pool.snapshot()
        return cache_refs, snap

    # -- checks ------------------------------------------------------------

    def check(self, where: str = "step", drained: bool = False,
              inflight: int = 0) -> None:
        """Raise KVSanitizerError on the first violated invariant.

        ``inflight``: decode chunks dispatched but not yet retired (the
        pipelined engine, docs/pipelined_decode.md). Conservation and
        free-list invariants hold at EVERY instant — in-flight chunks only
        defer page frees, they never hide references — but the drain-time
        "no slot holds pages" rule is meaningful only once the pipeline is
        empty, so a drained audit with chunks still in flight downgrades to
        a regular audit rather than misreporting deferred frees as leaks."""
        self.checks += 1
        drained = drained and int(inflight) == 0
        cache_refs, snap = self._snapshot()
        refs: List[int] = snap["refs"]
        free: List[int] = snap["free"]
        slot_pages: List[List[int]] = snap["slot_pages"]
        slot_len: List[int] = snap["slot_len"]
        pins: Dict[int, int] = snap["pins"]
        pending_cow = snap["pending_cow"]

        def fail(message: str, pages: Optional[List[int]] = None) -> None:
            self.failures += 1
            raise KVSanitizerError(
                "KV sanitizer [{}]: {}".format(where, message),
                where=where, pages=pages,
            )

        # (0) scale-pool/page-pool consistency (int8 pools): a page id must
        # address a scale row in BOTH scale pools — shape drift would make
        # every dequant read the wrong row, silently
        pc = self.paged_cache
        quantized = pc is not None and getattr(pc, "has_scales", False)
        if quantized:
            for name in ("k_scale", "v_scale"):
                sp = getattr(pc, name)
                if sp.shape[2] != self.pool.num_pages or (
                    sp.shape[3] != self.pool.page_size
                ):
                    fail(
                        "{} pool shape {} does not address the page pool "
                        "({} pages x {} tokens): pages and scale rows no "
                        "longer share a lifecycle".format(
                            name, tuple(sp.shape), self.pool.num_pages,
                            self.pool.page_size,
                        )
                    )

        # (7) two-tier exclusivity (host-RAM tier, docs/kv_tiering.md)
        tier = getattr(pc, "host_tier", None) if pc is not None else None
        if (
            tier is not None
            and self.prefix is not None
            and getattr(self.prefix, "_host", None) is tier
        ):
            host_refs, dual = self.prefix.tier_refs()
            hsnap = tier.snapshot()
            if dual:
                fail(
                    "{} cache node(s) hold BOTH a device and a host "
                    "payload: a page must live in exactly one tier".format(
                        dual
                    )
                )
            hfree, hused = hsnap["free"], hsnap["used"]
            if len(set(hfree)) != len(hfree):
                dupes = sorted({h for h in hfree if hfree.count(h) > 1})
                fail(
                    "host-tier free list contains duplicates: {}".format(
                        dupes
                    ),
                    pages=dupes,
                )
            overlap = sorted(set(hfree) & hused)
            if overlap:
                fail(
                    "host pages {} are both free and allocated".format(
                        overlap
                    ),
                    pages=overlap,
                )
            if len(hfree) + len(hused) != hsnap["num_pages"]:
                fail(
                    "host tier accounts for {} + {} pages of {}".format(
                        len(hfree), len(hused), hsnap["num_pages"]
                    )
                )
            orphans = sorted(h for h in hused if host_refs.get(h, 0) != 1)
            orphans += sorted(h for h in host_refs if h not in hused)
            if orphans:
                fail(
                    "host-tier ownership violated (each allocated id must "
                    "be referenced by exactly one cache node): {}".format(
                        sorted(set(orphans))
                    ),
                    pages=sorted(set(orphans)),
                )
            if quantized != tier.quantized:
                fail(
                    "host tier {} scale slabs but the device pools are "
                    "{}quantized: demoted scale rows no longer track "
                    "their pages".format(
                        "lacks" if not tier.quantized else "carries",
                        "" if quantized else "not ",
                    )
                )
            if tier.page_size != self.pool.page_size:
                fail(
                    "host tier page size {} != device page size {}".format(
                        tier.page_size, self.pool.page_size
                    )
                )

        # slot-table occurrences per page (a page CAN legally appear in
        # several slots — shared prefix mapped into multiple page tables)
        slot_occ: Dict[int, int] = {}
        for slot, pages in enumerate(slot_pages):
            for page in pages:
                slot_occ[page] = slot_occ.get(page, 0) + 1
        # (3) slot-table shape
        for slot, pages in enumerate(slot_pages):
            need = self.pool.pages_needed(slot_len[slot])
            if len(pages) != need:
                fail(
                    "slot {} holds {} pages for {} tokens (expected {})".format(
                        slot, len(pages), slot_len[slot], need
                    ),
                    pages=pages,
                )

        # (2) free-list integrity + null page
        if len(set(free)) != len(free):
            dupes = sorted({p for p in free if free.count(p) > 1})
            fail("free list contains duplicates: {}".format(dupes), pages=dupes)
        bad = sorted(p for p in free if refs[p] != 0)
        if bad:
            fail(
                "pages {} are on the free list with refcount > 0".format(bad),
                pages=bad,
            )
        if 0 in free or refs[0] != 0 or slot_occ.get(0) or cache_refs.get(0):
            fail("reserved null page 0 entered circulation", pages=[0])
        free_set = set(free)

        # (1) refcount conservation, page by page
        leaked: List[str] = []
        leaked_ids: List[int] = []
        for page in range(1, len(refs)):
            expected = (
                slot_occ.get(page, 0)
                + cache_refs.get(page, 0)
                + pins.get(page, 0)
            )
            if refs[page] != expected:
                leaked_ids.append(page)
                leaked.append(
                    "page {}: refcount {} != {} accounted "
                    "(slots {} + cache {} + pins {})".format(
                        page, refs[page], expected,
                        slot_occ.get(page, 0), cache_refs.get(page, 0),
                        pins.get(page, 0),
                    )
                )
            if refs[page] == 0 and page not in free_set:
                leaked_ids.append(page)
                leaked.append(
                    "page {}: refcount 0 but missing from the free list".format(
                        page
                    )
                )
        if leaked:
            fail(
                "refcount conservation violated:\n  " + "\n  ".join(leaked),
                pages=sorted(set(leaked_ids)),
            )

        # (4) pending-CoW sanity
        for src, dst in pending_cow:
            if refs[src] <= 0:
                fail(
                    "pending CoW src page {} has no live references".format(src),
                    pages=[src],
                )
            if not slot_occ.get(dst):
                fail(
                    "pending CoW dst page {} is in no slot table".format(dst),
                    pages=[dst],
                )

        # (5) drain: only the prefix cache may keep references
        if drained:
            held = {
                slot: pages for slot, pages in enumerate(slot_pages) if pages
            }
            if held:
                detail = ", ".join(
                    "slot {} -> pages {}".format(slot, pages)
                    for slot, pages in sorted(held.items())
                )
                leaked_pages = sorted(
                    p for pages in held.values() for p in pages
                )
                scale_note = (
                    " (each leaked page also strands its k/v scale rows "
                    "{})".format(leaked_pages)
                    if quantized
                    else ""
                )
                fail(
                    "leaked pages at drain (no live requests): {}{}".format(
                        detail, scale_note
                    ),
                    pages=leaked_pages,
                )
            if pins:
                fail(
                    "admission pins outlived drain: {}".format(dict(pins)),
                    pages=sorted(pins),
                )

    def stats(self) -> Dict[str, int]:
        return {"checks": self.checks, "failures": self.failures}
