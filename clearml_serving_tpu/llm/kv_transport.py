"""KVTransport: cross-replica shipping of prefilled KV pages
(docs/disaggregation.md).

Disaggregated prefill/decode splits the two jobs a serving replica does
over role-specialized replicas: PREFILL replicas run the compute-bound
admission (ragged admission rows, int4 weights), DECODE replicas run the
bandwidth-bound token loop (batch-fill maximized, multi-step ragged
rows), and the prefilled KV moves between them through this module. The
payload is exactly what the host-RAM tier already serializes on its
demote path (docs/kv_tiering.md): the prompt's block-aligned prefix as
PAGE-MAJOR int8 (or bf16) page slabs plus, on quantized pools, the f32
scale rows that share each page's lifecycle — 2x cheaper than bf16 to
hold and transfer. A :class:`KVShipment` is that payload plus enough
metadata for the receiver to validate geometry before touching its pool.

The interface is STREAM-SHAPED on purpose: a sender addresses a
destination replica by name and pushes one bounded message; a receiver
pops by content key. The in-process :class:`SharedSlabTransport` backend
(this PR) implements it as one bounded receive slab (a page-capacity
mailbox) per destination replica; a process-group backend
(parallel/multihost.py collectives) or a remote backend (gRPC stream /
RDMA write into a registered receive slab) plugs in behind the same
`send`/`recv` pair without touching the engine or the router.

Delivery contract (the fallback matrix lives in docs/disaggregation.md):

- ``send`` is BEST-EFFORT: a full receive slab drops the OLDEST
  shipment first (the sender never blocks a serving loop on transport
  backpressure), and a send that still does not fit is dropped and
  counted. A dropped shipment is never an error — the decode replica
  falls back to recomputing the prefix (the same drop-to-recompute
  contract as a failed host-tier promotion).
- ``recv`` is CONSUME-ONCE by content key: the decode replica's receive
  path pops the shipment, imports the pages under its own dispatch-lock
  fence (kv_cache.PagedKVCache.import_pages), and attaches them to its
  radix prefix cache (prefix_cache.RadixPrefixCache.store_shipped). A
  shipment nobody consumes ages out of the bounded mailbox.

Content keys (:func:`shipment_key`) digest the storable block-aligned
prefix — the same ``longest_prefix_len`` math the radix trie and the
router's affinity key use — so the sender and receiver derive the same
key from the same prompt independently, with no id handshake.

This module is jax-free on purpose: payloads are numpy slabs, and the
router/CLI processes must be able to import it without an accelerator
runtime.
"""

from __future__ import annotations

import hashlib
import struct
import threading
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Dict, Optional, Sequence

import numpy as np

from . import lifecycle_ledger as _ledger


def shipment_key(prompt_ids: Sequence[int], block: int, lora: int = 0) -> bytes:
    """Deterministic content key for a prompt's storable block-aligned
    prefix: sender (at commit) and receiver (before admission) derive the
    same key from the same prompt with no coordination. Mirrors
    ``RadixPrefixCache.longest_prefix_len`` — the final token never ships
    (it always computes live to seed decoding)."""
    ids = list(prompt_ids)
    depth = ((len(ids) - 1) // max(1, int(block))) * max(1, int(block))
    digest = hashlib.blake2b(digest_size=16)
    digest.update(struct.pack("<iI", int(lora), depth))
    for token in ids[:depth]:
        digest.update(struct.pack("<q", int(token)))
    return digest.digest()


@dataclass
class KVShipment:
    """One prompt's prefilled prefix KV, page-major (docs/disaggregation.md).

    ``hk``/``hv`` are ``[N, L, Hkv, P, D]`` slabs (one row per shipped
    page, the host-tier demote layout); quantized pools add the
    ``[N, L, Hkv, P]`` f32 scale rows. ``prefix_len`` is the block-aligned
    token count the pages cover (``N * page_size``).

    DRAFT-AHEAD shipping (docs/spec_decode_trees.md) splits one prefix
    across several frames sharing the same content key: a non-``final``
    frame carries pages ``[page_offset, page_offset + N)`` of the prefix
    (``prefix_len`` = tokens covered SO FAR, exactly page-aligned), and
    the ``final`` frame seals the assembly with the tail pages plus the
    authoritative full ``prefix_len``. The default ``page_offset=0,
    final=True`` is the legacy single-frame shipment — the wire codec
    omits the keys entirely for it, so PR 19 frames are byte-identical.
    The transport reassembles IN ORDER and only a sealed assembly ever
    becomes consumable; any gap/duplicate/out-of-order frame drops the
    whole assembly (drop-to-recompute)."""

    key: bytes
    src: str                       # sender replica name
    prefix_len: int                # storable prefix tokens covered
    page_size: int
    lora: int
    hk: np.ndarray                 # [N, L, Hkv, P, D]
    hv: np.ndarray
    hk_scale: Optional[np.ndarray] = None   # [N, L, Hkv, P] on int8 pools
    hv_scale: Optional[np.ndarray] = None
    page_offset: int = 0           # first page's index within the prefix
    final: bool = True             # False = unsealed draft-ahead frame
    seq: int = field(default=0, compare=False)

    @property
    def pages(self) -> int:
        return int(self.hk.shape[0])

    @property
    def quantized(self) -> bool:
        return self.hk_scale is not None

    @property
    def nbytes(self) -> int:
        per = int(self.hk.nbytes) + int(self.hv.nbytes)
        if self.hk_scale is not None:
            per += int(self.hk_scale.nbytes) + int(self.hv_scale.nbytes)
        return per

    # -- wire codec (llm/kv_wire.py; docs/disaggregation.md) ---------------

    def to_wire(self) -> bytes:
        """One self-validating frame for the socket backend (header:
        geometry/dtype/lora/content key; body: the raw slabs)."""
        from .kv_wire import shipment_to_wire

        return shipment_to_wire(self)

    @staticmethod
    def from_wire(frame) -> "KVShipment":
        """Decode + validate a frame into a shipment whose slabs are
        zero-copy views; raises ``kv_wire.WireFormatError`` (before any
        attach) on truncation or geometry/dtype/key lies."""
        from .kv_wire import shipment_from_wire

        return shipment_from_wire(frame)


class TransportEndpoint:
    """One replica's handle on a transport: ``send`` addresses a peer by
    name, ``recv`` pops from this replica's own receive slab. The engine
    holds exactly one of these (``LLMEngineCore.attach_kv_transport``) —
    it never sees the broker or the peer set."""

    def __init__(self, transport: "SharedSlabTransport", name: str):
        self._transport = transport
        self.name = name

    def send(self, dst: str, shipment: KVShipment) -> bool:
        return self._transport.send(dst, shipment)

    def recv(self, key: bytes) -> Optional[KVShipment]:
        return self._transport.recv(self.name, key)

    def stats(self) -> Dict[str, object]:
        return self._transport.stats()


class SharedSlabTransport:
    """In-process KVTransport backend: one bounded receive slab per
    destination replica (docs/disaggregation.md).

    A "receive slab" is a page-capacity mailbox: shipments queue in
    arrival order keyed by content, capacity is counted in PAGES (the
    unit pool pressure is measured in everywhere else), and overflow
    drops the OLDEST shipment first — the decode replica it was addressed
    to simply recomputes, exactly like a failed host-tier promotion.
    Remote backends replace this class, not its callers: the engine's
    ship/receive paths and the router's role logic only consume the
    ``TransportEndpoint`` surface."""

    # lock-discipline registry (tpuserve-analyze TPU301): mailbox state is
    # mutated only under self._lock — senders run on their replica's loop
    # thread, receivers pop from the group's receive worker
    __guarded_by__ = {
        "_lock": ("_slabs", "_slab_pages", "_ship_seq", "_assemblies"),
    }

    # ownership-discipline registry (tpuserve-analyze TPU7xx): a sent
    # shipment sits in the destination mailbox until the consume-once
    # recv pops it (or capacity eviction drops the oldest). The pairing
    # crosses replicas, so the static pass leaves it to the runtime
    # ownership ledger; TPU704 pins the consume-once half.
    __acquires__ = {
        "send": {"resource": "transport.shipment",
                 "releases": ("recv", "_drop_oldest"), "static": False,
                 "receivers": ("transport", "endpoint", "_transport",
                               "_kv_transport", "ep")},
    }

    def __init__(self, capacity_pages: int = 1024,
                 max_shipments: int = 64):
        if capacity_pages <= 0:
            raise ValueError(
                "kv transport needs a positive receive-slab capacity "
                "(got {} pages)".format(capacity_pages)
            )
        self.capacity_pages = int(capacity_pages)
        self.max_shipments = int(max_shipments)
        self._lock = threading.Lock()
        # dst name -> OrderedDict[key, KVShipment] (arrival order)
        self._slabs: Dict[str, "OrderedDict[bytes, KVShipment]"] = {}
        self._slab_pages: Dict[str, int] = {}
        # dst name -> {key: [unsealed draft-ahead frames, in page order]}
        # — invisible to recv() until the final frame seals the assembly
        self._assemblies: Dict[str, Dict[bytes, list]] = {}
        self._ship_seq = 0
        # observability (GIL-atomic bumps; surfaced through stats())
        self.sent = 0
        self.sent_pages = 0
        self.received = 0
        self.received_pages = 0
        self.dropped = 0           # evicted/oversized shipments
        self.dropped_pages = 0
        self.partial_frames = 0    # draft-ahead frames accepted unsealed
        self.assembled = 0         # assemblies sealed into the mailbox
        self.assembly_drops = 0    # gap/dup/out-of-order/oversize drops

    def register(self, name: str) -> TransportEndpoint:
        with self._lock:
            self._slabs.setdefault(name, OrderedDict())
            self._slab_pages.setdefault(name, 0)
        return TransportEndpoint(self, name)

    def _drop_oldest(self, dst: str) -> None:  # tpuserve: ignore[TPU301] lock held by caller
        key, old = self._slabs[dst].popitem(last=False)
        self._slab_pages[dst] -= old.pages
        self.dropped += 1
        self.dropped_pages += old.pages
        if _ledger.armed():
            _ledger.release("transport.shipment", key=key, domain=self)

    def _assemble(self, dst: str, shipment: KVShipment):
        """In-order reassembly of one draft-ahead frame. Returns
        ``(accepted, complete)``: ``complete`` is the fused sealed
        shipment once the final frame lands (deliver it through the
        normal mailbox path); until then accepted frames queue unsealed
        — invisible to ``recv``. ANY ordering violation — a duplicate, a
        gap, a seal with no assembly, geometry drift between frames —
        drops the ENTIRE assembly: a prefix that cannot be proven
        contiguous must never attach (drop-to-recompute)."""
        key = shipment.key
        with self._lock:
            asm_map = self._assemblies.setdefault(dst, {})
            if shipment.page_offset == 0:
                # first frame (never final here): replaces a stale start
                if shipment.pages > self.capacity_pages:
                    asm_map.pop(key, None)
                    self.assembly_drops += 1
                    return False, None
                asm_map[key] = [shipment]
                self.partial_frames += 1
                return True, None
            parts = asm_map.get(key)
            have = sum(p.pages for p in parts) if parts else 0
            head = parts[0] if parts else None
            if (
                parts is None
                or shipment.page_offset != have
                or shipment.page_size != head.page_size
                or shipment.quantized != head.quantized
                or shipment.lora != head.lora
            ):
                asm_map.pop(key, None)
                self.assembly_drops += 1
                return False, None
            if have + shipment.pages > self.capacity_pages:
                asm_map.pop(key, None)
                self.assembly_drops += 1
                return False, None
            parts.append(shipment)
            if not shipment.final:
                self.partial_frames += 1
                return True, None
            del asm_map[key]
        # sealed: fuse OUTSIDE the lock (the concatenation is the heavy
        # part; the assembly is already detached from shared state)
        total = have + shipment.pages
        if not (0 < shipment.prefix_len <= total * shipment.page_size):
            self.assembly_drops += 1
            return False, None
        complete = KVShipment(
            key=key, src=shipment.src, prefix_len=shipment.prefix_len,
            page_size=shipment.page_size, lora=shipment.lora,
            hk=np.concatenate([p.hk for p in parts], axis=0),
            hv=np.concatenate([p.hv for p in parts], axis=0),
            hk_scale=(
                np.concatenate([p.hk_scale for p in parts], axis=0)
                if shipment.quantized else None
            ),
            hv_scale=(
                np.concatenate([p.hv_scale for p in parts], axis=0)
                if shipment.quantized else None
            ),
        )
        self.assembled += 1
        return True, complete

    def send(self, dst: str, shipment: KVShipment) -> bool:
        """Deliver ``shipment`` into ``dst``'s receive slab. Returns False
        (counted drop) when the shipment exceeds the slab outright;
        otherwise the oldest queued shipments age out until it fits. A
        re-ship of the same key replaces the stale payload. Draft-ahead
        frames (``final=False`` or ``page_offset > 0``) reassemble in
        order and only the SEALED whole enters the mailbox."""
        if not shipment.final or shipment.page_offset:
            accepted, complete = self._assemble(dst, shipment)
            if complete is None:
                return accepted
            shipment = complete
        if shipment.pages > self.capacity_pages:
            self.dropped += 1
            self.dropped_pages += shipment.pages
            return False
        with self._lock:
            # a full legacy re-ship supersedes any unsealed assembly
            asm_map = self._assemblies.get(dst)
            if asm_map is not None:
                asm_map.pop(shipment.key, None)
            slab = self._slabs.get(dst)
            if slab is None:
                slab = self._slabs[dst] = OrderedDict()
                self._slab_pages[dst] = 0
            stale = slab.pop(shipment.key, None)
            if stale is not None:
                self._slab_pages[dst] -= stale.pages
            while (
                slab
                and (
                    self._slab_pages[dst] + shipment.pages
                    > self.capacity_pages
                    or len(slab) >= self.max_shipments
                )
            ):
                self._drop_oldest(dst)
            self._ship_seq += 1
            shipment.seq = self._ship_seq
            slab[shipment.key] = shipment
            self._slab_pages[dst] += shipment.pages
            if _ledger.armed():
                if stale is not None:
                    _ledger.release("transport.shipment", key=shipment.key,
                                    domain=self)
                _ledger.acquire("transport.shipment", key=shipment.key,
                                domain=self)
        self.sent += 1
        self.sent_pages += shipment.pages
        return True

    def recv(self, dst: str, key: bytes) -> Optional[KVShipment]:
        """Consume-once pop of ``dst``'s shipment for ``key`` (None when
        nothing matching is queued — dropped, never sent, or already
        consumed)."""
        with self._lock:
            slab = self._slabs.get(dst)
            shipment = slab.pop(key, None) if slab is not None else None
            if shipment is not None:
                self._slab_pages[dst] -= shipment.pages
                if _ledger.armed():
                    _ledger.release("transport.shipment", key=key,
                                    domain=self)
        if shipment is not None:
            self.received += 1
            self.received_pages += shipment.pages
        return shipment

    def stats(self) -> Dict[str, object]:
        with self._lock:
            queued = {
                dst: {"shipments": len(slab),
                      "pages": self._slab_pages.get(dst, 0)}
                for dst, slab in self._slabs.items()
            }
        return {
            "backend": "shared_slab",
            "capacity_pages": self.capacity_pages,
            "sent": self.sent,
            "sent_pages": self.sent_pages,
            "received": self.received,
            "received_pages": self.received_pages,
            "dropped": self.dropped,
            "dropped_pages": self.dropped_pages,
            "partial_frames": self.partial_frames,
            "assembled": self.assembled,
            "assembly_drops": self.assembly_drops,
            "queued": queued,
        }
