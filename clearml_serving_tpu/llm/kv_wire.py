"""Wire-format KVTransport backend: KV shipments over real sockets
(docs/disaggregation.md "process backends").

PR 14's :class:`~.kv_transport.SharedSlabTransport` moves
:class:`~.kv_transport.KVShipment` payloads between replicas by
reference — correct only while every replica lives in one process. This
module is the first REAL wire under the same ``TransportEndpoint``
surface: :class:`SocketSlabTransport` frames a shipment
(:func:`shipment_to_wire` / :func:`shipment_from_wire`) and pushes it
over a UNIX or TCP socket into the destination replica's bounded
receive slab, so disaggregated prefill/decode crosses process (and
later host) boundaries without the engine or the router noticing.

Frame layout (the table in docs/disaggregation.md mirrors this)::

    [ u32 frame_len ][ b"KVW1" ][ u8 version ][ u8 flags ][ u16 hdr_len ]
    [ hdr_len bytes JSON header ][ body: hk | hv | hk_scale | hv_scale ]

The JSON header carries everything needed to validate BEFORE touching
the pool: content key, sender, geometry (prefix_len / page_size / lora),
the optional draft-ahead framing keys (``page_offset`` / ``final`` —
omitted for whole-prefix shipments, so legacy frames are byte-identical;
docs/spec_decode_trees.md), and one ``{dtype, shape}`` descriptor per
body section. The body is the
raw page slabs exactly as ``PagedKVCache.export_pages`` laid them out —
page-major ``[N, L, Hkv, P, D]`` int8/bf16 planes plus, on quantized
pools, the f32 scale rows. Decoding is ZERO-COPY: the receiver's arrays
are ``np.frombuffer`` views into the single received buffer.

Delivery contract (identical to the in-process backend, by construction:
the receive side IS a ``SharedSlabTransport`` mailbox):

- ``send`` is best-effort with a DEADLINE: connect/write/ack failures,
  timeouts, injected ``transport.wire.send`` faults, and receiver-side
  decode failures (nack) all drop the shipment — counted, never raised.
  The decode replica recomputes, exactly like an in-process drop.
- the receive slab keeps mailbox semantics: overflow drops the OLDEST
  shipment, a re-ship of the same key replaces the stale payload, and
  ``recv`` is consume-once by content key.
- a truncated/garbled frame (``transport.wire.recv`` fault, partial
  write, geometry/dtype/key lies) is rejected by the frame validator
  before any attach — the named :class:`WireFormatError` drops it
  leak-free and the sender sees a nack.

Like kv_transport.py, this module is jax-free on purpose: the router
and CLI processes must import it without an accelerator runtime, and
bf16 support degrades gracefully when ``ml_dtypes`` is absent.
"""

from __future__ import annotations

import json
import logging
import os
import socket
import struct
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from . import faults
from . import lifecycle_ledger as _ledger
from .kv_transport import KVShipment, SharedSlabTransport

logger = logging.getLogger(__name__)

MAGIC = b"KVW1"
WIRE_VERSION = 1
_FLAG_QUANTIZED = 0x01
# frames above this are rejected before allocation (a lying length
# prefix must not make the receiver allocate gigabytes)
MAX_FRAME_BYTES = 1 << 31

# wire dtype names -> numpy dtypes. bfloat16 comes from ml_dtypes (a
# jax-independent package); without it bf16 frames are rejected with the
# named error instead of silently misinterpreting the bytes.
_WIRE_DTYPES: Dict[str, np.dtype] = {
    "int8": np.dtype(np.int8),
    "uint8": np.dtype(np.uint8),
    "float16": np.dtype(np.float16),
    "float32": np.dtype(np.float32),
}
try:  # pragma: no cover - present in the jax toolchain image
    import ml_dtypes as _ml_dtypes

    _WIRE_DTYPES["bfloat16"] = np.dtype(_ml_dtypes.bfloat16)
except ImportError:  # pragma: no cover
    _ml_dtypes = None


class WireFormatError(ValueError):
    """A frame failed validation (truncated, bad magic/version, geometry/
    dtype/key inconsistency). Raised BEFORE any pool or cache attach, so
    dropping the frame is the complete cleanup — the receive path maps it
    to drop-to-recompute."""


def _dtype_name(dtype: np.dtype) -> str:
    name = np.dtype(dtype).name
    if name not in _WIRE_DTYPES:
        raise WireFormatError(
            "kv wire cannot carry dtype {!r} (supported: {})".format(
                name, ", ".join(sorted(_WIRE_DTYPES))
            )
        )
    return name


def shipment_to_wire(shipment: KVShipment) -> bytes:
    """Encode a shipment into one self-validating frame (sans the socket
    layer's u32 length prefix)."""
    sections: List[Tuple[str, np.ndarray]] = [
        ("hk", shipment.hk), ("hv", shipment.hv)
    ]
    if shipment.quantized:
        sections += [
            ("hk_scale", shipment.hk_scale), ("hv_scale", shipment.hv_scale)
        ]
    header = {
        "key": shipment.key.hex(),
        "src": str(shipment.src),
        "prefix_len": int(shipment.prefix_len),
        "page_size": int(shipment.page_size),
        "lora": int(shipment.lora),
        "sections": [
            {"name": name, "dtype": _dtype_name(arr.dtype),
             "shape": [int(d) for d in arr.shape]}
            for name, arr in sections
        ],
    }
    # draft-ahead framing (docs/spec_decode_trees.md): the keys are
    # OMITTED for the legacy whole-prefix shipment, so PR 19 frames stay
    # byte-identical and old receivers keep decoding them (version 1)
    if shipment.page_offset or not shipment.final:
        header["page_offset"] = int(shipment.page_offset)
        header["final"] = bool(shipment.final)
    hdr = json.dumps(header, separators=(",", ":")).encode("utf-8")
    flags = _FLAG_QUANTIZED if shipment.quantized else 0
    parts = [MAGIC, struct.pack("<BBH", WIRE_VERSION, flags, len(hdr)), hdr]
    for _, arr in sections:
        parts.append(np.ascontiguousarray(arr).tobytes())
    return b"".join(parts)


def shipment_from_wire(frame) -> KVShipment:
    """Decode + validate one frame into a shipment whose arrays are
    ZERO-COPY read-only views into ``frame``. Every inconsistency —
    truncation, bad magic, unknown dtype, geometry that disagrees with
    itself or with the body length — raises :class:`WireFormatError`
    before anything is attached anywhere."""
    buf = memoryview(frame)
    if len(buf) < len(MAGIC) + 4:
        raise WireFormatError(
            "truncated kv wire frame ({} bytes: shorter than the fixed "
            "prefix)".format(len(buf))
        )
    if bytes(buf[:4]) != MAGIC:
        raise WireFormatError(
            "bad kv wire magic {!r} (want {!r})".format(bytes(buf[:4]), MAGIC)
        )
    version, flags, hdr_len = struct.unpack("<BBH", buf[4:8])
    if version != WIRE_VERSION:
        raise WireFormatError(
            "kv wire version {} unsupported (speak {})".format(
                version, WIRE_VERSION
            )
        )
    if len(buf) < 8 + hdr_len:
        raise WireFormatError(
            "truncated kv wire frame (header says {} bytes, {} remain)"
            .format(hdr_len, len(buf) - 8)
        )
    try:
        header = json.loads(bytes(buf[8:8 + hdr_len]).decode("utf-8"))
    except (ValueError, UnicodeDecodeError) as ex:
        raise WireFormatError("unparseable kv wire header: {}".format(ex))
    try:
        key = bytes.fromhex(header["key"])
        src = str(header["src"])
        prefix_len = int(header["prefix_len"])
        page_size = int(header["page_size"])
        lora = int(header["lora"])
        page_offset = int(header.get("page_offset", 0))
        final = bool(header.get("final", True))
        sections = list(header["sections"])
    except (KeyError, TypeError, ValueError) as ex:
        raise WireFormatError("malformed kv wire header: {!r}".format(ex))
    if page_offset < 0:
        raise WireFormatError(
            "kv wire page_offset must be >= 0 (got {})".format(page_offset)
        )
    if len(key) != 16:
        raise WireFormatError(
            "kv wire content key must be 16 bytes (got {})".format(len(key))
        )
    want_names = ["hk", "hv"]
    if flags & _FLAG_QUANTIZED:
        want_names += ["hk_scale", "hv_scale"]
    if [s.get("name") for s in sections] != want_names:
        raise WireFormatError(
            "kv wire sections {} disagree with flags (want {})".format(
                [s.get("name") for s in sections], want_names
            )
        )
    arrays: Dict[str, np.ndarray] = {}
    offset = 8 + hdr_len
    for sec in sections:
        dtype_name = str(sec.get("dtype"))
        if dtype_name not in _WIRE_DTYPES:
            raise WireFormatError(
                "kv wire dtype {!r} unsupported (supported: {})".format(
                    dtype_name, ", ".join(sorted(_WIRE_DTYPES))
                )
            )
        dtype = _WIRE_DTYPES[dtype_name]
        shape = tuple(int(d) for d in sec["shape"])
        if any(d < 0 for d in shape):
            raise WireFormatError(
                "kv wire section {!r} has a negative dim: {}".format(
                    sec["name"], shape
                )
            )
        nbytes = int(np.prod(shape, dtype=np.int64)) * dtype.itemsize
        if offset + nbytes > len(buf):
            raise WireFormatError(
                "truncated kv wire frame (section {!r} wants {} bytes, "
                "{} remain)".format(sec["name"], nbytes, len(buf) - offset)
            )
        arrays[sec["name"]] = np.frombuffer(
            buf[offset:offset + nbytes], dtype=dtype
        ).reshape(shape)
        offset += nbytes
    if offset != len(buf):
        raise WireFormatError(
            "kv wire frame carries {} trailing bytes past its sections"
            .format(len(buf) - offset)
        )
    hk, hv = arrays["hk"], arrays["hv"]
    if hk.ndim != 5 or hk.shape != hv.shape:
        raise WireFormatError(
            "kv wire geometry mismatch: hk {} vs hv {} (want matching "
            "[N, L, Hkv, P, D])".format(hk.shape, hv.shape)
        )
    if hk.dtype != hv.dtype:
        raise WireFormatError(
            "kv wire dtype mismatch: hk {} vs hv {}".format(
                hk.dtype, hv.dtype
            )
        )
    if hk.shape[3] != page_size:
        raise WireFormatError(
            "kv wire geometry mismatch: header page_size {} vs slab page "
            "dim {}".format(page_size, hk.shape[3])
        )
    pages = int(hk.shape[0])
    if pages < 1:
        raise WireFormatError(
            "kv wire frame carries no pages (empty slab)"
        )
    if final:
        # final frame: prefix_len is the AUTHORITATIVE full prefix and
        # its tail must land inside this frame's pages
        if not (page_offset * page_size
                < prefix_len <= (page_offset + pages) * page_size):
            raise WireFormatError(
                "kv wire geometry mismatch: prefix_len {} outside pages "
                "[{}, {}) x {} tokens".format(
                    prefix_len, page_offset, page_offset + pages, page_size
                )
            )
    elif prefix_len != (page_offset + pages) * page_size:
        # unsealed draft-ahead frame: covers WHOLE pages exactly
        raise WireFormatError(
            "kv wire geometry mismatch: partial frame prefix_len {} != "
            "({} + {} pages) x {} tokens".format(
                prefix_len, page_offset, pages, page_size
            )
        )
    hk_scale = hv_scale = None
    if flags & _FLAG_QUANTIZED:
        hk_scale, hv_scale = arrays["hk_scale"], arrays["hv_scale"]
        for name, scale in (("hk_scale", hk_scale), ("hv_scale", hv_scale)):
            if scale.shape != hk.shape[:4]:
                raise WireFormatError(
                    "kv wire geometry mismatch: {} {} vs page planes {}"
                    .format(name, scale.shape, hk.shape[:4])
                )
            if scale.dtype != np.float32:
                raise WireFormatError(
                    "kv wire scale rows must be float32 (got {} for {})"
                    .format(scale.dtype, name)
                )
    return KVShipment(
        key=key, src=src, prefix_len=prefix_len, page_size=page_size,
        lora=lora, hk=hk, hv=hv, hk_scale=hk_scale, hv_scale=hv_scale,
        page_offset=page_offset, final=final,
    )


def _parse_addr(addr: str):
    """``unix:<path>`` or ``tcp:<host>:<port>`` -> (family, sockaddr)."""
    if addr.startswith("unix:"):
        return socket.AF_UNIX, addr[len("unix:"):]
    if addr.startswith("tcp:"):
        host, _, port = addr[len("tcp:"):].rpartition(":")
        return socket.AF_INET, (host or "127.0.0.1", int(port))
    raise ValueError(
        "kv wire address must be unix:<path> or tcp:<host>:<port>: "
        "got {!r}".format(addr)
    )


class _WireHistogram:
    """Jax-free fixed-bucket ms histogram matching the engine's snapshot
    shape (``{buckets, counts, sum_ms, count}``) so statistics/metrics.py
    exports it like any other lifecycle histogram."""

    BUCKETS = (0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 100.0, 1000.0)

    def __init__(self):
        self.counts = [0] * (len(self.BUCKETS) + 1)
        self.total_ms = 0.0
        self.n = 0

    def observe(self, ms: float) -> None:
        for i, edge in enumerate(self.BUCKETS):
            if ms <= edge:
                break
        else:
            i = len(self.BUCKETS)
        self.counts[i] += 1
        self.total_ms += float(ms)
        self.n += 1

    def snapshot(self) -> dict:
        return {
            "buckets": list(self.BUCKETS),
            "counts": list(self.counts),
            "sum_ms": self.total_ms,
            "count": self.n,
        }


class SocketSlabTransport:
    """One replica's socket-backed ``TransportEndpoint``: a listener
    thread feeds decoded frames into a local :class:`SharedSlabTransport`
    mailbox (so every bounded-slab semantic — overflow drops oldest,
    re-ship replaces, consume-once recv, ledger pairing — is the SAME
    CODE as the in-process backend), and ``send`` frames shipments to a
    peer's listener with a deadline and a one-byte ack.

    ``peers`` is a live name->address map shared with the fabric (or the
    process-replica spec): destinations registered after this endpoint
    are visible at send time.
    """

    # lock-discipline registry (tpuserve-analyze TPU301): the per-peer
    # connection cache is shared between the sender (its replica's loop
    # thread) and close(); wire counters are plain GIL-atomic bumps
    __guarded_by__ = {"_lock": ("_conns",)}

    # ownership-discipline registry (tpuserve-analyze TPU7xx): each cached
    # peer connection is released by the failure path or close(); the
    # mailbox's transport.shipment pairing is SharedSlabTransport's own
    # declaration (this class delegates to it verbatim)
    __acquires__ = {
        "_connect": {"resource": "transport.wire.conn",
                     "releases": ("_drop_conn", "close"), "static": False,
                     "receivers": ("transport", "endpoint", "_transport",
                                   "_kv_transport", "ep")},
    }

    def __init__(
        self,
        name: str,
        bind: str,
        peers: Dict[str, str],
        *,
        capacity_pages: int = 1024,
        max_shipments: int = 64,
        send_deadline_s: float = 5.0,
        recv_deadline_s: float = 5.0,
    ):
        self.name = str(name)
        self.bind = str(bind)
        self._peers = peers
        self.send_deadline_s = float(send_deadline_s)
        self.recv_deadline_s = float(recv_deadline_s)
        # the receive slab IS the in-process backend, scoped to one dst:
        # bounded-mailbox behavior cannot drift between the two backends
        self._mailbox = SharedSlabTransport(
            capacity_pages=capacity_pages, max_shipments=max_shipments
        )
        self._mailbox.register(self.name)
        self._lock = threading.Lock()
        self._conns: Dict[str, socket.socket] = {}
        self._closing = False
        # wire observability (GIL-atomic bumps; surfaced through stats())
        self.wire_bytes_sent = 0
        self.wire_bytes_received = 0
        self.wire_frames_sent = 0
        self.wire_frames_received = 0
        self.wire_send_failures = 0
        self.wire_recv_failures = 0
        self._hist_rtt_ms = _WireHistogram()
        family, sockaddr = _parse_addr(self.bind)
        self._listener = socket.socket(family, socket.SOCK_STREAM)
        if family == socket.AF_INET:
            self._listener.setsockopt(
                socket.SOL_SOCKET, socket.SO_REUSEADDR, 1
            )
        self._listener.bind(sockaddr)
        if family == socket.AF_INET and sockaddr[1] == 0:
            # ephemeral TCP port: publish the real one
            self.bind = "tcp:{}:{}".format(*self._listener.getsockname())
        self._listener.listen(8)
        self._accept_thread = threading.Thread(
            target=self._accept_loop,
            name="kvwire-accept-{}".format(self.name), daemon=True,
        )
        self._accept_thread.start()

    # -- receive side (listener threads) ------------------------------------

    def _accept_loop(self) -> None:
        while not self._closing:
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return  # listener closed
            threading.Thread(
                target=self._serve_conn, args=(conn,),
                name="kvwire-recv-{}".format(self.name), daemon=True,
            ).start()

    def _serve_conn(self, conn: socket.socket) -> None:
        conn.settimeout(self.recv_deadline_s)
        try:
            while not self._closing:
                head = self._read_exact(conn, 4)
                if head is None:
                    return  # peer closed between frames: clean
                (frame_len,) = struct.unpack("<I", head)
                if not (0 < frame_len < MAX_FRAME_BYTES):
                    self.wire_recv_failures += 1
                    return  # lying length prefix: drop the connection
                frame = self._read_exact(conn, frame_len)
                if frame is None:
                    # truncated mid-frame (sender died / deadline):
                    # drop-to-recompute — nothing was attached
                    self.wire_recv_failures += 1
                    return
                self.wire_frames_received += 1
                self.wire_bytes_received += 4 + frame_len
                ok = self._ingest(frame)
                try:
                    conn.sendall(b"\x01" if ok else b"\x00")
                except OSError:
                    return
        finally:
            try:
                conn.close()
            except OSError:  # pragma: no cover - close is best-effort
                pass

    def _read_exact(self, conn: socket.socket,
                    n: int) -> Optional[bytearray]:
        """``n`` bytes or None (EOF/timeout mid-read = truncated frame)."""
        buf = bytearray()
        while len(buf) < n:
            try:
                chunk = conn.recv(min(1 << 20, n - len(buf)))
            except (socket.timeout, OSError):
                return None
            if not chunk:
                return None if buf or n else buf
            buf.extend(chunk)
        return buf

    def _ingest(self, frame: bytearray) -> bool:
        """Decode one frame into the receive slab. Every failure —
        injected ``transport.wire.recv`` fault, wire-format violation —
        drops the frame leak-free (the slabs are views into ``frame``;
        nothing was attached) and nacks the sender."""
        try:
            faults.fire("transport.wire.recv")
            shipment = shipment_from_wire(bytes(frame))
        except (faults.InjectedFault, WireFormatError) as ex:
            self.wire_recv_failures += 1
            logger.warning(
                "kv wire frame into %s dropped (%s); sender nacked -> "
                "decode-side recompute", self.name, ex,
            )
            return False
        return self._mailbox.send(self.name, shipment)

    # -- send side (sender replica's loop thread) ---------------------------

    def _connect(self, dst: str) -> socket.socket:
        addr = self._peers.get(dst)
        if addr is None:
            raise OSError("no kv wire address for peer {!r}".format(dst))
        family, sockaddr = _parse_addr(addr)
        conn = socket.socket(family, socket.SOCK_STREAM)
        conn.settimeout(self.send_deadline_s)
        try:
            conn.connect(sockaddr)
        except OSError:
            conn.close()
            raise
        if _ledger.armed():
            _ledger.acquire("transport.wire.conn", key=id(conn), domain=self)
        return conn

    def _close_conn(self, conn: socket.socket) -> None:
        if _ledger.armed():
            _ledger.release("transport.wire.conn", key=id(conn), domain=self)
        try:
            conn.close()
        except OSError:  # pragma: no cover - close is best-effort
            pass

    def _drop_conn(self, dst: str, conn: socket.socket) -> None:
        with self._lock:
            if self._conns.get(dst) is conn:
                del self._conns[dst]
        self._close_conn(conn)

    def send(self, dst: str, shipment: KVShipment) -> bool:
        """Frame + ship with a deadline. EVERY failure path — injected
        fault, unknown/unreachable peer, timeout, truncated ack, receiver
        nack — is a counted drop returning False; the decode replica
        recomputes. One shipment is in flight per peer connection (the
        ack doubles as backpressure and the RTT sample)."""
        if self._closing:
            self.wire_send_failures += 1
            return False
        if shipment.pages > self._mailbox.capacity_pages:
            # oversized outright: the receiver would evict its whole slab
            # and still fail — drop sender-side like the shared backend
            self._mailbox.dropped += 1
            self._mailbox.dropped_pages += shipment.pages
            return False
        try:
            faults.fire("transport.wire.send")
            frame = shipment_to_wire(shipment)
        except (faults.InjectedFault, WireFormatError):
            self.wire_send_failures += 1
            return False
        with self._lock:
            conn = self._conns.pop(dst, None)
        t0 = time.perf_counter()
        try:
            if conn is None:
                conn = self._connect(dst)
            conn.sendall(struct.pack("<I", len(frame)) + frame)
            ack = self._read_exact(conn, 1)
        except OSError:
            if conn is not None:
                self._drop_conn(dst, conn)
            self.wire_send_failures += 1
            return False
        if not ack:
            self._drop_conn(dst, conn)
            self.wire_send_failures += 1
            return False
        surplus = True
        with self._lock:
            if not self._closing and self._conns.get(dst) is None:
                self._conns[dst] = conn
                surplus = False
        if surplus:
            # a racing send already cached a connection (or we are
            # closing): this one is extra — release it now
            self._close_conn(conn)
        self._hist_rtt_ms.observe((time.perf_counter() - t0) * 1e3)
        self.wire_frames_sent += 1
        self.wire_bytes_sent += 4 + len(frame)
        if ack != b"\x01":
            self.wire_send_failures += 1
            return False
        self._mailbox.sent += 1
        self._mailbox.sent_pages += shipment.pages
        return True

    # -- endpoint surface ----------------------------------------------------

    def recv(self, key: bytes) -> Optional[KVShipment]:
        return self._mailbox.recv(self.name, key)

    def stats(self) -> Dict[str, object]:
        out = self._mailbox.stats()
        out["backend"] = "socket_slab"
        out["bind"] = self.bind
        out["wire"] = {
            "bytes_sent": self.wire_bytes_sent,
            "bytes_received": self.wire_bytes_received,
            "frames_sent": self.wire_frames_sent,
            "frames_received": self.wire_frames_received,
            "send_failures": self.wire_send_failures,
            "recv_failures": self.wire_recv_failures,
            "rtt_ms": self._hist_rtt_ms.snapshot(),
        }
        return out

    def close(self) -> None:
        self._closing = True
        try:
            self._listener.close()
        except OSError:  # pragma: no cover
            pass
        with self._lock:
            conns = list(self._conns.values())
            self._conns.clear()
        for conn in conns:
            self._close_conn(conn)
        family, sockaddr = _parse_addr(self.bind)
        if family == socket.AF_UNIX:
            try:
                os.unlink(sockaddr)
            except OSError:
                pass


class SocketSlabFabric:
    """In-process broker for socket endpoints: allocates one listener
    address per replica and shares the live peer map, presenting the
    ``register``/``stats`` surface ``ReplicaGroup`` already drives for
    the shared-slab backend. The chaos suite runs the SAME tests against
    both backends through this class; the process backend builds the
    peer map in the worker specs instead."""

    def __init__(self, capacity_pages: int = 1024, max_shipments: int = 64,
                 base_dir: Optional[str] = None):
        self.capacity_pages = int(capacity_pages)
        self.max_shipments = int(max_shipments)
        if base_dir is None:
            import tempfile

            self._tmp = tempfile.TemporaryDirectory(prefix="kvwire-")
            base_dir = self._tmp.name
        else:
            self._tmp = None
        self._base_dir = base_dir
        self._addrs: Dict[str, str] = {}
        self._endpoints: Dict[str, SocketSlabTransport] = {}

    def register(self, name: str) -> SocketSlabTransport:
        if name in self._endpoints:
            return self._endpoints[name]
        bind = "unix:{}".format(
            os.path.join(self._base_dir, "{}.sock".format(name))
        )
        endpoint = SocketSlabTransport(
            name, bind, self._addrs,
            capacity_pages=self.capacity_pages,
            max_shipments=self.max_shipments,
        )
        self._addrs[name] = endpoint.bind
        self._endpoints[name] = endpoint
        return endpoint

    def stats(self) -> Dict[str, object]:
        per = {name: ep.stats() for name, ep in self._endpoints.items()}
        agg = {
            "backend": "socket_slab",
            "capacity_pages": self.capacity_pages,
            "queued": {},
            "endpoints": per,
        }
        for key in ("sent", "sent_pages", "received", "received_pages",
                    "dropped", "dropped_pages", "partial_frames",
                    "assembled", "assembly_drops"):
            agg[key] = sum(int(s[key]) for s in per.values())
        for s in per.values():
            agg["queued"].update(s["queued"])
        return agg

    def close(self) -> None:
        for endpoint in self._endpoints.values():
            endpoint.close()
        if self._tmp is not None:
            self._tmp.cleanup()
