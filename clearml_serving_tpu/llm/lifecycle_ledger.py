"""Runtime ownership ledger: prove acquire/release pairing, don't assume it.

The static TPU7xx pass (analyze/rules_lifecycle.py) proves per-function
pairing over exception paths, but declares its blind spots openly: handles
stored into attributes, pairing across functions and threads, aliased
handles. This module is the dynamic net behind those blind spots — the same
arm-and-audit-at-the-loop-boundary shape as the KV sanitizer
(llm/kv_sanitizer.py) and the compile sentry (llm/compile_sentry.py).

Armed with ``TPUSERVE_LEDGER=1`` (count) or ``=strict`` (raise), every
declared acquire/release in the KV primitives and the engine records an
entry with its **owner** (the request the engine attributed it to, when
known), its **acquire site** (the first caller frame outside the
instrumented primitives), and a count. The engine then audits:

- **per request**, at emit-finish / fail / cancel: every request-scoped
  entry owned by the exiting request must be gone — a surviving entry is a
  lost release, reported with the resource and the acquire site;
- **globally**, at drain (the same boundary as the sanitizer's leak audit):
  every ``drain_zero`` resource must have zero outstanding entries in the
  auditing engine's domains (pins, hits, resume pins, slot pages,
  quarantine entries, in-flight promotions);
- **always**: a release with nothing outstanding is a double free,
  recorded immediately.

In strict mode :meth:`OwnershipLedger.check` raises :class:`LedgerError`
(an AssertionError subclass — armed test suites fail closed) at the next
loop boundary, naming the leaked resource and its acquire site; in count
mode violations accumulate in ``stats()`` and surface as
``engine_ledger_outstanding{resource}`` / ``engine_ledger_leaks_total``
(statistics/metrics.py, from ``lifecycle_stats()["ledger"]``).

Entries carry the id of the primitive that recorded them (the *domain*),
so co-hosted engines — replica fleets run N engines in one process — audit
only their own pools/caches at drain while sharing one process-wide
ledger. Cache-scoped resources (radix-cache page refs, host-tier ids,
unconsumed transport shipments) are tracked for the outstanding gauges but
exempt from drain-zero: the cache legitimately holds them across requests.

The chaos seam ``engine.ledger.leak`` (llm/faults.py) suppresses exactly
one release firing on the preemption resume-pin path, proving end to end
that a real lost free surfaces here — and nowhere else: pinned radix NODES
are invisible to the KV sanitizer's page accounting.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from contextlib import contextmanager
from typing import Any, Dict, List, Optional, Tuple

__all__ = [
    "ENV",
    "RESOURCES",
    "LedgerError",
    "OwnershipLedger",
    "enabled",
    "strict_enabled",
    "armed",
    "arm",
    "disarm",
    "get",
    "acquire",
    "release",
    "owner",
    "request_tag",
]

ENV = "TPUSERVE_LEDGER"

# resource -> policy. "scope" documents the natural owner; "drain_zero"
# resources must have zero outstanding entries at an engine drain audit.
# Keep in sync with the __acquires__ declarations / LIFECYCLE_REGISTRY
# resources (tests pin the agreement).
RESOURCES: Dict[str, Dict[str, Any]] = {
    "pages.slot": {"scope": "engine", "drain_zero": True},
    "pages.pin": {"scope": "request", "drain_zero": True},
    "pages.ref": {"scope": "cache", "drain_zero": False},
    "prefix.hit": {"scope": "request", "drain_zero": True},
    "prefix.resume_pin": {"scope": "request", "drain_zero": True},
    "host.pages": {"scope": "cache", "drain_zero": False},
    "slot.quarantine": {"scope": "engine", "drain_zero": True},
    "kv.promotion": {"scope": "engine", "drain_zero": True},
    "transport.shipment": {"scope": "cache", "drain_zero": False},
    "transport.wire.conn": {"scope": "cache", "drain_zero": False},
    "replica.worker_proc": {"scope": "engine", "drain_zero": False},
    "guided.ref": {"scope": "request", "drain_zero": True},
}

# frames whose code lives in these basenames are the instrumented
# primitives themselves: the interesting acquire site is their caller
_SKIP_BASENAMES = frozenset({
    "lifecycle_ledger.py", "kv_cache.py", "prefix_cache.py",
    "kv_transport.py",
})


def enabled() -> bool:
    """Armed via ``TPUSERVE_LEDGER`` (1/true/yes/strict; 0/empty disarms)."""
    return os.environ.get(ENV, "").lower() in ("1", "true", "yes", "strict")


def strict_enabled() -> bool:
    return os.environ.get(ENV, "").lower() == "strict"


class LedgerError(AssertionError):
    """An ownership invariant failed. Carries the resource and the acquire
    site (``resource``, ``site``) for programmatic triage."""

    def __init__(self, message: str, *, resource: str = "",
                 site: str = "", where: str = ""):
        super().__init__(message)
        self.resource = resource
        self.site = site
        self.where = where


def _call_site() -> str:
    """file:line of the first frame outside the instrumented primitives."""
    frame = sys._getframe(2)
    for _ in range(8):
        if frame is None:
            break
        name = os.path.basename(frame.f_code.co_filename)
        if name not in _SKIP_BASENAMES:
            return "{}:{}".format(name, frame.f_lineno)
        frame = frame.f_back
    return "<unknown>"


class OwnershipLedger:
    """Process-wide acquire/release bookkeeping (one per process: replica
    fleets co-host engines, and the primitives they share record here).
    Thread-safe; owner attribution is thread-local so admission workers tag
    the acquires their own requests trigger."""

    def __init__(self, strict: bool = False):
        self.strict = bool(strict)
        self._lock = threading.Lock()
        self._tls = threading.local()
        # (resource, domain, key) -> list of {owner, site, n, t}
        self._entries: Dict[Tuple[str, int, Any], List[Dict[str, Any]]] = {}
        self.acquires = 0
        self.releases = 0
        self.leaks = 0              # leak violations found (monotonic)
        self.double_releases = 0
        self.violations: List[Dict[str, Any]] = []

    # -- owner attribution -------------------------------------------------

    @contextmanager
    def owner(self, tag: Optional[str]):
        """Attribute acquires on THIS thread to ``tag`` (the engine wraps
        its per-request admission/preemption paths)."""
        prev = getattr(self._tls, "owner", None)
        self._tls.owner = tag
        try:
            yield
        finally:
            self._tls.owner = prev

    def _owner(self) -> Optional[str]:
        return getattr(self._tls, "owner", None)

    # -- recording ---------------------------------------------------------

    def acquire(self, resource: str, key: Any = None, n: int = 1,
                domain: Any = None, owner: Optional[str] = None,
                site: Optional[str] = None) -> None:
        if n <= 0:
            return
        if resource not in RESOURCES:
            raise ValueError("unknown ledger resource {!r}".format(resource))
        entry = {
            "owner": owner if owner is not None else self._owner(),
            "site": site if site is not None else _call_site(),
            "n": int(n),
            "t": time.time(),
        }
        slot_key = (resource, id(domain), key)
        with self._lock:
            self.acquires += int(n)
            self._entries.setdefault(slot_key, []).append(entry)

    def release(self, resource: str, key: Any = None, n: int = 1,
                domain: Any = None, all_of_key: bool = False,
                owner: Optional[str] = None) -> None:
        """Discharge ``n`` units. Slabs owned by ``owner`` (explicit, else
        the thread-local owner context) discharge FIRST, then newest-first
        — two requests sharing one resource key (the same grammar, the
        same pinned page run) must not discharge each other's entries, or
        the survivor's request-exit audit reports a phantom leak."""
        if resource not in RESOURCES:
            raise ValueError("unknown ledger resource {!r}".format(resource))
        slot_key = (resource, id(domain), key)
        who = owner if owner is not None else self._owner()
        with self._lock:
            slabs = self._entries.get(slot_key)
            if all_of_key:
                n = sum(s["n"] for s in slabs) if slabs else 0
                if slabs:
                    del self._entries[slot_key]
                    self.releases += n
                return
            remaining = int(n)
            self.releases += remaining
            if slabs:
                order = (
                    [s for s in reversed(slabs) if s["owner"] == who]
                    + [s for s in reversed(slabs) if s["owner"] != who]
                )
            else:
                order = []
            for slab in order:
                if remaining <= 0:
                    break
                take = min(slab["n"], remaining)
                slab["n"] -= take
                remaining -= take
            if slabs is not None:
                slabs[:] = [s for s in slabs if s["n"] > 0]
                if not slabs:
                    del self._entries[slot_key]
            if remaining > 0:
                self.double_releases += 1
                self.violations.append({
                    "kind": "double_release",
                    "resource": resource,
                    "key": key,
                    "n": remaining,
                    "site": _call_site(),
                    "where": "release",
                })

    # -- audits ------------------------------------------------------------

    def audit_request(self, tag: str, where: str = "request-exit") -> None:
        """Every request-scoped entry owned by ``tag`` must be gone. In
        strict mode the first survivor raises immediately (the engine's
        emit/fail/cancel boundaries run on the loop thread — the structured
        step-failure path handles it, like a sanitizer violation)."""
        found: List[Dict[str, Any]] = []
        with self._lock:
            for (resource, _domain, key), slabs in self._entries.items():
                if RESOURCES[resource]["scope"] != "request":
                    continue
                for slab in slabs:
                    if (
                        slab["owner"] == tag
                        and slab["n"] > 0
                        and not slab.get("reported")
                    ):
                        slab["reported"] = True  # count each lost free ONCE
                        found.append({
                            "kind": "request_leak",
                            "resource": resource,
                            "key": key,
                            "n": slab["n"],
                            "site": slab["site"],
                            "owner": tag,
                            "where": where,
                        })
            if found:
                self.leaks += len(found)
                self.violations.extend(found)
        if found and self.strict:
            v = found[0]
            raise LedgerError(
                "ownership ledger [{}]: request {} exited holding {} x "
                "{} acquired at {} — a lost release on a request exit "
                "path".format(
                    where, tag, v["n"], v["resource"], v["site"]
                ),
                resource=v["resource"], site=v["site"], where=where,
            )

    def check(self, where: str = "step", drained: bool = False,
              domains: Optional[List[Any]] = None) -> None:
        """Loop-boundary audit (the engine calls this where it calls the KV
        sanitizer). Raises the first pending strict violation; at a drained
        boundary additionally requires zero outstanding entries for every
        ``drain_zero`` resource within ``domains`` (None = everywhere)."""
        domain_ids = (
            None if domains is None else {id(d) for d in domains}
        )
        leaked: List[Dict[str, Any]] = []
        with self._lock:
            pending = list(self.violations) if self.strict else []
            if drained:
                for (resource, domain, key), slabs in self._entries.items():
                    if not RESOURCES[resource]["drain_zero"]:
                        continue
                    if domain_ids is not None and domain not in domain_ids:
                        continue
                    for slab in slabs:
                        # a leaked entry survives in the books until
                        # reset(); count it ONCE, not once per drained
                        # boundary (the counter is lost frees, not drains
                        # that observed them; the violations list must
                        # not grow unboundedly on a long-lived server)
                        if slab["n"] > 0 and not slab.get("reported"):
                            slab["reported"] = True
                            leaked.append({
                                "kind": "drain_leak",
                                "resource": resource,
                                "key": key,
                                "n": slab["n"],
                                "site": slab["site"],
                                "owner": slab["owner"],
                                "where": where,
                            })
                if leaked:
                    self.leaks += len(leaked)
                    self.violations.extend(leaked)
        if not self.strict:
            return
        for v in pending + leaked:
            if v["kind"] == "double_release":
                raise LedgerError(
                    "ownership ledger [{}]: released {} x {} that was "
                    "never acquired (double free / release-after-free) at "
                    "{}".format(where, v["n"], v["resource"], v["site"]),
                    resource=v["resource"], site=v["site"], where=where,
                )
            raise LedgerError(
                "ownership ledger [{}]: {} x {} still outstanding at the "
                "drained boundary (owner {}), acquired at {} — a leaked "
                "resource the exception paths never released".format(
                    where, v["n"], v["resource"], v.get("owner"), v["site"]
                ),
                resource=v["resource"], site=v["site"], where=where,
            )

    # -- introspection -----------------------------------------------------

    def outstanding(self) -> Dict[str, int]:
        """resource -> total outstanding count (all domains)."""
        out: Dict[str, int] = {r: 0 for r in RESOURCES}
        with self._lock:
            for (resource, _domain, _key), slabs in self._entries.items():
                out[resource] += sum(s["n"] for s in slabs)
        return out

    def outstanding_entries(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [
                {"resource": resource, "key": key, "n": slab["n"],
                 "owner": slab["owner"], "site": slab["site"]}
                for (resource, _d, key), slabs in self._entries.items()
                for slab in slabs if slab["n"] > 0
            ]

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            violations = len(self.violations)
            leaks = self.leaks
            double = self.double_releases
            acquires = self.acquires
            releases = self.releases
        return {
            "strict": self.strict,
            "acquires": acquires,
            "releases": releases,
            "leaks": leaks,
            "double_releases": double,
            "violations": violations,
            "outstanding": self.outstanding(),
        }

    def reset(self, strict: Optional[bool] = None) -> None:
        with self._lock:
            self._entries.clear()
            self.acquires = 0
            self.releases = 0
            self.leaks = 0
            self.double_releases = 0
            self.violations = []
            if strict is not None:
                self.strict = bool(strict)


# -- module singleton ---------------------------------------------------------

_ledger: Optional[OwnershipLedger] = None
_armed = False
_guard = threading.Lock()


def get() -> OwnershipLedger:
    """The process-wide ledger (created on first use; strictness from the
    env at creation — tests flip ``.strict`` / call ``.reset()``)."""
    global _ledger
    with _guard:
        if _ledger is None:
            _ledger = OwnershipLedger(strict=strict_enabled())
        return _ledger


def armed() -> bool:
    """Fast hot-path gate: one module-global read when disarmed."""
    return _armed


def arm(strict: Optional[bool] = None) -> OwnershipLedger:
    """Start recording (idempotent: co-hosted engines arm at construction
    and share the ledger; arming never resets accumulated state)."""
    global _armed
    ledger = get()
    if strict is not None:
        ledger.strict = bool(strict)
    _armed = True
    return ledger


def disarm() -> None:
    global _armed
    _armed = False


def acquire(resource: str, key: Any = None, n: int = 1, domain: Any = None,
            owner: Optional[str] = None) -> None:
    """Record an acquire when armed (no-op otherwise). Call sites guard
    with ``armed()`` so the disarmed cost is one global read."""
    if _armed:
        get().acquire(resource, key=key, n=n, domain=domain, owner=owner)


def release(resource: str, key: Any = None, n: int = 1, domain: Any = None,
            all_of_key: bool = False) -> None:
    if _armed:
        get().release(
            resource, key=key, n=n, domain=domain, all_of_key=all_of_key
        )


@contextmanager
def owner(tag: Optional[str]):
    """Attribute this thread's acquires to ``tag`` while armed (no-op
    context otherwise)."""
    if not _armed:
        yield
        return
    with get().owner(tag):
        yield


def request_tag(request: Any) -> str:
    """Stable owner tag for a request object."""
    return "req:{:x}".format(id(request))
