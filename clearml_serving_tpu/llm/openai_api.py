"""OpenAI-compatible LLM engine endpoint ("llm" engine type).

Route-surface parity with the reference's vLLM engine handlers
(clearml_serving/serving/preprocess_service.py:836-1095): chat completions
(+SSE streaming), completions, models, tokenize/detokenize — dispatched through
the router's ``/serve/openai/{type}`` path exactly like the reference
(serve_type "v1/chat/completions" → ``v1_chat_completions``). Capability-gated
routes (embeddings / pooling / classify / score / audio) return a clean
backend error when the loaded model does not support them, mirroring the
reference's task/runner gating (preprocess_service.py:711-808).

The compute path is the continuous-batching engine in engine.py on TPU via
JAX — no CUDA, no vLLM.
"""

from __future__ import annotations

import asyncio
import json
import logging
import time
import uuid
from typing import Any, AsyncIterator, Dict, List, Optional, Tuple

from ..engines.base import BaseEngineRequest, EndpointModelError, register_engine
from ..serving.responses import StreamingOutput
from .tokenizer import load_tokenizer

# engine.py / sampling.py import jax at module level; defer so registering the
# "llm" engine (CLI import path) stays jax-free.
if False:  # typing only
    from .engine import GenRequest, LLMEngineCore  # noqa: F401


def _now() -> int:
    return int(time.time())


def _gen_id(prefix: str) -> str:
    return "{}-{}".format(prefix, uuid.uuid4().hex[:24])


@register_engine("llm", modules=["jax", "flax"])
class LLMEngineRequest(BaseEngineRequest):
    """One continuous-batching engine per endpoint per process."""

    is_preprocess_async = True
    is_process_async = True
    is_postprocess_async = True

    def __init__(self, *args, **kwargs):
        self.engine = None
        self.encoder = None
        self.audio = None
        self.tokenizer = None
        self._model_name = "model"
        # aux engine.chat block (reference vLLM chat_settings:
        # examples/vllm/preprocess.py:14-33): response_role etc.
        self._chat_cfg: Dict[str, Any] = {}
        # endpoint-level SLO class default (docs/slo_scheduling.md): aux
        # engine.default_priority; a request body `priority` overrides it
        self._default_priority = "interactive"
        # startup shape warmup (aux engine.warmup; llm/warmup.py)
        self._warmup_needed = False
        self._warmup_full = False
        self._warmup_task = None
        super().__init__(*args, **kwargs)

    async def _ensure_warm(self) -> None:
        """First arrivals share one warmup task (llm/warmup.py) and wait
        for it; afterwards this is one attribute read. A failed warmup is
        logged and disabled rather than bricking the endpoint — serving
        then compiles lazily, exactly the pre-knob behavior."""
        if not self._warmup_needed or self.engine is None:
            return
        if self._warmup_task is None:
            self._warmup_task = asyncio.create_task(
                self.engine.warmup(full=self._warmup_full)
            )
        try:
            await asyncio.shield(self._warmup_task)
        except Exception as ex:  # tpuserve: ignore[TPU401] warmup is best-effort by contract; failure falls back to lazy compiles and is logged
            logging.getLogger(__name__).warning(
                "engine warmup failed (serving will compile lazily): %s", ex
            )
        self._warmup_needed = False

    # -- loading --------------------------------------------------------------

    def _native_load(self) -> Any:
        import jax

        from ..engines.jax_engine import enable_persistent_compilation_cache, load_bundle
        from .. import models
        from .engine import LLMEngineCore, PRIORITY_CLASSES

        enable_persistent_compilation_cache()
        aux = self.endpoint.auxiliary_cfg if isinstance(self.endpoint.auxiliary_cfg, dict) else {}
        engine_cfg = dict(aux.get("engine") or {})
        self._chat_cfg = dict(engine_cfg.get("chat") or {})

        # weight quantization (docs/w4a16.md): aux engine.weight_quant
        # ("quantize" stays as the legacy alias) selects int8 per-channel or
        # int4 group-quantized weights; int4 decode matmuls route through
        # the Pallas fused dequant-matmul (ops/fused_matmul.py). Validated
        # at ENDPOINT LOAD like default_priority: a typo'd value must fail
        # fast naming the knob, not surface as a per-request error after
        # the endpoint looked healthy.
        weight_quant = engine_cfg.get(
            "weight_quant", engine_cfg.get("quantize")
        )
        legacy = engine_cfg.get("quantize")
        if (
            engine_cfg.get("weight_quant") and legacy
            and engine_cfg["weight_quant"] != legacy
        ):
            # same fail-fast contract as the engine kwargs: a config that
            # spells the knob both ways with different values must not
            # silently pick one
            raise ValueError(
                "aux engine.weight_quant={!r} conflicts with the legacy "
                "engine.quantize={!r} alias; set only one".format(
                    engine_cfg["weight_quant"], legacy
                )
            )
        if weight_quant in ("", None):
            weight_quant = None
        elif str(weight_quant) not in ("int8", "int4"):
            raise ValueError(
                "aux engine.weight_quant must be 'int8' or 'int4': got "
                "{!r}".format(weight_quant)
            )

        # multi-LoRA (reference vLLM knob `lora_modules`,
        # preprocess_service.py:740-767): aux engine.lora = {"modules":
        # {name: adapter_dir}, "rank": r?, "targets": [...]?, "max_loras": n?}
        # — adapters load host-side, install into stacked factors, and route
        # by the OpenAI request's `model` field (models/lora.py).
        lora_overrides, lora_adapters = self._load_lora_cfg(engine_cfg)
        cfg_overrides = dict(lora_overrides)
        if engine_cfg.get("kv_quant"):
            # int8 KV cache: a serving-time build knob like lora, so it can
            # be set per endpoint without touching the stored bundle config.
            # Honored by BOTH cache backends: the dense cache stores
            # int8+scales in its buffers, and the paged backend allocates
            # int8 page pools with per-page scale rows and dequantizes
            # inside the Pallas decode kernel (docs/paged_kv_quant.md) —
            # so `engine.cache: paged` endpoints get the halved KV HBM the
            # b>=32 roofline configs need.
            cfg_overrides["kv_quant"] = str(engine_cfg["kv_quant"])

        if self._model_local_path:
            bundle, params = load_bundle(
                self._model_local_path, config_overrides=cfg_overrides or None
            )
        elif engine_cfg.get("preset"):
            # weightless demo/bench mode: architecture preset, random params
            bundle = models.build_model(
                engine_cfg.get("arch", "llama"),
                {
                    "preset": engine_cfg["preset"],
                    **(engine_cfg.get("config") or {}),
                    **cfg_overrides,
                },
            )
            params = bundle.init(jax.random.PRNGKey(int(engine_cfg.get("seed", 0))))
        else:
            raise EndpointModelError(
                "llm endpoint {!r} needs a model bundle or aux_config engine.preset".format(
                    self.endpoint.serving_url
                )
            )

        mesh = None
        if aux.get("mesh"):
            from ..parallel import mesh_from_aux_cfg

            if len(jax.devices()) > 1:
                mesh = mesh_from_aux_cfg(aux)

        self.tokenizer = load_tokenizer(
            self._model_local_path, int(bundle.config.get("vocab_size", 0))
        )

        # task gating like the reference's model-task handler instantiation
        # (preprocess_service.py:711-808): encoder bundles (no .decode) serve
        # the embeddings/pooling/classify/score/rerank routes; decoder bundles
        # serve chat/completions.
        task = engine_cfg.get("task")
        if task is None:
            if hasattr(bundle, "encode") and hasattr(bundle, "init_cache") and not hasattr(bundle, "prefill"):
                task = "transcribe"  # speech encoder-decoder (whisper family)
            elif hasattr(bundle, "decode"):
                task = "generate"
            else:
                task = "embed"
        encoder_tasks = {
            "embed", "embedding", "pooling", "classify", "classification",
            "score", "rerank",
        }
        audio_tasks = {"transcribe", "translate", "audio"}
        if task not in encoder_tasks and task not in audio_tasks and task != "generate":
            raise EndpointModelError(
                "unknown engine task {!r} for endpoint {!r} (expected "
                "'generate' or one of {})".format(
                    task,
                    self.endpoint.serving_url,
                    sorted(encoder_tasks | audio_tasks),
                )
            )
        if task in audio_tasks:
            from .audio import AudioCore

            self.audio = AudioCore(
                bundle,
                params,
                decode_steps=int(engine_cfg.get("decode_steps", 16)),
                max_new_tokens=engine_cfg.get("max_tokens"),
            )
            self._model_name = self.endpoint.serving_url
            return self.audio
        if task in encoder_tasks:
            from .encoder import EncoderCore

            hf = getattr(self.tokenizer, "_tok", None)
            self.encoder = EncoderCore(
                bundle,
                params,
                pooling=engine_cfg.get("pooling", "mean"),
                normalize=bool(engine_cfg.get("normalize", True)),
                seq_buckets=engine_cfg.get("seq_buckets"),
                batch_buckets=engine_cfg.get("batch_buckets"),
                sep_token_id=getattr(hf, "sep_token_id", None),
                cls_token_id=getattr(hf, "cls_token_id", None),
            )
            self._model_name = self.endpoint.serving_url
            return self.encoder
        engine_kwargs = dict(
            max_batch=int(engine_cfg.get("max_batch", 8)),
            max_seq_len=int(engine_cfg.get("max_seq_len", bundle.config.get("max_seq_len", 2048))),
            prefill_buckets=engine_cfg.get("prefill_buckets"),
            mesh=mesh,
            eos_token_id=self.tokenizer.eos_token_id,
            decode_steps=int(engine_cfg.get("decode_steps", 4)),
            weight_quant=weight_quant,
            cache_mode=engine_cfg.get("cache", "dense"),
            # int8 paged pools default to 32-token pages: the int8 Pallas
            # tile is (32, 128), so 16-token pages would silently route
            # every TPU decode to the XLA-gather fallback and forfeit the
            # halved-DMA win (docs/paged_kv_quant.md); an explicit
            # engine.page_size still wins
            page_size=int(
                engine_cfg.get("page_size")
                or (32 if (
                    engine_cfg.get("kv_quant")
                    and engine_cfg.get("cache", "dense") == "paged"
                ) else 16)
            ),
            num_pages=int(engine_cfg["num_pages"]) if engine_cfg.get("num_pages") else None,
            long_prefill_threshold=engine_cfg.get("long_prefill_threshold"),
            long_bucket_step=engine_cfg.get("long_bucket_step"),
            chunked_prefill_size=engine_cfg.get("chunked_prefill"),
            prefill_segments_per_decode=engine_cfg.get(
                "prefill_segments_per_decode", 2
            ),
            prefill_stall_timeout=engine_cfg.get("prefill_stall_timeout"),
            speculation=engine_cfg.get("speculation"),
            spec_k=int(engine_cfg.get("spec_k", 4)),
            spec_ngram=int(engine_cfg.get("spec_ngram", 2)),
            spec_sampling=bool(engine_cfg.get("spec_sampling", True)),
            # draft-tree verify rows (docs/spec_decode_trees.md): aux
            # engine.spec_tree branches each verify row's k-draft budget
            # across up to engine.spec_branch root continuations (needs
            # speculation + a paged cache — the constructor validates at
            # ENDPOINT LOAD; tree rows engage under the ragged scheduler)
            spec_tree=bool(engine_cfg.get("spec_tree", False)),
            spec_branch=int(engine_cfg.get("spec_branch", 2)),
            pipeline_chunk=int(engine_cfg.get("pipeline_chunk", 512)),
            # decode-pipeline depth (docs/pipelined_decode.md): None defers
            # to TPUSERVE_PIPELINE_DEPTH (default 2); 1 = serial decode
            pipeline_depth=(
                int(engine_cfg["pipeline_depth"])
                if engine_cfg.get("pipeline_depth")
                else None
            ),
            # ragged token-budget scheduler (docs/ragged_attention.md):
            # aux engine.scheduler = "ragged" puts chunked prefill and
            # decode in one launch per step, paced by
            # engine.step_token_budget; unset defers to TPUSERVE_SCHEDULER
            # (constructor validates values at ENDPOINT LOAD)
            scheduler=engine_cfg.get("scheduler"),
            step_token_budget=(
                int(engine_cfg["step_token_budget"])
                if engine_cfg.get("step_token_budget")
                else None
            ),
            # multi-step ragged decode rows (docs/ragged_attention.md):
            # max chained positions per decode row per mixed launch;
            # unset inherits decode_steps, 1 restores q=1 rows
            ragged_decode_steps=(
                int(engine_cfg["ragged_decode_steps"])
                if engine_cfg.get("ragged_decode_steps")
                else None
            ),
            lora_adapters=lora_adapters,
            prefix_cache=engine_cfg.get("prefix_cache"),
            prefix_block=int(engine_cfg.get("prefix_block", 64)),
            logprobs_k=int(engine_cfg.get("logprobs_k", 20)),
            prefix_cache_bytes=(
                int(float(engine_cfg["prefix_cache_mb"]) * (1 << 20))
                if engine_cfg.get("prefix_cache_mb")
                else None
            ),
            prefix_cache_pages=(
                int(engine_cfg["prefix_cache_pages"])
                if engine_cfg.get("prefix_cache_pages")
                else None
            ),
            # host-RAM KV tier (docs/kv_tiering.md): aux
            # engine.prefix_cache_host_pages preallocates that many host
            # pages behind the prefix cache (paged backend); eviction then
            # demotes instead of dropping. 0/unset disables.
            prefix_cache_host_pages=(
                int(engine_cfg["prefix_cache_host_pages"])
                if engine_cfg.get("prefix_cache_host_pages")
                else None
            ),
            # "auto" sizes the tier from /proc/meminfo at endpoint load
            # (clamped; HostTierAutoSizeError names unsupported platforms)
            prefix_cache_host_bytes=(
                "auto"
                if str(engine_cfg.get("prefix_cache_host_mb", "")
                       ).strip().lower() == "auto"
                else int(float(engine_cfg["prefix_cache_host_mb"]) * (1 << 20))
                if engine_cfg.get("prefix_cache_host_mb")
                else None
            ),
            tokenizer=self.tokenizer,  # guided decoding needs token bytes
            # request-lifecycle hardening (docs/robustness.md): production
            # defaults ON at the serving front — bounded admission and a
            # stall watchdog; aux engine.* knobs override, 0/false disables
            max_pending=self._lifecycle_knob(
                engine_cfg, "max_pending",
                max(16, 4 * int(engine_cfg.get("max_batch", 8))),
            ),
            queue_timeout=self._lifecycle_knob(engine_cfg, "queue_timeout", None),
            ttft_timeout=self._lifecycle_knob(engine_cfg, "ttft_timeout", None),
            total_timeout=self._lifecycle_knob(engine_cfg, "timeout", None),
            watchdog_interval=self._lifecycle_knob(
                engine_cfg, "watchdog_interval", 30.0
            ),
            # SLO-aware scheduling (docs/slo_scheduling.md): preemptible
            # batch lane + brownout controller; aux engine.* knobs override
            preempt_batch=bool(engine_cfg.get("preemption", True)),
            preempt_budget=int(engine_cfg.get("preempt_budget", 2)),
            starvation_floor=int(engine_cfg.get("starvation_floor", 8)),
            brownout=(
                bool(engine_cfg["brownout"])
                if "brownout" in engine_cfg
                else None
            ),
            brownout_batch_cap=int(engine_cfg.get("brownout_batch_cap", 32)),
            brownout_dwell=float(engine_cfg.get("brownout_dwell", 2.0)),
        )
        # startup shape warmup (llm/warmup.py, docs/static_analysis.md
        # TPU6xx): parsed BEFORE engine construction because the replica
        # group's ring-entry gate needs it. "startup" runs the cheap
        # per-bucket pass before the first request is admitted, "full"
        # runs the whole zero-recompile-certified sweep. Runs as ONE
        # shared task the first arrivals await.
        warmup_mode = str(engine_cfg.get("warmup", "off")).lower()
        if warmup_mode in ("1", "true", "on"):
            warmup_mode = "startup"
        if warmup_mode in ("0", "false"):
            warmup_mode = "off"
        if warmup_mode not in ("off", "startup", "full"):
            # fail at ENDPOINT LOAD, same contract as default_priority
            raise ValueError(
                "aux engine.warmup must be off/startup/full: got {!r}"
                .format(engine_cfg.get("warmup"))
            )
        # replica fleet (docs/replication.md): aux engine.replicas > 1
        # builds N identically configured engine replicas — ONE shared
        # params tree (read-only for compute), private KV pools — behind
        # the prefix-affine router (serving/replica_router.py). Validated
        # at ENDPOINT LOAD like default_priority: a bad value must fail
        # fast naming the knob, not 422 per request.
        raw_replicas = engine_cfg.get("replicas")
        if raw_replicas is None:
            n_replicas = 1
        else:
            try:
                n_replicas = int(raw_replicas)
                # a non-integral float (2.5) must not silently truncate
                if float(raw_replicas) != n_replicas:
                    raise ValueError(raw_replicas)
            except (TypeError, ValueError):
                raise ValueError(
                    "aux engine.replicas must be an integer >= 1: got {!r}"
                    .format(raw_replicas)
                )
        if not 1 <= n_replicas <= 16:
            raise ValueError(
                "aux engine.replicas must be in 1..16: got {}".format(
                    n_replicas
                )
            )
        # replica roles (docs/disaggregation.md): aux engine.replica_roles
        # dedicates replicas to prefill or decode and wires the KV
        # transport between them. Accepts a list or a comma string;
        # validated at ENDPOINT LOAD naming the knob.
        raw_roles = engine_cfg.get("replica_roles")
        replica_roles = None
        if raw_roles is not None:
            if isinstance(raw_roles, str):
                replica_roles = [
                    r.strip().lower() for r in raw_roles.split(",") if r.strip()
                ]
            elif isinstance(raw_roles, (list, tuple)):
                replica_roles = [str(r).strip().lower() for r in raw_roles]
            else:
                raise ValueError(
                    "aux engine.replica_roles must be a list (or comma "
                    "string) of prefill/decode/hybrid: got {!r}"
                    .format(raw_roles)
                )
            if n_replicas <= 1:
                raise ValueError(
                    "aux engine.replica_roles needs engine.replicas >= 2 "
                    "(got {} replica)".format(n_replicas)
                )
        # replica backend (docs/replication.md): "inprocess" = N engines
        # on this heap (the default), "process" = supervised worker
        # subprocesses (serving/process_replica.py). Validated at ENDPOINT
        # LOAD like every other fleet knob.
        replica_backend = str(
            engine_cfg.get("replica_backend", "inprocess")
        ).strip().lower()
        if replica_backend not in ("inprocess", "process"):
            raise ValueError(
                "aux engine.replica_backend must be inprocess/process: got "
                "{!r}".format(engine_cfg.get("replica_backend"))
            )
        # KV transport backend for disaggregated fleets
        # (docs/disaggregation.md): in-heap shared slabs or the socket
        # wire (llm/kv_wire.py). The process backend always uses sockets
        # (its workers have no shared heap).
        kv_transport_backend = str(
            engine_cfg.get("kv_transport_backend", "shared")
        ).strip().lower()
        if kv_transport_backend not in ("shared", "socket"):
            raise ValueError(
                "aux engine.kv_transport_backend must be shared/socket: "
                "got {!r}".format(engine_cfg.get("kv_transport_backend"))
            )
        if replica_backend == "process":
            if n_replicas <= 1:
                raise ValueError(
                    "aux engine.replica_backend=process needs "
                    "engine.replicas >= 2 (got {})".format(n_replicas)
                )
            if self._model_local_path:
                raise EndpointModelError(
                    "engine.replica_backend=process needs an engine.preset "
                    "model: worker processes rebuild the model from the "
                    "preset spec, and a local-path bundle cannot be "
                    "re-materialized in them yet (docs/replication.md)"
                )
            if lora_adapters:
                raise ValueError(
                    "engine.replica_backend=process does not support LoRA "
                    "adapters yet: the adapter registry is not shipped to "
                    "worker processes (docs/replication.md)"
                )
            from ..serving.process_replica import build_process_fleet

            # JSON-safe engine kwargs only: the worker rebuilds tokenizer-
            # dependent pieces (eos id rides along as plain data) and owns
            # its own mesh; anything unserializable stays parent-side
            worker_engine_cfg = {}
            for key, value in engine_kwargs.items():
                if key in ("tokenizer", "mesh", "lora_adapters"):
                    continue
                try:
                    json.dumps(value)
                except (TypeError, ValueError):
                    continue
                worker_engine_cfg[key] = value
            self.engine = build_process_fleet(
                {
                    "arch": engine_cfg.get("arch", "llama"),
                    "config": {
                        "preset": engine_cfg["preset"],
                        **(engine_cfg.get("config") or {}),
                        **cfg_overrides,
                    },
                    "seed": int(engine_cfg.get("seed", 0)),
                },
                worker_engine_cfg,
                n_replicas,
                roles=replica_roles,
                warmup_mode=warmup_mode,
                affinity_blocks=int(
                    engine_cfg.get("router_affinity_blocks", 4)
                ),
                spill_queue_depth=(
                    int(engine_cfg["router_spill_queue_depth"])
                    if engine_cfg.get("router_spill_queue_depth") is not None
                    else None
                ),
                spill_brownout_stage=int(
                    engine_cfg.get("router_spill_stage", 2)
                ),
                fleet_shed_stage=int(
                    engine_cfg.get("router_fleet_shed_stage", 3)
                ),
                kv_transport_pages=(
                    int(engine_cfg["kv_transport_pages"])
                    if engine_cfg.get("kv_transport_pages")
                    else None
                ),
            )
        elif n_replicas > 1:
            from .replica import ReplicaGroup

            engines = [
                # "rN" everywhere: the engine's replica id must match the
                # ring member names, registry keys, and /ready blocks so
                # one identity joins every surface (PromQL on(replica))
                LLMEngineCore(
                    bundle, params, replica="r{}".format(i), **engine_kwargs
                )
                for i in range(n_replicas)
            ]
            self.engine = ReplicaGroup(
                engines,
                warmup_mode=warmup_mode,
                affinity_blocks=int(
                    engine_cfg.get("router_affinity_blocks", 4)
                ),
                # `is not None`, not truthiness: an explicit 0 is the
                # documented "never spill on queue depth" spelling and
                # must not silently fall back to the max_pending default
                spill_queue_depth=(
                    int(engine_cfg["router_spill_queue_depth"])
                    if engine_cfg.get("router_spill_queue_depth") is not None
                    else None
                ),
                spill_brownout_stage=int(
                    engine_cfg.get("router_spill_stage", 2)
                ),
                fleet_shed_stage=int(
                    engine_cfg.get("router_fleet_shed_stage", 3)
                ),
                roles=replica_roles,
                kv_transport_pages=(
                    int(engine_cfg["kv_transport_pages"])
                    if engine_cfg.get("kv_transport_pages")
                    else None
                ),
                kv_transport_backend=kv_transport_backend,
            )
        else:
            self.engine = LLMEngineCore(bundle, params, **engine_kwargs)
        self._default_priority = str(
            engine_cfg.get("default_priority", "interactive")
        )
        if self._default_priority not in PRIORITY_CLASSES:
            # fail at ENDPOINT LOAD: a typo'd default would otherwise 422
            # every request that omits an explicit body priority
            raise ValueError(
                "aux engine.default_priority must be one of {}: got {!r}"
                .format("/".join(PRIORITY_CLASSES), self._default_priority)
            )
        self._warmup_full = warmup_mode == "full"
        self._warmup_needed = warmup_mode != "off"
        self._warmup_task = None
        self._model_name = self.endpoint.serving_url
        self._register_metrics(n_replicas > 1)
        return self.engine

    def _register_metrics(self, fleet: bool) -> None:
        """Prometheus wiring for the engine (or engine group). Every
        provider holds its engine WEAKLY: the process-lifetime registry
        must not pin an evicted endpoint's engine (params + KV = GBs of
        device memory) after the processor cache drops it.

        Fleet mode (docs/replication.md): each replica registers its OWN
        lifecycle/prefix-cache entry — the engine's payloads carry the
        replica id, so the lifecycle families grow a ``replica`` label —
        and the router registers the ring/route counters."""
        import weakref

        model = self._model_name

        def _lifecycle_provider(engine_ref, inject_model=None):
            def provider():
                engine = engine_ref()
                if engine is None:
                    return None
                s = engine.lifecycle_stats()
                if inject_model is not None:
                    s["model"] = inject_model
                return s
            return provider

        def _register_prefix(engine, key, replica=None):
            prefix = getattr(engine, "_prefix", None)
            if prefix is None or not hasattr(prefix, "stats"):
                # process-backend proxies expose a routing-only prefix
                # probe (block size + match lengths over the RPC) with no
                # stats surface — the real cache lives in the worker and
                # reports through the health RPC, not this collector
                return None
            # hit rate / shared pages / CoW visible from day one on the
            # same Prometheus registry the serving process already exports.
            # Fleet entries keep the real model label and carry `replica`
            # (same {model, replica} split as the lifecycle families)
            try:
                from ..statistics.metrics import register_prefix_cache

                pool = (
                    engine.paged_cache.pool
                    if engine.paged_cache is not None
                    else None
                )
                return register_prefix_cache(
                    engine._prefix, pool, key=key,
                    model=model if replica is not None else None,
                    replica=replica,
                )
            except Exception:
                return None  # registry unavailable etc.

        try:
            from ..statistics.metrics import (
                prune_engine_lifecycle,
                prune_prefix_caches,
                prune_replica_router,
                register_engine_lifecycle,
            )

            if not fleet:
                self._prefix_collector = _register_prefix(self.engine, model)
                self._lifecycle_collector = register_engine_lifecycle(
                    _lifecycle_provider(weakref.ref(self.engine)), key=model
                )
                # hot-reload hygiene: a previous FLEET incarnation of this
                # endpoint left per-replica entries (model@rN) that would
                # otherwise pin dead engines' caches and export frozen
                # series forever
                prune_prefix_caches(model, {model})
                prune_engine_lifecycle(model, {model})
                prune_replica_router(model, set())
                return
            keep = {
                "{}@{}".format(model, r.name) for r in self.engine.replicas
            }
            for replica in self.engine.replicas:
                key = "{}@{}".format(model, replica.name)
                self._prefix_collector = _register_prefix(
                    replica.engine, key, replica=replica.name
                )
                self._lifecycle_collector = register_engine_lifecycle(
                    _lifecycle_provider(
                        weakref.ref(replica.engine), inject_model=model
                    ),
                    key=key,
                )
            # prune a previous incarnation's bare-model entry and any
            # replicas beyond the current count (scale-down reload)
            prune_prefix_caches(model, keep)
            prune_engine_lifecycle(model, keep)
            from ..statistics.metrics import register_replica_router

            group_ref = weakref.ref(self.engine)

            def _router_provider():
                group = group_ref()
                if group is None:
                    return None
                s = group.router.stats()
                s["model"] = model
                return s

            self._router_collector = register_replica_router(
                _router_provider, key=model
            )
        except Exception:
            self._lifecycle_collector = None

    @staticmethod
    def _lifecycle_knob(engine_cfg: Dict[str, Any], key: str, default):
        """Aux-config override for a lifecycle knob: absent -> default,
        0/false/None -> disabled (the engine treats falsy as off)."""
        if key not in engine_cfg:
            return default
        value = engine_cfg[key]
        return float(value) if value else None

    def _load_lora_cfg(self, engine_cfg: Dict[str, Any]):
        """(config_overrides, adapters) from the aux engine.lora block."""
        from pathlib import Path

        lora_cfg = dict(engine_cfg.get("lora") or {})
        modules = dict(lora_cfg.get("modules") or {})
        if not modules:
            return {}, None
        from ..models import lora as lora_lib
        from ..models import llama as llama_mod

        # layer count comes from the model config (stored bundle meta or the
        # preset); adapters only apply to the llama-family decoder arch
        if self._model_local_path:
            from ..utils.files import read_json

            meta = read_json(Path(self._model_local_path) / "model_config.json")
            if not meta or meta.get("arch") != "llama":
                raise EndpointModelError(
                    "lora modules need a native llama-family bundle "
                    "(got {!r})".format((meta or {}).get("arch"))
                )
            model_cfg = llama_mod.resolve_config(dict(meta.get("config") or {}))
        else:
            model_cfg = llama_mod.resolve_config(
                {
                    "preset": engine_cfg.get("preset", ""),
                    **(engine_cfg.get("config") or {}),
                }
            )
        n_layers = int(model_cfg["n_layers"])
        adapters: Dict[str, Any] = {}
        for name, p in modules.items():
            path = Path(str(p))
            if not path.is_absolute() and self._model_local_path:
                cand = Path(self._model_local_path) / str(p)
                if cand.exists():
                    path = cand
            adapters[name] = lora_lib.load_adapter(path, n_layers)
        rank = int(lora_cfg.get("rank") or 0) or max(
            ab["a"].shape[-1] for tree in adapters.values() for ab in tree.values()
        )
        targets = list(
            lora_cfg.get("targets")
            or sorted({t for tree in adapters.values() for t in tree})
        )
        overrides = {
            "lora_rank": rank,
            "lora_targets": targets,
            "max_loras": max(len(adapters), int(lora_cfg.get("max_loras") or 0)),
        }
        return overrides, adapters

    # -- helpers ----------------------------------------------------------------

    def _adapter_for(self, body: Dict[str, Any]) -> Optional[str]:
        """OpenAI multi-LoRA routing: a `model` field naming a loaded adapter
        selects it; anything else (endpoint name, absent) is the base model."""
        name = body.get("model")
        if (
            self.engine is not None
            and name
            and name in getattr(self.engine, "_adapter_index", {})
        ):
            return name
        return None

    def _gen_request_from_body(self, body: Dict[str, Any], prompt_ids: List[int],
                               chat: bool = True, guided_override=None):
        """``guided_override``: a GuidedSpec that supersedes the body's own
        response_format/guided_* (tool_choice required/forced compiles the
        tool-call JSON into the grammar)."""
        from .engine import GenRequest

        logit_bias = body.get("logit_bias") or None
        if logit_bias is not None:
            logit_bias = {int(k): float(v) for k, v in logit_bias.items()}
        # logprobs: chat uses `logprobs: bool` + `top_logprobs: int`;
        # completions uses `logprobs: int` directly (0 = chosen token only)
        if chat:
            logprobs = (
                int(body.get("top_logprobs", 0) or 0)
                if body.get("logprobs")
                else None
            )
        else:
            raw_lp = body.get("logprobs")
            logprobs = int(raw_lp) if raw_lp is not None and raw_lp is not False else None
        request = GenRequest(
            prompt_ids=prompt_ids,
            max_new_tokens=int(body.get("max_tokens") or body.get("max_completion_tokens") or 128),
            temperature=float(body.get("temperature", 0.0) or 0.0),
            top_k=int(body.get("top_k", 0) or 0),
            top_p=float(body.get("top_p", 1.0) or 1.0),
            presence_penalty=float(body.get("presence_penalty", 0.0) or 0.0),
            frequency_penalty=float(body.get("frequency_penalty", 0.0) or 0.0),
            repetition_penalty=float(body.get("repetition_penalty", 1.0) or 1.0),
            seed=(int(body["seed"]) if body.get("seed") is not None else None),
            logit_bias=logit_bias,
            logprobs=logprobs,
            adapter=self._adapter_for(body),
            min_tokens=int(body.get("min_tokens", 0) or 0),
            guided=guided_override or self._guided_spec(body),
            # per-request lifecycle budgets (seconds); engine defaults apply
            # when absent. `timeout` bounds the WHOLE request (vLLM-style).
            total_timeout=(
                float(body["timeout"]) if body.get("timeout") is not None else None
            ),
            queue_timeout=(
                float(body["queue_timeout"])
                if body.get("queue_timeout") is not None
                else None
            ),
            ttft_timeout=(
                float(body["ttft_timeout"])
                if body.get("ttft_timeout") is not None
                else None
            ),
            # SLO class: body `priority` wins, else the endpoint's aux
            # engine.default_priority (docs/slo_scheduling.md); the engine's
            # validate() rejects unknown values with a 422
            priority=str(
                body.get("priority") or self._default_priority
            ),
        )
        # vLLM `return_tokens_as_token_ids`: logprob token strings become
        # "token_id:<id>" (API-layer formatting, so not a GenRequest field)
        request.tokens_as_ids = bool(body.get("return_tokens_as_token_ids"))
        return request

    @staticmethod
    def _guided_spec(body: Dict[str, Any]):
        """OpenAI ``response_format`` (json_object / json_schema) and
        vLLM-style ``guided_regex`` / ``guided_json`` extras -> GuidedSpec.
        Enforced on device by the engine's grammar tables (llm/guided.py);
        the reference's vLLM engine applies the same surface host-side."""
        import json as _json

        from .guided import GuidedSpec

        if body.get("guided_choice"):
            from .guided import _regex_escape_literal

            choices = body["guided_choice"]
            if not isinstance(choices, (list, tuple)) or not choices:
                raise ValueError("guided_choice must be a non-empty list")
            return GuidedSpec(
                "regex",
                "({})".format(
                    "|".join(_regex_escape_literal(str(c)) for c in choices)
                ),
            )
        if body.get("guided_regex"):
            return GuidedSpec("regex", str(body["guided_regex"]))
        if body.get("guided_json") is not None:
            schema = body["guided_json"]
            if isinstance(schema, str):
                schema = _json.loads(schema)
            # NO sort_keys: property DECLARATION order is part of the
            # grammar (json_schema_to_regex emits members in order);
            # sorting would reorder the forced output's keys
            return GuidedSpec("json_schema", _json.dumps(schema))
        rf = body.get("response_format")
        if not rf:
            return None
        if isinstance(rf, str):  # audio routes use a plain string; tolerate
            return None
        kind = rf.get("type")
        if kind == "json_object":
            return GuidedSpec("json_object")
        if kind == "json_schema":
            schema = (rf.get("json_schema") or {}).get("schema")
            if schema is None:
                raise ValueError("response_format.json_schema.schema missing")
            return GuidedSpec("json_schema", _json.dumps(schema))
        if kind in (None, "text"):
            return None
        raise ValueError("unsupported response_format type {!r}".format(kind))

    def _n_requests(self, body: Dict[str, Any], prompt_ids: List[int],
                    chat: bool = True, guided_override=None):
        """OpenAI `n` choices: n independent requests through the continuous
        batch; seeded requests offset the seed per choice so choices differ."""
        n = int(body.get("n", 1) or 1)
        if n < 1:
            raise ValueError("n must be >= 1")
        requests = []
        for i in range(n):
            r = self._gen_request_from_body(
                body, list(prompt_ids), chat=chat,
                guided_override=guided_override,
            )
            if r.seed is not None and i:
                r.seed = r.seed + i
            requests.append(r)
        return requests

    @staticmethod
    def _report_gen_stats(request, collect_fn) -> None:
        """TTFT + token counts into the sampled-stats pipeline (BASELINE.md
        per-endpoint metrics). Streaming handlers call this when the SSE body
        finishes — the router defers the stats packet to stream completion
        (StreamingOutput.on_complete), so streaming TTFT is recorded too."""
        if collect_fn is None:
            return
        stats = {"gen_tokens": request.produced, "prompt_tokens": request.prompt_len}
        if request.first_token_at is not None:
            stats["ttft"] = round(request.first_token_at - request.submitted_at, 6)
        collect_fn(stats)

    @staticmethod
    def _stops_from_body(body: Dict[str, Any]) -> List[str]:
        """OpenAI `stop`: str | [str] (stop TOKEN ids go through the engine;
        strings are matched on the decoded text here)."""
        stop = body.get("stop")
        if stop is None:
            return []
        if isinstance(stop, str):
            return [stop] if stop else []
        return [str(s) for s in stop if s]

    @staticmethod
    def _first_stop_hit(text: str, stops: List[str]) -> int:
        """Earliest index where any stop string occurs, or -1."""
        hits = [text.find(s) for s in stops]
        hits = [h for h in hits if h >= 0]
        return min(hits) if hits else -1

    def _tokens_covering(self, ids: List[int], n_chars: int) -> int:
        """Smallest token count whose decoded prefix covers n_chars — the
        single criterion both the streaming and non-streaming paths use to
        trim tokens/logprobs/usage to emitted text."""
        j = len(ids)
        while j > 0 and len(self.tokenizer.decode(ids[: j - 1])) >= n_chars:
            j -= 1
        return j

    async def _collect_text(self, request, stops: Optional[List[str]] = None) -> Dict[str, Any]:
        ids: List[int] = []
        stops = stops or []
        # stop scanning decodes only a TAIL window per token (a full decode
        # per token would be O(T^2) of blocking tokenizer work on the event
        # loop): every token decodes to >= 1 character, so a window of
        # max-stop-length + margin tokens always covers a newly completed
        # stop match; the full decode happens once, on hit or at the end
        window = (max(len(s) for s in stops) + 8) if stops else 0
        async for token in self.engine.generate(request):
            ids.append(token)
            if stops:
                tail = self.tokenizer.decode(ids[-window:])
                if self._first_stop_hit(tail, stops) >= 0:
                    # OpenAI semantics: output excludes the stop sequence
                    request.stopped_on_string = True
                    request.cancel()
                    text = self.tokenizer.decode(ids)
                    cut = self._first_stop_hit(text, stops)
                    if cut >= 0:
                        # trim ids to the tokens that produce text[:cut] so
                        # logprobs/usage stay consistent with the returned
                        # text (no phantom stop-sequence tokens)
                        ids = ids[: self._tokens_covering(ids, cut)]
                        request.produced = len(ids)
                        text = text[:cut]
                    return {
                        "text": text,
                        "ids": ids,
                        "finish_reason": "stop",
                    }
        eos = self.tokenizer.eos_token_id
        if ids and eos is not None and ids[-1] == eos:
            ids = ids[:-1]
            finish = "stop"
        else:
            finish = self._finish_reason(request)
        return {"text": self.tokenizer.decode(ids), "ids": ids, "finish_reason": finish}

    async def _stream_deltas(self, request, stops: Optional[List[str]] = None) -> AsyncIterator[Dict[str, Any]]:
        """Yields text deltas (incremental decode keeps multi-byte tokens
        correct for HF tokenizers). Stop strings hold back a potential
        stop-prefix tail so matched stops are never partially emitted."""
        ids: List[int] = []
        sent = ""
        stops = stops or []
        holdback = max((len(s) for s in stops), default=1) - 1
        eos = self.tokenizer.eos_token_id
        lp_cursor = 0

        def take_entries(upto_tokens: int):
            """Logprob entries for tokens [lp_cursor, upto_tokens) — only
            tokens whose text has actually been emitted, so streamed entries
            never lead the deltas or include held-back/stop tokens."""
            nonlocal lp_cursor
            new = request.logprob_entries[lp_cursor:upto_tokens]
            lp_cursor = max(lp_cursor, upto_tokens)
            return new

        def entries_for(n_chars: int):
            """None when logprobs are off — and then the token-boundary
            decode (O(ids)) is skipped entirely, so plain streams pay no
            extra detokenization."""
            if request.logprobs is None:
                return None
            return take_entries(self._tokens_covering(ids, n_chars))

        async for token in self.engine.generate(request):
            if eos is not None and token == eos:
                break
            ids.append(token)
            text = self.tokenizer.decode(ids)
            if text.endswith("�"):  # partial multi-byte sequence
                continue
            if stops:
                cut = self._first_stop_hit(text, stops)
                if cut >= 0:
                    request.stopped_on_string = True
                    request.cancel()
                    # trim to the tokens producing text[:cut] so streamed
                    # entries/usage match the non-streaming path exactly
                    j = self._tokens_covering(ids, cut)
                    del ids[j:]
                    request.produced = j
                    entries = take_entries(j) if request.logprobs is not None else None
                    if cut > len(sent) or entries:
                        yield {"delta": text[len(sent):cut],
                               "entries": entries}
                    return
                text = text[: len(text) - holdback] if holdback else text
            if len(text) > len(sent):
                prev = len(sent)
                sent = text
                yield {
                    "delta": text[prev:],
                    "entries": entries_for(len(text)),
                }
        # flush any held-back tail: if the final decode legitimately ends with
        # the replacement character (truncated multi-byte at stop, or a real
        # '�' from the tokenizer), it must not be silently dropped — and
        # logprob entries for tokens that decoded to EMPTY text (so no delta
        # ever carried them) still need a final (possibly empty-delta) piece
        text = self.tokenizer.decode(ids)
        if stops:
            cut = self._first_stop_hit(text, stops)
            if cut >= 0:
                request.stopped_on_string = True
                text = text[:cut]
                j = self._tokens_covering(ids, cut)
                del ids[j:]
                request.produced = j
        tail_entries = (
            take_entries(len(ids)) if request.logprobs is not None else None
        )
        if len(text) > len(sent) or tail_entries:
            yield {"delta": text[len(sent):], "entries": tail_entries}

    def _finish_reason(self, request) -> str:
        """OpenAI semantics: "length" covers BOTH max_tokens truncation and
        hitting the model's context limit."""
        if request.stopped_on_string:
            return "stop"
        if request.produced >= request.max_new_tokens:
            return "length"
        if request.prompt_len + request.produced >= self.engine.max_seq_len:
            return "length"
        return "stop"

    # -- logprob formatting (OpenAI chat vs completions shapes) ---------------

    def _token_str(self, tid: int) -> str:
        return self.tokenizer.decode([int(tid)])

    def _token_repr(self, tid: int, as_ids: bool) -> str:
        """vLLM return_tokens_as_token_ids: "token_id:<id>" instead of the
        decoded piece (lets callers distinguish tokens that decode alike)."""
        return "token_id:{}".format(int(tid)) if as_ids else self._token_str(tid)

    def _chat_lp_entries(self, entries: List[dict], k: int,
                         as_ids: bool = False) -> List[dict]:
        """Chat-shape logprob items from engine entries ({"id", "logprob",
        "top_ids", "top_logprobs"}); shared by the streaming chunks and the
        final response."""
        content = []
        for entry in entries:
            tok = self._token_repr(entry["id"], as_ids)
            tops = []
            for t, lp in zip(entry["top_ids"][:k], entry["top_logprobs"][:k]):
                ts = self._token_repr(t, as_ids)
                tops.append(
                    {"token": ts, "logprob": lp, "bytes": list(ts.encode("utf-8"))}
                )
            content.append(
                {
                    "token": tok,
                    "logprob": entry["logprob"],
                    "bytes": list(tok.encode("utf-8")),
                    "top_logprobs": tops,
                }
            )
        return content

    def _chat_logprobs(self, request, ids: List[int]) -> Dict[str, Any]:
        return {
            "content": self._chat_lp_entries(
                request.logprob_entries[: len(ids)], int(request.logprobs or 0),
                as_ids=getattr(request, "tokens_as_ids", False),
            )
        }

    def _completion_lp_entries(
        self, entries: List[dict], k: int, offset: int = 0,
        as_ids: bool = False,
    ) -> Tuple[Dict[str, Any], int]:
        """-> (logprobs dict, next text offset). text_offset tracks the
        EMITTED text even in token_id mode, so each token decodes once."""
        tokens, token_logprobs, top_logprobs, offsets = [], [], [], []
        for entry in entries:
            decoded = self._token_str(entry["id"])
            tokens.append(
                "token_id:{}".format(int(entry["id"])) if as_ids else decoded
            )
            token_logprobs.append(entry["logprob"])
            tops = {}
            for t, lp in zip(entry["top_ids"][:k], entry["top_logprobs"][:k]):
                tops[self._token_repr(t, as_ids)] = lp
            top_logprobs.append(tops)
            offsets.append(offset)
            offset += len(decoded)
        return {
            "tokens": tokens,
            "token_logprobs": token_logprobs,
            "top_logprobs": top_logprobs,
            "text_offset": offsets,
        }, offset

    def _completion_logprobs(self, request, ids: List[int]) -> Dict[str, Any]:
        lp, _ = self._completion_lp_entries(
            request.logprob_entries[: len(ids)], int(request.logprobs or 0),
            as_ids=getattr(request, "tokens_as_ids", False),
        )
        return lp

    async def _fanout_stream(self, requests, stops, collect_fn, *,
                             head, delta, finish, usage):
        """Shared multi-choice SSE core (chat and completions n>1
        streaming): one _stream_deltas pump per choice feeds a queue and
        chunks interleave by arrival, tagged with the OpenAI per-chunk
        index by the format callbacks. ``head``: pre-built leading chunks;
        ``delta(i, req, piece)`` / ``finish(i, req)`` format per-choice
        chunks; ``usage()`` returns the trailing usage chunk or None. The
        finally block frees every decode slot and reports stats on normal
        completion AND client disconnect."""
        queue: "asyncio.Queue" = asyncio.Queue()

        async def pump(i, req):
            try:
                async for piece in self._stream_deltas(req, stops):
                    await queue.put((i, "delta", piece))
                await queue.put((i, "finish", None))
            except Exception as ex:  # surfaced as an SSE error event
                await queue.put((i, "error", ex))

        tasks: List[asyncio.Task] = []
        try:
            for chunk in head:
                yield chunk
            tasks = [
                asyncio.get_running_loop().create_task(pump(i, r))
                for i, r in enumerate(requests)
            ]
            live = len(requests)
            while live:
                i, kind, payload = await queue.get()
                if kind == "error":
                    yield "data: {}\n\n".format(json.dumps(
                        {"error": {"message": str(payload),
                                   "type": type(payload).__name__}}
                    ))
                    yield "data: [DONE]\n\n"
                    return
                if kind == "finish":
                    yield finish(i, requests[i])
                    live -= 1
                    continue
                yield delta(i, requests[i], payload)
            tail = usage()
            if tail is not None:
                yield tail
            yield "data: [DONE]\n\n"
        finally:
            for t in tasks:
                t.cancel()
            for r in requests:
                r.cancel()
                self._report_gen_stats(r, collect_fn)

    def _prompt_logprobs_payload(self, prompt_ids: List[int], n_top: int,
                                 adapter: Optional[str], entries=None):
        """vLLM `prompt_logprobs` extension: per-prompt-position dicts of
        token_id -> {logprob, rank, decoded_token} (first position None —
        no conditional), the top-n_top tokens plus the actual token with
        its EXACT vocab rank. Blocking device work unless precomputed
        ``entries`` are passed (echo+prompt_logprobs shares ONE scoring
        pass) — call off-loop."""
        if entries is None:
            entries = self.engine.score_prompt(prompt_ids, adapter=adapter)
        out: List[Optional[dict]] = [None]
        for e, tok in zip(entries, prompt_ids[1:]):
            d: Dict[str, Any] = {}
            for r_i, (t, lp) in enumerate(
                zip(e["top_ids"][:n_top], e["top_logprobs"][:n_top])
            ):
                d[str(int(t))] = {
                    "logprob": lp,
                    "rank": r_i + 1,
                    "decoded_token": self._token_str(int(t)),
                }
            d.setdefault(str(int(tok)), {
                "logprob": e["logprob"],
                "rank": int(e["rank"]),
                "decoded_token": self._token_str(int(tok)),
            })
            out.append(d)
        return out

    def _prompt_logprobs_n(self, body: Dict[str, Any]) -> Optional[int]:
        """Parse + validate the vLLM `prompt_logprobs` knob (None = off)."""
        raw = body.get("prompt_logprobs")
        if raw is None or raw is False:
            return None
        n_top = int(raw)
        if n_top < 0:
            raise ValueError("prompt_logprobs must be >= 0")
        ceiling = int(self.engine.logprobs_k)
        if n_top > ceiling:
            raise ValueError(
                "prompt_logprobs {} exceeds the engine ceiling {}".format(
                    n_top, ceiling
                )
            )
        return n_top

    def _echo_prompt_logprobs(self, prompt_ids: List[int], request,
                              entries=None):
        """OpenAI `echo` + `logprobs`: the logprobs block starts with the
        PROMPT tokens — the first has null logprob/top (no conditional), the
        rest come from one teacher-forced scoring pass
        (engine.score_prompt, same LoRA adapter as the generation). Returns
        (lp dict, next text offset) for the generated entries to append to.
        Blocking device work unless precomputed ``entries`` are passed —
        callers run it via asyncio.to_thread."""
        k = int(request.logprobs or 0)
        as_ids = getattr(request, "tokens_as_ids", False)
        if entries is None:
            entries = self.engine.score_prompt(
                prompt_ids, adapter=getattr(request, "adapter", None)
            )
        first = self._token_repr(prompt_ids[0], as_ids)
        lp, offset = self._completion_lp_entries(
            entries, k, offset=len(self._token_str(prompt_ids[0])),
            as_ids=as_ids,
        )
        lp["tokens"].insert(0, first)
        lp["token_logprobs"].insert(0, None)
        lp["top_logprobs"].insert(0, None)
        lp["text_offset"].insert(0, 0)
        return lp, offset

    # -- OpenAI route handlers (dispatched by serve_type) -----------------------

    def _require_engine(self, route: str) -> None:
        if self.engine is None:
            raise EndpointModelError(
                "model {!r} does not support {} (encoder endpoint — task-gated "
                "like the reference's vLLM handler instantiation)".format(
                    self._model_name, route
                )
            )

    def _require_encoder(self, route: str) -> None:
        if self.encoder is None:
            raise EndpointModelError(
                "model {!r} does not support {} (decoder-only LLM endpoint; "
                "serve an encoder bundle or set aux_config engine.task)".format(
                    self._model_name, route
                )
            )

    async def v1_chat_completions(self, body: Dict[str, Any], state: dict, collect_fn=None):
        from .tools import (
            TOOL_TAG,
            parse_tool_calls,
            render_chat_with_tools,
            resolve_tool_choice,
            split_tag_holdback,
            strip_tool_blocks,
            tool_call_objects,
            tool_call_schema,
            validate_tools,
        )

        self._require_engine("v1/chat/completions")
        await self._ensure_warm()
        messages = body.get("messages") or []
        tool_mode, forced_tool = resolve_tool_choice(body)
        # OpenAI semantics: tool_choice "none" only prevents CALLING — the
        # definitions stay visible in the prompt (multi-turn histories
        # reference them); only parsing/constraint is disabled
        tools_render = validate_tools(body["tools"]) if body.get("tools") else []
        tools = tools_render if tool_mode != "none" else []
        tool_names = [t["name"] for t in tools]
        guided_override = None
        if tool_mode in ("required", "forced"):
            # arguments enforced BY CONSTRUCTION: the tool-call JSON
            # compiles into the on-device decode grammar (llm/guided.py)
            from .guided import GuidedSpec

            # no sort_keys: the grammar must force name BEFORE arguments
            # (sorting would make the model commit arguments first — in
            # multi-tool required mode, before the tool is even pinned)
            guided_override = GuidedSpec(
                "json_schema",
                json.dumps(tool_call_schema(tools, forced_tool)),
            )
        # OpenAI `parallel_tool_calls` (default true): false caps auto-mode
        # parses at ONE call (required/forced already emit exactly one by
        # grammar construction)
        single_call = body.get("parallel_tool_calls") is False
        prompt = render_chat_with_tools(self.tokenizer, messages, tools_render)
        # encode_chat: no special-token re-add — HF chat templates already
        # emit BOS in the template text (double-BOS degrades fidelity)
        prompt_ids = self.tokenizer.encode_chat(prompt)
        stops = self._stops_from_body(body)
        model = body.get("model", self._model_name)
        completion_id = _gen_id("chatcmpl")
        created = _now()
        # vLLM `response_role`: request body overrides the endpoint's
        # aux-config chat block; default matches OpenAI ("assistant")
        role = str(
            body.get("response_role")
            or self._chat_cfg.get("response_role")
            or "assistant"
        )
        include_usage = bool(
            (body.get("stream_options") or {}).get("include_usage")
        )

        def chat_chunk(choice, usage="omit"):
            chunk = {
                "id": completion_id, "object": "chat.completion.chunk",
                "created": created, "model": model,
                "choices": [choice] if choice is not None else [],
            }
            if include_usage:
                # OpenAI stream_options semantics: every chunk carries
                # usage: null; one final choices-less chunk carries totals
                chunk["usage"] = None if usage == "omit" else usage
            return "data: {}\n\n".format(json.dumps(chunk))

        plp_n = self._prompt_logprobs_n(body)  # validate BEFORE any device work
        if body.get("stream"):
            if plp_n is not None:
                # vLLM semantics: prompt_logprobs cannot stream
                raise EndpointModelError(
                    "prompt_logprobs is not supported with streaming"
                )
            n_stream = int(body.get("n", 1) or 1)
            if n_stream != 1:
                if tools:
                    # the tool-call sniff/buffer machinery is per-choice
                    # state; multi-choice streaming is supported for plain
                    # chat only
                    raise EndpointModelError(
                        "streaming chat with tools supports a single "
                        "choice (n=1)"
                    )
                requests = self._n_requests(
                    body, prompt_ids, guided_override=guided_override
                )
                for i, r in enumerate(requests):
                    self.engine.validate(r)
                    # shed/deadline BEFORE the 200 headers: a saturated
                    # engine answers 429/408, not a broken SSE body; the
                    # reserve accounts for this batch's own earlier choices
                    self.engine.check_admission(r, reserve=i)

                def chat_delta(i, req, piece):
                    choice = {"index": i,
                              "delta": {"content": piece["delta"]},
                              "finish_reason": None}
                    if piece.get("entries") is not None:
                        choice["logprobs"] = {
                            "content": self._chat_lp_entries(
                                piece["entries"], int(req.logprobs or 0),
                                as_ids=getattr(req, "tokens_as_ids", False),
                            )
                        }
                    return chat_chunk(choice)

                def chat_finish(i, req):
                    return chat_chunk({
                        "index": i, "delta": {},
                        "finish_reason": self._finish_reason(req),
                    })

                def chat_usage():
                    if not include_usage:
                        return None
                    total = sum(r.produced for r in requests)
                    return chat_chunk(None, usage={
                        "prompt_tokens": requests[0].prompt_len,
                        "completion_tokens": total,
                        "total_tokens": requests[0].prompt_len + total,
                    })

                return StreamingOutput(self._fanout_stream(
                    requests, stops, collect_fn,
                    head=[
                        chat_chunk({"index": i, "delta": {"role": role},
                                    "finish_reason": None})
                        for i in range(n_stream)
                    ],
                    delta=chat_delta, finish=chat_finish, usage=chat_usage,
                ))
            request = self._gen_request_from_body(
                body, prompt_ids, guided_override=guided_override
            )
            # validate BEFORE returning the stream — a late ValueError would
            # abort mid-SSE after the 200 headers are already sent; same for
            # load-shed/expired-deadline (429/408 precede the headers)
            self.engine.validate(request)
            self.engine.check_admission(request)
            # required/forced always buffers (output IS a tool call); auto
            # sniffs the first text for a call-shaped prefix and buffers
            # only then, so plain answers still stream token by token. A
            # guided response_format (json_object/json_schema) forces the
            # output to start with '{'/'[' without it being a tool call, so
            # sniffing would buffer the whole response — stream normally.
            buffer_all = tool_mode in ("required", "forced")
            sniffing = (
                tool_mode == "auto" and bool(tools)
                and request.guided is None
            )

            def call_prefix(text):
                """Could `text` still grow into a tool call? -> 'yes'
                (buffer to end), 'maybe' (keep sniffing), 'no' (flush)."""
                s = text.lstrip()
                if not s:
                    return "maybe"
                if s.startswith(("{", "[", "<tool_call>")):
                    return "yes"
                if "<tool_call>".startswith(s):
                    return "maybe"
                return "no"

            async def sse():
                # mode machine: "buffer" = withholding a (suspected or
                # certain) tool call to stream end; "sniff" = deciding from
                # the first text; "watch" = streaming live but holding back
                # a potential <tool_call> tag (hermes models narrate BEFORE
                # calling, so tags can appear mid-answer); "stream" = plain.
                mode = "buffer" if buffer_all else (
                    "sniff" if sniffing else "stream"
                )
                held: List[str] = []      # text awaiting the decision
                stashed: List[dict] = []  # logprob entries withheld with it
                watch_pending = ""        # tag holdback in watch mode

                def lp(entries):
                    return {"content": self._chat_lp_entries(
                        entries, int(request.logprobs or 0),
                        as_ids=getattr(request, "tokens_as_ids", False),
                    )}

                def content_chunk(text, entries):
                    choice = {"index": 0, "delta": {"content": text},
                              "finish_reason": None}
                    if entries:
                        # withheld entries attach to the chunk that finally
                        # emits their text — every entry is delivered once
                        choice["logprobs"] = lp(entries)
                    return chat_chunk(choice)

                def watch_emit(text):
                    """Emittable prefix of `text`; switches to buffer mode
                    when a full tool tag appears, holds back partial tags."""
                    nonlocal mode, watch_pending, held
                    watch_pending += text
                    idx = watch_pending.find(TOOL_TAG)
                    if idx >= 0:
                        emit = watch_pending[:idx]
                        held = [watch_pending[idx:]]
                        watch_pending = ""
                        mode = "buffer"
                        return emit
                    emit, watch_pending = split_tag_holdback(watch_pending)
                    return emit

                try:
                    yield chat_chunk({"index": 0,
                                      "delta": {"role": role},
                                      "finish_reason": None})
                    try:
                        async for piece in self._stream_deltas(request, stops):
                            entries = piece.get("entries") or []
                            if mode in ("buffer", "sniff"):
                                held.append(piece["delta"])
                                stashed.extend(entries)
                                if mode == "sniff":
                                    # verdict settles within the first few
                                    # non-space chars; 'yes' locks buffer
                                    # mode so long buffered outputs don't
                                    # re-join `held` on every delta
                                    verdict = call_prefix("".join(held))
                                    if verdict == "yes":
                                        mode = "buffer"
                                    elif verdict == "no":
                                        mode = "watch"
                                        text, held = "".join(held), []
                                        emit = watch_emit(text)
                                        if emit:
                                            yield content_chunk(emit, stashed)
                                            stashed = []
                                continue
                            if mode == "watch":
                                emit = watch_emit(piece["delta"])
                                stashed.extend(entries)
                                if emit:
                                    yield content_chunk(emit, stashed)
                                    stashed = []
                                continue
                            choice = {"index": 0,
                                      "delta": {"content": piece["delta"]},
                                      "finish_reason": None}
                            if piece.get("entries") is not None:
                                choice["logprobs"] = lp(piece["entries"])
                            yield chat_chunk(choice)
                    except Exception as ex:
                        yield "data: {}\n\n".format(json.dumps(
                            {"error": {"message": str(ex), "type": type(ex).__name__}}
                        ))
                        yield "data: [DONE]\n\n"
                        return
                    finish = self._finish_reason(request)
                    text = "".join(held) + watch_pending
                    calls = (
                        parse_tool_calls(text, tool_names)
                        if text and tools and finish != "length"
                        else None
                    )
                    if calls and single_call:
                        calls = calls[:1]
                    if calls:
                        # prose around <tool_call> blocks still streams as
                        # content (OpenAI allows content + tool_calls)
                        prose = (
                            strip_tool_blocks(text)
                            if TOOL_TAG in text else ""
                        )
                        if prose:
                            yield content_chunk(prose, stashed)
                            stashed = []
                        for ci, tc in enumerate(tool_call_objects(calls)):
                            first = {
                                "index": 0,
                                "delta": {"tool_calls": [{
                                    "index": ci, "id": tc["id"],
                                    "type": "function",
                                    "function": {
                                        "name": tc["function"]["name"],
                                        "arguments": "",
                                    },
                                }]},
                                "finish_reason": None,
                            }
                            if ci == 0 and stashed:
                                first["logprobs"] = lp(stashed)
                                stashed = []
                            yield chat_chunk(first)
                            yield chat_chunk({
                                "index": 0,
                                "delta": {"tool_calls": [{
                                    "index": ci,
                                    "function": {"arguments":
                                                 tc["function"]["arguments"]},
                                }]},
                                "finish_reason": None,
                            })
                        finish = "tool_calls"
                    elif text:
                        yield content_chunk(text, stashed)
                        stashed = []
                    yield chat_chunk({"index": 0, "delta": {},
                                      "finish_reason": finish})
                    if include_usage:
                        yield chat_chunk(None, usage={
                            "prompt_tokens": request.prompt_len,
                            "completion_tokens": request.produced,
                            "total_tokens": request.prompt_len
                            + request.produced,
                        })
                    yield "data: [DONE]\n\n"
                finally:
                    # runs on normal completion AND on client disconnect
                    # (GeneratorExit): free the decode slot early and record
                    # streaming TTFT/token stats at stream end
                    request.cancel()
                    self._report_gen_stats(request, collect_fn)

            return StreamingOutput(sse())

        requests = self._n_requests(body, prompt_ids,
                                    guided_override=guided_override)
        results = await asyncio.gather(
            *[self._collect_text(r, stops) for r in requests]
        )
        for r in requests:
            self._report_gen_stats(r, collect_fn)
        # vLLM prompt_logprobs extension: one scoring pass, shared by choices
        plp_payload = None
        if plp_n is not None:
            plp_payload = await asyncio.to_thread(
                self._prompt_logprobs_payload, prompt_ids, plp_n,
                requests[0].adapter,
            )
        choices = []
        for i, (r, res) in enumerate(zip(requests, results)):
            choice = {
                "index": i,
                "message": {"role": role, "content": res["text"]},
                "finish_reason": res["finish_reason"],
                "logprobs": (
                    self._chat_logprobs(r, res["ids"])
                    if r.logprobs is not None
                    else None
                ),
            }

            # a body-supplied guided response_format pins the OUTPUT shape —
            # the JSON answer is the deliverable, not a tool call; skipping
            # the parse keeps stream and non-stream responses identical
            # (streaming disables its call sniff under the same condition)
            parse_ok = tool_mode in ("required", "forced") or r.guided is None
            if tools and parse_ok and res["finish_reason"] != "length":
                calls = parse_tool_calls(res["text"], tool_names)
                if calls and single_call:
                    calls = calls[:1]
                if calls:
                    # hermes-style prose around the <tool_call> blocks is
                    # kept as content (OpenAI allows content + tool_calls)
                    prose = (
                        strip_tool_blocks(res["text"])
                        if TOOL_TAG in res["text"] else ""
                    )
                    choice["message"] = {
                        "role": role,
                        "content": prose or None,
                        "tool_calls": tool_call_objects(calls),
                    }
                    choice["finish_reason"] = "tool_calls"
            choices.append(choice)
        out = {
            "id": completion_id,
            "object": "chat.completion",
            "created": created,
            "model": model,
            "choices": choices,
            # OpenAI semantics: the prompt counts once regardless of n
            "usage": {
                "prompt_tokens": requests[0].prompt_len,
                "completion_tokens": sum(r.produced for r in requests),
                "total_tokens": requests[0].prompt_len
                + sum(r.produced for r in requests),
            },
        }
        if plp_payload is not None:
            # vLLM ChatCompletionResponse shape: prompt_logprobs is a
            # TOP-LEVEL response field (per-choice is the completions shape)
            out["prompt_logprobs"] = plp_payload
        return out

    def _check_token_ids(self, ids: List[int]) -> List[int]:
        core = self.engine if self.engine is not None else self.encoder
        vocab = int(core.bundle.config.get("vocab_size", 0))
        for t in ids:
            if not (0 <= int(t) < vocab):
                raise ValueError(
                    "token id {} out of range for vocab size {}".format(t, vocab)
                )
        return [int(t) for t in ids]

    def _encode_prompts(self, prompt) -> List[List[int]]:
        """OpenAI completions `prompt` polymorphism: str | [str] | [int] |
        [[int]] — token-id forms pass through (range-checked, not re-encoded)."""
        if isinstance(prompt, str):
            return [self.tokenizer.encode(prompt)]
        if isinstance(prompt, list):
            if not prompt:
                return [self.tokenizer.encode("")]
            if all(isinstance(p, int) for p in prompt):
                return [self._check_token_ids(prompt)]
            if all(isinstance(p, list) for p in prompt):
                return [self._check_token_ids(p) for p in prompt]
            return [self.tokenizer.encode(str(p)) for p in prompt]
        return [self.tokenizer.encode(str(prompt))]

    async def v1_completions(self, body: Dict[str, Any], state: dict, collect_fn=None):
        self._require_engine("v1/completions")
        await self._ensure_warm()
        if body.get("suffix") is not None:
            # vLLM rejects suffix explicitly — even "" — (fill-in-middle
            # needs a FIM-trained model + template); silent ignoring would
            # return a continuation the client believes is an infill.
            # Checked before prompt tokenization: doomed requests pay no
            # host work and report THIS error, not a downstream one.
            raise EndpointModelError(
                "suffix is not supported (no fill-in-middle template)"
            )
        prompt_id_lists = self._encode_prompts(body.get("prompt") or "")
        stops = self._stops_from_body(body)
        model = body.get("model", self._model_name)
        completion_id = _gen_id("cmpl")
        created = _now()

        plp_n = self._prompt_logprobs_n(body)  # validate BEFORE any device work
        if body.get("stream") and plp_n is not None:
            # vLLM semantics: prompt_logprobs cannot stream (checked before
            # the max_tokens=0 short-circuit so that path can't bypass it)
            raise EndpointModelError(
                "prompt_logprobs is not supported with streaming"
            )
        raw_max = body.get("max_tokens", body.get("max_completion_tokens"))
        if raw_max is not None and int(raw_max) == 0:
            # OpenAI's canonical prompt-scoring call: echo + logprobs +
            # max_tokens 0 returns the scored prompt and generates nothing
            # (the falsy-zero would otherwise fall through to the default
            # budget and bill 128 unasked-for tokens)
            return await self._zero_completion(body, prompt_id_lists, model,
                                               completion_id, created,
                                               collect_fn, plp_n)

        if body.get("stream"):
            if len(prompt_id_lists) != 1:
                raise EndpointModelError(
                    "streaming completions support a single prompt per request"
                )
            stream_n = int(body.get("n", 1) or 1)
            if (
                body.get("best_of") is not None
                and int(body["best_of"]) != stream_n
            ):
                # OpenAI: a server-side candidate pool cannot stream (which
                # choice to emit is unknown until the end); best_of == n
                # degenerates to plain n and may stream
                raise EndpointModelError(
                    "best_of must equal n when streaming"
                )
            stream_requests = self._n_requests(body, prompt_id_lists[0],
                                               chat=False)
            for i, r in enumerate(stream_requests):
                self.engine.validate(r)
                self.engine.check_admission(r, reserve=i)

            include_usage = bool(
                (body.get("stream_options") or {}).get("include_usage")
            )

            def cmpl_chunk(choices, usage="omit"):
                chunk = {
                    "id": completion_id, "object": "text_completion",
                    "created": created, "model": model, "choices": choices,
                }
                if include_usage:
                    chunk["usage"] = None if usage == "omit" else usage
                return "data: {}\n\n".format(json.dumps(chunk))

            echo = bool(body.get("echo"))

            lp_offsets = [0] * stream_n

            def cmpl_delta(i, req, piece):
                choice = {"index": i, "text": piece["delta"],
                          "finish_reason": None}
                if piece.get("entries") is not None:
                    lp, lp_offsets[i] = self._completion_lp_entries(
                        piece["entries"], int(req.logprobs or 0),
                        offset=lp_offsets[i],
                        as_ids=getattr(req, "tokens_as_ids", False),
                    )
                    choice["logprobs"] = lp
                return cmpl_chunk([choice])

            def cmpl_finish(i, req):
                return cmpl_chunk(
                    [{"index": i, "text": "",
                      "finish_reason": self._finish_reason(req)}]
                )

            def cmpl_usage():
                if not include_usage:
                    return None
                total = sum(r.produced for r in stream_requests)
                return cmpl_chunk([], usage={
                    "prompt_tokens": stream_requests[0].prompt_len,
                    "completion_tokens": total,
                    "total_tokens": stream_requests[0].prompt_len + total,
                })

            async def sse():
                head = []
                if echo:
                    # OpenAI echo semantics: the prompt text arrives as
                    # each choice's first chunk (logprob entries scored
                    # ONCE off-loop; choices share the prompt)
                    prompt_text = self.tokenizer.decode(prompt_id_lists[0])
                    echo_lp = None
                    if stream_requests[0].logprobs is not None:
                        echo_lp, off = await asyncio.to_thread(
                            self._echo_prompt_logprobs,
                            prompt_id_lists[0], stream_requests[0],
                        )
                        lp_offsets[:] = [off] * stream_n
                    for i in range(stream_n):
                        first = {"index": i, "text": prompt_text,
                                 "finish_reason": None}
                        if echo_lp is not None:
                            first["logprobs"] = {
                                k: list(v) for k, v in echo_lp.items()
                            }
                        head.append(cmpl_chunk([first]))
                async for chunk in self._fanout_stream(
                    stream_requests, stops, collect_fn,
                    head=head, delta=cmpl_delta, finish=cmpl_finish,
                    usage=cmpl_usage,
                ):
                    yield chunk

            return StreamingOutput(sse())

        # n choices per prompt, all generated concurrently through the
        # continuous batch (OpenAI batched-prompt semantics: choice index is
        # prompt-major, prompt_idx * n + choice_idx). vLLM `best_of`:
        # generate best_of candidates per prompt server-side, return the
        # top n ranked by cumulative logprob; every candidate's tokens
        # count toward usage (OpenAI billing semantics).
        n = int(body.get("n", 1) or 1)
        best_of = int(body.get("best_of") or n)
        if best_of < n:
            raise ValueError("best_of must be >= n")
        cand_body = dict(body, n=best_of) if best_of != n else body
        requests: List[Any] = []
        for ids in prompt_id_lists:
            requests.extend(self._n_requests(cand_body, ids, chat=False))
        # ranking needs per-token chosen logprobs; when the user did not ask
        # for them (None OR false — the request parser treats both as off),
        # collect them internally and omit them from the reply
        lp_internal = best_of != n and requests[0].logprobs is None
        if lp_internal:
            for r in requests:
                r.logprobs = 0
        results = await asyncio.gather(
            *[self._collect_text(r, stops) for r in requests]
        )
        for r in requests:
            self._report_gen_stats(r, collect_fn)
        if best_of != n:
            def cumulative_lp(i: int) -> float:
                # +1 keeps the finishing token's entry (EOS is stripped
                # from ids): vLLM's cumulative_logprob includes it, and
                # without it an immediate-EOS candidate would sum an empty
                # slice to 0.0 and outrank every real completion
                ents = requests[i].logprob_entries[: len(results[i]["ids"]) + 1]
                return sum(e["logprob"] for e in ents)

            sel: List[int] = []
            for p in range(len(prompt_id_lists)):
                grp = list(range(p * best_of, (p + 1) * best_of))
                grp.sort(key=cumulative_lp, reverse=True)
                sel.extend(grp[:n])
        else:
            sel = list(range(len(requests)))
        echo = bool(body.get("echo"))
        # echo+logprobs: ONE teacher-forced scoring pass per distinct
        # prompt (choices share it), off the event loop — the jitted
        # forward (plus a first-hit compile) would stall every concurrent
        # stream if run inline
        # echo+logprobs and prompt_logprobs share ONE teacher-forced scoring
        # pass per distinct prompt; the payload build (O(prompt x top_k)
        # tokenizer decodes) stays off the event loop with it
        echo_lp: Dict[int, Any] = {}
        plp: Dict[int, Any] = {}
        want_echo_lp = (
            echo and requests[0].logprobs is not None and not lp_internal
        )
        if want_echo_lp or plp_n is not None:
            def build_payloads(ids, req0):
                entries = self.engine.score_prompt(ids, req0.adapter)
                e = (
                    self._echo_prompt_logprobs(ids, req0, entries=entries)
                    if want_echo_lp
                    else None
                )
                q = (
                    self._prompt_logprobs_payload(
                        ids, plp_n, req0.adapter, entries=entries
                    )
                    if plp_n is not None
                    else None
                )
                return e, q

            for p, ids in enumerate(prompt_id_lists):
                e, q = await asyncio.to_thread(
                    build_payloads, ids, requests[p * best_of]
                )
                if e is not None:
                    echo_lp[p] = e
                if q is not None:
                    plp[p] = q
        choices = []
        for i, idx in enumerate(sel):
            r, res = requests[idx], results[idx]
            choice = {
                "index": i,
                "text": res["text"],
                "finish_reason": res["finish_reason"],
                "logprobs": (
                    self._completion_logprobs(r, res["ids"])
                    if r.logprobs is not None and not lp_internal
                    else None
                ),
            }
            if idx // best_of in plp:
                choice["prompt_logprobs"] = plp[idx // best_of]
            if echo:
                # OpenAI `echo`: the prompt text leads the output; with
                # logprobs, prompt-token entries lead the block (first one
                # null — no conditional)
                p_ids = requests[idx].prompt_ids
                choice["text"] = self.tokenizer.decode(p_ids) + res["text"]
                if idx // best_of in echo_lp:
                    lp0, off = echo_lp[idx // best_of]
                    lp = {k2: list(v2) for k2, v2 in lp0.items()}
                    gen_lp, _ = self._completion_lp_entries(
                        r.logprob_entries[: len(res["ids"])],
                        int(r.logprobs or 0), offset=off,
                        as_ids=getattr(r, "tokens_as_ids", False),
                    )
                    for key in ("tokens", "token_logprobs", "top_logprobs",
                                "text_offset"):
                        lp[key].extend(gen_lp[key])
                    choice["logprobs"] = lp
            choices.append(choice)
        prompt_tokens = sum(len(ids) for ids in prompt_id_lists)
        return {
            "id": completion_id,
            "object": "text_completion",
            "created": created,
            "model": model,
            "choices": choices,
            "usage": {
                "prompt_tokens": prompt_tokens,
                "completion_tokens": sum(r.produced for r in requests),
                "total_tokens": prompt_tokens + sum(r.produced for r in requests),
            },
        }

    async def _zero_completion(self, body, prompt_id_lists, model,
                               completion_id, created, collect_fn,
                               plp_n=None):
        """max_tokens=0 completions: no generation; echo/logprobs and
        prompt_logprobs still apply (per-prompt scoring passes off the
        event loop) — this IS the canonical prompt-scoring call."""
        echo = bool(body.get("echo"))
        n = int(body.get("n", 1) or 1)
        if n < 1:
            raise ValueError("n must be >= 1")
        choices = []
        for p, ids in enumerate(prompt_id_lists):
            if not ids:
                raise ValueError("prompt must not be empty")
            # a probe request runs the SAME validation (prompt length,
            # logprobs ceiling, guided config) every generating path runs —
            # this path must not 500 where those would 4xx
            probe = self._gen_request_from_body(body, list(ids), chat=False)
            probe.max_new_tokens = 1
            probe.prompt_len = len(ids)
            self.engine.validate(probe)
            text = self.tokenizer.decode(ids) if echo else ""
            lp = None
            plp_payload = None
            if (probe.logprobs is not None and echo) or plp_n is not None:
                def build_payloads(ids=ids, probe=probe):
                    entries = self.engine.score_prompt(ids, probe.adapter)
                    e = (
                        self._echo_prompt_logprobs(ids, probe,
                                                   entries=entries)
                        if probe.logprobs is not None and echo
                        else None
                    )
                    q = (
                        self._prompt_logprobs_payload(
                            ids, plp_n, probe.adapter, entries=entries
                        )
                        if plp_n is not None
                        else None
                    )
                    return e, q

                e, plp_payload = await asyncio.to_thread(build_payloads)
                if e is not None:
                    lp = e[0]
            if probe.logprobs is not None and lp is None:
                # logprobs without echo: nothing generated -> empty block
                lp = {"tokens": [], "token_logprobs": [],
                      "top_logprobs": [], "text_offset": []}
            for _ in range(n):
                choice = {
                    "index": len(choices),
                    "text": text,
                    "finish_reason": "length",
                    "logprobs": dict(lp) if lp is not None else None,
                }
                if plp_payload is not None:
                    choice["prompt_logprobs"] = plp_payload
                choices.append(choice)
        if collect_fn is not None:
            collect_fn({
                "gen_tokens": 0,
                "prompt_tokens": sum(len(i) for i in prompt_id_lists),
            })
        prompt_tokens = sum(len(i) for i in prompt_id_lists)
        out = {
            "id": completion_id,
            "object": "text_completion",
            "created": created,
            "model": model,
            "choices": choices,
            "usage": {
                "prompt_tokens": prompt_tokens,
                "completion_tokens": 0,
                "total_tokens": prompt_tokens,
            },
        }
        if body.get("stream"):
            include_usage = bool(
                (body.get("stream_options") or {}).get("include_usage")
            )

            async def sse():
                for ch in choices:
                    chunk = {
                        "id": completion_id, "object": "text_completion",
                        "created": created, "model": model, "choices": [ch],
                    }
                    if include_usage:
                        chunk["usage"] = None
                    yield "data: {}\n\n".format(json.dumps(chunk))
                if include_usage:
                    yield "data: {}\n\n".format(json.dumps({
                        "id": completion_id, "object": "text_completion",
                        "created": created, "model": model, "choices": [],
                        "usage": out["usage"],
                    }))
                yield "data: [DONE]\n\n"

            return StreamingOutput(sse())
        return out

    async def v1_models(self, body: Dict[str, Any], state: dict, collect_fn=None):
        data = [
            {
                "id": self._model_name,
                "object": "model",
                "created": _now(),
                "owned_by": "tpu-serving",
            }
        ]
        # loaded LoRA adapters list as models with a parent (vLLM-compatible
        # multi-LoRA discovery; select one via the request's `model` field)
        for name in getattr(self.engine, "adapter_names", []) or []:
            data.append(
                {
                    "id": name,
                    "object": "model",
                    "created": _now(),
                    "owned_by": "tpu-serving",
                    "parent": self._model_name,
                }
            )
        return {"object": "list", "data": data}

    async def version(self, body: Dict[str, Any], state: dict, collect_fn=None):
        """The 13th OpenAI route type (reference preprocess_service.py:890
        ``show_version`` → GET /serve/openai/version)."""
        from ..version import __version__

        return {"version": __version__}

    @property
    def _max_model_len(self) -> int:
        core = self.engine if self.engine is not None else self.encoder
        return core.max_seq_len if core is not None else 0

    async def v1_tokenize(self, body: Dict[str, Any], state: dict, collect_fn=None):
        ids = self.tokenizer.encode(str(body.get("prompt") or body.get("text") or ""))
        return {"tokens": ids, "count": len(ids), "max_model_len": self._max_model_len}

    async def v1_detokenize(self, body: Dict[str, Any], state: dict, collect_fn=None):
        ids = body.get("tokens") or []
        return {"prompt": self.tokenizer.decode([int(i) for i in ids])}

    # -- encoder routes (OpenAI embeddings API + vLLM-compatible extensions) --

    def _encode_texts(self, value) -> List[List[int]]:
        """OpenAI embeddings `input` polymorphism, same as completions
        `prompt`: str | [str] | [int] | [[int]]."""
        return self._encode_prompts(value)

    @staticmethod
    def _format_vec(vec, fmt: str):
        if fmt == "base64":
            import base64

            import numpy as _np

            return base64.b64encode(
                _np.asarray(vec, _np.float32).tobytes()
            ).decode("ascii")
        return [float(x) for x in vec]

    async def v1_embeddings(self, body: Dict[str, Any], state: dict, collect_fn=None):
        self._require_encoder("v1/embeddings")
        id_lists = self._encode_texts(body.get("input") or "")
        fmt = body.get("encoding_format", "float")
        if fmt not in ("float", "base64"):
            raise ValueError("encoding_format must be 'float' or 'base64'")
        dims = body.get("dimensions")
        if dims is not None:
            dims = int(dims)  # type/lower-bound BEFORE the device forward
            if dims < 1:
                raise ValueError("dimensions must be >= 1")
        vecs = await asyncio.to_thread(self.encoder.embed, id_lists)
        if dims is not None:
            # OpenAI `dimensions` (matryoshka truncation): keep the leading
            # dims and re-normalize so cosine similarity stays meaningful
            import numpy as _np

            full = len(vecs[0]) if len(vecs) else 0
            if full and dims > full:
                raise ValueError(
                    "dimensions must be in [1, {}]".format(full)
                )
            out_vecs = []
            for v in vecs:
                t = _np.asarray(v, _np.float32)[:dims]
                norm = float(_np.linalg.norm(t))
                out_vecs.append(t / norm if norm > 0 else t)
            vecs = out_vecs
        n_tokens = sum(len(ids) for ids in id_lists)
        if collect_fn is not None:
            collect_fn({"prompt_tokens": n_tokens, "n_inputs": len(id_lists)})
        return {
            "object": "list",
            "model": body.get("model", self._model_name),
            "data": [
                {
                    "object": "embedding",
                    "index": i,
                    "embedding": self._format_vec(vec, fmt),
                }
                for i, vec in enumerate(vecs)
            ],
            "usage": {"prompt_tokens": n_tokens, "total_tokens": n_tokens},
        }

    async def v1_pooling(self, body: Dict[str, Any], state: dict, collect_fn=None):
        """vLLM pooling API: raw per-token hidden states (or pooled vector)."""
        self._require_encoder("v1/pooling")
        id_lists = self._encode_texts(body.get("input") or "")
        per_token = body.get("return_token_states", False)
        if per_token:
            states = await asyncio.to_thread(self.encoder.token_states, id_lists)
            data = [
                {"object": "pooling", "index": i, "data": s.tolist()}
                for i, s in enumerate(states)
            ]
        else:
            vecs = await asyncio.to_thread(self.encoder.embed, id_lists)
            data = [
                {"object": "pooling", "index": i, "data": [float(x) for x in v]}
                for i, v in enumerate(vecs)
            ]
        n_tokens = sum(len(ids) for ids in id_lists)
        if collect_fn is not None:
            collect_fn({"prompt_tokens": n_tokens, "n_inputs": len(id_lists)})
        return {
            "object": "list",
            "model": body.get("model", self._model_name),
            "data": data,
            "usage": {"prompt_tokens": n_tokens, "total_tokens": n_tokens},
        }

    async def v1_classify(self, body: Dict[str, Any], state: dict, collect_fn=None):
        self._require_encoder("v1/classify")
        id_lists = self._encode_texts(body.get("input") or "")
        logits = await asyncio.to_thread(self.encoder.classify, id_lists)
        import numpy as _np

        probs = _np.exp(logits - logits.max(axis=-1, keepdims=True))
        probs = probs / probs.sum(axis=-1, keepdims=True)
        labels = self.endpoint_labels()
        data = []
        for i in range(len(id_lists)):
            idx = int(_np.argmax(probs[i]))
            data.append(
                {
                    "index": i,
                    "label": labels[idx] if idx < len(labels) else str(idx),
                    "probs": [float(p) for p in probs[i]],
                    "num_classes": int(probs.shape[-1]),
                }
            )
        n_tokens = sum(len(ids) for ids in id_lists)
        if collect_fn is not None:
            collect_fn({"prompt_tokens": n_tokens, "n_inputs": len(id_lists)})
        return {
            "object": "list",
            "model": body.get("model", self._model_name),
            "data": data,
            "usage": {"prompt_tokens": n_tokens, "total_tokens": n_tokens},
        }

    def endpoint_labels(self) -> List[str]:
        aux = self.endpoint.auxiliary_cfg if isinstance(self.endpoint.auxiliary_cfg, dict) else {}
        return list((aux.get("engine") or {}).get("labels") or [])

    def _score_pairs_body(self, body: Dict[str, Any]):
        t1, t2 = body.get("text_1"), body.get("text_2")
        if t1 is None or t2 is None:
            raise ValueError("score requests need text_1 and text_2")
        list1 = t1 if isinstance(t1, list) else [t1]
        list2 = t2 if isinstance(t2, list) else [t2]
        if len(list1) == 1 and len(list2) > 1:
            list1 = list1 * len(list2)
        if len(list2) == 1 and len(list1) > 1:
            list2 = list2 * len(list1)
        if len(list1) != len(list2):
            raise ValueError("text_1/text_2 lengths do not broadcast")
        # cross-encoder: segments encoded bare; EncoderCore assembles the
        # [CLS] a [SEP] b [SEP] pair itself. bi-encoder: full encodes.
        bare = self.encoder.is_cross_encoder
        pairs = [
            (
                self.tokenizer.encode(str(a), add_bos=not bare),
                self.tokenizer.encode(str(b), add_bos=not bare),
            )
            for a, b in zip(list1, list2)
        ]
        return pairs

    async def v1_score(self, body: Dict[str, Any], state: dict, collect_fn=None):
        """vLLM score API: pairwise relevance of text_1 x text_2."""
        self._require_encoder("v1/score")
        pairs = self._score_pairs_body(body)
        scores = await asyncio.to_thread(self.encoder.score_pairs, pairs)
        n_tokens = sum(len(a) + len(b) for a, b in pairs)
        if collect_fn is not None:
            collect_fn({"prompt_tokens": n_tokens, "n_inputs": len(pairs)})
        return {
            "object": "list",
            "model": body.get("model", self._model_name),
            "data": [
                {"object": "score", "index": i, "score": s}
                for i, s in enumerate(scores)
            ],
            "usage": {"prompt_tokens": n_tokens, "total_tokens": n_tokens},
        }

    async def v1_rerank(self, body: Dict[str, Any], state: dict, collect_fn=None):
        """Jina/Cohere-compatible rerank (vLLM do_rerank semantics): score
        each document against the query, return top_n descending."""
        self._require_encoder("v1/rerank")
        query = body.get("query")
        documents = body.get("documents") or []
        if query is None or not documents:
            raise ValueError("rerank requests need query and documents")
        doc_texts = []
        for i, d in enumerate(documents):
            if isinstance(d, dict):
                text = d.get("text", d.get("content"))
                if not isinstance(text, str):
                    raise ValueError(
                        "documents[{}] needs a string 'text' field".format(i)
                    )
                doc_texts.append(text)
            else:
                doc_texts.append(str(d))
        bare = self.encoder.is_cross_encoder
        q_ids = self.tokenizer.encode(str(query), add_bos=not bare)
        doc_ids = [self.tokenizer.encode(t, add_bos=not bare) for t in doc_texts]
        scores = await asyncio.to_thread(self.encoder.rerank, q_ids, doc_ids)
        order = sorted(range(len(scores)), key=lambda i: scores[i], reverse=True)
        top_n = int(body.get("top_n") or len(order))
        results = [
            {
                "index": i,
                "document": {"text": doc_texts[i]},
                "relevance_score": scores[i],
            }
            for i in order[:top_n]
        ]
        n_tokens = len(q_ids) + sum(len(d) for d in doc_ids)
        if collect_fn is not None:
            collect_fn({"prompt_tokens": n_tokens, "n_inputs": len(doc_ids)})
        return {
            "id": _gen_id("rerank"),
            "model": body.get("model", self._model_name),
            "results": results,
            "usage": {"total_tokens": n_tokens},
        }

    # -- audio routes (OpenAI transcription API; whisper-family bundles) ------

    def _require_audio(self, route: str) -> None:
        if self.audio is None:
            raise EndpointModelError(
                "model {!r} does not support {} (serve a speech bundle — "
                "arch 'whisper' — on this endpoint)".format(self._model_name, route)
            )

    def _audio_pcm(self, body: Dict[str, Any]):
        from ..ops.audio import decode_wav

        data = body.get("file")
        if isinstance(data, str):
            import base64

            try:
                data = base64.b64decode(data)
            except Exception:
                raise ValueError("'file' must be WAV bytes or base64-encoded WAV")
        if not isinstance(data, (bytes, bytearray)):
            raise ValueError(
                "audio requests need a 'file' field (multipart upload or "
                "base64 WAV in JSON)"
            )
        return decode_wav(bytes(data), target_rate=self.audio.sampling_rate)

    async def _audio_route(self, body, collect_fn, task: str, route: str):
        self._require_audio(route)
        pcm = self._audio_pcm(body)
        duration = round(len(pcm) / self.audio.sampling_rate, 3)
        verbose = body.get("response_format") == "verbose_json"
        # verbose_json decodes WITH timestamp conditioning (segments need
        # the marker tokens); the plain paths keep the faster
        # <|notimestamps|> prompt. Bundles converted before the timestamp
        # vocabulary was recorded fall back to text-only verbose output.
        with_ts = verbose and self.audio.timestamp_begin is not None
        # batching front door: concurrent same-(task, timestamps) requests
        # share one encode/decode pass (AudioCore micro-batcher)
        windows = await self.audio.transcribe_windows_async(
            pcm, task, timestamps=with_ts
        )
        ids = [t for w in windows for t in w]
        ts_begin = self.audio.timestamp_begin
        text_ids = (
            [t for t in ids if t < ts_begin] if ts_begin is not None else ids
        )
        text = self.tokenizer.decode(text_ids)
        if collect_fn is not None:
            collect_fn(
                {
                    "gen_tokens": len(ids),
                    "audio_seconds": duration,
                }
            )
        if body.get("response_format") == "text":
            from ..serving.responses import TextOutput

            return TextOutput(text)
        out = {"text": text}
        if verbose:
            out.update(
                task=task,
                duration=duration,
                language=body.get("language"),
            )
            if with_ts:
                segments = self.audio.parse_segments(windows, duration)
                for seg in segments:
                    seg["text"] = self.tokenizer.decode(seg["tokens"])
                granularities = body.get("timestamp_granularities") or ["segment"]
                if isinstance(granularities, str):
                    granularities = [granularities]
                if "segment" in granularities:
                    out["segments"] = segments
                if "word" in granularities:
                    # whisper-faithful word timing: DTW over cross-attention
                    # alignment heads; proportional interpolation only when
                    # the bundle lacks the alignment surface or the DTW
                    # pass fails (docs/parity.md Whisper row)
                    words = None
                    try:
                        words = await asyncio.to_thread(
                            self.audio.words_dtw, pcm, windows,
                            self.tokenizer, task,
                        )
                    except Exception:
                        # degraded word timing must leave a signal — a
                        # silent fall-back would hide a persistently
                        # failing DTW pass that still pays encode+align
                        logging.getLogger(__name__).warning(
                            "word-timestamp DTW failed; falling back to "
                            "proportional interpolation",
                            exc_info=True,
                        )
                    out["words"] = (
                        words
                        if words is not None
                        else self.audio.words_from_segments(segments)
                    )
        return out

    async def v1_audio_transcriptions(self, body, state, collect_fn=None):
        return await self._audio_route(
            body or {}, collect_fn, "transcribe", "v1/audio/transcriptions"
        )

    async def v1_audio_translations(self, body, state, collect_fn=None):
        return await self._audio_route(
            body or {}, collect_fn, "translate", "v1/audio/translations"
        )

    # -- phases -----------------------------------------------------------------

    async def preprocess(self, body: Any, state: dict, collect_fn=None) -> Any:
        if self._preprocess is not None and hasattr(self._preprocess, "preprocess"):
            out = self._preprocess.preprocess(body, state, collect_fn)
            if asyncio.iscoroutine(out):
                out = await out
            return out
        return body

    async def process(self, data: Any, state: dict, collect_fn=None) -> Any:
        """Plain /serve/{endpoint} POST: non-streaming chat completion for
        decoder endpoints, embeddings for encoder endpoints."""
        if self.engine is None and self.encoder is not None:
            return await self.v1_embeddings(data or {}, state, collect_fn)
        return await self.v1_chat_completions(data or {}, state, collect_fn)

    async def postprocess(self, data: Any, state: dict, collect_fn=None) -> Any:
        if self._preprocess is not None and hasattr(self._preprocess, "postprocess"):
            out = self._preprocess.postprocess(data, state, collect_fn)
            if asyncio.iscoroutine(out):
                out = await out
            return out
        return data
