"""OpenAI-compatible LLM engine endpoint ("llm" engine type).

Route-surface parity with the reference's vLLM engine handlers
(clearml_serving/serving/preprocess_service.py:836-1095): chat completions
(+SSE streaming), completions, models, tokenize/detokenize — dispatched through
the router's ``/serve/openai/{type}`` path exactly like the reference
(serve_type "v1/chat/completions" → ``v1_chat_completions``). Capability-gated
routes (embeddings / pooling / classify / score / audio) return a clean
backend error when the loaded model does not support them, mirroring the
reference's task/runner gating (preprocess_service.py:711-808).

The compute path is the continuous-batching engine in engine.py on TPU via
JAX — no CUDA, no vLLM.
"""

from __future__ import annotations

import asyncio
import json
import time
import uuid
from typing import Any, AsyncIterator, Dict, List, Optional

from ..engines.base import BaseEngineRequest, EndpointModelError, register_engine
from ..serving.responses import StreamingOutput
from .tokenizer import load_tokenizer

# engine.py / sampling.py import jax at module level; defer so registering the
# "llm" engine (CLI import path) stays jax-free.
if False:  # typing only
    from .engine import GenRequest, LLMEngineCore  # noqa: F401


def _now() -> int:
    return int(time.time())


def _gen_id(prefix: str) -> str:
    return "{}-{}".format(prefix, uuid.uuid4().hex[:24])


@register_engine("llm", modules=["jax", "flax"])
class LLMEngineRequest(BaseEngineRequest):
    """One continuous-batching engine per endpoint per process."""

    is_preprocess_async = True
    is_process_async = True
    is_postprocess_async = True

    def __init__(self, *args, **kwargs):
        self.engine = None
        self.tokenizer = None
        self._model_name = "model"
        super().__init__(*args, **kwargs)

    # -- loading --------------------------------------------------------------

    def _native_load(self) -> Any:
        import jax

        from ..engines.jax_engine import enable_persistent_compilation_cache, load_bundle
        from .. import models
        from .engine import LLMEngineCore

        enable_persistent_compilation_cache()
        aux = self.endpoint.auxiliary_cfg if isinstance(self.endpoint.auxiliary_cfg, dict) else {}
        engine_cfg = dict(aux.get("engine") or {})

        if self._model_local_path:
            bundle, params = load_bundle(self._model_local_path)
        elif engine_cfg.get("preset"):
            # weightless demo/bench mode: architecture preset, random params
            bundle = models.build_model(
                "llama", {"preset": engine_cfg["preset"], **(engine_cfg.get("config") or {})}
            )
            params = bundle.init(jax.random.PRNGKey(int(engine_cfg.get("seed", 0))))
        else:
            raise EndpointModelError(
                "llm endpoint {!r} needs a model bundle or aux_config engine.preset".format(
                    self.endpoint.serving_url
                )
            )

        mesh = None
        if aux.get("mesh"):
            from ..parallel import mesh_from_aux_cfg

            if len(jax.devices()) > 1:
                mesh = mesh_from_aux_cfg(aux)

        self.tokenizer = load_tokenizer(
            self._model_local_path, int(bundle.config.get("vocab_size", 0))
        )
        self.engine = LLMEngineCore(
            bundle,
            params,
            max_batch=int(engine_cfg.get("max_batch", 8)),
            max_seq_len=int(engine_cfg.get("max_seq_len", bundle.config.get("max_seq_len", 2048))),
            prefill_buckets=engine_cfg.get("prefill_buckets"),
            mesh=mesh,
            eos_token_id=self.tokenizer.eos_token_id,
            decode_steps=int(engine_cfg.get("decode_steps", 4)),
            quantize=engine_cfg.get("quantize"),
            cache_mode=engine_cfg.get("cache", "dense"),
            page_size=int(engine_cfg.get("page_size", 16)),
            num_pages=int(engine_cfg["num_pages"]) if engine_cfg.get("num_pages") else None,
        )
        self._model_name = self.endpoint.serving_url
        return self.engine

    # -- helpers ----------------------------------------------------------------

    def _gen_request_from_body(self, body: Dict[str, Any], prompt_ids: List[int]):
        from .engine import GenRequest

        return GenRequest(
            prompt_ids=prompt_ids,
            max_new_tokens=int(body.get("max_tokens") or body.get("max_completion_tokens") or 128),
            temperature=float(body.get("temperature", 0.0) or 0.0),
            top_k=int(body.get("top_k", 0) or 0),
            top_p=float(body.get("top_p", 1.0) or 1.0),
        )

    @staticmethod
    def _report_gen_stats(request, collect_fn) -> None:
        """TTFT + token counts into the sampled-stats pipeline (BASELINE.md
        per-endpoint metrics). Streaming handlers call this when the SSE body
        finishes — the router defers the stats packet to stream completion
        (StreamingOutput.on_complete), so streaming TTFT is recorded too."""
        if collect_fn is None:
            return
        stats = {"gen_tokens": request.produced, "prompt_tokens": request.prompt_len}
        if request.first_token_at is not None:
            stats["ttft"] = round(request.first_token_at - request.submitted_at, 6)
        collect_fn(stats)

    async def _collect_text(self, request) -> Dict[str, Any]:
        ids: List[int] = []
        async for token in self.engine.generate(request):
            ids.append(token)
        eos = self.tokenizer.eos_token_id
        if ids and eos is not None and ids[-1] == eos:
            ids = ids[:-1]
            finish = "stop"
        else:
            finish = self._finish_reason(request)
        return {"text": self.tokenizer.decode(ids), "ids": ids, "finish_reason": finish}

    async def _stream_deltas(self, request) -> AsyncIterator[Dict[str, Any]]:
        """Yields text deltas (incremental decode keeps multi-byte tokens
        correct for HF tokenizers)."""
        ids: List[int] = []
        sent = ""
        eos = self.tokenizer.eos_token_id
        async for token in self.engine.generate(request):
            if eos is not None and token == eos:
                break
            ids.append(token)
            text = self.tokenizer.decode(ids)
            if text.endswith("�"):  # partial multi-byte sequence
                continue
            if len(text) > len(sent):
                yield {"delta": text[len(sent):]}
                sent = text
        # flush any held-back tail: if the final decode legitimately ends with
        # the replacement character (truncated multi-byte at stop, or a real
        # '�' from the tokenizer), it must not be silently dropped
        text = self.tokenizer.decode(ids)
        if len(text) > len(sent):
            yield {"delta": text[len(sent):]}

    def _finish_reason(self, request) -> str:
        """OpenAI semantics: "length" covers BOTH max_tokens truncation and
        hitting the model's context limit."""
        if request.produced >= request.max_new_tokens:
            return "length"
        if request.prompt_len + request.produced >= self.engine.max_seq_len:
            return "length"
        return "stop"

    # -- OpenAI route handlers (dispatched by serve_type) -----------------------

    async def v1_chat_completions(self, body: Dict[str, Any], state: dict, collect_fn=None):
        messages = body.get("messages") or []
        prompt = self.tokenizer.apply_chat_template(messages)
        # encode_chat: no special-token re-add — HF chat templates already
        # emit BOS in the template text (double-BOS degrades fidelity)
        prompt_ids = self.tokenizer.encode_chat(prompt)
        request = self._gen_request_from_body(body, prompt_ids)
        model = body.get("model", self._model_name)
        completion_id = _gen_id("chatcmpl")
        created = _now()

        if body.get("stream"):
            # validate BEFORE returning the stream — a late ValueError would
            # abort mid-SSE after the 200 headers are already sent
            self.engine.validate(request)

            async def sse():
                try:
                    first = {
                        "id": completion_id, "object": "chat.completion.chunk",
                        "created": created, "model": model,
                        "choices": [{"index": 0, "delta": {"role": "assistant"},
                                     "finish_reason": None}],
                    }
                    yield "data: {}\n\n".format(json.dumps(first))
                    try:
                        async for piece in self._stream_deltas(request):
                            chunk = {
                                "id": completion_id, "object": "chat.completion.chunk",
                                "created": created, "model": model,
                                "choices": [{"index": 0, "delta": {"content": piece["delta"]},
                                             "finish_reason": None}],
                            }
                            yield "data: {}\n\n".format(json.dumps(chunk))
                    except Exception as ex:
                        yield "data: {}\n\n".format(json.dumps(
                            {"error": {"message": str(ex), "type": type(ex).__name__}}
                        ))
                        yield "data: [DONE]\n\n"
                        return
                    done = {
                        "id": completion_id, "object": "chat.completion.chunk",
                        "created": created, "model": model,
                        "choices": [{"index": 0, "delta": {},
                                     "finish_reason": self._finish_reason(request)}],
                    }
                    yield "data: {}\n\n".format(json.dumps(done))
                    yield "data: [DONE]\n\n"
                finally:
                    # runs on normal completion AND on client disconnect
                    # (GeneratorExit): free the decode slot early and record
                    # streaming TTFT/token stats at stream end
                    request.cancel()
                    self._report_gen_stats(request, collect_fn)

            return StreamingOutput(sse())

        result = await self._collect_text(request)
        self._report_gen_stats(request, collect_fn)
        return {
            "id": completion_id,
            "object": "chat.completion",
            "created": created,
            "model": model,
            "choices": [
                {
                    "index": 0,
                    "message": {"role": "assistant", "content": result["text"]},
                    "finish_reason": result["finish_reason"],
                }
            ],
            "usage": {
                "prompt_tokens": request.prompt_len,
                "completion_tokens": request.produced,
                "total_tokens": request.prompt_len + request.produced,
            },
        }

    def _check_token_ids(self, ids: List[int]) -> List[int]:
        vocab = int(self.engine.bundle.config.get("vocab_size", 0))
        for t in ids:
            if not (0 <= int(t) < vocab):
                raise ValueError(
                    "token id {} out of range for vocab size {}".format(t, vocab)
                )
        return [int(t) for t in ids]

    def _encode_prompts(self, prompt) -> List[List[int]]:
        """OpenAI completions `prompt` polymorphism: str | [str] | [int] |
        [[int]] — token-id forms pass through (range-checked, not re-encoded)."""
        if isinstance(prompt, str):
            return [self.tokenizer.encode(prompt)]
        if isinstance(prompt, list):
            if not prompt:
                return [self.tokenizer.encode("")]
            if all(isinstance(p, int) for p in prompt):
                return [self._check_token_ids(prompt)]
            if all(isinstance(p, list) for p in prompt):
                return [self._check_token_ids(p) for p in prompt]
            return [self.tokenizer.encode(str(p)) for p in prompt]
        return [self.tokenizer.encode(str(prompt))]

    async def v1_completions(self, body: Dict[str, Any], state: dict, collect_fn=None):
        prompt_id_lists = self._encode_prompts(body.get("prompt") or "")
        model = body.get("model", self._model_name)
        completion_id = _gen_id("cmpl")
        created = _now()

        if body.get("stream"):
            if len(prompt_id_lists) != 1:
                raise EndpointModelError(
                    "streaming completions support a single prompt per request"
                )
            request = self._gen_request_from_body(body, prompt_id_lists[0])
            self.engine.validate(request)

            async def sse():
                try:
                    try:
                        async for piece in self._stream_deltas(request):
                            chunk = {
                                "id": completion_id, "object": "text_completion",
                                "created": created, "model": model,
                                "choices": [{"index": 0, "text": piece["delta"],
                                             "finish_reason": None}],
                            }
                            yield "data: {}\n\n".format(json.dumps(chunk))
                    except Exception as ex:
                        yield "data: {}\n\n".format(json.dumps(
                            {"error": {"message": str(ex), "type": type(ex).__name__}}
                        ))
                        yield "data: [DONE]\n\n"
                        return
                    final = {
                        "id": completion_id, "object": "text_completion",
                        "created": created, "model": model,
                        "choices": [{"index": 0, "text": "",
                                     "finish_reason": self._finish_reason(request)}],
                    }
                    yield "data: {}\n\n".format(json.dumps(final))
                    yield "data: [DONE]\n\n"
                finally:
                    # normal completion AND client disconnect (GeneratorExit):
                    # free the decode slot early, record streaming stats
                    request.cancel()
                    self._report_gen_stats(request, collect_fn)

            return StreamingOutput(sse())

        # one choice per prompt, generated concurrently through the continuous
        # batch (OpenAI batched-prompt semantics)
        requests = [
            self._gen_request_from_body(body, ids) for ids in prompt_id_lists
        ]
        results = await asyncio.gather(*[self._collect_text(r) for r in requests])
        for r in requests:
            self._report_gen_stats(r, collect_fn)
        return {
            "id": completion_id,
            "object": "text_completion",
            "created": created,
            "model": model,
            "choices": [
                {"index": i, "text": res["text"], "finish_reason": res["finish_reason"]}
                for i, res in enumerate(results)
            ],
            "usage": {
                "prompt_tokens": sum(r.prompt_len for r in requests),
                "completion_tokens": sum(r.produced for r in requests),
                "total_tokens": sum(r.prompt_len + r.produced for r in requests),
            },
        }

    async def v1_models(self, body: Dict[str, Any], state: dict, collect_fn=None):
        return {
            "object": "list",
            "data": [
                {
                    "id": self._model_name,
                    "object": "model",
                    "created": _now(),
                    "owned_by": "tpu-serving",
                }
            ],
        }

    async def v1_tokenize(self, body: Dict[str, Any], state: dict, collect_fn=None):
        ids = self.tokenizer.encode(str(body.get("prompt") or body.get("text") or ""))
        return {"tokens": ids, "count": len(ids), "max_model_len": self.engine.max_seq_len}

    async def v1_detokenize(self, body: Dict[str, Any], state: dict, collect_fn=None):
        ids = body.get("tokens") or []
        return {"prompt": self.tokenizer.decode([int(i) for i in ids])}

    # capability-gated routes (model family does not support them yet)
    async def _unsupported(self, route: str):
        raise EndpointModelError(
            "model {!r} does not support {} (decoder-only LLM endpoint)".format(
                self._model_name, route
            )
        )

    async def v1_embeddings(self, body, state, collect_fn=None):
        await self._unsupported("v1/embeddings")

    async def v1_pooling(self, body, state, collect_fn=None):
        await self._unsupported("v1/pooling")

    async def v1_classify(self, body, state, collect_fn=None):
        await self._unsupported("v1/classify")

    async def v1_score(self, body, state, collect_fn=None):
        await self._unsupported("v1/score")

    async def v1_rerank(self, body, state, collect_fn=None):
        await self._unsupported("v1/rerank")

    async def v1_audio_transcriptions(self, body, state, collect_fn=None):
        await self._unsupported("v1/audio/transcriptions")

    async def v1_audio_translations(self, body, state, collect_fn=None):
        await self._unsupported("v1/audio/translations")

    # -- phases -----------------------------------------------------------------

    async def preprocess(self, body: Any, state: dict, collect_fn=None) -> Any:
        if self._preprocess is not None and hasattr(self._preprocess, "preprocess"):
            out = self._preprocess.preprocess(body, state, collect_fn)
            if asyncio.iscoroutine(out):
                out = await out
            return out
        return body

    async def process(self, data: Any, state: dict, collect_fn=None) -> Any:
        """Plain /serve/{endpoint} POST == non-streaming chat completion."""
        return await self.v1_chat_completions(data or {}, state, collect_fn)

    async def postprocess(self, data: Any, state: dict, collect_fn=None) -> Any:
        if self._preprocess is not None and hasattr(self._preprocess, "postprocess"):
            out = self._preprocess.postprocess(data, state, collect_fn)
            if asyncio.iscoroutine(out):
                out = await out
            return out
        return data
