"""Automatic prefix caching: reuse prompt-prefix KV across requests.

The reference's LLM engine (vLLM, reference serving/preprocess_service.py
§2.8) ships automatic prefix caching — chat workloads share a system prompt,
so the prefix's KV is computed once and reused, cutting TTFT for every
follow-up request. This is the TPU-native equivalent for the dense-slot
engine (llm/engine.py):

- Prefixes are **block-aligned** (default 64 tokens, like vLLM's block size):
  a prompt stores its KV up to the largest block multiple that is strictly
  shorter than the prompt (the final token must always be processed live to
  produce the first-token logits).
- Entries live in an LRU keyed by the EXACT token prefix (and the LoRA
  adapter index — K/V projections differ per adapter). Values are jax device
  arrays sliced from the admission's prefill cache: immutable, shareable
  across slots, and resident in HBM until evicted.
- On admission, the longest stored prefix is assembled into the mini-cache
  (one dynamic_update_slice) and only the remainder runs through
  ``prefill_chunk`` — an admission that shares a 1000-token system prompt
  prefills only its tail.

Thread-safety: admissions run in worker threads; a single mutex guards the
OrderedDict. The stored arrays themselves are immutable jax buffers.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple


class PrefixKVCache:
    """LRU of block-aligned prompt-prefix KV buffers.

    Bounded by BOTH entry count and bytes: a stored prefix holds
    ~2·L·P·Hkv·D·itemsize of HBM (hundreds of MB for a multi-thousand-token
    prefix on an 8B model), so an entry-only bound could exceed a chip's HBM
    next to the weights and the decode cache. Default byte budget: 2 GiB.
    """

    def __init__(self, max_entries: int = 32, block: int = 64,
                 max_bytes: Optional[int] = None):
        self.block = int(block)
        self.max_entries = int(max_entries)
        self.max_bytes = int(max_bytes) if max_bytes else 2 << 30
        self._entries: "OrderedDict[Tuple, Dict[str, Any]]" = OrderedDict()
        self._bytes = 0
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0

    def _key(self, ids: List[int], p: int, lora: int) -> Tuple:
        return (lora, tuple(ids[:p]))

    def longest_prefix_len(self, n_tokens: int) -> int:
        """Largest storable/lookupable prefix for a prompt of n tokens: the
        final token always computes live (its logits seed decoding)."""
        return ((n_tokens - 1) // self.block) * self.block

    def lookup(self, ids: List[int], lora: int = 0) -> Optional[Dict[str, Any]]:
        """Longest stored entry matching a block-aligned prefix of ``ids``.
        Returns {"k": [L,1,P,H,D], "v": ..., "len": P} or None."""
        with self._lock:
            p = self.longest_prefix_len(len(ids))
            while p >= self.block:
                entry = self._entries.get(self._key(ids, p, lora))
                if entry is not None:
                    self._entries.move_to_end(self._key(ids, p, lora))
                    self.hits += 1
                    return entry
                p -= self.block
            self.misses += 1
            return None

    def store(self, ids: List[int], lora: int, bufs: Dict[str, Any]) -> None:
        """Store the prompt's largest block-aligned prefix KV. ``bufs`` maps
        cache buffer keys (k/v, plus k_scale/v_scale on the int8-KV path) to
        the admission's prefill buffers [L, 1, bucket, ...] with the token
        dim at axis 2 (any bucket >= the prefix length); slices are taken
        here."""
        p = self.longest_prefix_len(len(ids))
        if p < self.block:
            return
        key = self._key(ids, p, lora)
        with self._lock:
            if key in self._entries:
                self._entries.move_to_end(key)
                return
            slices = {name: buf[:, :, :p] for name, buf in bufs.items()}
            nbytes = sum(
                int(getattr(s, "nbytes", 0)) for s in slices.values()
            )
            if nbytes > self.max_bytes:
                return  # a single over-budget prefix is never worth the HBM
            entry = dict(slices)
            entry["len"] = p
            entry["nbytes"] = nbytes
            self._entries[key] = entry
            self._bytes += nbytes
            while (
                len(self._entries) > self.max_entries
                or self._bytes > self.max_bytes
            ):
                _, old = self._entries.popitem(last=False)
                self._bytes -= old["nbytes"]

    @property
    def total_bytes(self) -> int:
        return self._bytes

    def __len__(self) -> int:
        return len(self._entries)
