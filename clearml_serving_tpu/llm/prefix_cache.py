"""Radix-tree prefix caching: block-granular prompt-prefix KV reuse.

The reference's LLM engine (vLLM, reference serving/preprocess_service.py
§2.8) ships automatic prefix caching — chat workloads share a system prompt,
so the prefix's KV is computed once and reused, cutting TTFT for every
follow-up request. This module is the TPU-native equivalent for BOTH cache
backends of llm/engine.py, organized as a radix tree over block-granular
token runs (SGLang's RadixAttention layout; see docs/prefix_caching.md):

- Each tree edge carries exactly one ``block`` of tokens (default 64, like
  vLLM's block size); children are keyed by the block's token tuple, so a
  probe walks the tree block by block — O(prompt) TOTAL hashing per lookup,
  not O(prompt) per candidate length like the previous exact-match LRU.
- ANY shared block run matches (partial-prefix hits): two prompts sharing
  only their first k blocks reuse exactly those k blocks, whether or not
  that exact prefix was ever stored as a whole.
- Payloads are per-backend:
  * dense — immutable jax KV slices ([L, 1, block, Hkv, D] per node), which
    the engine concatenates and assembles into the admission mini cache;
  * paged — page ids in the engine's ``PagePool`` with CACHE-HELD refcounts:
    storing a prompt's prefix takes a reference on the admitting slot's own
    pages (zero copies), and a hit maps those pages straight into the new
    slot's page table (zero copies again). Pages are physically freed only
    when the last referencing slot AND the cache let go.
- Eviction is LRU at LEAF granularity (a node is evictable only once no
  longer prefix depends on it), under three budgets: node count, bytes, and
  (paged) pages. Evicting a paged node only drops the cache's reference —
  a page a live slot still maps keeps its data until that slot frees.
- Trees are namespaced per LoRA adapter index (K/V projections differ per
  adapter), exactly like the previous cache's key tuple.

The prompt's final token is never cached: it must always compute live to
produce the first-token logits (``longest_prefix_len``).

Host-RAM tier (docs/kv_tiering.md): with a ``backend`` (the PagedKVCache
whose ``host_tier`` was enabled), eviction under the DEVICE budgets DEMOTES
instead of dropping — the victim's pages (int8 + scale rows) copy into
host-tier pages and the node flips to a host payload; only the HOST budgets
drop runs for real (host-tier leaf LRU). Pinned runs stay resident in both
senses: never demoted, never host-dropped. A lookup whose matched run has a
demoted suffix PROMOTES it in place — fresh device pages are allocated, the
async host→device DMA is enqueued BEFORE the new page ids become visible to
any consumer (ordering then holds by data dependency on the pool handles —
the tier fence; llm/schedule_explorer.py's ``tier_promotion`` scenario),
and the hit returns tagged ``tier="host"``. A failed promotion (pool
pressure, injected ``engine.kv.promote`` fault) falls back to the resident
prefix and drops the demoted suffix — recompute, never a leak. Demotion
candidates come from the RESIDENT FRONTIER (resident nodes with no resident
children), so along any root→leaf path the demoted nodes are always a
suffix; ``store_pages`` preserves that invariant by re-onlining demoted
path nodes BY REFERENCE to the admitting slot's own pages (zero copies)
before attaching new resident children below them.

Thread-safety: admissions run in worker threads; one mutex guards the tree.
Dense payloads are immutable jax buffers. Paged lookups PIN the returned
pages (refcount bump under the tree lock) so a concurrent eviction cannot
free them between lookup and slot mapping; the engine releases the pin once
the pages are mapped (or the admission fails).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Tuple

from . import faults
from . import lifecycle_ledger as _ledger


class _Node:
    """One block-granular edge of the radix tree."""

    __slots__ = (
        "parent", "edge", "children", "bufs", "pages", "nbytes", "last_used",
        "pinned", "host_pages",
    )

    def __init__(self, parent: Optional["_Node"], edge: Tuple[int, ...]):
        self.parent = parent
        self.edge = edge          # this node's block of tokens
        self.children: Dict[Tuple[int, ...], "_Node"] = {}
        self.bufs: Optional[Dict[str, Any]] = None   # dense payload
        self.pages: Optional[List[int]] = None       # paged payload (HBM)
        # host-tier payload (docs/kv_tiering.md): host page ids; EXACTLY one
        # of pages/host_pages is set on a tiered paged node (the sanitizer's
        # two-tier invariant)
        self.host_pages: Optional[List[int]] = None
        self.nbytes = 0
        self.last_used = 0
        # pin_run() holds: eviction must not drop this node (the engine
        # promised a preempted request its history replays from the cache)
        self.pinned = 0


class RadixPrefixCache:
    """Radix tree of block-aligned prompt-prefix KV.

    Bounded by node count AND bytes (and pages on the paged backend): a
    cached block holds ~2·L·block·Hkv·D·itemsize of HBM, so an entry-only
    bound could exceed a chip's HBM next to the weights and the decode
    cache. Default byte budget: 2 GiB.

    ``pool``/``page_bytes`` select the paged backend: payloads are page ids
    refcounted against ``pool`` instead of dense KV slices.
    """

    # lock-discipline registry (tpuserve-analyze TPU301): tree state is
    # mutated only under self._lock; helpers called with it held annotate
    # their def line
    __guarded_by__ = {
        "_lock": ("_roots", "_leaf_nodes", "_n_nodes", "_clock",
                  "_frontier", "_n_resident", "_host_pages", "_host_bytes"),
    }

    # ownership-discipline registry (tpuserve-analyze TPU7xx,
    # docs/static_analysis.md): lookup hits carry a pin the caller MUST
    # release(); pin_run holds survive until unpin_run. Mirrored in
    # analyze/rules_lifecycle.py LIFECYCLE_REGISTRY (consistency-tested).
    __acquires__ = {
        "lookup_pages": {"resource": "prefix.hit",
                         "releases": ("release", "_release_prefix_hit"),
                         "drops": ("uncount_hit",)},
        "pin_run": {"resource": "prefix.resume_pin",
                    "releases": ("unpin_run", "_release_resume_pin")},
    }

    def __init__(
        self,
        max_nodes: int = 512,
        block: int = 64,
        max_bytes: Optional[int] = None,
        *,
        max_pages: Optional[int] = None,
        pool=None,
        page_bytes: int = 0,
        # host-RAM tier (docs/kv_tiering.md): the PagedKVCache whose
        # host_tier was enabled; None keeps the legacy drop-on-evict
        # behavior byte-identical
        backend=None,
        host_max_pages: Optional[int] = None,
        host_max_bytes: Optional[int] = None,
        host_max_nodes: Optional[int] = None,
    ):
        self.block = int(block)
        self.max_nodes = int(max_nodes)
        self.max_bytes = int(max_bytes) if max_bytes else 2 << 30
        self.max_pages = int(max_pages) if max_pages else None
        self._pool = pool
        self._page_bytes = int(page_bytes)
        self._backend = backend
        self._host = getattr(backend, "host_tier", None) if backend else None
        if backend is not None and self._host is None:
            raise ValueError(
                "tiering backend given but its host tier is not enabled "
                "(PagedKVCache.enable_host_tier)"
            )
        # host-tier budgets: page budget defaults to the tier's capacity;
        # bytes/nodes unbounded unless set
        self.host_max_pages = (
            min(int(host_max_pages), self._host.num_pages)
            if (self._host is not None and host_max_pages)
            else (self._host.num_pages if self._host is not None else None)
        )
        self.host_max_bytes = int(host_max_bytes) if host_max_bytes else None
        self.host_max_nodes = int(host_max_nodes) if host_max_nodes else None
        self._roots: Dict[int, _Node] = {}
        # incrementally maintained leaf set (nodes with no children): LRU
        # eviction scans candidates directly instead of a whole-tree DFS per
        # evicted node (O(leaves) vs O(nodes) with the lock held)
        self._leaf_nodes: set = set()
        # resident frontier (tiered paged backend only): resident nodes with
        # no resident children — the demotion candidates. Because only
        # frontier nodes demote and store_pages re-onlines demoted path
        # nodes before attaching below them, demoted nodes are always a
        # path SUFFIX.
        self._frontier: set = set()
        self._bytes = 0
        self._pages = 0
        self._host_bytes = 0
        self._host_pages = 0
        self._n_nodes = 0
        self._n_resident = 0    # resident paged nodes (device budgets)
        self._clock = 0
        self._lock = threading.Lock()
        # observability (statistics/metrics.py PrefixCacheCollector)
        self.hits = 0
        self.misses = 0
        self.hit_tokens = 0     # prompt tokens served from cache
        self.evictions = 0
        # tier movement + hits by serving tier (hbm = fully resident run,
        # host = the run needed promotion)
        self.demotions = 0
        self.promotions = 0
        self._hit_tiers: Dict[str, int] = {"hbm": 0, "host": 0}

    # -- shared helpers ------------------------------------------------------

    def longest_prefix_len(self, n_tokens: int) -> int:
        """Largest storable/lookupable prefix for a prompt of n tokens: the
        final token always computes live (its logits seed decoding)."""
        return ((n_tokens - 1) // self.block) * self.block

    def _root(self, lora: int) -> _Node:  # tpuserve: ignore[TPU301] lock held by caller
        root = self._roots.get(lora)
        if root is None:
            root = _Node(None, ())
            self._roots[lora] = root
        return root

    def _tick(self) -> int:  # tpuserve: ignore[TPU301] lock held by caller
        self._clock += 1
        return self._clock

    def _walk(self, ids: List[int], lora: int) -> Tuple[_Node, int]:
        """Descend matching blocks; returns (deepest node, depth tokens).
        Touches every node on the path (LRU). Lock held by caller."""
        node = self._roots.get(lora)
        if node is None:
            return self._root(lora), 0
        depth = 0
        limit = self.longest_prefix_len(len(ids))
        now = self._tick()
        while depth + self.block <= limit:
            blk = tuple(ids[depth : depth + self.block])
            child = node.children.get(blk)
            if child is None:
                break
            child.last_used = now
            node = child
            depth += self.block
        return node, depth

    def _path_nodes(self, node: _Node) -> List[_Node]:
        """Root-exclusive path from the root down to ``node``."""
        path: List[_Node] = []
        while node is not None and node.parent is not None:
            path.append(node)
            node = node.parent
        path.reverse()
        return path

    def _attach(self, parent: _Node, child: _Node) -> None:  # tpuserve: ignore[TPU301] lock held by caller
        """Insert ``child`` under ``parent`` and keep the leaf set current.
        Lock held by caller; accounting is the caller's job."""
        parent.children[child.edge] = child
        self._leaf_nodes.discard(parent)
        self._leaf_nodes.add(child)
        self._n_nodes += 1
        if self._host is not None:
            self._frontier_fix(child)
            self._frontier_fix(parent)

    def _frontier_fix(self, node: Optional[_Node]) -> None:  # tpuserve: ignore[TPU301] lock held by caller
        """Re-derive one node's resident-frontier membership (resident with
        no resident children). O(fanout); lock held by caller."""
        if node is None or node.parent is None:
            return  # roots carry no payload
        if node.pages is not None and not any(
            c.pages is not None for c in node.children.values()
        ):
            self._frontier.add(node)
        else:
            self._frontier.discard(node)

    def uncount_hit(self, hit: Optional[Dict[str, Any]]) -> None:
        """The engine could not use a returned hit (no prefill bucket fits
        the prefix+tail): reclassify it as a miss so hit-rate metrics and
        hit_tokens reflect prefill compute actually skipped, not matches
        that were recomputed cold anyway."""
        if not hit:
            return
        with self._lock:
            self.hits -= 1
            self.misses += 1
            self.hit_tokens -= int(hit.get("len", 0))
            tier = hit.get("tier", "hbm")
            if tier in self._hit_tiers:
                self._hit_tiers[tier] -= 1

    # -- dense backend -------------------------------------------------------

    def match_len(self, ids: List[int], lora: int = 0) -> int:
        """Tokens a lookup for ``ids`` would serve from the cache, WITHOUT
        pinning pages or counting a hit/miss. Admission control uses this to
        size its KV-pool headroom check: a request whose prefix is cached
        only needs pages for the tail. With a host tier, only the RESIDENT
        run counts — a demoted suffix will allocate fresh device pages at
        promotion, so headroom must still cover it."""
        with self._lock:
            node, depth = self._walk(ids, lora)
            if self._host is None:
                return depth
            resident = 0
            for n in self._path_nodes(node):
                if n.pages is None:
                    break
                resident += self.block
        return min(resident, depth)

    def lookup(self, ids: List[int], lora: int = 0) -> Optional[Dict[str, Any]]:
        """Longest shared block run of ``ids`` (dense backend).
        Returns {"len": P, "bufs": {name: [L, 1, P, ...]}} or None."""
        with self._lock:
            node, depth = self._walk(ids, lora)
            if depth < self.block:
                self.misses += 1
                return None
            self.hits += 1
            self.hit_tokens += depth
            self._hit_tiers["hbm"] += 1
            blocks = [n.bufs for n in self._path_nodes(node)]
        # concatenate outside the lock: blocks are immutable device arrays,
        # and the eager concat dispatch must not serialize other admissions
        import jax.numpy as jnp

        if len(blocks) == 1:
            bufs = dict(blocks[0])
        else:
            bufs = {
                name: jnp.concatenate([b[name] for b in blocks], axis=2)
                for name in blocks[0]
            }
        return {"len": depth, "bufs": bufs}

    def store(self, ids: List[int], lora: int, bufs: Dict[str, Any]) -> None:
        """Store the prompt's block-aligned prefix KV (dense backend).
        ``bufs`` maps cache buffer keys (k/v, plus k_scale/v_scale on the
        int8-KV path) to the admission's prefill buffers [L, 1, bucket, ...]
        with the token dim at axis 2 (any bucket >= the prefix length);
        blocks already in the tree are only touched, new ones are sliced."""
        p = self.longest_prefix_len(len(ids))
        if p < self.block:
            return
        with self._lock:
            _, depth0 = self._walk(ids, lora)
        # slice the missing blocks OUTSIDE the lock: each slice is an eager
        # device dispatch, and holding the mutex across them would stall
        # every concurrent admission's lookup (worst case: a cold long
        # prompt storing dozens of blocks). A raced store of the same blocks
        # just wastes these slices — the insert below skips existing nodes.
        pending = []
        for depth in range(depth0, p, self.block):
            slices = {
                name: buf[:, :, depth : depth + self.block]
                for name, buf in bufs.items()
            }
            nbytes = sum(
                int(getattr(s, "nbytes", 0)) for s in slices.values()
            )
            if nbytes > self.max_bytes:
                break  # a single over-budget block is never worth it
            pending.append((depth, slices, nbytes))
        if not pending:
            return
        with self._lock:
            node, depth = self._walk(ids, lora)
            now = self._clock
            for blk_depth, slices, nbytes in pending:
                if blk_depth < depth:
                    continue  # another admission inserted it meanwhile
                if blk_depth > depth:
                    break  # budget broke the chain above this block
                blk = tuple(ids[depth : depth + self.block])
                child = _Node(node, blk)
                child.bufs = slices
                child.nbytes = nbytes
                child.last_used = now
                self._attach(node, child)
                self._bytes += nbytes
                node = child
                depth += self.block
            self._evict_over_budget()

    # -- paged backend -------------------------------------------------------

    def lookup_pages(self, ids: List[int], lora: int = 0) -> Optional[Dict[str, Any]]:
        """Longest shared block run (paged backend). Returns {"len": P,
        "pages": [ids], "tier": "hbm"|"host"} with the pages PINNED (one
        cache-side refcount taken on the caller's behalf) so eviction
        cannot free them before the engine maps them into a slot — the
        caller MUST release() the hit.

        Host tier (docs/kv_tiering.md): a matched run whose suffix was
        demoted is PROMOTED in place — fresh device pages are allocated and
        the async host→device DMA is enqueued before those page ids become
        visible (any consumer program dispatched later is ordered after the
        copy by data dependency on the pool handles), then the hit returns
        ``tier="host"``. If promotion fails (pool pressure, injected
        ``engine.kv.promote`` fault) the demoted suffix is DROPPED and the
        hit shortens to the resident prefix — the tail recomputes; nothing
        leaks."""
        with self._lock:
            node, depth = self._walk(ids, lora)
            if depth < self.block:
                self.misses += 1
                return None
            path = self._path_nodes(node)
            tier = "hbm"
            if self._host is not None:
                first_demoted = next(
                    (i for i, n in enumerate(path) if n.pages is None), None
                )
                if first_demoted is not None:
                    if self._promote_run(path[first_demoted:]):
                        tier = "host"
                    else:
                        # fall back to the resident prefix (recompute the
                        # tail) — but a PINNED demoted suffix must survive:
                        # pin_run promised some preempted request its
                        # history replays from here, so only unpinned
                        # suffixes drop (zero leaks either way)
                        if not self._subtree_pinned(path[first_demoted]):
                            self._drop_subtree(path[first_demoted])
                        path = path[:first_demoted]
                        depth = first_demoted * self.block
                        if depth < self.block:
                            self.misses += 1
                            return None
            self.hits += 1
            self.hit_tokens += depth
            self._hit_tiers[tier] += 1
            pages: List[int] = []
            for n in path:
                pages.extend(n.pages)
            # ownership of the pin transfers to the returned hit: the
            # caller MUST release() it (the engine's _release_prefix_hit
            # paths; the ownership ledger audits the pairing per request)
            self._pool.pin_pages(pages)  # tpuserve: ignore[TPU701] pin rides the returned hit
        hit = {"len": depth, "pages": pages, "tier": tier}
        if _ledger.armed():
            _ledger.acquire("prefix.hit", key=id(hit), domain=self)
        return hit

    def release(self, hit: Dict[str, Any]) -> None:
        """Drop a lookup_pages() pin (after slot mapping took its own refs,
        or the admission failed)."""
        pages = hit.pop("pages", None) if hit else None
        if pages:
            self._pool.unpin_pages(pages)
            if _ledger.armed():
                _ledger.release("prefix.hit", key=id(hit), domain=self)

    def store_pages(self, ids: List[int], lora: int, slot_pages: List[int]) -> None:
        """Store the prompt's block-aligned prefix by REFERENCE to the
        admitting slot's pages (paged backend; zero copies). ``block`` must
        be a page-size multiple so shared runs cover whole pages. Blocks
        already in the tree are skipped — their pages may belong to an
        earlier admission and are already shared."""
        p = self.longest_prefix_len(len(ids))
        if p < self.block:
            return
        ppb = self.block // self._pool.page_size
        with self._lock:
            node, depth = self._walk(ids, lora)
            now = self._clock
            if self._host is not None:
                # re-online any demoted node on the matched path BY
                # REFERENCE to the admitting slot's own pages (the slot just
                # computed this exact prefix — zero copies, and the
                # demoted-suffix invariant survives attaching resident
                # children below). Top-down, so residency stays
                # prefix-closed along the path at every instant.
                reonlined = 0
                for i, n in enumerate(self._path_nodes(node)):
                    if n.pages is not None or n.host_pages is None:
                        continue
                    first = (i * self.block) // self._pool.page_size
                    pages = list(slot_pages[first : first + ppb])
                    if len(pages) < ppb:
                        break  # slot shorter than this depth: leave demoted
                    self._pool.ref_pages(pages)
                    self._host.free(n.host_pages)
                    self._host_pages -= len(n.host_pages)
                    self._host_bytes -= n.nbytes
                    n.host_pages = None
                    n.pages = pages
                    self._pages += len(pages)
                    self._bytes += n.nbytes
                    self._n_resident += 1
                    reonlined += 1
                    self._frontier_fix(n)
                    self._frontier_fix(n.parent)
                if reonlined:
                    # one promotion EVENT per re-onlined run, matching
                    # _promote_run's unit (engine_kv_promotions_total
                    # counts runs; promoted_pages_total counts pages)
                    self.promotions += 1
            while depth + self.block <= p:
                blk = tuple(ids[depth : depth + self.block])
                first = (depth // self._pool.page_size)
                pages = list(slot_pages[first : first + ppb])
                if len(pages) < ppb:
                    break  # slot shorter than the prefix? defensive stop
                child = _Node(node, blk)
                child.pages = pages
                child.nbytes = ppb * self._page_bytes
                child.last_used = now
                self._pool.ref_pages(pages)
                self._attach(node, child)
                self._bytes += child.nbytes
                self._pages += ppb
                self._n_resident += 1
                node = child
                depth += self.block
            self._evict_over_budget()

    def store_shipped(self, ids: List[int], lora: int,
                      shipment, backend) -> int:
        """Import a KV-transport shipment (llm/kv_transport.py KVShipment)
        as RESIDENT nodes for the prompt's block-aligned prefix — the
        receive half of disaggregated prefill/decode
        (docs/disaggregation.md). ``backend`` is the PagedKVCache whose
        ``import_pages`` enqueues the host→device scatter; the fence is
        the host-tier promotion's, verbatim: fresh device pages are
        allocated, the async upload is ENQUEUED under the dispatch lock
        BEFORE the page ids become visible to any consumer (ordering then
        holds by data dependency on the pool handles —
        llm/schedule_explorer.py's ``kv_ship`` scenario models losing
        it), and only then do the nodes attach.

        Blocks already resident are SKIPPED (their pages may be shared
        with live slots); demoted path nodes re-online from the shipment
        (the demoted-suffix invariant survives attaching resident children
        below). Returns device pages imported (0 = nothing missing).
        Raises MemoryError on pool pressure and ValueError on geometry
        mismatch — the caller drops the shipment and falls back to
        recompute, zero leaks either way."""
        import numpy as np

        if self._pool is None:
            raise ValueError("store_shipped needs the paged backend")
        page_size = self._pool.page_size
        if int(shipment.page_size) != page_size:
            raise ValueError(
                "shipment page size {} != pool page size {}".format(
                    shipment.page_size, page_size
                )
            )
        if bool(shipment.hk_scale is not None) != bool(
            getattr(backend, "kv_quant", "")
        ):
            raise ValueError(
                "shipment quantization does not match the pool (scales {}, "
                "kv_quant {!r})".format(
                    "present" if shipment.hk_scale is not None else "absent",
                    getattr(backend, "kv_quant", ""),
                )
            )
        p = min(self.longest_prefix_len(len(ids)), int(shipment.prefix_len))
        if p < self.block:
            return 0
        ppb = self.block // page_size
        with self._lock:
            node, depth = self._walk(ids, lora)
            now = self._clock
            # one import job per missing block: demoted path nodes re-online
            # (flip), absent blocks attach as new children
            jobs: List[tuple] = []
            for i, n in enumerate(self._path_nodes(node)):
                if n.pages is None and n.host_pages is not None:
                    jobs.append((i * self.block, n))
            d = depth
            while d + self.block <= p:
                jobs.append((d, None))
                d += self.block
            if not jobs:
                return 0
            total = len(jobs) * ppb
            fresh = self._pool.allocate_cache_pages(total)
            try:
                # EVERYTHING between the mint and the publish sits under
                # this unref-on-failure guard (tpuserve-analyze TPU701: a
                # raise out of the row gather used to leak the fresh pages
                # — the mint must reach a release on the exception path)
                rows = np.asarray(
                    [
                        tok_depth // page_size + j
                        for tok_depth, _ in jobs
                        for j in range(ppb)
                    ],
                    np.int64,
                )
                # fancy indexing COPIES the selected slab rows; the upload
                # never aliases the transport mailbox's memory
                backend.import_pages(
                    shipment.hk[rows], shipment.hv[rows], fresh,
                    shipment.hk_scale[rows]
                    if shipment.hk_scale is not None else None,
                    shipment.hv_scale[rows]
                    if shipment.hv_scale is not None else None,
                )
            except BaseException:
                self._pool.unref_pages(fresh)
                raise
            # the scatter is in the device queue: publish the page ids
            i = 0
            for tok_depth, existing in jobs:
                pages = list(fresh[i * ppb : (i + 1) * ppb])
                i += 1
                if existing is not None:
                    # demoted node re-onlines from the shipment
                    if self._host is not None:
                        self._host.free(existing.host_pages)
                    self._host_pages -= len(existing.host_pages)
                    self._host_bytes -= existing.nbytes
                    existing.host_pages = None
                    existing.pages = pages
                    existing.last_used = now
                    self._bytes += existing.nbytes
                    self._pages += ppb
                    self._n_resident += 1
                    self._frontier_fix(existing)
                    self._frontier_fix(existing.parent)
                    continue
                blk = tuple(ids[tok_depth : tok_depth + self.block])
                child = _Node(node, blk)
                child.pages = pages
                child.nbytes = ppb * self._page_bytes
                child.last_used = now
                self._attach(node, child)
                self._bytes += child.nbytes
                self._pages += ppb
                self._n_resident += 1
                node = child
            self._evict_over_budget()
        return total

    def pin_run(self, ids: List[int], lora: int = 0) -> Optional[Dict[str, Any]]:
        """Protect the stored run for ``ids`` from eviction until
        unpin_run(). The preemptible batch lane relies on this: a preempted
        request's generated-so-far KV is stored here with the PROMISE that
        its re-admission replays near-zero prefill — without the pin, pool
        pressure while it waits in the queue can LRU-evict exactly those
        nodes, and the resume silently degrades to a full prefill of an
        arbitrary-length prompt (a fresh XLA compile per length, measured
        80-200 ms stalls on the serving loop). Returns an opaque handle for
        unpin_run(), or None when nothing is stored for ``ids``.

        Pin/unpin balance across every queue-exit path is audited by the
        KV sanitizer's drain check and explored under seeded thread
        interleavings by llm/schedule_explorer.py's ``pin_balance``
        scenario (``--mutate drop_unpin`` models a lost release).

        Host tier: a demoted run pins exactly the same way — the pin is a
        PROMOTION PLAN, not a miss: pinned host nodes survive host-LRU
        drops, and the resume's lookup_pages promotes them back to HBM
        (``host_nodes`` in the handle reports how many will need it)."""
        with self._lock:
            node, depth = self._walk(ids, lora)
            if depth < self.block:
                return None
            nodes = self._path_nodes(node)
            for n in nodes:
                n.pinned += 1
            handle = {
                "nodes": nodes,
                "len": depth,
                "host_nodes": sum(
                    1 for n in nodes if n.host_pages is not None
                ),
            }
            if _ledger.armed():
                _ledger.acquire("prefix.resume_pin", key=id(handle),
                                domain=self)
            return handle

    def unpin_run(self, handle: Optional[Dict[str, Any]]) -> None:
        """Release a pin_run() hold; eviction deferred by the pin (the cache
        may sit over budget while pins are held) runs now."""
        if not handle:
            return
        with self._lock:
            for n in handle.pop("nodes", ()):
                n.pinned = max(0, n.pinned - 1)
            if _ledger.armed():
                _ledger.release("prefix.resume_pin", key=id(handle),
                                domain=self)
            self._evict_over_budget()

    # -- eviction / tiering --------------------------------------------------

    def _over_budget(self) -> bool:
        """Device-tier budgets. With a host tier, the node budget counts
        only RESIDENT nodes (demotion must make progress against it — a
        total count would loop forever, since demoting never removes a
        node from the tree)."""
        nodes = self._n_resident if self._host is not None else self._n_nodes
        return (
            nodes > self.max_nodes
            or self._bytes > self.max_bytes
            or (self.max_pages is not None and self._pages > self.max_pages)
        )

    def _host_over_budget(self, extra_pages: int = 0, extra_bytes: int = 0,
                          extra_nodes: int = 0) -> bool:
        """Host-tier budgets (``extra_*`` reserves room for a demotion about
        to land, so demote→host-evict never ping-pongs)."""
        if self._host is None:
            return False
        host_nodes = self._n_nodes - self._n_resident
        return (
            self._host_pages + extra_pages > self.host_max_pages
            or (
                self.host_max_bytes is not None
                and self._host_bytes + extra_bytes > self.host_max_bytes
            )
            or (
                self.host_max_nodes is not None
                and host_nodes + extra_nodes > self.host_max_nodes
            )
        )

    def _release_node_payload(self, n: _Node) -> None:  # tpuserve: ignore[TPU301] lock held by caller
        """Shared accounting for removing one node from the tree (either
        tier, or dense): leaf/frontier sets, per-tier counters, page refs /
        host ids. A paged node only drops the CACHE's page refs; pages a
        live slot still maps stay allocated until that slot frees (the
        pool's refcount is the single source of truth)."""
        self._leaf_nodes.discard(n)
        self._frontier.discard(n)
        self._n_nodes -= 1
        if n.host_pages is not None:
            self._host_pages -= len(n.host_pages)
            self._host_bytes -= n.nbytes
            self._host.free(n.host_pages)
        else:
            self._bytes -= n.nbytes
            if n.pages is not None:
                self._pages -= len(n.pages)
                self._n_resident -= 1
                self._pool.unref_pages(n.pages)
        n.parent = None
        self.evictions += 1

    def _drop_leaf(self, victim: _Node) -> None:  # tpuserve: ignore[TPU301] lock held by caller
        """Structurally remove one leaf (either tier, or dense)."""
        parent = victim.parent
        parent.children.pop(victim.edge, None)
        if not parent.children and parent.parent is not None:
            self._leaf_nodes.add(parent)  # parent became a leaf
        self._release_node_payload(victim)
        if self._host is not None:
            self._frontier_fix(parent)

    def _subtree_pinned(self, root: _Node) -> bool:  # tpuserve: ignore[TPU301] lock held by caller
        """True when ``root`` or any descendant holds a pin_run() pin (such
        runs must never drop — the promotion plan survives for the pin
        holder's resume)."""
        stack = [root]
        while stack:
            n = stack.pop()
            if n.pinned:
                return True
            stack.extend(n.children.values())
        return False

    def _drop_subtree(self, root: _Node) -> None:  # tpuserve: ignore[TPU301] lock held by caller
        """Structurally remove ``root`` and every descendant (the
        promote/demote-failure fallbacks: the run recomputes instead of
        leaking). Callers must route pinned subtrees elsewhere
        (_subtree_pinned) — eviction victims are unpinned by construction
        (a pinned descendant pins every ancestor)."""
        stack, nodes = [root], []
        while stack:
            n = stack.pop()
            nodes.append(n)
            stack.extend(n.children.values())
        parent = root.parent
        parent.children.pop(root.edge, None)
        if not parent.children and parent.parent is not None:
            self._leaf_nodes.add(parent)
        for n in nodes:
            self._release_node_payload(n)
        if self._host is not None:
            self._frontier_fix(parent)

    def _demote(self, victims: List[_Node]) -> bool:  # tpuserve: ignore[TPU301] lock held by caller
        """Move resident frontier nodes' pages to the host tier in ONE
        backend call (docs/kv_tiering.md): a batched device→host copy of
        the int8 pages and their scale rows (synchronous readback, ordered
        after every enqueued write by data dependency), then the HBM pages'
        cache references drop — a page no live slot still maps returns to
        the free list with its bytes already safe on the host. Batching
        matters: eviction pressure demotes whole runs at once, and one
        gather+readback per NODE put O(blocks) device round-trips on the
        store/commit path. Returns False (caller drops instead) when the
        tier is full or the ``engine.kv.demote`` fault seam fires."""
        all_pages = [p for v in victims for p in v.pages]
        if faults.active():
            try:
                faults.fire("engine.kv.demote", pages=all_pages)
            except faults.InjectedFault:
                return False
        try:
            host_ids = self._backend.demote_pages(all_pages)
        except MemoryError:
            return False
        i = 0
        for victim in victims:
            pages = victim.pages
            k = len(pages)
            victim.host_pages = host_ids[i : i + k]
            i += k
            victim.pages = None
            self._pages -= k
            self._bytes -= victim.nbytes
            self._n_resident -= 1
            self._host_pages += k
            self._host_bytes += victim.nbytes
            self._pool.unref_pages(pages)
            self._frontier.discard(victim)
            self._frontier_fix(victim.parent)
        # one demotion EVENT per batched round (pages are counted by the
        # backend's demoted_pages_total), mirroring the promotion unit
        self.demotions += 1
        return True

    def _promote_run(self, nodes: List[_Node]) -> bool:  # tpuserve: ignore[TPU301] lock held by caller
        """Re-online a demoted path suffix: allocate device pages, enqueue
        the async host→device DMA (the page ids become visible only AFTER
        the copy is in the device queue — the tier fence), flip the nodes.
        Returns False on pool pressure or an injected ``engine.kv.promote``
        fault; the caller then drops the suffix (recompute, no leak)."""
        total = sum(len(n.host_pages) for n in nodes)
        if faults.active():
            try:
                faults.fire("engine.kv.promote", pages=total)
            except faults.InjectedFault:
                return False
        try:
            fresh = self._pool.allocate_cache_pages(total)
        except MemoryError:
            return False
        host_ids = [h for n in nodes for h in n.host_pages]
        try:
            self._backend.promote_pages(host_ids, fresh)
        except BaseException:
            # the backend freed the host ids up front (staging copy): the
            # payloads are gone either way — orphan the nodes' host side so
            # the caller's drop cannot double-free, release the fresh pages
            for n in nodes:
                self._host_pages -= len(n.host_pages)
                self._host_bytes -= n.nbytes
                n.host_pages = None
                n.nbytes = 0
            self._pool.unref_pages(fresh)
            return False
        i = 0
        for n in nodes:
            k = len(n.host_pages)
            n.pages = list(fresh[i : i + k])
            i += k
            n.host_pages = None
            self._pages += k
            self._bytes += n.nbytes
            self._n_resident += 1
            self._host_pages -= k
            self._host_bytes -= n.nbytes
            self._frontier_fix(n)
            self._frontier_fix(n.parent)
        self.promotions += 1
        return True

    def spill(self, target_pages: int = 0) -> int:
        """Demote resident cached runs (LRU over the resident frontier;
        pinned runs stay) until at most ``target_pages`` device pages remain
        cached. Test/bench/ops hook: forces the cold-cache state the tier
        exists for without waiting on budget pressure. Returns pages
        demoted."""
        if self._host is None:
            return 0
        moved = 0
        with self._lock:
            while self._pages > target_pages:
                victims = self._demotion_round(
                    lambda pages, _b, _n: pages > target_pages
                )
                if not victims:
                    break
                self._evict_host_over_budget(
                    extra_pages=sum(len(v.pages) for v in victims),
                    extra_bytes=sum(v.nbytes for v in victims),
                    extra_nodes=len(victims),
                )
                if not self._demote(victims):
                    break
                moved += sum(len(v.host_pages) for v in victims)
            # a spill into a smaller host budget trims LRU host runs, same
            # as the budget-driven eviction path
            self._evict_host_over_budget()
        return moved

    def _demotion_round(self, still_over) -> List[_Node]:  # tpuserve: ignore[TPU301] lock held by caller
        """LRU-ordered victims whose PROJECTED removal clears
        ``still_over(pages, bytes, resident_nodes)`` — selected up front so
        ONE batched backend copy moves the whole round. Selecting a
        frontier node exposes its parent as the next candidate (projected
        frontier), so a whole cold run demotes before any page of a newer
        run is touched — run-level LRU, and O(1) device round-trips per
        eviction burst instead of one per block."""
        cand = {n for n in self._frontier if not n.pinned}
        victims: List[_Node] = []
        selected: set = set()
        pages, nbytes, nres = self._pages, self._bytes, self._n_resident
        while cand and still_over(pages, nbytes, nres):
            victim = min(cand, key=lambda n: n.last_used)
            cand.discard(victim)
            victims.append(victim)
            selected.add(victim)
            pages -= len(victim.pages)
            nbytes -= victim.nbytes
            nres -= 1
            parent = victim.parent
            if (
                parent is not None
                and parent.parent is not None
                and parent.pages is not None
                and not parent.pinned
                and all(
                    c.pages is None or c in selected
                    for c in parent.children.values()
                )
            ):
                cand.add(parent)
        return victims

    def _evict_over_budget(self) -> None:  # tpuserve: ignore[TPU301] lock held by caller
        """LRU eviction. Without a host tier: the historical leaf drop over
        the incrementally maintained leaf set. With one: DEVICE pressure
        demotes LRU resident-frontier nodes into the host tier (a batched
        round per pass, host room made first, so the two loops never
        ping-pong) and only HOST pressure drops runs for real — warm
        prefixes degrade to a host hit instead of a cold prefill.

        Pinned nodes (preempted-request histories awaiting resume) are
        never victims of either motion; all candidates pinned = tolerate
        the overage until unpin_run() re-runs eviction."""
        while self._over_budget():
            if self._host is None:
                candidates = [n for n in self._leaf_nodes if not n.pinned]
                if not candidates:
                    return
                self._drop_leaf(min(candidates, key=lambda n: n.last_used))
                continue
            max_nodes = self.max_nodes
            max_bytes = self.max_bytes
            max_pages = self.max_pages
            victims = self._demotion_round(
                lambda pages, nbytes, nres: (
                    nres > max_nodes
                    or nbytes > max_bytes
                    or (max_pages is not None and pages > max_pages)
                )
            )
            if not victims:
                break
            self._evict_host_over_budget(
                extra_pages=sum(len(v.pages) for v in victims),
                extra_bytes=sum(v.nbytes for v in victims),
                extra_nodes=len(victims),
            )
            if not self._demote(victims):
                # tier full even after host eviction (pinned host runs) or
                # an injected demote fault: drop the LRU victim and its
                # (all non-resident) descendants for real; the loop
                # re-plans the rest
                self._drop_subtree(victims[0])
        self._evict_host_over_budget()

    def _evict_host_over_budget(self, extra_pages: int = 0,
                                extra_bytes: int = 0,
                                extra_nodes: int = 0) -> None:  # tpuserve: ignore[TPU301] lock held by caller
        while self._host_over_budget(extra_pages, extra_bytes, extra_nodes):
            candidates = [
                n for n in self._leaf_nodes
                if not n.pinned and n.host_pages is not None
            ]
            if not candidates:
                return
            self._drop_leaf(min(candidates, key=lambda n: n.last_used))

    # -- sanitizer support ---------------------------------------------------

    def page_refs(self, pool=None):
        """Cache-held references per page id (each node's pages hold one
        pool reference apiece). With ``pool`` given, also return a pool
        snapshot taken UNDER the tree lock, so no store/evict can slip
        between the two — the lock order (tree, then pool) matches every
        mutating cache path."""
        with self._lock:
            counts: Dict[int, int] = {}
            stack = [root for root in self._roots.values()]
            while stack:
                node = stack.pop()
                stack.extend(node.children.values())
                for page in node.pages or ():
                    counts[page] = counts.get(page, 0) + 1
            if pool is None:
                return counts
            return counts, pool.snapshot()

    def tier_refs(self) -> Tuple[Dict[int, int], int]:
        """(host-tier page references per host id, dual-payload node count)
        under ONE tree-lock hold — the KV sanitizer's two-tier audit: every
        allocated host id must be referenced by exactly one node, and no
        node may hold both a device and a host payload."""
        with self._lock:
            counts: Dict[int, int] = {}
            dual = 0
            stack = [root for root in self._roots.values()]
            while stack:
                node = stack.pop()
                stack.extend(node.children.values())
                if node.pages is not None and node.host_pages is not None:
                    dual += 1
                for hid in node.host_pages or ():
                    counts[hid] = counts.get(hid, 0) + 1
            return counts, dual

    # -- observability -------------------------------------------------------

    @property
    def total_bytes(self) -> int:
        return self._bytes

    @property
    def cached_pages(self) -> int:
        return self._pages

    def __len__(self) -> int:
        return self._n_nodes

    @property
    def host_pages_cached(self) -> int:
        return self._host_pages

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "hits": self.hits,
                "misses": self.misses,
                "hit_tokens": self.hit_tokens,
                "evictions": self.evictions,
                "nodes": self._n_nodes,
                "cached_bytes": self._bytes,
                "cached_pages": self._pages,
                # host tier (docs/kv_tiering.md): zeroes when untiered so
                # consumers need no schema branch
                "hits_by_tier": dict(self._hit_tiers),
                "host_nodes": (
                    self._n_nodes - self._n_resident
                    if self._host is not None
                    else 0
                ),
                "host_bytes": self._host_bytes,
                "host_pages": self._host_pages,
                "demotions": self.demotions,
                "promotions": self.promotions,
            }
