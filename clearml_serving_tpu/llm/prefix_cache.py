"""Radix-tree prefix caching: block-granular prompt-prefix KV reuse.

The reference's LLM engine (vLLM, reference serving/preprocess_service.py
§2.8) ships automatic prefix caching — chat workloads share a system prompt,
so the prefix's KV is computed once and reused, cutting TTFT for every
follow-up request. This module is the TPU-native equivalent for BOTH cache
backends of llm/engine.py, organized as a radix tree over block-granular
token runs (SGLang's RadixAttention layout; see docs/prefix_caching.md):

- Each tree edge carries exactly one ``block`` of tokens (default 64, like
  vLLM's block size); children are keyed by the block's token tuple, so a
  probe walks the tree block by block — O(prompt) TOTAL hashing per lookup,
  not O(prompt) per candidate length like the previous exact-match LRU.
- ANY shared block run matches (partial-prefix hits): two prompts sharing
  only their first k blocks reuse exactly those k blocks, whether or not
  that exact prefix was ever stored as a whole.
- Payloads are per-backend:
  * dense — immutable jax KV slices ([L, 1, block, Hkv, D] per node), which
    the engine concatenates and assembles into the admission mini cache;
  * paged — page ids in the engine's ``PagePool`` with CACHE-HELD refcounts:
    storing a prompt's prefix takes a reference on the admitting slot's own
    pages (zero copies), and a hit maps those pages straight into the new
    slot's page table (zero copies again). Pages are physically freed only
    when the last referencing slot AND the cache let go.
- Eviction is LRU at LEAF granularity (a node is evictable only once no
  longer prefix depends on it), under three budgets: node count, bytes, and
  (paged) pages. Evicting a paged node only drops the cache's reference —
  a page a live slot still maps keeps its data until that slot frees.
- Trees are namespaced per LoRA adapter index (K/V projections differ per
  adapter), exactly like the previous cache's key tuple.

The prompt's final token is never cached: it must always compute live to
produce the first-token logits (``longest_prefix_len``).

Thread-safety: admissions run in worker threads; one mutex guards the tree.
Dense payloads are immutable jax buffers. Paged lookups PIN the returned
pages (refcount bump under the tree lock) so a concurrent eviction cannot
free them between lookup and slot mapping; the engine releases the pin once
the pages are mapped (or the admission fails).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Tuple


class _Node:
    """One block-granular edge of the radix tree."""

    __slots__ = (
        "parent", "edge", "children", "bufs", "pages", "nbytes", "last_used",
        "pinned",
    )

    def __init__(self, parent: Optional["_Node"], edge: Tuple[int, ...]):
        self.parent = parent
        self.edge = edge          # this node's block of tokens
        self.children: Dict[Tuple[int, ...], "_Node"] = {}
        self.bufs: Optional[Dict[str, Any]] = None   # dense payload
        self.pages: Optional[List[int]] = None       # paged payload
        self.nbytes = 0
        self.last_used = 0
        # pin_run() holds: eviction must not drop this node (the engine
        # promised a preempted request its history replays from the cache)
        self.pinned = 0


class RadixPrefixCache:
    """Radix tree of block-aligned prompt-prefix KV.

    Bounded by node count AND bytes (and pages on the paged backend): a
    cached block holds ~2·L·block·Hkv·D·itemsize of HBM, so an entry-only
    bound could exceed a chip's HBM next to the weights and the decode
    cache. Default byte budget: 2 GiB.

    ``pool``/``page_bytes`` select the paged backend: payloads are page ids
    refcounted against ``pool`` instead of dense KV slices.
    """

    # lock-discipline registry (tpuserve-analyze TPU301): tree state is
    # mutated only under self._lock; helpers called with it held annotate
    # their def line
    __guarded_by__ = {
        "_lock": ("_roots", "_leaf_nodes", "_n_nodes", "_clock"),
    }

    def __init__(
        self,
        max_nodes: int = 512,
        block: int = 64,
        max_bytes: Optional[int] = None,
        *,
        max_pages: Optional[int] = None,
        pool=None,
        page_bytes: int = 0,
    ):
        self.block = int(block)
        self.max_nodes = int(max_nodes)
        self.max_bytes = int(max_bytes) if max_bytes else 2 << 30
        self.max_pages = int(max_pages) if max_pages else None
        self._pool = pool
        self._page_bytes = int(page_bytes)
        self._roots: Dict[int, _Node] = {}
        # incrementally maintained leaf set (nodes with no children): LRU
        # eviction scans candidates directly instead of a whole-tree DFS per
        # evicted node (O(leaves) vs O(nodes) with the lock held)
        self._leaf_nodes: set = set()
        self._bytes = 0
        self._pages = 0
        self._n_nodes = 0
        self._clock = 0
        self._lock = threading.Lock()
        # observability (statistics/metrics.py PrefixCacheCollector)
        self.hits = 0
        self.misses = 0
        self.hit_tokens = 0     # prompt tokens served from cache
        self.evictions = 0

    # -- shared helpers ------------------------------------------------------

    def longest_prefix_len(self, n_tokens: int) -> int:
        """Largest storable/lookupable prefix for a prompt of n tokens: the
        final token always computes live (its logits seed decoding)."""
        return ((n_tokens - 1) // self.block) * self.block

    def _root(self, lora: int) -> _Node:  # tpuserve: ignore[TPU301] lock held by caller
        root = self._roots.get(lora)
        if root is None:
            root = _Node(None, ())
            self._roots[lora] = root
        return root

    def _tick(self) -> int:  # tpuserve: ignore[TPU301] lock held by caller
        self._clock += 1
        return self._clock

    def _walk(self, ids: List[int], lora: int) -> Tuple[_Node, int]:
        """Descend matching blocks; returns (deepest node, depth tokens).
        Touches every node on the path (LRU). Lock held by caller."""
        node = self._roots.get(lora)
        if node is None:
            return self._root(lora), 0
        depth = 0
        limit = self.longest_prefix_len(len(ids))
        now = self._tick()
        while depth + self.block <= limit:
            blk = tuple(ids[depth : depth + self.block])
            child = node.children.get(blk)
            if child is None:
                break
            child.last_used = now
            node = child
            depth += self.block
        return node, depth

    def _path_nodes(self, node: _Node) -> List[_Node]:
        """Root-exclusive path from the root down to ``node``."""
        path: List[_Node] = []
        while node is not None and node.parent is not None:
            path.append(node)
            node = node.parent
        path.reverse()
        return path

    def _attach(self, parent: _Node, child: _Node) -> None:  # tpuserve: ignore[TPU301] lock held by caller
        """Insert ``child`` under ``parent`` and keep the leaf set current.
        Lock held by caller; accounting is the caller's job."""
        parent.children[child.edge] = child
        self._leaf_nodes.discard(parent)
        self._leaf_nodes.add(child)
        self._n_nodes += 1

    def uncount_hit(self, hit: Optional[Dict[str, Any]]) -> None:
        """The engine could not use a returned hit (no prefill bucket fits
        the prefix+tail): reclassify it as a miss so hit-rate metrics and
        hit_tokens reflect prefill compute actually skipped, not matches
        that were recomputed cold anyway."""
        if not hit:
            return
        with self._lock:
            self.hits -= 1
            self.misses += 1
            self.hit_tokens -= int(hit.get("len", 0))

    # -- dense backend -------------------------------------------------------

    def match_len(self, ids: List[int], lora: int = 0) -> int:
        """Tokens a lookup for ``ids`` would serve from the cache, WITHOUT
        pinning pages or counting a hit/miss. Admission control uses this to
        size its KV-pool headroom check: a request whose prefix is cached
        only needs pages for the tail."""
        with self._lock:
            _, depth = self._walk(ids, lora)
        return depth

    def lookup(self, ids: List[int], lora: int = 0) -> Optional[Dict[str, Any]]:
        """Longest shared block run of ``ids`` (dense backend).
        Returns {"len": P, "bufs": {name: [L, 1, P, ...]}} or None."""
        with self._lock:
            node, depth = self._walk(ids, lora)
            if depth < self.block:
                self.misses += 1
                return None
            self.hits += 1
            self.hit_tokens += depth
            blocks = [n.bufs for n in self._path_nodes(node)]
        # concatenate outside the lock: blocks are immutable device arrays,
        # and the eager concat dispatch must not serialize other admissions
        import jax.numpy as jnp

        if len(blocks) == 1:
            bufs = dict(blocks[0])
        else:
            bufs = {
                name: jnp.concatenate([b[name] for b in blocks], axis=2)
                for name in blocks[0]
            }
        return {"len": depth, "bufs": bufs}

    def store(self, ids: List[int], lora: int, bufs: Dict[str, Any]) -> None:
        """Store the prompt's block-aligned prefix KV (dense backend).
        ``bufs`` maps cache buffer keys (k/v, plus k_scale/v_scale on the
        int8-KV path) to the admission's prefill buffers [L, 1, bucket, ...]
        with the token dim at axis 2 (any bucket >= the prefix length);
        blocks already in the tree are only touched, new ones are sliced."""
        p = self.longest_prefix_len(len(ids))
        if p < self.block:
            return
        with self._lock:
            _, depth0 = self._walk(ids, lora)
        # slice the missing blocks OUTSIDE the lock: each slice is an eager
        # device dispatch, and holding the mutex across them would stall
        # every concurrent admission's lookup (worst case: a cold long
        # prompt storing dozens of blocks). A raced store of the same blocks
        # just wastes these slices — the insert below skips existing nodes.
        pending = []
        for depth in range(depth0, p, self.block):
            slices = {
                name: buf[:, :, depth : depth + self.block]
                for name, buf in bufs.items()
            }
            nbytes = sum(
                int(getattr(s, "nbytes", 0)) for s in slices.values()
            )
            if nbytes > self.max_bytes:
                break  # a single over-budget block is never worth it
            pending.append((depth, slices, nbytes))
        if not pending:
            return
        with self._lock:
            node, depth = self._walk(ids, lora)
            now = self._clock
            for blk_depth, slices, nbytes in pending:
                if blk_depth < depth:
                    continue  # another admission inserted it meanwhile
                if blk_depth > depth:
                    break  # budget broke the chain above this block
                blk = tuple(ids[depth : depth + self.block])
                child = _Node(node, blk)
                child.bufs = slices
                child.nbytes = nbytes
                child.last_used = now
                self._attach(node, child)
                self._bytes += nbytes
                node = child
                depth += self.block
            self._evict_over_budget()

    # -- paged backend -------------------------------------------------------

    def lookup_pages(self, ids: List[int], lora: int = 0) -> Optional[Dict[str, Any]]:
        """Longest shared block run (paged backend). Returns {"len": P,
        "pages": [ids]} with the pages PINNED (one cache-side refcount taken
        on the caller's behalf) so eviction cannot free them before the
        engine maps them into a slot — the caller MUST release() the hit."""
        with self._lock:
            node, depth = self._walk(ids, lora)
            if depth < self.block:
                self.misses += 1
                return None
            self.hits += 1
            self.hit_tokens += depth
            pages: List[int] = []
            for n in self._path_nodes(node):
                pages.extend(n.pages)
            self._pool.pin_pages(pages)  # pin for the admission in flight
        return {"len": depth, "pages": pages}

    def release(self, hit: Dict[str, Any]) -> None:
        """Drop a lookup_pages() pin (after slot mapping took its own refs,
        or the admission failed)."""
        pages = hit.pop("pages", None) if hit else None
        if pages:
            self._pool.unpin_pages(pages)

    def store_pages(self, ids: List[int], lora: int, slot_pages: List[int]) -> None:
        """Store the prompt's block-aligned prefix by REFERENCE to the
        admitting slot's pages (paged backend; zero copies). ``block`` must
        be a page-size multiple so shared runs cover whole pages. Blocks
        already in the tree are skipped — their pages may belong to an
        earlier admission and are already shared."""
        p = self.longest_prefix_len(len(ids))
        if p < self.block:
            return
        ppb = self.block // self._pool.page_size
        with self._lock:
            node, depth = self._walk(ids, lora)
            now = self._clock
            while depth + self.block <= p:
                blk = tuple(ids[depth : depth + self.block])
                first = (depth // self._pool.page_size)
                pages = list(slot_pages[first : first + ppb])
                if len(pages) < ppb:
                    break  # slot shorter than the prefix? defensive stop
                child = _Node(node, blk)
                child.pages = pages
                child.nbytes = ppb * self._page_bytes
                child.last_used = now
                self._pool.ref_pages(pages)
                self._attach(node, child)
                self._bytes += child.nbytes
                self._pages += ppb
                node = child
                depth += self.block
            self._evict_over_budget()

    def pin_run(self, ids: List[int], lora: int = 0) -> Optional[Dict[str, Any]]:
        """Protect the stored run for ``ids`` from eviction until
        unpin_run(). The preemptible batch lane relies on this: a preempted
        request's generated-so-far KV is stored here with the PROMISE that
        its re-admission replays near-zero prefill — without the pin, pool
        pressure while it waits in the queue can LRU-evict exactly those
        nodes, and the resume silently degrades to a full prefill of an
        arbitrary-length prompt (a fresh XLA compile per length, measured
        80-200 ms stalls on the serving loop). Returns an opaque handle for
        unpin_run(), or None when nothing is stored for ``ids``.

        Pin/unpin balance across every queue-exit path is audited by the
        KV sanitizer's drain check and explored under seeded thread
        interleavings by llm/schedule_explorer.py's ``pin_balance``
        scenario (``--mutate drop_unpin`` models a lost release)."""
        with self._lock:
            node, depth = self._walk(ids, lora)
            if depth < self.block:
                return None
            nodes = self._path_nodes(node)
            for n in nodes:
                n.pinned += 1
            return {"nodes": nodes, "len": depth}

    def unpin_run(self, handle: Optional[Dict[str, Any]]) -> None:
        """Release a pin_run() hold; eviction deferred by the pin (the cache
        may sit over budget while pins are held) runs now."""
        if not handle:
            return
        with self._lock:
            for n in handle.pop("nodes", ()):
                n.pinned = max(0, n.pinned - 1)
            self._evict_over_budget()

    # -- eviction ------------------------------------------------------------

    def _over_budget(self) -> bool:
        return (
            self._n_nodes > self.max_nodes
            or self._bytes > self.max_bytes
            or (self.max_pages is not None and self._pages > self.max_pages)
        )

    def _evict_over_budget(self) -> None:  # tpuserve: ignore[TPU301] lock held by caller
        """LRU leaf eviction over the incrementally maintained leaf set
        (O(leaves) per eviction, no tree walk). A paged leaf only drops the
        CACHE's page refs; pages a live slot still maps stay allocated until
        that slot frees (the pool's refcount is the single source of
        truth)."""
        while self._over_budget():
            # pinned leaves (preempted-request histories awaiting resume)
            # are never victims; their ancestors are not leaves while they
            # live, so a whole pinned run survives. All leaves pinned =
            # tolerate the overage until unpin_run() re-runs eviction.
            candidates = [n for n in self._leaf_nodes if not n.pinned]
            if not candidates:
                return
            victim = min(candidates, key=lambda n: n.last_used)
            self._leaf_nodes.discard(victim)
            parent = victim.parent
            parent.children.pop(victim.edge, None)
            if not parent.children and parent.parent is not None:
                self._leaf_nodes.add(parent)  # parent became a leaf
            self._n_nodes -= 1
            self._bytes -= victim.nbytes
            if victim.pages is not None:
                self._pages -= len(victim.pages)
                self._pool.unref_pages(victim.pages)
            victim.parent = None
            self.evictions += 1

    # -- sanitizer support ---------------------------------------------------

    def page_refs(self, pool=None):
        """Cache-held references per page id (each node's pages hold one
        pool reference apiece). With ``pool`` given, also return a pool
        snapshot taken UNDER the tree lock, so no store/evict can slip
        between the two — the lock order (tree, then pool) matches every
        mutating cache path."""
        with self._lock:
            counts: Dict[int, int] = {}
            stack = [root for root in self._roots.values()]
            while stack:
                node = stack.pop()
                stack.extend(node.children.values())
                for page in node.pages or ():
                    counts[page] = counts.get(page, 0) + 1
            if pool is None:
                return counts
            return counts, pool.snapshot()

    # -- observability -------------------------------------------------------

    @property
    def total_bytes(self) -> int:
        return self._bytes

    @property
    def cached_pages(self) -> int:
        return self._pages

    def __len__(self) -> int:
        return self._n_nodes

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "hits": self.hits,
                "misses": self.misses,
                "hit_tokens": self.hit_tokens,
                "evictions": self.evictions,
                "nodes": self._n_nodes,
                "cached_bytes": self._bytes,
                "cached_pages": self._pages,
            }
