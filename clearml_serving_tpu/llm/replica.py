"""Replica engine group: N ``LLMEngineCore`` replicas behind one
prefix-affine router (docs/replication.md).

``ReplicaGroup`` presents the single-engine surface the OpenAI front and
the serving router already consume (``validate`` / ``check_admission`` /
``generate`` / ``score_prompt`` / ``warmup`` / ``health`` /
``lifecycle_stats`` / ``stop``), so a fleet drops in wherever one engine
stood. Routing is delegated to ``serving/replica_router.py``: every
request's block-aligned prompt prefix picks the replica whose KV tier
already holds its conversation, with health-aware rebalance and
load-aware spill.

Failure drain ("kill one replica, zero user-visible 503s"): when a
replica fails a stream with a REPLICA-level error (watchdog trip →
``EngineStuckError``, stop/eject → ``EngineUnavailableError``), the group
resumes the request on a sibling — the generated-so-far tokens become
part of the resume prompt (the same history-as-prompt trick the
preemptible batch lane uses, docs/slo_scheduling.md), so a greedy stream
continues byte-identically and the consumer only observes latency.
Eligibility matches the preemption lane's rule: plain-sampling requests
only — guided or penalty-bearing requests would resume WRONG (the
history-as-prompt resume resets the device penalty histogram / DFA
state) and propagate their error instead. Request-attributable errors
(deadlines, sheds, per-request step failures) propagate unchanged:
retrying those would hide real contract violations.

In-process replicas share one params tree (read-only for compute: the
engines donate only their KV buffers) and allocate private KV pools —
the same interface a per-mesh process group (parallel/multihost.py)
plugs into later with RPC instead of method calls.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Any, AsyncIterator, List, Optional

from ..errors import EngineStuckError, EngineUnavailableError
from ..serving.replica_router import ReplicaRouter

logger = logging.getLogger(__name__)


class EngineReplica:
    """One ring member: an engine plus its warmup gate and identity.

    The warmup gate (docs/static_analysis.md TPU6xx, llm/warmup.py) gates
    RING ENTRY: a cold replica never takes serve traffic, and an ejected
    replica re-warms before re-admission (fast no-compile pass when its
    jit caches survived, a real warmup when they did not).
    """

    def __init__(self, index: int, engine, *, warmup_mode: str = "off"):
        if warmup_mode not in ("off", "startup", "full"):
            raise ValueError(
                "replica warmup mode must be off/startup/full: got {!r}".format(
                    warmup_mode
                )
            )
        self.index = int(index)
        self.name = "r{}".format(index)
        self.engine = engine
        # one replica identity across every surface (metrics labels, ring
        # names, registry keys, /ready blocks): default-fill the engine's
        # id with the ring name when the caller left it unset
        if getattr(engine, "replica_id", None) is None:
            engine.replica_id = self.name
        self._warmup_mode = warmup_mode
        # gate open from birth when warmup is off — the legacy lazy-compile
        # behavior, byte-identical to a single engine without warmup
        self.warmed = warmup_mode == "off"
        # whether the FULL sweep has run (a cheap startup pass opens the
        # gate but must not satisfy a full-certification warmup request)
        self.warmed_full = False
        # last warmup sweep's run_warmup result (group.warmup aggregates)
        self.warm_result = {"requests": 0, "cow_buckets": 0}
        self._warm_task: Optional[asyncio.Task] = None

    # -- state the router consumes ------------------------------------------

    @property
    def engine_ready(self) -> bool:
        return bool(self.engine.is_ready)

    @property
    def serving_ready(self) -> bool:
        return self.engine_ready and self.warmed

    @property
    def warming(self) -> bool:
        return self._warm_task is not None and not self._warm_task.done()

    @property
    def queue_depth(self) -> int:
        return int(self.engine._pending.qsize())

    @property
    def brownout_stage(self) -> int:
        snap = self.engine._brownout_snapshot()
        return int((snap or {}).get("stage", 0))

    # -- warmup gate --------------------------------------------------------

    def invalidate_warm(self) -> None:
        """Close the gate on ejection so re-admission re-warms (no-op when
        warmup is disabled — then the gate never closes)."""
        if self._warmup_mode != "off":
            self.warmed = False
            self.warmed_full = False

    def begin_warm(self) -> None:
        """Schedule the shared warmup task (event loop only). The gate
        reopens when it finishes; a FAILED warmup logs and reopens the
        gate anyway — serving then compiles lazily, the same best-effort
        contract as the endpoint-level warmup knob."""
        if self.warmed or self.warming or not self.engine_ready:
            return
        if self._warmup_mode == "off":
            self.warmed = True
            return
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            # no running loop (construction-time sweep): defer — the next
            # sweep from a loop context schedules the task; scheduling on
            # a never-running loop would leave the gate closed forever
            return
        self._warm_task = loop.create_task(self.ensure_warm())

    async def ensure_warm(self, full: Optional[bool] = None) -> None:
        from .warmup import run_warmup

        if full is None:
            full = self._warmup_mode == "full"
        try:
            self.warm_result = await run_warmup(
                self.engine, full=full, fence=False
            )
        except Exception as ex:  # tpuserve: ignore[TPU401] warmup is best-effort by contract; failure falls back to lazy compiles and is logged
            logger.warning(
                "replica %s warmup failed (will serve with lazy compiles): %s",
                self.name, ex,
            )
        self.warmed = True
        self.warmed_full = self.warmed_full or bool(full)

    # -- observability ------------------------------------------------------

    def health(self) -> dict:
        out = self.engine.health()
        out["replica"] = self.name
        out["ring_state"] = (
            "ready" if self.serving_ready
            else ("warming" if self.warming else "ejected")
        )
        return out


class ReplicaGroup:
    """Engine-group facade: routes the single-engine API over N replicas."""

    def __init__(
        self,
        engines: List[Any],
        *,
        warmup_mode: str = "off",
        affinity_blocks: int = 4,
        spill_queue_depth: Optional[int] = None,
        spill_brownout_stage: int = 2,
        fleet_shed_stage: int = 3,
        # disaggregated prefill/decode (docs/disaggregation.md): one role
        # per engine ("prefill" | "decode" | "hybrid"); None/all-hybrid =
        # the legacy every-replica-does-both fleet. Any non-hybrid role
        # builds the in-process KV transport and wires every engine into
        # it (aux engine.replica_roles).
        roles: Optional[List[str]] = None,
        # per-replica receive-slab capacity in pages (aux
        # engine.kv_transport_pages); default: four full-prefix shipments
        kv_transport_pages: Optional[int] = None,
        # KV transport backend (aux engine.kv_transport_backend,
        # docs/disaggregation.md): "shared" = in-heap slab mailboxes,
        # "socket" = the wire-framed socket backend (llm/kv_wire.py) —
        # same mailbox semantics, shipments cross a real byte boundary
        kv_transport_backend: str = "shared",
    ):
        if not engines:
            raise ValueError("a replica group needs at least one engine")
        if kv_transport_backend not in ("shared", "socket"):
            raise ValueError(
                "engine.kv_transport_backend must be shared/socket: got "
                "{!r}".format(kv_transport_backend)
            )
        self.replicas = [
            EngineReplica(i, engine, warmup_mode=warmup_mode)
            for i, engine in enumerate(engines)
        ]
        prefix = engines[0]._prefix
        block = prefix.block if prefix is not None else 64
        # -- replica roles + KV transport (docs/disaggregation.md) --------
        role_map = None
        self._disaggregated = False
        self.transport = None
        if roles is not None:
            roles = [str(r) for r in roles]
            if len(roles) != len(engines):
                raise ValueError(
                    "engine.replica_roles lists {} roles for {} replicas"
                    .format(len(roles), len(engines))
                )
            for role in roles:
                if role not in ("prefill", "decode", "hybrid"):
                    raise ValueError(
                        "engine.replica_roles entries must be prefill/"
                        "decode/hybrid: got {!r}".format(role)
                    )
            self._disaggregated = any(r != "hybrid" for r in roles)
            if self._disaggregated:
                if not any(r in ("decode", "hybrid") for r in roles):
                    raise ValueError(
                        "engine.replica_roles needs at least one decode-"
                        "capable (decode/hybrid) replica to serve streams"
                    )
                if not any(r in ("prefill", "hybrid") for r in roles):
                    raise ValueError(
                        "engine.replica_roles needs at least one prefill-"
                        "capable (prefill/hybrid) replica"
                    )
                if prefix is None or engines[0].paged_cache is None:
                    raise ValueError(
                        "disaggregated replica roles need cache='paged' "
                        "and a prefix_cache (the shipped payload is the "
                        "radix-storable prefix; docs/disaggregation.md)"
                    )
                if kv_transport_pages is None:
                    per_seq = engines[0].paged_cache.pool.pages_needed(
                        engines[0].max_seq_len
                    )
                    kv_transport_pages = max(64, 4 * per_seq)
                if kv_transport_backend == "socket":
                    from .kv_wire import SocketSlabFabric

                    self.transport = SocketSlabFabric(
                        capacity_pages=int(kv_transport_pages)
                    )
                else:
                    from .kv_transport import SharedSlabTransport

                    self.transport = SharedSlabTransport(
                        capacity_pages=int(kv_transport_pages)
                    )
            role_map = {
                replica.name: role
                for replica, role in zip(self.replicas, roles)
            }
            for replica, role in zip(self.replicas, roles):
                replica.engine.attach_kv_transport(
                    self.transport.register(replica.name)
                    if self.transport is not None else None,
                    role=role,
                )
        self._finish_init(
            self.replicas,
            block=block,
            role_map=role_map,
            disaggregated=self._disaggregated,
            transport=self.transport,
            spill_queue_depth=spill_queue_depth,
            spill_brownout_stage=spill_brownout_stage,
            fleet_shed_stage=fleet_shed_stage,
            affinity_blocks=affinity_blocks,
            replica_backend="inprocess",
            max_pending_hint=engines[0].max_pending,
            runtime=None,
        )

    def _finish_init(self, replicas, *, block, role_map, disaggregated,
                     transport, spill_queue_depth, spill_brownout_stage,
                     fleet_shed_stage, affinity_blocks, replica_backend,
                     max_pending_hint, runtime):
        """Shared construction tail: router + counters. Called by
        ``__init__`` (in-process engines) and by the process-fleet builder
        (serving/process_replica.py, docs/replication.md), which assembles
        its ring from worker subprocesses and has no engine objects in
        hand — each proxy replica arrives pre-built."""
        self.replicas = replicas
        self._disaggregated = bool(disaggregated)
        self.transport = transport
        # which replica backend runs this fleet ("inprocess" | "process");
        # exported on the router's stats for the info-gauge metric
        self.replica_backend = str(replica_backend)
        self._process_runtime = runtime
        # spill bound defaults to half the admission bound: deep enough
        # that transient bursts stay affine, shallow enough to redirect
        # before the affine member starts shedding. An EXPLICIT 0 disables
        # queue-depth spill (maps to the router's None spelling).
        if spill_queue_depth is None and max_pending_hint:
            spill_queue_depth = max(2, int(max_pending_hint) // 2)
        elif spill_queue_depth is not None and int(spill_queue_depth) <= 0:
            spill_queue_depth = None
        self.router = ReplicaRouter(
            replicas,
            block=block,
            affinity_blocks=affinity_blocks,
            spill_queue_depth=spill_queue_depth,
            spill_brownout_stage=spill_brownout_stage,
            fleet_shed_stage=fleet_shed_stage,
            roles=role_map,
            replica_backend=self.replica_backend,
        )
        self.failovers = 0
        # disaggregation counters (mirrored in health()/lifecycle_stats())
        self.ship_legs = 0          # prefill legs run
        self.ship_leg_failures = 0  # leg failed -> decode-side recompute
        self.ship_warm_skips = 0    # decode already held the prefix
        self.receive_reroutes = 0   # receive failed -> hybrid re-route

    # -- single-engine surface (config/readonly) ----------------------------

    def _first_engine(self):
        return self.replicas[0].engine

    @property
    def bundle(self):
        # replicas share one model bundle (and its params tree)
        return self._first_engine().bundle

    @property
    def max_seq_len(self) -> int:
        return self._first_engine().max_seq_len

    @property
    def max_batch(self) -> int:
        return self._first_engine().max_batch

    @property
    def logprobs_k(self) -> int:
        return self._first_engine().logprobs_k

    @property
    def _adapter_index(self):
        return getattr(self._first_engine(), "_adapter_index", {})

    @property
    def adapter_names(self) -> List[str]:
        # mirrors the engine's @property (a method here would break the
        # /v1/models iteration over it)
        return self._first_engine().adapter_names

    @property
    def _prefix(self):
        # replica 0's cache stands in for "the" prefix cache on config
        # probes; metrics register EVERY replica's cache separately
        return self._first_engine()._prefix

    @property
    def paged_cache(self):
        return self._first_engine().paged_cache

    @property
    def is_ready(self) -> bool:
        """Fleet readiness: at least one ring member serves."""
        self.router.sweep()
        return self.router.ring_size >= 1

    # -- request path -------------------------------------------------------

    def validate(self, request) -> None:
        # replicas are identically configured: validation is config-only
        self._first_engine().validate(request)

    def check_admission(self, request, reserve: int = 0) -> None:
        """Route and pre-admit: the chosen replica is pinned on the request
        so the later ``generate`` lands on the engine whose admission
        state this check consulted (streaming callers run this before
        response headers, exactly like the single-engine contract)."""
        replica, route = self.router.pick(request)
        request._replica_name = replica.name
        replica.engine.check_admission(request, reserve=reserve)

    def _replica_by_name(self, name: Optional[str]):
        for replica in self.replicas:
            if replica.name == name:
                return replica
        return None

    @staticmethod
    def _resume_clone(request, emitted: List[int]):
        """A fresh request continuing ``request`` after ``emitted`` tokens:
        history rides as prompt (the radix cache replays its KV on the
        sibling when warm; recompute when not). Greedy continuations are
        byte-identical; seeded sampling replays its stream from the resume
        point (documented failover approximation).

        Deadline budgets carry REMAINING time, not fresh values: the
        original request's resolved monotonic deadlines bound the clone —
        a 10s-budget request 9s in when its replica trips gets ~1s on the
        sibling, not a fresh 10s (the 408 contract survives failover).
        The TTFT budget only still applies when no token was emitted; the
        queue budget likewise covered the ORIGINAL admission wait, so a
        mid-stream resume is bounded by the total budget alone."""
        import time as _time

        from .engine import GenRequest

        done = len(emitted)
        now = _time.monotonic()

        def _remaining(deadline):
            # floor, not fail-fast: an exactly-elapsed budget still gets
            # one admission attempt and fails there with a structured 408
            return None if deadline is None else max(0.05, deadline - now)

        return GenRequest(
            prompt_ids=list(request.prompt_ids) + list(emitted),
            max_new_tokens=max(1, request.max_new_tokens - done),
            temperature=request.temperature,
            top_k=request.top_k,
            top_p=request.top_p,
            stop_token_ids=list(request.stop_token_ids or []) or None,
            presence_penalty=request.presence_penalty,
            frequency_penalty=request.frequency_penalty,
            repetition_penalty=request.repetition_penalty,
            seed=request.seed,
            logit_bias=dict(request.logit_bias) if request.logit_bias else None,
            logprobs=request.logprobs,
            adapter=request.adapter,
            min_tokens=max(0, request.min_tokens - done),
            priority=request.priority,
            queue_timeout=(
                _remaining(request._queue_deadline) if done == 0 else None
            ),
            ttft_timeout=(
                _remaining(request._ttft_deadline) if done == 0 else None
            ),
            total_timeout=_remaining(request._deadline),
        )

    @staticmethod
    def _resumable(request) -> bool:
        """Failover eligibility, matching the engine's own preemption-lane
        rule (engine._preempt_slot): history-as-prompt resume resets the
        device penalty histogram and guided DFA state, so requests using
        either must propagate their error instead of resuming WRONG.
        (Seeded sampling resumes with a replayed RNG stream — an explicit,
        documented approximation; greedy resumes byte-identically.)"""
        return (
            request.guided is None
            and request.presence_penalty == 0.0
            and request.frequency_penalty == 0.0
            and request.repetition_penalty == 1.0
        )

    async def _disagg_preamble(self, request, decode_replica):
        """Disaggregated prefill/decode, the ship lifecycle's group half
        (docs/disaggregation.md):

        1. Skip when the decode replica already holds the whole storable
           prefix (repeat conversation turn — its radix cache is warm).
        2. Run the PREFILL LEG: a plain one-token clone of the request on
           a prefill-capable replica with ``_ship_to`` set — at its
           commit, that engine exports the prefix pages into a transport
           shipment addressed to the decode replica. KV does not depend
           on sampling, so the clone strips guided/penalty state; its
           single discarded token is the cost of role specialization.
        3. RECEIVE on the decode replica (off the event loop): pop the
           shipment and re-online it through the promote-under-dispatch-
           lock fence. The stream's admission then hits the shipped
           prefix and recomputes only the unshipped tail.

        Every failure degrades, never fails the request: a failed leg or
        empty shipment means decode-side recompute, a failed RECEIVE
        re-routes the stream to a hybrid-capable sibling (counted).
        Returns the (possibly re-routed) replica the stream must run on."""
        import asyncio as _asyncio
        import time as _time

        engine = decode_replica.engine
        prefix = getattr(engine, "_prefix", None)
        if prefix is None or engine.paged_cache is None:
            return decode_replica
        ids = request.prompt_ids
        storable = prefix.longest_prefix_len(len(ids))
        if storable < prefix.block:
            return decode_replica  # nothing shippable: too short
        lora = engine._slot_lora(request)
        if prefix.match_len(ids, lora) >= storable:
            self.ship_warm_skips += 1
            return decode_replica
        pre = self.router.pick_prefill(request, exclude=decode_replica.name)
        if pre is None:
            # prefill class empty/browned out: hybrid degradation — the
            # decode replica prefills for itself
            return decode_replica
        from .engine import GenRequest

        # the leg is bounded by the ORIGINAL request's total budget (the
        # _resume_clone convention): a wedged prefill replica must not
        # stall the stream past its deadline. The deadline is usually
        # UNRESOLVED here (the engine resolves it at its own generate),
        # so fall back to the raw body budget when no monotonic deadline
        # exists yet.
        if request._deadline is not None:
            leg_budget = max(0.05, request._deadline - _time.monotonic())
        else:
            leg_budget = request.total_timeout
        ship_req = GenRequest(
            prompt_ids=list(ids),
            max_new_tokens=1,
            priority=request.priority,
            adapter=request.adapter,
            total_timeout=leg_budget,
        )
        ship_req._ship_to = decode_replica.name
        self.ship_legs += 1
        try:
            async for _ in pre.engine.generate(ship_req):
                pass  # the leg's one token is discarded by design
        except _asyncio.CancelledError:
            ship_req.cancel()
            raise
        except Exception as ex:  # noqa: BLE001 - the leg is best-effort
            self.ship_leg_failures += 1
            logger.warning(
                "prefill replica %s failed a ship leg (%s); decode-side "
                "recompute on %s", pre.name, type(ex).__name__,
                decode_replica.name,
            )
            return decode_replica
        request._shipped = True
        res = await _asyncio.to_thread(engine.receive_shipment, ids, lora)
        if res.get("status") != "failed":
            return decode_replica
        # receive failure (injected engine.kv.receive fault, pool
        # pressure, geometry mismatch): re-route the stream to a HYBRID
        # sibling — a replica that can do both jobs takes it cold
        self.receive_reroutes += 1
        self.router.sweep()
        for r in self.router.order_for(ids):
            if (
                r.name in self.router._ring_members
                and r.name != decode_replica.name
                and self.router.role_of(r.name) == "hybrid"
            ):
                logger.warning(
                    "decode replica %s failed a shipment receive; "
                    "re-routing the stream to hybrid %s",
                    decode_replica.name, r.name,
                )
                return r
        return decode_replica  # no hybrid available: recompute in place

    async def generate(self, request) -> AsyncIterator[int]:
        """Routed generation with failure drain: replica-level failures
        (stuck/unavailable) resume the stream on the next-choice sibling;
        request-attributable errors propagate unchanged."""
        replica = self._replica_by_name(getattr(request, "_replica_name", None))
        if replica is None or replica.name not in self.router._ring_members:
            replica, _ = self.router.pick(request)
            request._replica_name = replica.name
        # set before the engine does: a pre-admission failover must not
        # leave the caller's usage accounting reading prompt_len == 0
        request.prompt_len = len(request.prompt_ids)
        if self._disaggregated:
            # disaggregated prefill/decode (docs/disaggregation.md): run
            # the prefill leg + shipment receive first; may re-route the
            # stream to a hybrid sibling on a receive failure
            replica = await self._disagg_preamble(request, replica)
            request._replica_name = replica.name
        emitted: List[int] = []
        base_lp = 0  # caller-side logprob entries at the last failover
        active = request
        tried = set()
        try:
            while True:
                tried.add(replica.name)
                failed: Optional[BaseException] = None
                try:
                    async for token in replica.engine.generate(active):
                        emitted.append(int(token))
                        if active is not request:
                            # mirror progress onto the caller's request:
                            # usage/TTFT/logprobs read from it post-stream
                            request.produced = len(emitted)
                            if request.first_token_at is None:
                                request.first_token_at = active.first_token_at
                            if request.logprobs is not None:
                                request.logprob_entries.extend(
                                    active.logprob_entries[
                                        len(request.logprob_entries) - base_lp:
                                    ]
                                )
                        yield token
                except (EngineStuckError, EngineUnavailableError) as ex:
                    failed = ex
                if failed is None:
                    return
                if len(emitted) >= request.max_new_tokens:
                    # the stream already delivered everything the caller
                    # asked for (the replica failed between the last token
                    # and the finish marker): finish normally — a resume
                    # would overshoot max_new_tokens by at least one
                    return
                if not self._resumable(request):
                    raise failed
                self.router.sweep()
                candidates = [
                    r for r in self.router.order_for(request.prompt_ids)
                    if r.name in self.router._ring_members
                    and r.name not in tried
                ]
                # role-split fleets: resume on a decode-capable sibling
                # when one exists; a lone prefill replica still beats a 503
                candidates.sort(
                    key=lambda r: self.router.role_of(r.name) == "prefill"
                )
                if not candidates:
                    raise failed
                failed_name = replica.name
                replica = candidates[0]
                self.failovers += 1
                logger.warning(
                    "replica %s failed a stream (%s); resuming %d-token "
                    "history on %s", failed_name, type(failed).__name__,
                    len(emitted), replica.name,
                )
                active = self._resume_clone(request, emitted)
                base_lp = len(request.logprob_entries)
                request._replica_name = replica.name
        finally:
            # consumer stopped early (GeneratorExit lands here): flag the
            # LIVE request so its engine frees the slot/pages promptly —
            # closing the wrapper does not synchronously close a resumed
            # clone's inner generator. Redundant after a normal finish.
            active.cancelled = True

    def score_prompt(self, prompt_ids, adapter: Optional[str] = None):
        # stateless readonly compute: any ring member serves it
        replica = self._replica_by_name(next(iter(self.router.ring()), None))
        engine = replica.engine if replica is not None else self._first_engine()
        return engine.score_prompt(prompt_ids, adapter)

    # -- lifecycle ----------------------------------------------------------

    async def warmup(self, full: bool = True) -> dict:
        """Warm every replica through its gate, then set the process-wide
        compile-sentry fence once (only a FULL sweep certifies — the same
        contract as llm/warmup.run_warmup). Every sweep runs AS the
        replica's own gate task (``_warm_task``): a concurrent ring sweep
        (e.g. a /ready probe mid-warmup) sees ``warming`` and never
        schedules a duplicate run_warmup on the same engine; an in-flight
        gate task is awaited, then topped up with the full sweep if this
        call needs certification and the gate only ran the startup pass."""
        from . import compile_sentry

        results = []
        for replica in self.replicas:
            if replica.warming:
                try:
                    await asyncio.shield(replica._warm_task)
                except Exception:  # tpuserve: ignore[TPU401] gate task logs its own failure; warmup stays best-effort
                    pass
            if replica.warmed and (replica.warmed_full or not full):
                continue
            replica._warm_task = asyncio.get_running_loop().create_task(
                replica.ensure_warm(full=full)
            )
            try:
                await asyncio.shield(replica._warm_task)
            except Exception:  # tpuserve: ignore[TPU401] ensure_warm logs its own failure; warmup stays best-effort
                pass
            results.append(replica.warm_result)
        self.router.sweep()
        fenced = False
        if full and compile_sentry.enabled():
            compile_sentry.get().fence()
            fenced = True
        return {
            "replicas": len(self.replicas),
            "requests": sum(r.get("requests", 0) for r in results),
            "cow_buckets": sum(r.get("cow_buckets", 0) for r in results),
            "fenced": fenced,
        }

    def stop(self) -> None:
        for replica in self.replicas:
            replica.engine.stop()
        # the socket fabric holds OS resources (accept threads, unix
        # paths, a tmpdir); the in-heap slab backend has nothing to close
        if self.transport is not None and hasattr(self.transport, "close"):
            self.transport.close()
        runtime = getattr(self, "_process_runtime", None)
        if runtime is not None:
            # process backend: join supervisors, reap workers, drop the
            # control listener + spec/socket directory
            runtime.close()
        self.router.sweep()

    async def wait_drained(self, timeout: float = 30.0) -> None:
        for replica in self.replicas:
            await replica.engine.wait_drained(timeout=timeout)

    # -- observability ------------------------------------------------------

    def health(self) -> dict:
        """Fleet-aggregated health: ready iff the ring has >= 1 member;
        per-replica blocks + the router's ring/route state ride along so
        /ready can show WHICH replica is out and why."""
        self.router.sweep()
        stats = self.router.stats()
        return {
            "ready": stats["ring_size"] >= 1,
            "ring_size": stats["ring_size"],
            "replicas": {r.name: r.health() for r in self.replicas},
            "router": stats,
            "brownout": {"stage": stats["fleet_brownout"]["stage"]},
            "queue_depth": sum(r.queue_depth for r in self.replicas),
            "active_slots": sum(r.engine.active_slots for r in self.replicas),
            "failovers": self.failovers,
            "disaggregation": self._disagg_snapshot(),
        }

    def _disagg_snapshot(self) -> Optional[dict]:
        """Group-level ship-lifecycle counters (docs/disaggregation.md);
        None on a hybrid-only fleet. Engine-level movement/hit counters
        live in each replica's ``kv_ship`` lifecycle block."""
        if not self._disaggregated:
            return None
        return {
            "roles": dict(self.router._roles),
            "ship_legs": self.ship_legs,
            "ship_leg_failures": self.ship_leg_failures,
            "ship_warm_skips": self.ship_warm_skips,
            "receive_reroutes": self.receive_reroutes,
            "transport": (
                self.transport.stats() if self.transport is not None else None
            ),
        }

    def lifecycle_stats(self) -> dict:
        """Fleet view for dashboards: the router block plus per-replica
        engine snapshots (each replica ALSO registers its own provider so
        the Prometheus series carry the ``replica`` label)."""
        stats = self.router.stats()
        return {
            "ready": int(stats["ring_size"] >= 1),
            "ring_size": stats["ring_size"],
            "router": stats,
            "failovers": self.failovers,
            "disaggregation": self._disagg_snapshot(),
            "replicas": {
                r.name: r.engine.lifecycle_stats() for r in self.replicas
            },
        }
