"""Batched token sampling — one jitted function for the whole decode batch.

Per-slot temperature / top-k / top-p / penalties / seeds as data (arrays over
the batch), never as Python branches, so a single XLA executable covers every
mix of sampling settings in the continuous batch (recompilation-free,
SURVEY.md §7 hard part 1).

OpenAI/vLLM sampling-parameter parity (reference §2.8 route surface):
- ``presence_penalty`` / ``frequency_penalty``: subtracted from the logits of
  tokens already generated (vLLM semantics: output tokens only), presence as
  a flat hit, frequency scaled by the count.
- ``repetition_penalty``: multiplicative push-down on every token seen in the
  prompt OR the output (vLLM semantics), divide positive logits, multiply
  negative ones.
- ``logit_bias``: dense additive bias row per slot (built host-side from the
  OpenAI sparse {token_id: bias} map).
- ``seed``: per-request deterministic sampling stream — the row's key is
  fold_in(PRNGKey(seed), tokens_generated_so_far), so identical requests
  replay identical samples regardless of batch composition; unseeded rows
  draw from the engine's shared stream (split per row).

All extras are optional (None skips their compute at trace time, keeping the
no-extras graph identical to the minimal sampler).
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp


class SamplingParams(NamedTuple):
    temperature: jnp.ndarray  # [B] float32; 0 => greedy
    top_k: jnp.ndarray        # [B] int32; 0 => disabled
    top_p: jnp.ndarray        # [B] float32; 1.0 => disabled


class SamplingExtras(NamedTuple):
    """Per-slot penalty/bias/seed state (all optional as a bundle)."""

    presence: jnp.ndarray    # [B] f32; 0 disables
    frequency: jnp.ndarray   # [B] f32; 0 disables
    repetition: jnp.ndarray  # [B] f32; 1.0 disables
    bias: jnp.ndarray        # [B, V] f32 dense additive bias
    seeds: jnp.ndarray       # [B] int32; < 0 => unseeded (shared stream)
    counters: jnp.ndarray    # [B] int32 tokens generated so far (seed stream)
    # vLLM min_tokens: the request's stop tokens (EOS and stop_token_ids)
    # are suppressed until `min_new` tokens were generated (None fields
    # disable — old constructions stay valid)
    min_new: Optional[jnp.ndarray] = None  # [B] int32; 0 disables
    stop: Optional[jnp.ndarray] = None     # [B, K] int32, -1-padded


def make_sampling_params(batch, temperature=0.0, top_k=0, top_p=1.0):
    import numpy as np

    return SamplingParams(
        temperature=jnp.asarray(np.full(batch, temperature, np.float32)),
        top_k=jnp.asarray(np.full(batch, top_k, np.int32)),
        top_p=jnp.asarray(np.full(batch, top_p, np.float32)),
    )


def penalize_logits(
    logits: jnp.ndarray,
    extras: SamplingExtras,
    counts: Optional[jnp.ndarray],
    prompt_mask: Optional[jnp.ndarray],
) -> jnp.ndarray:
    """Apply bias + penalties to raw logits [B, V] (before temperature).

    ``counts`` [B, V] int32: per-slot generated-token histogram.
    ``prompt_mask`` [B, V] bool: tokens present in the prompt."""
    logits = logits + extras.bias
    if counts is not None:
        counts_f = counts.astype(jnp.float32)
        logits = logits - extras.frequency[:, None] * counts_f
        logits = logits - extras.presence[:, None] * (counts_f > 0)
    seen = None
    if counts is not None:
        seen = counts > 0
    if prompt_mask is not None:
        seen = prompt_mask if seen is None else (seen | prompt_mask)
    if seen is not None:
        rp = extras.repetition[:, None]
        logits = jnp.where(
            seen,
            jnp.where(logits > 0, logits / rp, logits * rp),
            logits,
        )
    if extras.min_new is not None and extras.stop is not None:
        v_idx = jnp.arange(logits.shape[-1], dtype=jnp.int32)
        is_stop = jnp.any(
            v_idx[None, None, :] == extras.stop[:, :, None], axis=1
        )                                                       # [B, V]
        # never blank the whole row: when an upstream constraint (a guided
        # grammar in an accepting-only state) leaves stop tokens as the only
        # admissible choices, the grammar wins over the min_tokens floor —
        # suppressing them too would force a grammar-violating sample
        others_alive = jnp.any(
            jnp.where(is_stop, -jnp.inf, logits) > jnp.float32(-1e29),
            axis=-1, keepdims=True,
        )
        blocked = (
            (extras.counters < extras.min_new)[:, None] & is_stop & others_alive
        )
        logits = jnp.where(blocked, jnp.float32(-1e30), logits)
    return logits


def _row_keys(rng: jax.Array, extras: SamplingExtras, batch: int):
    """Per-row PRNG keys: seeded rows get fold_in(PRNGKey(seed), counter);
    unseeded rows split the shared stream."""
    shared = jax.random.split(rng, batch)                     # [B, 2] u32
    seeded = jax.vmap(
        lambda s, c: jax.random.fold_in(jax.random.PRNGKey(s), c)
    )(jnp.maximum(extras.seeds, 0), extras.counters)
    use_seed = (extras.seeds >= 0)[:, None]
    return jnp.where(use_seed, seeded, shared)


def warp_logits(
    logits: jnp.ndarray,
    temperature: jnp.ndarray,
    top_k: jnp.ndarray,
    top_p: jnp.ndarray,
) -> jnp.ndarray:
    """Temperature-scale + top-k + top-p mask: [N, V] logits with per-row
    params [N] -> masked scaled logits (softmax of the result IS the
    sampling distribution). Shared by sample_tokens and the speculative
    rejection sampler so both sample from the identical law."""
    n, v = logits.shape
    temp = jnp.maximum(temperature, 1e-6)[:, None]
    scaled = logits / temp

    # top-k mask (k == 0 disables)
    sorted_desc = jnp.sort(scaled, axis=-1)[:, ::-1]              # [N, V]
    k = jnp.where(top_k > 0, top_k, v)
    kth = jnp.take_along_axis(
        sorted_desc, jnp.minimum(k - 1, v - 1)[:, None], axis=-1
    )                                                              # [N, 1]
    scaled = jnp.where(scaled < kth, -jnp.inf, scaled)

    # top-p (nucleus) mask over the sorted distribution
    sorted_scaled = jnp.sort(scaled, axis=-1)[:, ::-1]
    probs_sorted = jax.nn.softmax(sorted_scaled, axis=-1)
    cumulative = jnp.cumsum(probs_sorted, axis=-1)
    # keep tokens while cumulative(prev) < top_p  (always keep the first)
    keep_sorted = (cumulative - probs_sorted) < top_p[:, None]
    cutoff = jnp.where(
        keep_sorted, sorted_scaled, jnp.inf
    ).min(axis=-1, keepdims=True)                                  # lowest kept logit
    return jnp.where(scaled < cutoff, -jnp.inf, scaled)


@partial(jax.jit, donate_argnums=())
def sample_tokens(
    logits: jnp.ndarray,
    params: SamplingParams,
    rng: jax.Array,
    extras: Optional[SamplingExtras] = None,
    counts: Optional[jnp.ndarray] = None,
    prompt_mask: Optional[jnp.ndarray] = None,
):
    """logits: [B, V] float32 -> token ids [B] int32.

    Rows with temperature == 0 take the argmax; others sample from the
    temperature-scaled, top-k/top-p-filtered distribution. Penalties/bias
    (extras) apply to BOTH paths — greedy decoding respects them too.
    """
    b, v = logits.shape
    if extras is not None:
        logits = penalize_logits(logits, extras, counts, prompt_mask)
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    scaled = warp_logits(logits, params.temperature, params.top_k, params.top_p)

    if extras is None:
        sampled = jax.random.categorical(rng, scaled, axis=-1).astype(jnp.int32)
    else:
        keys = _row_keys(rng, extras, b)
        sampled = jax.vmap(
            lambda key, row: jax.random.categorical(key, row)
        )(keys, scaled).astype(jnp.int32)
    return jnp.where(params.temperature <= 0.0, greedy, sampled)


def speculative_sample_chain(
    logits: jnp.ndarray,   # [B, K+1, V] verify-pass logits (float32)
    drafts: jnp.ndarray,   # [B, K] int32 proposed draft tokens
    params: SamplingParams,
    rng: jax.Array,
):
    """Rejection-based speculative SAMPLING over a deterministic draft
    chain (vLLM spec-decode semantics for temperature > 0).

    The n-gram proposer is a point mass q = delta(d_i), so the standard
    accept rule collapses to: accept draft d_i with probability P_i(d_i);
    at the first rejection emit one sample from the residual (P_i with the
    draft removed, renormalized); if all K drafts are accepted emit a
    bonus sample from P_K. The marginal law of the emitted prefix is
    EXACTLY autoregressive sampling from the warped per-position
    distributions P_i = softmax(warp(logits_i)) — same warp (temperature /
    top-k / top-p) sample_tokens uses, so speculated and plain slots draw
    from an identical law.

    Returns (tokens [B, K+1], acc [B]): tokens[b, :acc[b]] are the accepted
    drafts and tokens[b, acc[b]] is the residual/bonus sample; entries past
    acc[b] are meaningless (the engine emits acc+1 per round).
    """
    b, k1, v = logits.shape
    k = k1 - 1
    rep = lambda x: jnp.repeat(x, k1)
    warped = warp_logits(
        logits.reshape(b * k1, v),
        rep(params.temperature), rep(params.top_k), rep(params.top_p),
    ).reshape(b, k1, v)
    probs = jax.nn.softmax(warped, axis=-1)
    r_acc, r_gum = jax.random.split(rng)
    u = jax.random.uniform(r_acc, (b, k))
    p_draft = jnp.take_along_axis(
        probs[:, :k], drafts[..., None].astype(jnp.int32), axis=-1
    )[..., 0]                                                      # [B, K]
    accept = u < p_draft
    acc = jnp.sum(jnp.cumprod(accept.astype(jnp.int32), axis=1), axis=1)
    # fallback samples per position: residual (draft masked out) for the
    # K draft positions, plain bonus for position K. A row whose residual
    # is empty (P(d) == 1) is unreachable: u < 1 always accepts it.
    draft_hot = jax.nn.one_hot(drafts, v, dtype=bool)              # [B, K, V]
    w_resid = jnp.where(draft_hot, -jnp.inf, warped[:, :k])
    w_all = jnp.concatenate([w_resid, warped[:, k:]], axis=1)      # [B, K+1, V]
    fallback = jax.random.categorical(
        r_gum, w_all, axis=-1
    ).astype(jnp.int32)                                            # [B, K+1]
    f_at = jnp.take_along_axis(fallback, acc[:, None], axis=1)[:, 0]
    tokens = jnp.concatenate(
        [drafts.astype(jnp.int32), fallback[:, k:]], axis=1
    )
    tokens = tokens.at[jnp.arange(b), acc].set(f_at)
    return tokens, acc
