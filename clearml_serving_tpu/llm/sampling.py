"""Batched token sampling — one jitted function for the whole decode batch.

Per-slot temperature / top-k / top-p as data (arrays over the batch), never as
Python branches, so a single XLA executable covers every mix of sampling
settings in the continuous batch (recompilation-free, SURVEY.md §7 hard part 1).
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp


class SamplingParams(NamedTuple):
    temperature: jnp.ndarray  # [B] float32; 0 => greedy
    top_k: jnp.ndarray        # [B] int32; 0 => disabled
    top_p: jnp.ndarray        # [B] float32; 1.0 => disabled


def make_sampling_params(batch, temperature=0.0, top_k=0, top_p=1.0):
    import numpy as np

    return SamplingParams(
        temperature=jnp.asarray(np.full(batch, temperature, np.float32)),
        top_k=jnp.asarray(np.full(batch, top_k, np.int32)),
        top_p=jnp.asarray(np.full(batch, top_p, np.float32)),
    )


@partial(jax.jit, donate_argnums=())
def sample_tokens(logits: jnp.ndarray, params: SamplingParams, rng: jax.Array):
    """logits: [B, V] float32 -> token ids [B] int32.

    Rows with temperature == 0 take the argmax; others sample from the
    temperature-scaled, top-k/top-p-filtered distribution.
    """
    b, v = logits.shape
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    temp = jnp.maximum(params.temperature, 1e-6)[:, None]
    scaled = logits / temp

    # top-k mask (k == 0 disables)
    sorted_desc = jnp.sort(scaled, axis=-1)[:, ::-1]              # [B, V]
    k = jnp.where(params.top_k > 0, params.top_k, v)
    kth = jnp.take_along_axis(
        sorted_desc, jnp.minimum(k - 1, v - 1)[:, None], axis=-1
    )                                                              # [B, 1]
    scaled = jnp.where(scaled < kth, -jnp.inf, scaled)

    # top-p (nucleus) mask over the sorted distribution
    sorted_scaled = jnp.sort(scaled, axis=-1)[:, ::-1]
    probs_sorted = jax.nn.softmax(sorted_scaled, axis=-1)
    cumulative = jnp.cumsum(probs_sorted, axis=-1)
    # keep tokens while cumulative(prev) < top_p  (always keep the first)
    keep_sorted = (cumulative - probs_sorted) < params.top_p[:, None]
    cutoff = jnp.where(
        keep_sorted, sorted_scaled, jnp.inf
    ).min(axis=-1, keepdims=True)                                  # lowest kept logit
    scaled = jnp.where(scaled < cutoff, -jnp.inf, scaled)

    sampled = jax.random.categorical(rng, scaled, axis=-1).astype(jnp.int32)
    return jnp.where(params.temperature <= 0.0, greedy, sampled)
