"""Batched token sampling — one jitted function for the whole decode batch.

Per-slot temperature / top-k / top-p / penalties / seeds as data (arrays over
the batch), never as Python branches, so a single XLA executable covers every
mix of sampling settings in the continuous batch (recompilation-free,
SURVEY.md §7 hard part 1).

OpenAI/vLLM sampling-parameter parity (reference §2.8 route surface):
- ``presence_penalty`` / ``frequency_penalty``: subtracted from the logits of
  tokens already generated (vLLM semantics: output tokens only), presence as
  a flat hit, frequency scaled by the count.
- ``repetition_penalty``: multiplicative push-down on every token seen in the
  prompt OR the output (vLLM semantics), divide positive logits, multiply
  negative ones.
- ``logit_bias``: dense additive bias row per slot (built host-side from the
  OpenAI sparse {token_id: bias} map).
- ``seed``: per-request deterministic sampling stream — the row's key is
  fold_in(PRNGKey(seed), tokens_generated_so_far), so identical requests
  replay identical samples regardless of batch composition; unseeded rows
  draw from the engine's shared stream (split per row).

All extras are optional (None skips their compute at trace time, keeping the
no-extras graph identical to the minimal sampler).
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp


class SamplingParams(NamedTuple):
    temperature: jnp.ndarray  # [B] float32; 0 => greedy
    top_k: jnp.ndarray        # [B] int32; 0 => disabled
    top_p: jnp.ndarray        # [B] float32; 1.0 => disabled


class SamplingExtras(NamedTuple):
    """Per-slot penalty/bias/seed state (all optional as a bundle)."""

    presence: jnp.ndarray    # [B] f32; 0 disables
    frequency: jnp.ndarray   # [B] f32; 0 disables
    repetition: jnp.ndarray  # [B] f32; 1.0 disables
    bias: jnp.ndarray        # [B, V] f32 dense additive bias
    seeds: jnp.ndarray       # [B] int32; < 0 => unseeded (shared stream)
    counters: jnp.ndarray    # [B] int32 tokens generated so far (seed stream)
    # vLLM min_tokens: the request's stop tokens (EOS and stop_token_ids)
    # are suppressed until `min_new` tokens were generated (None fields
    # disable — old constructions stay valid)
    min_new: Optional[jnp.ndarray] = None  # [B] int32; 0 disables
    stop: Optional[jnp.ndarray] = None     # [B, K] int32, -1-padded


def make_sampling_params(batch, temperature=0.0, top_k=0, top_p=1.0):
    import numpy as np

    return SamplingParams(
        temperature=jnp.asarray(np.full(batch, temperature, np.float32)),
        top_k=jnp.asarray(np.full(batch, top_k, np.int32)),
        top_p=jnp.asarray(np.full(batch, top_p, np.float32)),
    )


def penalize_logits(
    logits: jnp.ndarray,
    extras: SamplingExtras,
    counts: Optional[jnp.ndarray],
    prompt_mask: Optional[jnp.ndarray],
) -> jnp.ndarray:
    """Apply bias + penalties to raw logits [B, V] (before temperature).

    ``counts`` [B, V] int32: per-slot generated-token histogram.
    ``prompt_mask`` [B, V] bool: tokens present in the prompt."""
    logits = logits + extras.bias
    if counts is not None:
        counts_f = counts.astype(jnp.float32)
        logits = logits - extras.frequency[:, None] * counts_f
        logits = logits - extras.presence[:, None] * (counts_f > 0)
    seen = None
    if counts is not None:
        seen = counts > 0
    if prompt_mask is not None:
        seen = prompt_mask if seen is None else (seen | prompt_mask)
    if seen is not None:
        rp = extras.repetition[:, None]
        logits = jnp.where(
            seen,
            jnp.where(logits > 0, logits / rp, logits * rp),
            logits,
        )
    if extras.min_new is not None and extras.stop is not None:
        v_idx = jnp.arange(logits.shape[-1], dtype=jnp.int32)
        is_stop = jnp.any(
            v_idx[None, None, :] == extras.stop[:, :, None], axis=1
        )                                                       # [B, V]
        # never blank the whole row: when an upstream constraint (a guided
        # grammar in an accepting-only state) leaves stop tokens as the only
        # admissible choices, the grammar wins over the min_tokens floor —
        # suppressing them too would force a grammar-violating sample
        others_alive = jnp.any(
            jnp.where(is_stop, -jnp.inf, logits) > jnp.float32(-1e29),
            axis=-1, keepdims=True,
        )
        blocked = (
            (extras.counters < extras.min_new)[:, None] & is_stop & others_alive
        )
        logits = jnp.where(blocked, jnp.float32(-1e30), logits)
    return logits


def _row_keys(rng: jax.Array, extras: SamplingExtras, batch: int):
    """Per-row PRNG keys: seeded rows get fold_in(PRNGKey(seed), counter);
    unseeded rows split the shared stream."""
    shared = jax.random.split(rng, batch)                     # [B, 2] u32
    seeded = jax.vmap(
        lambda s, c: jax.random.fold_in(jax.random.PRNGKey(s), c)
    )(jnp.maximum(extras.seeds, 0), extras.counters)
    use_seed = (extras.seeds >= 0)[:, None]
    return jnp.where(use_seed, seeded, shared)


def warp_logits(
    logits: jnp.ndarray,
    temperature: jnp.ndarray,
    top_k: jnp.ndarray,
    top_p: jnp.ndarray,
) -> jnp.ndarray:
    """Temperature-scale + top-k + top-p mask: [N, V] logits with per-row
    params [N] -> masked scaled logits (softmax of the result IS the
    sampling distribution). Shared by sample_tokens and the speculative
    rejection sampler so both sample from the identical law."""
    n, v = logits.shape
    temp = jnp.maximum(temperature, 1e-6)[:, None]
    scaled = logits / temp

    # top-k mask (k == 0 disables)
    sorted_desc = jnp.sort(scaled, axis=-1)[:, ::-1]              # [N, V]
    k = jnp.where(top_k > 0, top_k, v)
    kth = jnp.take_along_axis(
        sorted_desc, jnp.minimum(k - 1, v - 1)[:, None], axis=-1
    )                                                              # [N, 1]
    scaled = jnp.where(scaled < kth, -jnp.inf, scaled)

    # top-p (nucleus) mask over the sorted distribution
    sorted_scaled = jnp.sort(scaled, axis=-1)[:, ::-1]
    probs_sorted = jax.nn.softmax(sorted_scaled, axis=-1)
    cumulative = jnp.cumsum(probs_sorted, axis=-1)
    # keep tokens while cumulative(prev) < top_p  (always keep the first)
    keep_sorted = (cumulative - probs_sorted) < top_p[:, None]
    cutoff = jnp.where(
        keep_sorted, sorted_scaled, jnp.inf
    ).min(axis=-1, keepdims=True)                                  # lowest kept logit
    return jnp.where(scaled < cutoff, -jnp.inf, scaled)


@partial(jax.jit, donate_argnums=())
def sample_tokens(
    logits: jnp.ndarray,
    params: SamplingParams,
    rng: jax.Array,
    extras: Optional[SamplingExtras] = None,
    counts: Optional[jnp.ndarray] = None,
    prompt_mask: Optional[jnp.ndarray] = None,
):
    """logits: [B, V] float32 -> token ids [B] int32.

    Rows with temperature == 0 take the argmax; others sample from the
    temperature-scaled, top-k/top-p-filtered distribution. Penalties/bias
    (extras) apply to BOTH paths — greedy decoding respects them too.
    """
    b, v = logits.shape
    if extras is not None:
        logits = penalize_logits(logits, extras, counts, prompt_mask)
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    scaled = warp_logits(logits, params.temperature, params.top_k, params.top_p)

    if extras is None:
        sampled = jax.random.categorical(rng, scaled, axis=-1).astype(jnp.int32)
    else:
        keys = _row_keys(rng, extras, b)
        sampled = jax.vmap(
            lambda key, row: jax.random.categorical(key, row)
        )(keys, scaled).astype(jnp.int32)
    return jnp.where(params.temperature <= 0.0, greedy, sampled)


def greedy_tree_walk(
    greedy: jnp.ndarray,    # [B, N] int32 argmax token per tree node
    tokens: jnp.ndarray,    # [B, N] int32 node tokens (node 0 = root)
    parents: jnp.ndarray,   # [B, N] int32, parents[:, 0] == -1
    n_nodes: jnp.ndarray,   # [B] int32 live node count (>= 1)
):
    """Longest accepted root-to-leaf path under GREEDY acceptance
    (docs/spec_decode_trees.md): walking from the root, a child node is
    accepted iff its draft token equals the argmax of its parent's
    verify logits — at most one child can match, so the walk is
    deterministic. Returns (path [B, N], acc [B]): path[b, :acc] are the
    accepted draft tokens in path order and path[b, acc] is the bonus
    token (the argmax at the last accepted node).

    Nodes are processed in index order; the parent-before-child layout
    (spec_proposer.DraftForest) makes that a topological order, and the
    frontier test ``parents[:, j] == cur`` skips every node off the
    accepted path. On the degenerate chain topology this reproduces the
    chain rule acc = sum(cumprod(drafts == argmax[:, :k])) exactly.

    The third output ``nodes`` [B, N] maps row POSITION to the tree NODE
    whose K/V belongs there after acceptance: nodes[b, i] is the node
    index of the i-th accepted path token (identity for i == 0 and for
    every position past acc) — the engine's in-launch KV path compaction
    gathers pool entries at nodes[b, i] and rewrites them at position i,
    so the kept prefix is contiguous exactly like a chain's.
    """
    b, n = tokens.shape
    rows = jnp.arange(b)
    col = jnp.arange(n, dtype=jnp.int32)[None, :]
    cur = jnp.zeros(b, jnp.int32)
    acc = jnp.zeros(b, jnp.int32)
    path = jnp.zeros((b, n), jnp.int32)
    nodes = jnp.broadcast_to(col.astype(jnp.int32), (b, n))
    for j in range(1, n):
        tok = tokens[:, j]
        ok = (
            (j < n_nodes)
            & (parents[:, j] == cur)
            & (tok == greedy[rows, cur])
        )
        path = jnp.where((col == acc[:, None]) & ok[:, None],
                         tok[:, None], path)
        nodes = jnp.where((col == acc[:, None] + 1) & ok[:, None],
                          jnp.int32(j), nodes)
        cur = jnp.where(ok, j, cur)
        acc = acc + ok.astype(jnp.int32)
    bonus = greedy[rows, cur]
    path = jnp.where(col == acc[:, None], bonus[:, None], path)
    return path, acc, nodes


def speculative_sample_tree(
    logits: jnp.ndarray,    # [B, N, V] verify logits per tree node
    tokens: jnp.ndarray,    # [B, N] int32 node tokens (node 0 = root)
    parents: jnp.ndarray,   # [B, N] int32, parents[:, 0] == -1
    n_nodes: jnp.ndarray,   # [B] int32 live node count
    params: SamplingParams,
    rng: jax.Array,
):
    """Multi-draft rejection sampling over a draft TREE (the SpecInfer /
    recursive-rejection scheme specialized to point-mass proposers,
    docs/spec_decode_trees.md).

    Walking from the root in node-index order, each frontier child with
    draft token d is accepted with probability P_cur(d) / (1 - R) where
    P_cur = softmax(warp(logits_cur)) and R is the mass of this node's
    already-REJECTED sibling drafts (the sequential point-mass residual
    correction); an accepted child advances the walk and resets R. After
    all nodes are processed, one token is sampled from the last accepted
    node's residual (its rejected children masked out, renormalized by
    the categorical) — or its plain warped distribution when every child
    was accepted. The emitted path's marginal law is exactly
    autoregressive sampling from the warped per-position distributions.

    On the degenerate chain topology (parents j-1, one child per node)
    the sibling correction divides by exactly 1.0 and the residual masks
    exactly the rejected draft, so the emitted tokens are BYTE-IDENTICAL
    to :func:`speculative_sample_chain` under the same rng — the shapes
    of both internal draws (u [B, N-1], categorical over [B, N, V])
    match the chain's, which tests/test_spec_tree.py pins.

    Returns (path [B, N], acc [B], nodes [B, N]) with the chain
    function's token contract: path[b, :acc] accepted draft tokens in
    path order, path[b, acc] the residual/bonus sample, entries past acc
    meaningless. ``nodes`` maps row position to accepted tree node like
    :func:`greedy_tree_walk` (identity past acc) for KV path compaction.
    """
    b, n, v = logits.shape
    rep = lambda x: jnp.repeat(x, n)
    warped = warp_logits(
        logits.reshape(b * n, v),
        rep(params.temperature), rep(params.top_k), rep(params.top_p),
    ).reshape(b, n, v)
    probs = jax.nn.softmax(warped, axis=-1)
    r_acc, r_gum = jax.random.split(rng)
    u = jax.random.uniform(r_acc, (b, n - 1))
    rows = jnp.arange(b)
    col = jnp.arange(n, dtype=jnp.int32)[None, :]
    cur = jnp.zeros(b, jnp.int32)
    acc = jnp.zeros(b, jnp.int32)
    path = jnp.zeros((b, n), jnp.int32)
    nodes = jnp.broadcast_to(col.astype(jnp.int32), (b, n))
    rej_mass = jnp.zeros(b, jnp.float32)
    rejected = jnp.zeros((b, n), bool)
    for j in range(1, n):
        tok = tokens[:, j]
        test = (j < n_nodes) & (parents[:, j] == cur)
        p_tok = probs[rows, cur, tok]
        p_adj = p_tok / jnp.maximum(1.0 - rej_mass, 1e-9)
        ok = test & (u[:, j - 1] < p_adj)
        rej = test & ~ok
        path = jnp.where((col == acc[:, None]) & ok[:, None],
                         tok[:, None], path)
        nodes = jnp.where((col == acc[:, None] + 1) & ok[:, None],
                          jnp.int32(j), nodes)
        rejected = rejected.at[:, j].set(rej)
        rej_mass = jnp.where(
            ok, 0.0, jnp.where(rej, rej_mass + p_tok, rej_mass)
        )
        cur = jnp.where(ok, j, cur)
        acc = acc + ok.astype(jnp.int32)
    # residual per NODE: its rejected children's draft tokens masked out.
    # Only the final node's row is selected, but drawing the categorical
    # over the full [B, N, V] keeps the gumbel stream aligned with the
    # chain sampler's w_all draw (byte-identity on chain topologies).
    par_oh = jax.nn.one_hot(parents[:, 1:], n, dtype=jnp.float32)
    tok_oh = jax.nn.one_hot(tokens[:, 1:], v, dtype=jnp.float32)
    rej_w = rejected[:, 1:].astype(jnp.float32)[..., None] * par_oh
    rej_tokens = jnp.einsum("bjn,bjv->bnv", rej_w, tok_oh) > 0.0
    w_all = jnp.where(rej_tokens, -jnp.inf, warped)
    fallback = jax.random.categorical(
        r_gum, w_all, axis=-1
    ).astype(jnp.int32)                                        # [B, N]
    f_at = jnp.take_along_axis(fallback, cur[:, None], axis=1)[:, 0]
    path = jnp.where(col == acc[:, None], f_at[:, None], path)
    return path, acc, nodes


def speculative_sample_chain(
    logits: jnp.ndarray,   # [B, K+1, V] verify-pass logits (float32)
    drafts: jnp.ndarray,   # [B, K] int32 proposed draft tokens
    params: SamplingParams,
    rng: jax.Array,
):
    """Rejection-based speculative SAMPLING over a deterministic draft
    chain (vLLM spec-decode semantics for temperature > 0).

    The n-gram proposer is a point mass q = delta(d_i), so the standard
    accept rule collapses to: accept draft d_i with probability P_i(d_i);
    at the first rejection emit one sample from the residual (P_i with the
    draft removed, renormalized); if all K drafts are accepted emit a
    bonus sample from P_K. The marginal law of the emitted prefix is
    EXACTLY autoregressive sampling from the warped per-position
    distributions P_i = softmax(warp(logits_i)) — same warp (temperature /
    top-k / top-p) sample_tokens uses, so speculated and plain slots draw
    from an identical law.

    Returns (tokens [B, K+1], acc [B]): tokens[b, :acc[b]] are the accepted
    drafts and tokens[b, acc[b]] is the residual/bonus sample; entries past
    acc[b] are meaningless (the engine emits acc+1 per round).
    """
    b, k1, v = logits.shape
    k = k1 - 1
    rep = lambda x: jnp.repeat(x, k1)
    warped = warp_logits(
        logits.reshape(b * k1, v),
        rep(params.temperature), rep(params.top_k), rep(params.top_p),
    ).reshape(b, k1, v)
    probs = jax.nn.softmax(warped, axis=-1)
    r_acc, r_gum = jax.random.split(rng)
    u = jax.random.uniform(r_acc, (b, k))
    p_draft = jnp.take_along_axis(
        probs[:, :k], drafts[..., None].astype(jnp.int32), axis=-1
    )[..., 0]                                                      # [B, K]
    accept = u < p_draft
    acc = jnp.sum(jnp.cumprod(accept.astype(jnp.int32), axis=1), axis=1)
    # fallback samples per position: residual (draft masked out) for the
    # K draft positions, plain bonus for position K. A row whose residual
    # is empty (P(d) == 1) is unreachable: u < 1 always accepts it.
    draft_hot = jax.nn.one_hot(drafts, v, dtype=bool)              # [B, K, V]
    w_resid = jnp.where(draft_hot, -jnp.inf, warped[:, :k])
    w_all = jnp.concatenate([w_resid, warped[:, k:]], axis=1)      # [B, K+1, V]
    fallback = jax.random.categorical(
        r_gum, w_all, axis=-1
    ).astype(jnp.int32)                                            # [B, K+1]
    f_at = jnp.take_along_axis(fallback, acc[:, None], axis=1)[:, 0]
    tokens = jnp.concatenate(
        [drafts.astype(jnp.int32), fallback[:, k:]], axis=1
    )
    tokens = tokens.at[jnp.arange(b), acc].set(f_at)
    return tokens, acc
