"""Batched token sampling — one jitted function for the whole decode batch.

Per-slot temperature / top-k / top-p / penalties / seeds as data (arrays over
the batch), never as Python branches, so a single XLA executable covers every
mix of sampling settings in the continuous batch (recompilation-free,
SURVEY.md §7 hard part 1).

OpenAI/vLLM sampling-parameter parity (reference §2.8 route surface):
- ``presence_penalty`` / ``frequency_penalty``: subtracted from the logits of
  tokens already generated (vLLM semantics: output tokens only), presence as
  a flat hit, frequency scaled by the count.
- ``repetition_penalty``: multiplicative push-down on every token seen in the
  prompt OR the output (vLLM semantics), divide positive logits, multiply
  negative ones.
- ``logit_bias``: dense additive bias row per slot (built host-side from the
  OpenAI sparse {token_id: bias} map).
- ``seed``: per-request deterministic sampling stream — the row's key is
  fold_in(PRNGKey(seed), tokens_generated_so_far), so identical requests
  replay identical samples regardless of batch composition; unseeded rows
  draw from the engine's shared stream (split per row).

All extras are optional (None skips their compute at trace time, keeping the
no-extras graph identical to the minimal sampler).
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp


class SamplingParams(NamedTuple):
    temperature: jnp.ndarray  # [B] float32; 0 => greedy
    top_k: jnp.ndarray        # [B] int32; 0 => disabled
    top_p: jnp.ndarray        # [B] float32; 1.0 => disabled


class SamplingExtras(NamedTuple):
    """Per-slot penalty/bias/seed state (all optional as a bundle)."""

    presence: jnp.ndarray    # [B] f32; 0 disables
    frequency: jnp.ndarray   # [B] f32; 0 disables
    repetition: jnp.ndarray  # [B] f32; 1.0 disables
    bias: jnp.ndarray        # [B, V] f32 dense additive bias
    seeds: jnp.ndarray       # [B] int32; < 0 => unseeded (shared stream)
    counters: jnp.ndarray    # [B] int32 tokens generated so far (seed stream)
    # vLLM min_tokens: the request's stop tokens (EOS and stop_token_ids)
    # are suppressed until `min_new` tokens were generated (None fields
    # disable — old constructions stay valid)
    min_new: Optional[jnp.ndarray] = None  # [B] int32; 0 disables
    stop: Optional[jnp.ndarray] = None     # [B, K] int32, -1-padded


def make_sampling_params(batch, temperature=0.0, top_k=0, top_p=1.0):
    import numpy as np

    return SamplingParams(
        temperature=jnp.asarray(np.full(batch, temperature, np.float32)),
        top_k=jnp.asarray(np.full(batch, top_k, np.int32)),
        top_p=jnp.asarray(np.full(batch, top_p, np.float32)),
    )


def penalize_logits(
    logits: jnp.ndarray,
    extras: SamplingExtras,
    counts: Optional[jnp.ndarray],
    prompt_mask: Optional[jnp.ndarray],
) -> jnp.ndarray:
    """Apply bias + penalties to raw logits [B, V] (before temperature).

    ``counts`` [B, V] int32: per-slot generated-token histogram.
    ``prompt_mask`` [B, V] bool: tokens present in the prompt."""
    logits = logits + extras.bias
    if counts is not None:
        counts_f = counts.astype(jnp.float32)
        logits = logits - extras.frequency[:, None] * counts_f
        logits = logits - extras.presence[:, None] * (counts_f > 0)
    seen = None
    if counts is not None:
        seen = counts > 0
    if prompt_mask is not None:
        seen = prompt_mask if seen is None else (seen | prompt_mask)
    if seen is not None:
        rp = extras.repetition[:, None]
        logits = jnp.where(
            seen,
            jnp.where(logits > 0, logits / rp, logits * rp),
            logits,
        )
    if extras.min_new is not None and extras.stop is not None:
        v_idx = jnp.arange(logits.shape[-1], dtype=jnp.int32)
        is_stop = jnp.any(
            v_idx[None, None, :] == extras.stop[:, :, None], axis=1
        )                                                       # [B, V]
        # never blank the whole row: when an upstream constraint (a guided
        # grammar in an accepting-only state) leaves stop tokens as the only
        # admissible choices, the grammar wins over the min_tokens floor —
        # suppressing them too would force a grammar-violating sample
        others_alive = jnp.any(
            jnp.where(is_stop, -jnp.inf, logits) > jnp.float32(-1e29),
            axis=-1, keepdims=True,
        )
        blocked = (
            (extras.counters < extras.min_new)[:, None] & is_stop & others_alive
        )
        logits = jnp.where(blocked, jnp.float32(-1e30), logits)
    return logits


def _row_keys(rng: jax.Array, extras: SamplingExtras, batch: int):
    """Per-row PRNG keys: seeded rows get fold_in(PRNGKey(seed), counter);
    unseeded rows split the shared stream."""
    shared = jax.random.split(rng, batch)                     # [B, 2] u32
    seeded = jax.vmap(
        lambda s, c: jax.random.fold_in(jax.random.PRNGKey(s), c)
    )(jnp.maximum(extras.seeds, 0), extras.counters)
    use_seed = (extras.seeds >= 0)[:, None]
    return jnp.where(use_seed, seeded, shared)


@partial(jax.jit, donate_argnums=())
def sample_tokens(
    logits: jnp.ndarray,
    params: SamplingParams,
    rng: jax.Array,
    extras: Optional[SamplingExtras] = None,
    counts: Optional[jnp.ndarray] = None,
    prompt_mask: Optional[jnp.ndarray] = None,
):
    """logits: [B, V] float32 -> token ids [B] int32.

    Rows with temperature == 0 take the argmax; others sample from the
    temperature-scaled, top-k/top-p-filtered distribution. Penalties/bias
    (extras) apply to BOTH paths — greedy decoding respects them too.
    """
    b, v = logits.shape
    if extras is not None:
        logits = penalize_logits(logits, extras, counts, prompt_mask)
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    temp = jnp.maximum(params.temperature, 1e-6)[:, None]
    scaled = logits / temp

    # top-k mask (k == 0 disables)
    sorted_desc = jnp.sort(scaled, axis=-1)[:, ::-1]              # [B, V]
    k = jnp.where(params.top_k > 0, params.top_k, v)
    kth = jnp.take_along_axis(
        sorted_desc, jnp.minimum(k - 1, v - 1)[:, None], axis=-1
    )                                                              # [B, 1]
    scaled = jnp.where(scaled < kth, -jnp.inf, scaled)

    # top-p (nucleus) mask over the sorted distribution
    sorted_scaled = jnp.sort(scaled, axis=-1)[:, ::-1]
    probs_sorted = jax.nn.softmax(sorted_scaled, axis=-1)
    cumulative = jnp.cumsum(probs_sorted, axis=-1)
    # keep tokens while cumulative(prev) < top_p  (always keep the first)
    keep_sorted = (cumulative - probs_sorted) < params.top_p[:, None]
    cutoff = jnp.where(
        keep_sorted, sorted_scaled, jnp.inf
    ).min(axis=-1, keepdims=True)                                  # lowest kept logit
    scaled = jnp.where(scaled < cutoff, -jnp.inf, scaled)

    if extras is None:
        sampled = jax.random.categorical(rng, scaled, axis=-1).astype(jnp.int32)
    else:
        keys = _row_keys(rng, extras, b)
        sampled = jax.vmap(
            lambda key, row: jax.random.categorical(key, row)
        )(keys, scaled).astype(jnp.int32)
    return jnp.where(params.temperature <= 0.0, greedy, sampled)
