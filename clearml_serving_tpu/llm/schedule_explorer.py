"""Deterministic interleaving explorer: permute thread schedules at the
engine's yield-point seams and prove the concurrency invariants hold under
EVERY explored ordering.

tpuserve-analyze's TPU5xx rules (analyze/rules_threads.py) are the static
half of the race net; this module is the dynamic half, mirroring how PR 3
paired the AST rules with the runtime KV sanitizer. The static pass has
documented blind spots — cross-module calls, dynamic dispatch, buffers
renamed through parameters — and exactly those are covered here: scenarios
model the engine's cross-thread protocols (the PR-4 host-buffer handoff,
the quarantine barrier, preemption pin balance, chain reset on failed
dispatch, lock-guarded refcounts) over the REAL primitives (PagePool, the
KV sanitizer) with explicit yield points, and a seeded scheduler explores
K interleavings per scenario.

How it works
------------

- Scenario threads are real ``threading.Thread``\\ s, but exactly ONE runs
  at any instant: each thread parks at every :meth:`ScenarioContext.
  yield_point` call and the scheduler hands the run token to a thread
  chosen by a seeded ``random.Random`` — so a schedule is a reproducible
  sequence of (thread, seam) steps, replayable from its seed.
- Yield-point labels are the engine's fault seams (``engine.dispatch.
  prepare``, ``engine.decode``, ``engine.decode.retire``, ...):
  :data:`YIELD_POINTS` must stay a subset of ``faults.KNOWN_POINTS``
  (test_schedule_explorer pins it), so the same seam vocabulary drives
  chaos specs, the analyzer's TPU403 registry, and this explorer.
- Invariants are asserted inside and after every schedule; a failure
  raises :class:`ScheduleViolation` carrying the scenario, seed, and the
  full schedule trace — the interleaving IS the repro.

Mutation self-test
------------------

Each scenario carries a seeded defect (:data:`MUTATIONS`): dropping the
PR-4 buffer copy, the quarantine barrier, a preemption unpin, the chain
reset, or a lock acquisition. ``self_test()`` proves the net has no holes:
with the mutation armed the explorer must CATCH it within K schedules;
without it, all K schedules must stay green. ``scripts/tier1.sh`` runs
``--smoke`` (clean sweep + self-test at small K, fixed seed) with the
other static checks.

CLI::

    python -m clearml_serving_tpu.llm.schedule_explorer                # full sweep
    python -m clearml_serving_tpu.llm.schedule_explorer --scenario pin_balance
    python -m clearml_serving_tpu.llm.schedule_explorer --mutate drop_unpin
    python -m clearml_serving_tpu.llm.schedule_explorer --self-test
    python -m clearml_serving_tpu.llm.schedule_explorer --smoke        # CI gate
"""

from __future__ import annotations

import random
import threading
from contextlib import contextmanager
from typing import Any, Callable, Dict, List, Optional

import numpy as np

__all__ = [
    "YIELD_POINTS",
    "MUTATIONS",
    "SCENARIOS",
    "ScheduleViolation",
    "ScenarioContext",
    "explore",
    "self_test",
]

# seam vocabulary: every engine-boundary label a scenario may park on.
# MUST stay a subset of llm/faults.py KNOWN_POINTS — the engine fires these
# as fault points at the same boundaries, so chaos specs, tpuserve-analyze
# TPU403, and the explorer share one registry.
YIELD_POINTS = frozenset({
    "engine.dispatch.prepare",   # loop snapshot done, worker not started
    "engine.decode",             # dispatch worker device call
    "engine.decode.retire",      # loop-thread readback/emission
    "engine.prefill",            # admission worker
    "engine.preempt",            # mid-preemption commit boundary
    "engine.watchdog",           # trip: epoch bump + in-flight failure
    "engine.drain",              # drained boundary before the leak audit
    "engine.release",            # slot teardown before page frees
    "engine.kv.ship",            # prefill-commit export into the transport
    "engine.kv.receive",         # decode-side shipment import + publish
})

# internal (non-engine) park labels the scheduler also accepts
_INTERNAL_LABELS = frozenset({"lock-wait"})

_STEP_TIMEOUT = 30.0   # a parked thread that never resumes = harness bug
_MAX_STEPS = 4000      # livelock guard (cooperative spins are bounded)


class ScheduleViolation(AssertionError):
    """A concurrency invariant failed under an explored interleaving.
    Carries the scenario, the schedule seed, and the (thread, seam) trace —
    enough to replay the exact ordering."""

    def __init__(self, message: str, *, scenario: str = "", seed: int = 0,
                 trace: Optional[List[str]] = None):
        super().__init__(message)
        self.scenario = scenario
        self.seed = seed
        self.trace = list(trace or [])


class _SceneThread:
    __slots__ = ("name", "fn", "thread", "go", "done", "error")

    def __init__(self, name: str, fn: Callable[[], None]):
        self.name = name
        self.fn = fn
        self.thread: Optional[threading.Thread] = None
        self.go = threading.Event()
        self.done = False
        self.error: Optional[BaseException] = None


class ScenarioContext:
    """One schedule's worth of deterministic scheduling state. Scenario
    bodies spawn threads, park at yield points, and query seeded defects;
    ``run()`` drives the interleaving chosen by the seeded RNG."""

    def __init__(self, rng: random.Random, mutations: frozenset = frozenset(),
                 *, scenario: str = "", seed: int = 0):
        self._rng = rng
        self._mutations = frozenset(mutations)
        self.scenario = scenario
        self.seed = seed
        self._threads: List[_SceneThread] = []
        self._handback = threading.Event()
        self._tls = threading.local()
        self._holders: Dict[str, _SceneThread] = {}
        self.trace: List[str] = []

    # -- scenario surface --------------------------------------------------

    def mutating(self, name: str) -> bool:
        """True when the named seeded defect is armed for this run."""
        return name in self._mutations

    def spawn(self, fn: Callable[[], None], name: str) -> None:
        self._threads.append(_SceneThread(name, fn))

    def yield_point(self, label: str) -> None:
        """Park the calling scenario thread at a seam; the scheduler decides
        who runs next. Labels must come from the shared seam vocabulary."""
        if label not in YIELD_POINTS and label not in _INTERNAL_LABELS:
            raise ValueError(
                "unknown yield point {!r} (known: {})".format(
                    label, ", ".join(sorted(YIELD_POINTS))
                )
            )
        st = getattr(self._tls, "st", None)
        if st is None:
            return  # called off a scenario thread (setup code): no-op
        self.trace.append("{}:{}".format(st.name, label))
        self._handback.set()
        if not st.go.wait(_STEP_TIMEOUT):
            raise RuntimeError("scheduler never resumed {}".format(st.name))
        st.go.clear()

    @contextmanager
    def critical(self, name: str = "lock"):
        """Cooperative mutex: models a lock at yield-point granularity
        without real-lock deadlocks against parked holders (the waiter
        parks instead of blocking, so the scheduler can run the holder)."""
        me = getattr(self._tls, "st", None)
        while self._holders.get(name) not in (None, me):
            self.yield_point("lock-wait")
        self._holders[name] = me
        try:
            yield
        finally:
            self._holders.pop(name, None)

    # -- scheduler ---------------------------------------------------------

    def _body(self, st: _SceneThread) -> None:
        self._tls.st = st
        if not st.go.wait(_STEP_TIMEOUT):
            st.error = RuntimeError("never scheduled")
            st.done = True
            self._handback.set()
            return
        st.go.clear()
        try:
            st.fn()
        except BaseException as ex:
            st.error = ex
        finally:
            st.done = True
            self._handback.set()

    def run(self) -> None:
        """Drive every spawned thread to completion under one seeded
        interleaving; re-raises the first scenario-thread error."""
        for st in self._threads:
            st.thread = threading.Thread(
                target=self._body, args=(st,), daemon=True,
                name="explorer-{}".format(st.name),
            )
            st.thread.start()
        steps = 0
        while any(not st.done for st in self._threads):
            runnable = sorted(
                (st for st in self._threads if not st.done),
                key=lambda s: s.name,
            )
            chosen = self._rng.choice(runnable)
            self._handback.clear()
            chosen.go.set()
            if not self._handback.wait(_STEP_TIMEOUT):
                raise RuntimeError(
                    "schedule wedged at step {} (thread {})".format(
                        steps, chosen.name
                    )
                )
            steps += 1
            if steps > _MAX_STEPS:
                raise RuntimeError("livelock: {} steps".format(steps))
        for st in self._threads:
            st.thread.join(_STEP_TIMEOUT)
        for st in sorted(self._threads, key=lambda s: s.name):
            if st.error is not None:
                self._stamp(st.error)
                raise st.error

    def _stamp(self, ex: BaseException) -> None:
        """Attach the replay coordinates (scenario, seed, schedule trace)
        to an escaping violation so it is a self-contained repro."""
        if isinstance(ex, ScheduleViolation):
            ex.scenario = ex.scenario or self.scenario
            ex.seed = ex.seed or self.seed
            ex.trace = ex.trace or list(self.trace)


# -- scenarios ----------------------------------------------------------------
#
# Each models one cross-thread protocol of the pipelined engine over the
# REAL primitives where the invariant lives (PagePool refcounts, the KV
# sanitizer), with a seeded defect that must be caught. Keep bodies small:
# a scenario is a protocol spec, not an engine re-implementation.


def _pool(num_pages: int = 5, page_size: int = 4, max_slots: int = 2):
    from .kv_cache import PagePool

    return PagePool(num_pages, page_size, max_slots)


def scenario_host_buffer_handoff(ctx: ScenarioContext) -> None:
    """The PR-4 race class: _prepare_dispatch snapshots the loop-owned
    next-token mirror for the dispatch worker; jnp.asarray is zero-copy on
    CPU, so WITHOUT the .copy() the worker's late read can observe the
    retire stage's in-place writeback. Mutation ``drop_buffer_copy`` skips
    the snapshot copy."""
    next_token = np.array([11, 12, 13, 14], np.int64)   # loop-owned mirror
    handoff: Dict[str, Any] = {}
    result: Dict[str, Any] = {}

    def loop_thread():
        # _prepare_dispatch: snapshot the chained tokens at the handoff
        snap = (
            next_token                      # seeded defect: aliasing handoff
            if ctx.mutating("drop_buffer_copy")
            else next_token.copy()
        )
        handoff["expect"] = next_token.tolist()
        handoff["tokens"] = snap
        ctx.yield_point("engine.dispatch.prepare")
        # retire writeback re-anchors the host mirror in place — the
        # worker may not have consumed the handoff yet
        next_token[:] = [91, 92, 93, 94]
        ctx.yield_point("engine.decode.retire")

    def worker_thread():
        while "tokens" not in handoff:
            ctx.yield_point("engine.decode")
        ctx.yield_point("engine.decode")    # device reads lazily
        result["consumed"] = list(np.asarray(handoff["tokens"]))

    ctx.spawn(loop_thread, "loop")
    ctx.spawn(worker_thread, "worker")
    ctx.run()
    if result["consumed"] != handoff["expect"]:
        raise ScheduleViolation(
            "worker consumed mutated host buffer {} (snapshot was {}): the "
            "handoff aliased a loop-owned mirror".format(
                result["consumed"], handoff["expect"]
            )
        )


def scenario_quarantine_barrier(ctx: ScenarioContext) -> None:  # tpuserve: ignore[TPU701] pairing crosses scenario threads by design
    """A slot freed at retire N is quarantined until every older in-flight
    chunk retires: its pages must never be re-allocated under a pending
    device write (docs/pipelined_decode.md). Mutation ``drop_quarantine``
    frees immediately, modelling a missing barrier."""
    from .kv_sanitizer import KVSanitizer

    pool = _pool(num_pages=5, page_size=4, max_slots=2)  # 4 usable pages
    pool.allocate(0, 16)                 # slot 0 owns the whole pool
    inflight_pages = pool.slot_pages(0)  # a younger chunk still writes these
    state: Dict[str, Any] = {"retired": False, "clobbered": []}
    quarantine: List[int] = []

    def loop_retire():
        # slot 0's request finished at this retire; a younger chunk is
        # still in flight against its pages
        if ctx.mutating("drop_quarantine"):
            pool.free(0)                 # seeded defect: no barrier
        else:
            quarantine.append(0)         # deferred to the barrier retire
        ctx.yield_point("engine.decode.retire")
        while not state["retired"]:
            ctx.yield_point("engine.decode.retire")
        # barrier passed: deferred frees execute now
        for slot in quarantine:
            pool.free(slot)

    def loop_admit():
        ctx.yield_point("engine.prefill")
        try:
            pool.allocate(1, 8)          # needs recycled pages to succeed
        except MemoryError:
            pass                         # barrier held: admission sheds
        ctx.yield_point("engine.prefill")

    def worker_chunk():
        ctx.yield_point("engine.decode")
        # the in-flight chunk's device writes land: every target page must
        # still belong to slot 0 (or its quarantine), never to slot 1
        owned_elsewhere = set(pool.slot_pages(1))
        state["clobbered"] = [p for p in inflight_pages if p in owned_elsewhere]
        state["retired"] = True
        ctx.yield_point("engine.decode")

    ctx.spawn(loop_retire, "loop-retire")
    ctx.spawn(loop_admit, "loop-admit")
    ctx.spawn(worker_chunk, "worker")
    ctx.run()
    if state["clobbered"]:
        raise ScheduleViolation(
            "in-flight chunk wrote pages {} already re-allocated to slot 1 "
            "(quarantine barrier violated)".format(state["clobbered"])
        )
    pool.free(1)
    KVSanitizer(pool).check("quarantine-barrier", drained=True)


def scenario_pin_balance(ctx: ScenarioContext) -> None:  # tpuserve: ignore[TPU701] pairing crosses scenario threads by design
    """Preemption/prefix-hit pins must balance: every pin_pages has a
    matching unpin on every queue-exit path, or the armed sanitizer's drain
    audit reports pins outliving the requests that took them. Mutation
    ``drop_unpin`` models a lost release on one path."""
    from .kv_sanitizer import KVSanitizer
    from .prefix_cache import RadixPrefixCache

    pool = _pool(num_pages=9, page_size=4, max_slots=2)
    cache = RadixPrefixCache(block=4, pool=pool, page_bytes=8)
    ids = list(range(8))
    pool.allocate(0, 8)
    cache.store_pages(ids, 0, pool.slot_pages(0))   # cache refs the prefix
    sanitizer = KVSanitizer(pool, prefix_cache=cache)

    def admission():
        # prefix-cache hit: lookup_pages pins on the caller's behalf; the
        # slot mapping takes its own refs; the transient pin MUST release
        hit = cache.lookup_pages(ids)
        ctx.yield_point("engine.prefill")
        pool.map_shared(1, hit["pages"], hit["len"])
        ctx.yield_point("engine.prefill")
        if not ctx.mutating("drop_unpin"):   # seeded defect: lost release
            cache.release(hit)

    def loop_free():
        # the storing slot finishes concurrently; cache refs + the pin must
        # keep the shared pages alive through the free
        ctx.yield_point("engine.decode.retire")
        pool.free(0)
        ctx.yield_point("engine.release")

    ctx.spawn(admission, "admit")
    ctx.spawn(loop_free, "loop")
    ctx.run()
    # conservation holds mid-protocol under every interleaving...
    sanitizer.check("pin-balance")
    # ...and at drain only the prefix cache may keep references
    pool.free(1)
    sanitizer.check("pin-balance", drained=True)


def scenario_stale_chain_commit(ctx: ScenarioContext) -> None:
    """A failed dispatch must reset the device-resident token chains before
    the next dispatch, or a freshly committed slot chains the dead chunk's
    stale token (engine._recover_failed_dispatch). Mutation
    ``drop_chain_reset`` skips the reset."""
    chain: Dict[str, Any] = {"dev": None}    # device-resident next-token
    host = np.array([5], np.int64)           # loop-owned host mirror
    state: Dict[str, Any] = {"failed": False}

    def worker_dispatch():
        # dispatch 1: chains its (about to be discarded) output on device,
        # then fails before any chunk lands
        chain["dev"] = 77
        ctx.yield_point("engine.decode")
        state["failed"] = True

    def loop():
        ctx.yield_point("engine.dispatch.prepare")
        while not state["failed"]:
            ctx.yield_point("engine.decode.retire")
        # recovery: forget the chains so the next dispatch re-uploads
        if not ctx.mutating("drop_chain_reset"):  # seeded defect
            chain["dev"] = None
        # a fresh commit lands on the loop thread
        host[0] = 42
        ctx.yield_point("engine.prefill")
        # next dispatch chains device state when present, host otherwise
        token = chain["dev"] if chain["dev"] is not None else int(host[0])
        if token != 42:
            raise ScheduleViolation(
                "fresh commit chained stale token {} instead of 42 "
                "(device chains not reset after the failed dispatch)".format(
                    token
                )
            )

    ctx.spawn(worker_dispatch, "worker")
    ctx.spawn(loop, "loop")
    ctx.run()


def scenario_refcount_lock(ctx: ScenarioContext) -> None:
    """Lock-guarded refcount discipline (the TPU301/TPU504 invariant, run
    dynamically): two threads bump a shared refcount through a
    read-modify-write that parks mid-update. Without the critical section
    (mutation ``drop_lock``) an interleaving loses updates."""
    refs = [0, 0]
    rounds = 3

    def bump(name: str):
        def body():
            for _ in range(rounds):
                if ctx.mutating("drop_lock"):   # seeded defect: no lock
                    value = refs[1]
                    ctx.yield_point("engine.decode")
                    refs[1] = value + 1
                else:
                    with ctx.critical("_lock"):
                        value = refs[1]
                        ctx.yield_point("engine.decode")
                        refs[1] = value + 1
                ctx.yield_point("engine.decode.retire")
        return body

    ctx.spawn(bump("loop"), "loop")
    ctx.spawn(bump("worker"), "worker")
    ctx.run()
    if refs[1] != 2 * rounds:
        raise ScheduleViolation(
            "refcount {} != {} after {} bumps per thread: lost update "
            "without the lock".format(refs[1], 2 * rounds, rounds)
        )


class _ModelTierBackend:
    """Explorer-local model of the KV tiering backend (docs/kv_tiering.md):
    page CONTENTS are plain ints, the host side uses the REAL HostKVTier id
    allocator, and the device queue is a list of pending copy ops. The tier
    fence — the real backend enqueues the promotion DMA under the dispatch
    lock BEFORE the new page ids become visible, so any later consumer
    program is ordered after the copy by data dependency — is modelled by
    ``flush()``: a consumer "program" first lands every op enqueued before
    it. Mutation ``drop_tier_fence`` defers the promotion op OUT of the
    queue (it lands only when a late "DMA thread" re-enqueues it), exactly
    the corruption an unfenced publish would allow."""

    def __init__(self, host_tier, device_data: Dict[int, int],
                 drop_fence: bool):
        self.host_tier = host_tier
        self.device_data = device_data
        self.host_data: Dict[int, int] = {}
        self.queue: List[list] = []     # enqueued device copy programs
        self.late: List[list] = []      # fence-dropped ops, landed late
        self.drop_fence = drop_fence

    def demote_pages(self, pages: List[int]) -> List[int]:
        # synchronous device->host readback: contents are safe on the host
        # BEFORE the caller releases the device pages
        ids = self.host_tier.allocate(len(pages))
        for hid, page in zip(ids, pages):
            self.host_data[hid] = self.device_data[page]
        return ids

    def promote_pages(self, host_ids: List[int], pages: List[int]) -> None:
        op = [(page, self.host_data.pop(hid))
              for hid, page in zip(host_ids, pages)]
        if self.drop_fence:
            self.late.append(op)        # seeded defect: DMA enqueued late
        else:
            self.queue.append(op)       # the fence: enqueue before publish
        self.host_tier.free(host_ids)

    def flush(self) -> None:
        """A consumer device program: data dependency lands every copy
        enqueued before it."""
        for op in self.queue:
            for page, value in op:
                self.device_data[page] = value
        self.queue.clear()

    def land_late(self) -> None:
        self.queue.extend(self.late)
        self.late = []


def scenario_tier_promotion(ctx: ScenarioContext) -> None:  # tpuserve: ignore[TPU701] pairing crosses scenario threads by design
    """KV tiering (docs/kv_tiering.md): an eviction DEMOTES a cached run to
    the host tier while a concurrent admission looks the same run up and
    map_shared's it. The admission must end up reading the run's original
    bytes whether it won the race (resident hit) or lost it (host hit whose
    promotion DMA is fenced ahead of every consumer program). Mutation
    ``drop_tier_fence`` lets the promotion's copy land AFTER the consumer
    read — the stale-page corruption an unfenced publish allows."""
    from .kv_cache import HostKVTier
    from .kv_sanitizer import KVSanitizer
    from .prefix_cache import RadixPrefixCache

    pool = _pool(num_pages=9, page_size=4, max_slots=2)
    host_tier = HostKVTier(4, 4, 1, 1, 2, dtype=np.int8, quantized=False)
    device_data: Dict[int, int] = {
        page: -1 for page in range(1, pool.num_pages)  # free pages: garbage
    }
    backend = _ModelTierBackend(
        host_tier, device_data, ctx.mutating("drop_tier_fence")
    )
    cache = RadixPrefixCache(
        block=4, pool=pool, page_bytes=8, backend=backend
    )
    ids = list(range(9))                 # 9 tokens -> 8 cacheable (2 blocks)
    pool.allocate(0, 9)
    run_pages = pool.slot_pages(0)[:2]   # the cached, block-aligned prefix
    expect = [100 + page for page in run_pages]
    for page, value in zip(run_pages, expect):
        device_data[page] = value
    cache.store_pages(ids, 0, pool.slot_pages(0))
    pool.free(0)                         # cache is now the only holder
    sanitizer = KVSanitizer(pool, prefix_cache=cache)
    state: Dict[str, Any] = {}

    def evictor():
        ctx.yield_point("engine.release")
        cache.spill(0)                   # demote the whole resident run
        # freed HBM gets reused by other tenants: scramble it so a stale
        # read can never luck into the original bytes
        for page in range(1, pool.num_pages):
            if pool.page_refcount(page) == 0:
                device_data[page] = -1
        ctx.yield_point("engine.release")

    def admit():
        ctx.yield_point("engine.prefill")
        hit = cache.lookup_pages(ids)
        ctx.yield_point("engine.prefill")
        pool.map_shared(1, hit["pages"], hit["len"])
        ctx.yield_point("engine.dispatch.prepare")
        # the consumer device program: ordered after every enqueued copy
        backend.flush()
        state["read"] = [device_data.get(p, -1) for p in hit["pages"]]
        state["tier"] = hit["tier"]
        cache.release(hit)
        ctx.yield_point("engine.decode")

    def dma():
        # the fence-dropped copy lands eventually — too late for a
        # consumer that already read
        ctx.yield_point("engine.decode")
        backend.land_late()
        ctx.yield_point("engine.decode")

    ctx.spawn(evictor, "evictor")
    ctx.spawn(admit, "admit")
    ctx.spawn(dma, "dma")
    ctx.run()
    if state.get("read") != expect:
        raise ScheduleViolation(
            "admission consumed {} instead of {} on a {} hit: the "
            "promotion copy was not fenced ahead of the consumer "
            "program".format(state.get("read"), expect, state.get("tier"))
        )
    pool.free(1)
    sanitizer.check("tier-promotion", drained=True)


def scenario_ragged_window_retire(ctx: ScenarioContext) -> None:  # tpuserve: ignore[TPU701] pairing crosses scenario threads by design
    """Multi-step ragged retire (docs/ragged_attention.md): a q=4 decode
    window's tokens are emitted IN ORDER under the mid-window EOS mask —
    the row's request finishes at the stop token, its slot pages free, and
    the surplus window tokens must never reach the stream (nor land after
    a concurrent admission re-allocated the freed pages). Mutation
    ``drop_window_eos_mask`` keeps emitting past the stop, exactly the
    corruption blind window emission would allow."""
    from .kv_sanitizer import KVSanitizer

    pool = _pool(num_pages=5, page_size=4, max_slots=2)
    pool.allocate(0, 8)                     # the decoding row's slot
    eos = 99
    window = [11, eos, 12, 13]              # q=4; EOS lands mid-window
    stream: List[int] = []
    state: Dict[str, Any] = {"finished": False}

    def loop_retire():
        # _retire_ragged._window_emit: token-by-token emission; _emit
        # frees the slot at the stop token and the window loop must break
        for tok in window:
            if state["finished"] and not ctx.mutating(
                "drop_window_eos_mask"
            ):
                break                       # the mid-window EOS mask
            if state["finished"]:
                # seeded defect: blind emission past the finish — the dead
                # request's surplus tokens leak into the stream
                stream.append(tok)
                ctx.yield_point("engine.decode.retire")
                continue
            stream.append(tok)
            ctx.yield_point("engine.decode.retire")
            if tok == eos:
                state["finished"] = True
                pool.free(0)                # _emit frees the slot's pages
                ctx.yield_point("engine.release")

    def loop_admit():
        # a concurrent admission takes whatever pages the finish freed
        ctx.yield_point("engine.prefill")
        try:
            pool.allocate(1, 8)
        except MemoryError:
            pass
        ctx.yield_point("engine.prefill")

    ctx.spawn(loop_retire, "loop-retire")
    ctx.spawn(loop_admit, "loop-admit")
    ctx.run()
    if eos in stream and stream[-1] != eos:
        raise ScheduleViolation(
            "window emission continued past the stop token: stream {} "
            "(mid-window EOS mask dropped)".format(stream)
        )
    pool.free(1)
    KVSanitizer(pool).check("ragged-window-retire", drained=True)


class _ModelShipBackend:
    """Explorer-local model of the KV-transport import backend
    (docs/disaggregation.md): page CONTENTS are plain ints riding real
    numpy shipment slabs, and the device queue is a list of pending copy
    ops. The ship fence — the real ``PagedKVCache.import_pages`` enqueues
    the scatter under the dispatch lock BEFORE ``store_shipped`` publishes
    the page ids, so any later consumer program is ordered after the copy
    by data dependency — is modelled by ``flush()``. Mutation
    ``drop_ship_fence`` defers the import op OUT of the queue (a late
    "DMA thread" lands it eventually), exactly the stale read an unfenced
    publish would allow."""

    kv_quant = ""   # store_shipped's scale/quantization geometry check

    def __init__(self, device_data: Dict[int, int], drop_fence: bool):
        self.device_data = device_data
        self.queue: List[list] = []
        self.late: List[list] = []
        self.drop_fence = drop_fence

    def import_pages(self, hk, hv, pages, hk_scale=None, hv_scale=None):
        op = [
            (page, int(hk[j, 0, 0, 0, 0]))
            for j, page in enumerate(pages)
        ]
        if self.drop_fence:
            self.late.append(op)        # seeded defect: DMA enqueued late
        else:
            self.queue.append(op)       # the fence: enqueue before publish

    def flush(self) -> None:
        for op in self.queue:
            for page, value in op:
                self.device_data[page] = value
        self.queue.clear()

    def land_late(self) -> None:
        self.queue.extend(self.late)
        self.late = []


def scenario_kv_ship(ctx: ScenarioContext) -> None:  # tpuserve: ignore[TPU701] pairing crosses scenario threads by design
    """Disaggregated KV shipping (docs/disaggregation.md): a prefill
    replica's shipment lands on the decode replica WHILE that replica's
    concurrent admission looks the same prefix up and ``map_shared``'s
    it. Whether the admission wins the race (miss — it recomputes) or
    loses it (hit over the just-published shipped pages), a hit must read
    the SHIPPED bytes: ``store_shipped`` enqueues the import scatter
    before the page ids publish, so the consumer program is ordered after
    the copy. Mutation ``drop_ship_fence`` lets the import land AFTER the
    consumer read — the stale-page corruption an unfenced publish
    allows."""
    from .kv_sanitizer import KVSanitizer
    from .kv_transport import KVShipment, SharedSlabTransport, shipment_key
    from .prefix_cache import RadixPrefixCache

    page = 4
    pool = _pool(num_pages=9, page_size=page, max_slots=2)
    device_data: Dict[int, int] = {
        p: -1 for p in range(1, pool.num_pages)   # fresh pages: garbage
    }
    backend = _ModelShipBackend(device_data, ctx.mutating("drop_ship_fence"))
    cache = RadixPrefixCache(block=page, pool=pool, page_bytes=8)
    ids = list(range(9))                 # 9 tokens -> 8 storable (2 blocks)
    expect = [101, 102]
    hk = np.zeros((2, 1, 1, page, 1), np.int32)
    hk[:, 0, 0, 0, 0] = expect           # page value rides slab row 0
    transport = SharedSlabTransport(capacity_pages=8)
    transport.register("decode")
    shipment = KVShipment(
        key=shipment_key(ids, page, 0), src="prefill", prefix_len=8,
        page_size=page, lora=0, hk=hk, hv=hk.copy(),
    )
    sanitizer = KVSanitizer(pool, prefix_cache=cache)
    state: Dict[str, Any] = {}

    def receiver():
        # the group's receive worker: pop + import + publish (bounded
        # retry: the shipper may not have sent yet under this schedule)
        got = None
        for _ in range(6):
            ctx.yield_point("engine.kv.receive")
            got = transport.recv("decode", shipment.key)
            if got is not None:
                break
        if got is not None:
            cache.store_shipped(ids, 0, got, backend)
            ctx.yield_point("engine.kv.receive")

    def admit():
        # the decode replica's concurrent admission: bounded lookup retry
        # so most schedules reach the interesting hit-over-shipped-pages
        # state; a final miss is the legitimate recompute path
        hit = None
        for _ in range(6):
            ctx.yield_point("engine.prefill")
            hit = cache.lookup_pages(ids)
            if hit is not None:
                break
        if hit is None:
            state["read"] = None        # won the race: recompute path
            return
        pool.map_shared(1, hit["pages"], hit["len"])
        ctx.yield_point("engine.dispatch.prepare")
        # the consumer device program: ordered after every enqueued copy
        backend.flush()
        state["read"] = [device_data.get(p, -1) for p in hit["pages"]]
        cache.release(hit)
        ctx.yield_point("engine.decode")

    def dma():
        # the fence-dropped copy lands eventually — too late for a
        # consumer that already read
        ctx.yield_point("engine.decode")
        backend.land_late()
        ctx.yield_point("engine.decode")

    def shipper():
        # the prefill replica's ship-at-commit export + send
        transport.send("decode", shipment)
        ctx.yield_point("engine.kv.ship")

    ctx.spawn(shipper, "shipper")
    ctx.spawn(receiver, "receiver")
    ctx.spawn(admit, "admit")
    ctx.spawn(dma, "dma")
    ctx.run()
    if state.get("read") is not None and state["read"] != expect:
        raise ScheduleViolation(
            "admission consumed {} instead of {} over shipped pages: the "
            "import scatter was not fenced ahead of the consumer "
            "program".format(state["read"], expect)
        )
    if pool.slot_pages(1):
        pool.free(1)
    sanitizer.check("kv-ship", drained=True)


def scenario_ledger_pairing(ctx: ScenarioContext) -> None:  # tpuserve: ignore[TPU701] pairing crosses scenario threads by design
    """Ownership-ledger pairing (docs/static_analysis.md TPU7xx): an
    admission takes a prefix-hit pin while a concurrent teardown frees the
    storing slot, and the REAL armed ledger (llm/lifecycle_ledger.py) must
    prove every acquire released at the drained boundary. Mutation
    ``drop_release_on_raise`` makes the admission's failure path skip its
    release() — the exception-path leak class TPU701 catches statically
    and the ledger catches at runtime; mutation ``double_free`` makes the
    teardown free the slot twice — the release-after-free class TPU702
    catches statically and the ledger reports as a double release."""
    from . import lifecycle_ledger
    from .kv_sanitizer import KVSanitizer
    from .prefix_cache import RadixPrefixCache

    was_armed = lifecycle_ledger.armed()
    prior_strict = lifecycle_ledger.get().strict  # BEFORE arm mutates it
    ledger = lifecycle_ledger.arm(strict=True)
    ledger.reset(strict=True)   # fresh books for the scenario's primitives
    pool = _pool(num_pages=9, page_size=4, max_slots=2)
    cache = RadixPrefixCache(block=4, pool=pool, page_bytes=8)
    ids = list(range(8))
    pool.allocate(0, 8)
    cache.store_pages(ids, 0, pool.slot_pages(0))
    mark = ledger.stats()
    try:

        def admission():
            with ledger.owner("req:scenario"):
                hit = cache.lookup_pages(ids)
            ctx.yield_point("engine.prefill")
            # the admission fails mid-flight: its exception path must
            # still release the pinned hit
            if not ctx.mutating("drop_release_on_raise"):
                cache.release(hit)
            ctx.yield_point("engine.prefill")

        def teardown():
            ctx.yield_point("engine.decode.retire")
            pool.free(0)
            if ctx.mutating("double_free"):
                # seeded defect: recovery re-frees what the normal path
                # freed — with the slot's entry gone, the ledger's books
                # see a release that was never acquired
                lifecycle_ledger.release(
                    "pages.slot", key=0, domain=pool, all_of_key=False
                )
            ctx.yield_point("engine.release")

        ctx.spawn(admission, "admit")
        ctx.spawn(teardown, "loop")
        ctx.run()
        stats = ledger.stats()
        if stats["double_releases"] > mark["double_releases"]:
            raise ScheduleViolation(
                "ownership ledger recorded a release never acquired "
                "(double free) during the scenario"
            )
        # drained boundary: the scenario's transient resources must be gone
        ledger.check("ledger-pairing", drained=True, domains=[pool, cache])
        KVSanitizer(pool, prefix_cache=cache).check(
            "ledger-pairing", drained=True
        )
    finally:
        ledger.reset(strict=prior_strict)
        if not was_armed:
            lifecycle_ledger.disarm()


SCENARIOS: Dict[str, Callable[[ScenarioContext], None]] = {
    "host_buffer_handoff": scenario_host_buffer_handoff,
    "quarantine_barrier": scenario_quarantine_barrier,
    "pin_balance": scenario_pin_balance,
    "stale_chain_commit": scenario_stale_chain_commit,
    "refcount_lock": scenario_refcount_lock,
    "tier_promotion": scenario_tier_promotion,
    "ragged_window_retire": scenario_ragged_window_retire,
    "kv_ship": scenario_kv_ship,
    "ledger_pairing": scenario_ledger_pairing,
}

# seeded defect -> the scenario that must catch it (self_test proves each)
MUTATIONS: Dict[str, str] = {
    "drop_buffer_copy": "host_buffer_handoff",
    "drop_quarantine": "quarantine_barrier",
    "drop_unpin": "pin_balance",
    "drop_chain_reset": "stale_chain_commit",
    "drop_lock": "refcount_lock",
    "drop_tier_fence": "tier_promotion",
    "drop_window_eos_mask": "ragged_window_retire",
    "drop_ship_fence": "kv_ship",
    "drop_release_on_raise": "ledger_pairing",
    "double_free": "ledger_pairing",
}


def explore(scenario: str, schedules: int = 16, seed: int = 0,
            mutate: Optional[str] = None) -> Dict[str, Any]:
    """Run ``schedules`` seeded interleavings of one scenario; returns a
    report with every violation's schedule index, message, and trace.
    Deterministic: (scenario, seed, schedule index) fully determine the
    interleaving."""
    if scenario not in SCENARIOS:
        raise ValueError(
            "unknown scenario {!r} (known: {})".format(
                scenario, ", ".join(sorted(SCENARIOS))
            )
        )
    if mutate is not None and mutate not in MUTATIONS:
        raise ValueError(
            "unknown mutation {!r} (known: {})".format(
                mutate, ", ".join(sorted(MUTATIONS))
            )
        )
    from .kv_sanitizer import KVSanitizerError
    from .lifecycle_ledger import LedgerError

    mutations = frozenset({mutate}) if mutate else frozenset()
    violations = []
    for i in range(schedules):
        rng = random.Random("{}:{}:{}".format(scenario, seed, i))
        ctx = ScenarioContext(rng, mutations, scenario=scenario, seed=seed)
        try:
            SCENARIOS[scenario](ctx)
        except (ScheduleViolation, KVSanitizerError, LedgerError) as ex:
            ctx._stamp(ex)
            # the armed KV sanitizer is part of the net: its invariant
            # failures count as caught violations, with the schedule trace
            violations.append({
                "schedule": i,
                "seed": seed,
                "message": str(ex),
                "trace": list(ctx.trace),
            })
    return {
        "scenario": scenario,
        "schedules": schedules,
        "seed": seed,
        "mutate": mutate,
        "violations": violations,
    }


def self_test(schedules: int = 16, seed: int = 0) -> Dict[str, Any]:
    """Prove the net has no holes: every seeded defect must be CAUGHT
    within ``schedules`` interleavings of its scenario, and every scenario
    must stay green without one. Returns {"ok": bool, "detail": {...}}."""
    detail: Dict[str, Any] = {}
    ok = True
    for mutation, scenario in sorted(MUTATIONS.items()):
        caught = bool(
            explore(scenario, schedules, seed, mutate=mutation)["violations"]
        )
        detail["mutation:{}".format(mutation)] = (
            "caught" if caught else "MISSED"
        )
        ok = ok and caught
    for scenario in sorted(SCENARIOS):
        clean = not explore(scenario, schedules, seed)["violations"]
        detail["clean:{}".format(scenario)] = "green" if clean else "VIOLATED"
        ok = ok and clean
    return {"ok": ok, "schedules": schedules, "seed": seed, "detail": detail}


def main(argv=None) -> int:
    import argparse
    import json

    parser = argparse.ArgumentParser(
        prog="python -m clearml_serving_tpu.llm.schedule_explorer",
        description="deterministic interleaving explorer "
        "(docs/static_analysis.md)",
    )
    parser.add_argument("--scenario", default=None,
                        help="one scenario (default: all)")
    parser.add_argument("--schedules", type=int, default=16,
                        help="interleavings per scenario (K)")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--mutate", default=None,
                        help="arm one seeded defect (see --list)")
    parser.add_argument("--self-test", action="store_true",
                        help="every seeded defect caught + clean runs green")
    parser.add_argument("--smoke", action="store_true",
                        help="CI gate: clean sweep + self-test at small K")
    parser.add_argument("--list", action="store_true",
                        help="print scenarios and mutations")
    args = parser.parse_args(argv)

    if args.list:
        for name in sorted(SCENARIOS):
            print("scenario  {}".format(name))
        for name, scenario in sorted(MUTATIONS.items()):
            print("mutation  {:<18} -> {}".format(name, scenario))
        return 0

    if args.smoke:
        report = self_test(schedules=max(4, min(args.schedules, 8)),
                           seed=args.seed)
        print(json.dumps(report, indent=2, sort_keys=True))
        return 0 if report["ok"] else 1

    if args.self_test:
        report = self_test(schedules=args.schedules, seed=args.seed)
        print(json.dumps(report, indent=2, sort_keys=True))
        return 0 if report["ok"] else 1

    names = [args.scenario] if args.scenario else sorted(SCENARIOS)
    rc = 0
    for name in names:
        report = explore(name, args.schedules, args.seed, mutate=args.mutate)
        status = (
            "VIOLATED ({} of {})".format(
                len(report["violations"]), report["schedules"]
            )
            if report["violations"]
            else "green ({} schedules)".format(report["schedules"])
        )
        print("{:<22} {}".format(name, status))
        for violation in report["violations"]:
            print("  schedule {}: {}".format(
                violation["schedule"], violation["message"]
            ))
            print("    trace: {}".format(" -> ".join(violation["trace"])))
        if report["violations"]:
            rc = 1
    return rc


if __name__ == "__main__":
    import sys

    sys.exit(main())
