"""Shared shape-bucketing helpers — the registered compile-surface
sanitizers (docs/static_analysis.md, TPU6xx).

Every serve-time XLA recompile is a 100-1000 ms stall of the loop thread
that masquerades as scheduling tail, so any value derived from per-request
data (prompt length, token count, page count) must collapse into a FINITE
key space before it reaches a jit boundary or an eager device op. These
helpers are the canonical collapses:

- ``pow2_bucket``      — next power of two (log2(max) keys);
- ``pad_to_multiple``  — round up to a fixed multiple (max/m keys, the
                         page-multiple pad of the PR-6 commit-slice fix);
- ``pad_pages``        — pad a device-page id list to a power-of-two
                         length with null-page (id 0) no-op entries, the
                         idiom ``PagedKVCache.apply_pending_cow`` proved:
                         gathers of page 0 are discarded host-side and
                         scatters into page 0 land in the dead null page.

The static analyzer (``analyze/rules_compile.py``, rule TPU601) treats a
call to any name in its ``BUCKETIZERS`` registry as laundering the
request-varying taint; this module is the project-level home of those
names — a new bucketizer is added HERE and registered THERE (the
registry-consistency test in tests/test_analyze_compile.py pins the two
together).
"""

from __future__ import annotations

from typing import List, Sequence

__all__ = [
    "pow2_bucket", "pad_to_multiple", "pad_pages", "decode_steps_bucket",
]


def pow2_bucket(n: int, lo: int = 1) -> int:
    """Smallest power of two >= max(n, lo). The canonical unbounded->log2
    cardinality collapse for counts (CoW pair lists, finish-row gathers,
    dense ragged chunk widths, tier demotion/promotion rounds)."""
    bucket = max(1, int(lo))
    n = int(n)
    while bucket < n:
        bucket *= 2
    return bucket


def decode_steps_bucket(n: int, cap: int = None) -> int:
    """Largest power of two <= max(1, n), optionally capped: the multi-step
    ragged decode-window bucketizer (docs/ragged_attention.md). The window a
    launch can afford varies per step with the token budget and the live
    row count — rounding DOWN to a power of two keeps the launch within
    budget while collapsing the per-launch scan length to log2(decode_steps)
    compile keys, each pre-compiled by the warmup sweep."""
    n = max(1, int(n))
    if cap is not None:
        n = min(n, max(1, int(cap)))
    bucket = 1
    while bucket * 2 <= n:
        bucket *= 2
    return bucket


def pad_to_multiple(n: int, multiple: int) -> int:
    """Round ``n`` up to a whole multiple (page-multiple pads: the compile
    key collapses from per-token-length to per-page-count)."""
    m = int(multiple)
    if m <= 0:
        raise ValueError("multiple must be positive (got {})".format(m))
    return -(-int(n) // m) * m


def pad_pages(pages: Sequence[int], lo: int = 1) -> List[int]:
    """Pad a page-id list to a power-of-two length with null-page (id 0)
    entries, so the gather/scatter consuming it compiles once per power of
    two instead of once per count. Page 0 is the pool's dead null page by
    project convention: gathered rows beyond the real count are discarded
    host-side, and scattered rows land where nothing ever reads."""
    pages = list(pages)
    return pages + [0] * (pow2_bucket(len(pages), lo) - len(pages))
