"""Runtime sharding sentry: audit live arrays against their declared specs
(docs/static_analysis.md TPU8xx — the dynamic net behind the static rules).

The sharding invariant says every long-lived device array the serve loop
touches — the params tree, the KV/scale pools, the chained decode state —
keeps the sharding its registered builder (``parallel/sharding.py``,
declared through the engine's ``__shardings__`` annotation) gave it at
init, and never silently round-trips through the host. The static rules
prove the declarations exist and the axis vocabulary is closed; this
sentry proves the INVARIANT ITSELF at runtime: armed with
``TPUSERVE_SHARD_SENTRY=1`` (count) or ``=strict`` (raise), the engine
audits its live arrays at every loop boundary (the same
check-at-the-boundary shape as the KV sanitizer / compile sentry /
ownership ledger), counts and attributes two violation classes per launch
using thread-local launch contexts (the compile sentry's context
plumbing):

- **implicit transfer** — an audited entry is host-materialized (a
  ``np.ndarray`` where the baseline was a device array, or vice versa):
  the silent device<->host round-trip that becomes a cross-host gather
  (or one shard's garbage) the moment there is more than one process;
- **unplanned reshard** — an entry's live sharding spec no longer equals
  what was declared (or first captured) for its path: a jit output or a
  stray ``device_put`` quietly moved data off the builder's layout.

In strict mode the engine raises :class:`ShardSentryError` at the next
loop boundary naming the array path and declared-vs-actual spec, through
the same structured step-failure path as the sanitizer.

Spec canonicalization is deliberately device-blind: a ``NamedSharding``
canonicalizes to its PartitionSpec tuple, anything else to its sharding
class name — so single-device placement churn across the 8 virtual CPU
devices never flags, while spec drift and host materialization always do.
``jax.transfer_guard`` is probe-detected only (it is inert on the CPU
backend) and reported via ``stats()["mode"]``; the sentry never installs
a global guard — the engine's registered readback sites do legitimate
host reads every step.
"""

from __future__ import annotations

import contextlib
import os
import threading
from typing import Any, Dict, Iterable, List, Optional, Tuple

ENV = "TPUSERVE_SHARD_SENTRY"

# keep full per-violation attribution for the most recent N events; the
# counters are unbounded
_MAX_EVENTS = 256

_HOST = "host(ndarray)"


def enabled() -> bool:
    return os.environ.get(ENV, "") not in ("", "0")


def strict_enabled() -> bool:
    return os.environ.get(ENV, "") == "strict"


class ShardSentryError(AssertionError):
    """A sharding-discipline violation under strict mode: names the array
    path, the declared (or init-captured) spec, and what the audit found."""

    def __init__(self, message: str, path: str = "", declared: str = "",
                 actual: str = "", kind: str = ""):
        super().__init__(message)
        self.path = path
        self.declared = declared
        self.actual = actual
        self.kind = kind


def _probe_mode() -> str:
    """Which enforcement net is available. ``jax.transfer_guard`` is inert
    on the CPU backend (no raise on host reads), so the sentry's primary
    net is spec-conformance + host-materialization auditing; the probe
    only reports whether a real guard WOULD be available on this backend.
    """
    try:
        import jax
        import jax.numpy as jnp
        import numpy as np

        x = jnp.zeros((2,), jnp.float32)
        with jax.transfer_guard_device_to_host("disallow"):
            np.asarray(x)
        return "audit"           # guard inert: conformance auditing only
    except Exception:
        return "transfer-guard"  # guard functional on this backend


class ShardingSentry:
    """Process-wide sharding auditor (one per process: the declared-spec
    table is global state shared by every engine in tests). Thread-safe;
    attribution context is thread-local so dispatch workers tag the
    violations their own launches surface."""

    def __init__(self, strict: bool = False):
        self.strict = bool(strict)
        self._lock = threading.Lock()
        self._tls = threading.local()
        self._mode = _probe_mode()
        # path -> canonical spec: explicit declares and first-audit
        # baselines land here; every later audit compares against it
        self.declared: Dict[str, str] = {}
        self.audits = 0
        self.arrays_checked = 0
        self.implicit_transfers = 0
        self.unplanned_reshards = 0
        self.events: List[Dict[str, Any]] = []
        self.violations: List[Dict[str, Any]] = []

    # -- spec canonicalization --------------------------------------------

    @staticmethod
    def _canon_spec(spec: Any, mesh: Any) -> str:
        """Equivalence-aware canonical form of a PartitionSpec: GSPMD
        normalizes specs as they flow through jit outputs — entries on
        size-1 mesh axes drop (sharding 1-way IS replication) and trailing
        ``None`` entries are omitted — so syntactic equality over the raw
        tuple would flag every donated rebind on a partly-degenerate mesh
        as a reshard. Size-1 axes collapse to None and trailing Nones
        strip before rendering."""
        sizes = dict(getattr(mesh, "shape", None) or {})
        norm = []
        for entry in tuple(spec):
            axes = entry if isinstance(entry, tuple) else (entry,)
            kept = tuple(
                a for a in axes
                if a is not None and int(sizes.get(a, 2)) > 1
            )
            if not kept:
                norm.append(None)
            elif len(kept) == 1:
                norm.append(kept[0])
            else:
                norm.append(kept)
        while norm and norm[-1] is None:
            norm.pop()
        return "P({})".format(", ".join(repr(e) for e in norm))

    @classmethod
    def _canon(cls, value: Any) -> Optional[str]:
        """Device-blind canonical spec for a live value: host ndarrays are
        ``host(ndarray)``, NamedShardings their normalized PartitionSpec,
        other shardings their class name, everything else unauditable
        (None)."""
        import numpy as np

        if isinstance(value, np.ndarray):
            return _HOST
        sharding = getattr(value, "sharding", None)
        if sharding is None:
            return None
        spec = getattr(sharding, "spec", None)
        if spec is not None:
            return cls._canon_spec(spec, getattr(sharding, "mesh", None))
        return type(sharding).__name__

    @classmethod
    def _canon_declared(cls, declared: Any) -> Optional[str]:
        """Canonical form of a DECLARED sharding (a NamedSharding /
        PartitionSpec a builder produced, not a live array)."""
        if declared is None:
            return None
        if isinstance(declared, str):
            return declared
        spec = getattr(declared, "spec", None)
        if spec is not None:
            return cls._canon_spec(spec, getattr(declared, "mesh", None))
        if isinstance(declared, tuple):
            return cls._canon_spec(declared, None)
        return type(declared).__name__

    # -- attribution context ----------------------------------------------

    @contextlib.contextmanager
    def context(self, **ctx):
        """Tag violations surfaced by audits on THIS thread (the engine
        wraps its dispatch workers: phase, dispatch seq, pipeline depth —
        the compile sentry's context plumbing, reused)."""
        prev = getattr(self._tls, "ctx", None)
        self._tls.ctx = dict(prev or {}, **ctx)
        try:
            yield
        finally:
            self._tls.ctx = prev

    # -- declare / audit / check ------------------------------------------

    def declare(self, path: str, sharding: Any) -> None:
        """Pin ``path``'s expected spec explicitly (the engine declares its
        builder outputs at init; undeclared paths baseline on first audit).
        """
        want = self._canon_declared(sharding)
        if want is None:
            return
        with self._lock:
            self.declared[path] = want

    def audit(
        self,
        entries: Iterable[Tuple[str, Any, Any]],
        where: str = "",
    ) -> int:
        """Check ``(path, value, declared)`` entries against the spec
        table. ``declared=None`` means "use the table, baselining on first
        sight"; ``value=None`` entries are skipped (unallocated state).
        Returns the number of NEW violations this audit found."""
        ctx = dict(getattr(self._tls, "ctx", None) or {})
        found = 0
        with self._lock:
            self.audits += 1
            for path, value, declared in entries:
                if value is None:
                    continue
                actual = self._canon(value)
                if actual is None:
                    continue
                self.arrays_checked += 1
                want = (
                    self._canon_declared(declared)
                    if declared is not None
                    else self.declared.get(path)
                )
                if want is None:
                    self.declared[path] = actual
                    continue
                if declared is not None:
                    self.declared.setdefault(path, want)
                if actual == want:
                    continue
                kind = (
                    "implicit_transfer"
                    if (actual == _HOST) != (want == _HOST)
                    else "unplanned_reshard"
                )
                if kind == "implicit_transfer":
                    self.implicit_transfers += 1
                else:
                    self.unplanned_reshards += 1
                event = {
                    "kind": kind,
                    "path": path,
                    "declared": want,
                    "actual": actual,
                    "where": where,
                    "context": ctx,
                }
                self.events.append(event)
                del self.events[:-_MAX_EVENTS]
                if self.strict:
                    self.violations.append(event)
                found += 1
        return found

    def check(self, where: str = "") -> None:
        """Raise the first pending strict violation (engine loop
        boundaries call this the way they call the KV sanitizer)."""
        with self._lock:
            if not (self.strict and self.violations):
                return
            v = self.violations[0]
        raise ShardSentryError(
            "sharding sentry: {} on {} — declared {} but the audit found "
            "{}{}{}; a silently host-materialized or resharded array is a "
            "multihost deadlock (docs/static_analysis.md TPU8xx)".format(
                v["kind"], v["path"], v["declared"], v["actual"],
                " at {}".format(where or v["where"])
                if (where or v["where"]) else "",
                " (context: {})".format(v["context"]) if v["context"] else "",
            ),
            path=v["path"], declared=v["declared"], actual=v["actual"],
            kind=v["kind"],
        )

    # -- stats / reset -----------------------------------------------------

    def reset(self, strict: Optional[bool] = None) -> None:
        """Drop the spec table and all accumulated state (tests; a new
        engine's init re-declares its builder outputs)."""
        with self._lock:
            self.declared = {}
            self.audits = 0
            self.arrays_checked = 0
            self.implicit_transfers = 0
            self.unplanned_reshards = 0
            self.events = []
            self.violations = []
            if strict is not None:
                self.strict = bool(strict)

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "mode": self._mode,
                "strict": self.strict,
                "audits": self.audits,
                "arrays_checked": self.arrays_checked,
                "implicit_transfers": self.implicit_transfers,
                "unplanned_reshards": self.unplanned_reshards,
                "declared_paths": len(self.declared),
                "violations": len(self.violations),
                "events": [dict(e) for e in self.events],
            }

    def stats_brief(self) -> Dict[str, Any]:
        """The lifecycle_stats()/health() "sharding" block (and what the
        metrics collector reads): counters only, no event list."""
        with self._lock:
            return {
                "mode": self._mode,
                "strict": self.strict,
                "audits": self.audits,
                "arrays_checked": self.arrays_checked,
                "implicit_transfers": self.implicit_transfers,
                "unplanned_reshards": self.unplanned_reshards,
                "declared_paths": len(self.declared),
                "violations": len(self.violations),
            }


# -- module singleton ---------------------------------------------------------

_sentry: Optional[ShardingSentry] = None
_guard = threading.Lock()
# fast gate: hot paths ask armed() before building audit entry lists
_armed = False


def get() -> ShardingSentry:
    """The process-wide sentry (strictness from the env at creation; tests
    flip ``.strict`` / call ``.reset()``)."""
    global _sentry
    with _guard:
        if _sentry is None:
            _sentry = ShardingSentry(strict=strict_enabled())
        return _sentry


def arm(strict: Optional[bool] = None) -> ShardingSentry:
    """Idempotent arm (engine init, chaos fixtures, the loadtest)."""
    global _armed
    sentry = get()
    if strict is not None:
        sentry.strict = bool(strict)
    with _guard:
        _armed = True
    return sentry


def armed() -> bool:
    return _armed


def disarm() -> None:
    global _armed
    with _guard:
        _armed = False
