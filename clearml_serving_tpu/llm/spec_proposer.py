"""Pluggable draft proposers for speculative verify rows
(docs/spec_decode_trees.md).

PR 13 made speculative decoding a q=k+1 verify ROW of the ragged mixed
launch; this module owns WHAT those k draft positions contain. A
:class:`SpecProposer` turns each eligible slot's token history into a
:class:`DraftForest` — a fixed-budget draft TREE of exactly ``k+1``
nodes (node 0 is the committed root token, nodes 1..k are drafts) laid
out parent-before-child so the row's flat token order is a valid
topological order. The engine only consumes the forest arrays; swapping
the draft source (n-gram forest today, medusa-style heads or a tiny
draft model tomorrow) never touches the launch layout, the tree mask,
or the acceptance rule.

Topology contract (shared with ops.paged_attention tree masking and
sampling.speculative_sample_tree):

- ``tokens[s, 0]`` is ignored by proposers (the engine writes the slot's
  committed next token there); ``tokens[s, 1:n]`` are draft tokens.
- ``parents[s, j] < j`` for every live node ``j >= 1`` and
  ``parents[s, 0] == -1``; nodes ``>= n_nodes[s]`` are dead padding
  (parent -1, token 0).
- A CHAIN is the degenerate forest ``parents = [-1, 0, 1, .., k-1]`` —
  the acceptance rule and the causal mask then collapse to PR 13's
  chain semantics byte-for-byte (tests/test_spec_tree.py pins it).

The n-gram FOREST proposer generalizes the chain proposer's history
matching: instead of continuing only from the LAST match of the
history's n-token tail, it branches the root across up to ``branch``
distinct continuations found at different match sites (most recent
first, first-token-deduped), then spends the remaining node budget
deepening the primary (most recent) branch. One rejected first draft no
longer truncates the whole window — a sibling can carry the row.

Proposers are jax-free and run on the loop thread (drafts are ragged
row CONTENT — they must exist before the launch is laid out), so
everything here is numpy at batch-of-slots scale.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Sequence

import numpy as np


@dataclass
class DraftForest:
    """Fixed-budget draft trees for a batch of spec-eligible slots.

    ``tokens``/``parents``/``depths`` are ``[S, k+1]`` int32 (node-major,
    parent-before-child); ``n_nodes`` [S] counts live nodes (>= 1: the
    root always exists). ``hits`` [S] marks slots whose drafts came from
    a real history match rather than the repeat-last fallback (the
    proposer hit-rate metric reads this)."""

    tokens: np.ndarray
    parents: np.ndarray
    depths: np.ndarray
    n_nodes: np.ndarray
    hits: np.ndarray

    @property
    def budget(self) -> int:
        return int(self.tokens.shape[1])


def chain_parents(k: int) -> np.ndarray:
    """The degenerate single-branch topology: node j hangs off node j-1."""
    return np.concatenate([[-1], np.arange(k, dtype=np.int32)]).astype(np.int32)


def validate_forest(forest: DraftForest) -> None:
    """Raise ValueError on a topology the mask/acceptance contract cannot
    represent (parent-after-child, dead-node parents, depth lies)."""
    s, n = forest.tokens.shape
    for arr, name in ((forest.parents, "parents"), (forest.depths, "depths")):
        if arr.shape != (s, n):
            raise ValueError("forest {} shape {} != {}".format(
                name, arr.shape, (s, n)))
    for b in range(s):
        live = int(forest.n_nodes[b])
        if not (1 <= live <= n):
            raise ValueError("forest row {}: n_nodes {} outside [1, {}]"
                             .format(b, live, n))
        if forest.parents[b, 0] != -1 or forest.depths[b, 0] != 0:
            raise ValueError("forest row {}: node 0 must be the root".format(b))
        for j in range(1, live):
            p = int(forest.parents[b, j])
            if not (0 <= p < j):
                raise ValueError(
                    "forest row {}: node {} parent {} not before it"
                    .format(b, j, p))
            if forest.depths[b, j] != forest.depths[b, p] + 1:
                raise ValueError(
                    "forest row {}: node {} depth {} != parent depth + 1"
                    .format(b, j, int(forest.depths[b, j])))


class SpecProposer:
    """Draft-source interface: history in, :class:`DraftForest` out.

    ``propose(slots, hists, tokbuf, k)`` receives the eligible slot ids,
    their generated-history lengths, and the engine's host token buffer
    (read-only), and returns a forest with budget ``k+1``. Implementations
    must be pure host-side (no jax) and deterministic given the buffer."""

    name = "base"

    def propose(self, slots: Sequence[int], hists: Sequence[int],
                tokbuf: np.ndarray, k: int) -> DraftForest:
        raise NotImplementedError

    def stats(self) -> Dict[str, int]:
        return {}


def _ngram_matches(buf: np.ndarray, hist: int, n: int, limit_matches: int):
    """Positions (most recent first) where the history's n-token tail
    re-occurs strictly before itself; the continuation after each match
    is a draft branch candidate. Mirrors the legacy proposer's window
    math (engine._ngram_draft_rows) so the single-match case reproduces
    the chain drafts exactly."""
    buf_len = buf.shape[0]
    tail_pos = np.clip(hist - n + np.arange(n), 0, buf_len - 1)
    tail = buf[tail_pos]
    limit = hist - 2 * n + 1
    if limit <= 0:
        return tail, []
    match = np.ones(limit, bool)
    for j in range(n):
        match &= buf[j: limit + j] == tail[j]
    idx = np.nonzero(match)[0]
    return tail, list(idx[::-1][:limit_matches])


class NgramChainProposer(SpecProposer):
    """PR 13's proposer behind the new interface: continue from the LAST
    match as a single chain (repeat-last-token fallback on no match).
    Kept as the degenerate case the byte-identity tests pin against."""

    name = "ngram-chain"

    def __init__(self, ngram: int = 2):
        self.ngram = int(ngram)
        self.proposed = 0
        self.hit = 0

    def propose(self, slots, hists, tokbuf, k):
        s = len(slots)
        buf_len = tokbuf.shape[1]
        tokens = np.zeros((s, k + 1), np.int32)
        parents = np.broadcast_to(chain_parents(k), (s, k + 1)).copy()
        depths = np.broadcast_to(
            np.arange(k + 1, dtype=np.int32), (s, k + 1)).copy()
        n_nodes = np.full(s, k + 1, np.int32)
        hits = np.zeros(s, bool)
        for i, (slot, hist) in enumerate(zip(slots, hists)):
            buf = tokbuf[slot]
            tail, matches = _ngram_matches(buf, int(hist), self.ngram, 1)
            if matches:
                pos = np.clip(matches[0] + self.ngram + np.arange(k),
                              0, buf_len - 1)
                tokens[i, 1:] = buf[pos]
                hits[i] = True
            else:
                tokens[i, 1:] = tail[-1]
        self.proposed += s
        self.hit += int(hits.sum())
        return DraftForest(tokens, parents, depths, n_nodes, hits)

    def stats(self):
        return {"proposed": self.proposed, "hit": self.hit}


class NgramForestProposer(SpecProposer):
    """N-gram FOREST drafting: the verify row's k draft nodes split
    across up to ``branch`` sibling continuations of the root.

    Budget layout (k nodes, all depth counted from the root):

    - The primary branch (most recent match) takes a chain of depth
      ``k - (extra siblings)`` — deep acceptance stays possible.
    - Each additional distinct match (older, first-token different from
      every earlier sibling) contributes ONE depth-1 sibling node, up to
      ``branch - 1`` of them. A rejected primary first draft then still
      has siblings to carry one accepted token + a repositioned bonus.
    - No match at all falls back to the chain proposer's repeat-last
      fallback (hits[i] stays False).
    """

    name = "ngram-forest"

    def __init__(self, ngram: int = 2, branch: int = 2,
                 scan_matches: int = 8):
        if branch < 1:
            raise ValueError("forest proposer needs branch >= 1")
        self.ngram = int(ngram)
        self.branch = int(branch)
        self.scan_matches = max(int(scan_matches), int(branch))
        self.proposed = 0
        self.hit = 0
        self.branched = 0       # slots that actually got > 1 root child

    def propose(self, slots, hists, tokbuf, k):
        s = len(slots)
        buf_len = tokbuf.shape[1]
        tokens = np.zeros((s, k + 1), np.int32)
        parents = np.full((s, k + 1), -1, np.int32)
        depths = np.zeros((s, k + 1), np.int32)
        n_nodes = np.ones(s, np.int32)
        hits = np.zeros(s, bool)
        for i, (slot, hist) in enumerate(zip(slots, hists)):
            buf = tokbuf[slot]
            tail, matches = _ngram_matches(
                buf, int(hist), self.ngram, self.scan_matches)
            if not matches:
                # repeat-last fallback chain (identical to the chain
                # proposer so the no-history regime stays unchanged)
                tokens[i, 1:] = tail[-1]
                parents[i] = chain_parents(k)
                depths[i] = np.arange(k + 1)
                n_nodes[i] = k + 1
                continue
            hits[i] = True
            # sibling candidates: distinct first tokens, most recent first
            first = lambda m: int(buf[min(m + self.ngram, buf_len - 1)])
            siblings = [matches[0]]
            for m in matches[1:]:
                if len(siblings) >= self.branch:
                    break
                if first(m) not in {first(x) for x in siblings}:
                    siblings.append(m)
            extra = min(len(siblings) - 1, max(0, k - 1))
            primary_depth = k - extra
            node = 1
            # primary branch: chain of primary_depth continuations
            pos = np.clip(matches[0] + self.ngram + np.arange(primary_depth),
                          0, buf_len - 1)
            prev = 0
            for t in buf[pos]:
                tokens[i, node] = t
                parents[i, node] = prev
                depths[i, node] = depths[i, prev] + 1
                prev = node
                node += 1
            # depth-1 siblings off the root from the older matches
            for m in siblings[1:1 + extra]:
                tokens[i, node] = first(m)
                parents[i, node] = 0
                depths[i, node] = 1
                node += 1
            n_nodes[i] = node
            if extra > 0:
                self.branched += 1
        self.proposed += s
        self.hit += int(hits.sum())
        return DraftForest(tokens, parents, depths, n_nodes, hits)

    def stats(self):
        return {"proposed": self.proposed, "hit": self.hit,
                "branched": self.branched}


PROPOSERS = {
    "ngram-chain": NgramChainProposer,
    "ngram-forest": NgramForestProposer,
}


def make_proposer(name: str, **kwargs) -> SpecProposer:
    try:
        cls = PROPOSERS[name]
    except KeyError:
        raise ValueError(
            "unknown spec proposer {!r} (have: {})".format(
                name, ", ".join(sorted(PROPOSERS)))) from None
    return cls(**kwargs)
