"""Tokenizer wrapper: HuggingFace fast tokenizers when the model bundle ships
one, byte-level fallback otherwise (zero-dependency, fits any vocab ≥ 259).

Replaces the reference's reliance on vLLM's internal tokenizer handling
(preprocess_service.py:688-710 chat-template resolution).
"""

from __future__ import annotations

from pathlib import Path
from typing import Iterable, List, Optional


class ByteTokenizer:
    """Bytes 0..255 as tokens + bos/eos/pad specials. Deterministic and
    dependency-free — the CI/test tokenizer, and the fallback when a bundle has
    no tokenizer files."""

    def __init__(self, vocab_size: int = 512):
        assert vocab_size >= 259, "byte tokenizer needs vocab >= 259"
        self.vocab_size = vocab_size
        self.bos_token_id = 256
        self.eos_token_id = 257
        self.pad_token_id = 258

    def encode(self, text: str, add_bos: bool = True) -> List[int]:
        ids = list(text.encode("utf-8"))
        return ([self.bos_token_id] if add_bos else []) + ids

    def encode_chat(self, templated: str) -> List[int]:
        """Encode apply_chat_template output. The byte-level template carries
        no special tokens, so BOS is prepended here."""
        return self.encode(templated, add_bos=True)

    def decode(self, ids: Iterable[int]) -> str:
        data = bytes(i for i in ids if 0 <= i < 256)
        return data.decode("utf-8", errors="replace")

    def apply_chat_template(self, messages: List[dict]) -> str:
        parts = []
        for m in messages:
            parts.append("<|{}|>\n{}\n".format(m.get("role", "user"), m.get("content", "")))
        parts.append("<|assistant|>\n")
        return "".join(parts)


class HFTokenizer:
    """transformers AutoTokenizer adapter (same surface as ByteTokenizer)."""

    def __init__(self, path: str):
        from transformers import AutoTokenizer

        self._tok = AutoTokenizer.from_pretrained(path, local_files_only=True)
        self.vocab_size = int(self._tok.vocab_size)
        self.bos_token_id = self._tok.bos_token_id
        self.eos_token_id = self._tok.eos_token_id
        # explicit None check: a valid pad_token_id of 0 must not be
        # silently replaced by eos
        self.pad_token_id = (
            self._tok.pad_token_id
            if self._tok.pad_token_id is not None
            else self._tok.eos_token_id
        )

    def encode(self, text: str, add_bos: bool = True) -> List[int]:
        return self._tok.encode(text, add_special_tokens=add_bos)

    def encode_chat(self, templated: str) -> List[int]:
        """Encode apply_chat_template output WITHOUT re-adding special tokens:
        HF chat templates (Llama family included) already emit BOS in the
        template text, so encode(add_special_tokens=True) would double it and
        degrade generation fidelity (matches vLLM's chat encoding)."""
        return self._tok.encode(templated, add_special_tokens=False)

    def decode(self, ids: Iterable[int]) -> str:
        return self._tok.decode(list(ids), skip_special_tokens=True)

    def apply_chat_template(self, messages: List[dict], tools=None) -> str:
        """``tools``: OpenAI-shape tool definitions forwarded to the HF
        template. Templates without a ``tools`` variable silently ignore
        them — llm/tools.py detects that by comparing against the
        tool-less render and falls back to a system preamble."""
        if tools:
            try:
                return self._tok.apply_chat_template(
                    messages, tokenize=False, add_generation_prompt=True,
                    tools=list(tools),
                )
            except Exception:
                # a failed tools= render (old transformers without the
                # kwarg, or a template choking on the tools variable) must
                # fall back to the TOOL-LESS render, not the byte-level
                # fallback text: returning different text here would make
                # the native-support probe read "template consumed tools"
                # and permanently serve degraded prompts (r4 code review)
                return self.apply_chat_template(messages)
        try:
            return self._tok.apply_chat_template(
                messages, tokenize=False, add_generation_prompt=True
            )
        except Exception:
            # no chat template: the fallback text carries no specials, so
            # prepend the BOS literal to keep encode_chat() (which never adds
            # special tokens) correct for both paths
            text = ByteTokenizer.apply_chat_template(self, messages)  # type: ignore
            if self._tok.bos_token:
                text = self._tok.bos_token + text
            return text


def load_tokenizer(model_path: Optional[str], vocab_size: int):
    """HF tokenizer if the bundle directory carries tokenizer files, else
    byte-level fallback."""
    if model_path:
        p = Path(model_path)
        base = p if p.is_dir() else p.parent
        if (base / "tokenizer.json").exists() or (base / "tokenizer_config.json").exists():
            try:
                return HFTokenizer(str(base))
            except Exception:
                pass
    return ByteTokenizer(vocab_size=max(vocab_size, 259))
