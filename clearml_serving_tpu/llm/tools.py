"""OpenAI tool / function calling for chat completions.

The reference exposes vLLM's tool calling through chat_settings (tool
parsing + reasoning parser enabled per endpoint:
/root/reference/clearml_serving/serving/preprocess_service.py:792-808,
/root/reference/examples/vllm/preprocess.py:25-33). vLLM's design is a
host-side OUTPUT PARSER per model family (hermes/mistral/llama-json...)
plus optional grammar enforcement.

TPU-native shape here:

- ``tool_choice`` "required" / {"function": {"name": ...}} compiles the
  tool-call JSON into the on-device guided-decoding DFA (llm/guided.py):
  the decode scan itself can only produce ``{"name": <tool>,
  "arguments": <schema-valid args>}`` — arguments are enforced by
  construction, not validated after the fact.
- ``tool_choice`` "auto" leaves sampling free and parses the finished
  text: Hermes/Qwen ``<tool_call>{...}</tool_call>`` blocks and bare
  Llama-3-style JSON objects ``{"name": ..., "arguments"|"parameters":
  {...}}`` (single or array), accepted only when the name matches a
  declared tool so ordinary JSON answers are never misread as calls.
- Tool definitions reach the model through the HF chat template's
  ``tools=`` kwarg when the template supports it; otherwise a system
  preamble is injected (render_chat_with_tools probes the rendered text
  for the tool names).
"""

from __future__ import annotations

import json
import re
import uuid
from typing import Any, Dict, List, Optional, Sequence, Tuple


def validate_tools(tools: Any) -> List[Dict[str, Any]]:
    """Normalize the OpenAI ``tools`` array -> [{name, description,
    parameters}]. Raises ValueError (-> 422) on malformed entries."""
    if not isinstance(tools, (list, tuple)) or not tools:
        raise ValueError("tools must be a non-empty array")
    out = []
    for i, t in enumerate(tools):
        if not isinstance(t, dict):
            raise ValueError("tools[{}] must be an object".format(i))
        if t.get("type", "function") != "function":
            raise ValueError(
                "tools[{}].type {!r} unsupported (only 'function')".format(
                    i, t.get("type")
                )
            )
        fn = t.get("function")
        if not isinstance(fn, dict) or not fn.get("name"):
            raise ValueError("tools[{}].function.name missing".format(i))
        params = fn.get("parameters")
        if params is not None and not isinstance(params, dict):
            raise ValueError(
                "tools[{}].function.parameters must be a JSON schema "
                "object".format(i)
            )
        out.append(
            {
                "name": str(fn["name"]),
                "description": str(fn.get("description") or ""),
                "parameters": params if params is not None else {"type": "object"},
            }
        )
    if len({t["name"] for t in out}) != len(out):
        raise ValueError("tool names must be unique")
    return out


def resolve_tool_choice(body: Dict[str, Any]) -> Tuple[str, Optional[str]]:
    """-> (mode, forced_name) with mode in none|auto|required|forced.
    OpenAI default: 'auto' when tools are present, 'none' otherwise."""
    tools = body.get("tools")
    choice = body.get("tool_choice")
    if not tools:
        if choice not in (None, "none"):
            raise ValueError("tool_choice given without tools")
        return "none", None
    if choice is None or choice == "auto":
        return "auto", None
    if choice == "none":
        return "none", None
    if choice == "required":
        return "required", None
    if isinstance(choice, dict):
        if choice.get("type", "function") != "function":
            raise ValueError(
                "tool_choice.type {!r} unsupported (only 'function')".format(
                    choice.get("type")
                )
            )
        name = (choice.get("function") or {}).get("name")
        if not name:
            raise ValueError("tool_choice.function.name missing")
        return "forced", str(name)
    raise ValueError("unsupported tool_choice {!r}".format(choice))


def tool_call_schema(
    tools: Sequence[Dict[str, Any]], forced_name: Optional[str] = None
) -> Dict[str, Any]:
    """JSON schema for one tool-call object, lowered by
    guided.json_schema_to_regex into the on-device DFA. ``const`` pins the
    name; the tool's own parameters schema constrains the arguments."""
    subset = [t for t in tools if forced_name is None or t["name"] == forced_name]
    if not subset:
        raise ValueError(
            "tool_choice names unknown tool {!r}".format(forced_name)
        )
    def arguments_schema(params: Dict[str, Any]) -> Dict[str, Any]:
        # OpenAI strict-function-calling semantics: the arguments object is
        # exactly the declared parameters. A declared-properties object is
        # already closed by the DFA lowering (only declared members can be
        # emitted); pinning additionalProperties: false extends that to the
        # propertyless case, which would otherwise lower to "any object" —
        # unbounded free-form members that a constrained decode could
        # wander in until max_tokens instead of closing the call.
        out = dict(params)
        out.setdefault("additionalProperties", False)
        # a bare `parameters: {}` has no "type" key either: without it the
        # DFA lowering would skip both object branches and fall through to
        # "any JSON value", un-closing the object the line above closed
        out.setdefault("type", "object")
        return out

    variants = [
        {
            "type": "object",
            "properties": {
                "name": {"const": t["name"]},
                "arguments": arguments_schema(t["parameters"]),
            },
            "required": ["name", "arguments"],
        }
        for t in subset
    ]
    return variants[0] if len(variants) == 1 else {"anyOf": variants}


# Hermes / Qwen style: one JSON object per <tool_call> block
_TOOL_BLOCK_RE = re.compile(r"<tool_call>\s*(\{.*?\})\s*</tool_call>", re.S)
TOOL_TAG = "<tool_call>"


def strip_tool_blocks(text: str) -> str:
    """Prose left after removing <tool_call> blocks — OpenAI allows content
    alongside tool_calls when the model narrates before calling."""
    return _TOOL_BLOCK_RE.sub("", text).strip()


def split_tag_holdback(pending: str) -> Tuple[str, str]:
    """(emit, keep): hold back the longest trailing prefix of
    ``<tool_call>`` so a tag spanning stream deltas is never partially
    emitted as content (same pattern as stop-string holdback)."""
    for k in range(min(len(TOOL_TAG) - 1, len(pending)), 0, -1):
        if pending.endswith(TOOL_TAG[:k]):
            return pending[:-k], pending[-k:]
    return pending, ""


def _normalize_call(
    value: Any, known: Optional[set]
) -> Optional[Dict[str, str]]:
    if not isinstance(value, dict):
        return None
    name = value.get("name")
    if not isinstance(name, str) or not name:
        return None
    if known is not None and name not in known:
        return None
    args = value.get("arguments", value.get("parameters"))
    if args is None:
        args = {}
    if isinstance(args, str):
        try:  # already JSON-encoded; OpenAI clients require an object
            parsed = json.loads(args)
        except ValueError:
            return None
        if not isinstance(parsed, dict):
            return None
        arg_str = args
    elif isinstance(args, dict):
        arg_str = json.dumps(args)
    else:  # list / scalar arguments are not a valid call shape
        return None
    return {"name": name, "arguments": arg_str}


def parse_tool_calls(
    text: str, tool_names: Optional[Sequence[str]] = None
) -> Optional[List[Dict[str, str]]]:
    """Extract tool calls from finished model text, or None if the text is
    a plain answer. ``tool_names`` gates bare-JSON detection so an ordinary
    JSON reply whose object happens to have a "name" key is not misread."""
    known = set(tool_names) if tool_names is not None else None
    stripped = text.strip()
    blocks = _TOOL_BLOCK_RE.findall(stripped)
    if blocks:
        calls = []
        for b in blocks:
            try:
                call = _normalize_call(json.loads(b), known)
            except ValueError:
                return None
            if call is None:
                return None
            calls.append(call)
        return calls or None
    if not stripped.startswith(("{", "[")):
        return None
    try:
        val = json.loads(stripped)
    except ValueError:
        return None
    vals = val if isinstance(val, list) else [val]
    calls = []
    for v in vals:
        call = _normalize_call(v, known)
        if call is None:
            return None
        calls.append(call)
    return calls or None


def tool_call_objects(calls: Sequence[Dict[str, str]]) -> List[Dict[str, Any]]:
    """-> OpenAI response shape with generated call ids."""
    return [
        {
            "id": "call_{}".format(uuid.uuid4().hex[:24]),
            "type": "function",
            "function": {"name": c["name"], "arguments": c["arguments"]},
        }
        for c in calls
    ]


def tools_preamble(tools: Sequence[Dict[str, Any]]) -> str:
    """System-message fallback for chat templates without native ``tools=``
    support; instructs the bare-JSON format parse_tool_calls accepts."""
    specs = json.dumps(
        [
            {"type": "function", "function": t}
            for t in tools
        ],
        indent=2,
    )
    return (
        "You have access to the following functions. To call a function, "
        'respond ONLY with a JSON object of the form {"name": '
        '"<function-name>", "arguments": <json-arguments-object>} and no '
        "other text.\n\nAvailable functions:\n" + specs
    )


def messages_with_tool_results(messages: List[dict]) -> List[dict]:
    """Rewrite message shapes a non-tool-aware chat template would drop:
    role 'tool' results and assistant tool_calls become textual content so
    every template renders the full call/result history."""
    out = []
    for m in messages:
        role = m.get("role")
        if role == "tool":
            out.append(
                {
                    "role": "user",
                    "content": "[tool result for {}]\n{}".format(
                        m.get("tool_call_id", "call"), m.get("content", "")
                    ),
                }
            )
        elif role == "assistant" and m.get("tool_calls") and not m.get("content"):
            calls = [
                {
                    "name": (c.get("function") or {}).get("name"),
                    "arguments": (c.get("function") or {}).get("arguments"),
                }
                for c in m["tool_calls"]
            ]
            out.append({"role": "assistant", "content": json.dumps(calls)})
        else:
            out.append(m)
    return out


def render_chat_with_tools(
    tokenizer, messages: List[dict], tools: Sequence[Dict[str, Any]]
) -> str:
    """Render the prompt so the model SEES the tool definitions: the HF
    template's native ``tools=`` path when it actually consumes them
    (probed by checking the rendered text mentions the tool names),
    otherwise a system preamble + normalized messages."""
    if tools:
        hf_tools = [{"type": "function", "function": t} for t in tools]
        # whether the template consumes `tools=` is a per-tokenizer
        # constant: probe once (two renders), then cache — long histories
        # shouldn't pay a double Jinja render on every request
        native = getattr(tokenizer, "_tools_template_native", None)
        if native is None or native:
            try:
                text = tokenizer.apply_chat_template(messages, tools=hf_tools)
            except Exception:
                text = None
            if native:
                return text if text is not None else tokenizer.apply_chat_template(
                    [{"role": "system", "content": tools_preamble(tools)}]
                    + messages_with_tool_results(messages)
                )
            # first probe: identical renders = the template has no `tools`
            # variable and dropped them silently
            try:
                base = tokenizer.apply_chat_template(messages)
            except Exception:
                base = text = None
            native = text is not None and text != base
            try:
                tokenizer._tools_template_native = native
            except Exception:
                pass
            if native:
                return text
        msgs = [{"role": "system", "content": tools_preamble(tools)}]
        msgs.extend(messages_with_tool_results(messages))
        return tokenizer.apply_chat_template(msgs)
    # no tools in the request: pass messages through untouched — chat
    # templates that natively render `tool` turns (Hermes/Qwen/Llama-3.1)
    # must see the real role structure, not the textual rewrite (which is
    # only for the preamble fallback path)
    return tokenizer.apply_chat_template(messages)
