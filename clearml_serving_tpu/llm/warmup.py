"""Warmup shape registry: compile the serve loop's XLA key space BEFORE the
serve fence (docs/static_analysis.md TPU6xx, docs/slo_scheduling.md).

Every serve-time XLA compile is a 100-1000 ms stall of the loop thread that
masquerades as scheduling tail — PR 6's loadtest measured each unwarmed
shape costing 100-1000 ms mid-run, and PR 10's tiering work re-discovered
the same class on resume-commit shapes. The fix was an inline warmup block
private to the loadtest; this module is that block extracted, generalized
over the ENGINE'S OWN configuration (prefill buckets, prefix block, page
size, scheduler), and made a registry three consumers share:

- engine startup (``LLMEngineCore.warmup()``, e.g. at endpoint load),
- ``bench.py --loadtest`` (benchmarks/slo_loadtest.py),
- tests (the warmup-coverage suite proves a warmed engine serves in-class
  traffic with ZERO further compiles under the strict compile sentry).

``WARMUP_COVERED`` is the machine-readable half: the engine jit entries
whose key space the sweep drives. The static analyzer (TPU603,
analyze/rules_compile.py) parses it FROM SOURCE — keep it a literal — and
requires every ``"serve"``-role entry of the engine's ``__compile_keys__``
to appear here, so a new dispatch-path jit entry cannot land without
either a warmup extension or an explicit role reclassification.

What the sweep enumerates (derived from engine attributes, never
hard-coded): cold prefill per bucket; radix-hit gather + tail chunk per
bucket; every resume-commit final-segment length 1..block per hit bucket
(preempted histories resume with arbitrary tails); cold-commit scatters at
every page count up to the largest bucket; multi-segment tails (partially
evicted prefixes replay tails longer than one block); power-of-two CoW
copy buckets (and, on int8 pools, their scale-row copies); the ragged
finish-row gathers at every power of two; a spec-decode round when
speculation is on. Coverage assumption, stated plainly: the sweep warms
the PLAIN-SAMPLING serve surface — sampling-extras / guided / logprob
variants trace on first use (each is one bounded compile per variant, not
a per-request key), and the compile sentry attributes them when armed.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

# The engine jit entries whose compile keys the sweep drives (conditional
# on the engine's configuration: a dense engine has no paged entries to
# warm, a two-dispatch engine no ragged ones). Parsed from source by
# analyze/rules_compile.py (TPU603) — MUST stay a literal; the analyzer's
# build-time mirror is consistency-tested in tests/test_analyze_compile.py.
WARMUP_COVERED = frozenset({
    "_prefill_jit",
    "_prefill_ring_jit",
    "_prefill_pipeline_jit",
    "_prefill_chunk_first_jit",
    "_prefill_chunk_jit",
    "_gather_pages_jit",
    "_assemble_prefix_jit",
    "_insert_jit",
    "_merge_rows_jit",
    "_decode_chunk_jit",
    "_decode_paged_chunk_jit",
    "_sample_jit",
    "_first_lp_jit",
    "_set_sampling_row_jit",
    "_spec_chunk_jit",
    "_spec_paged_jit",
    "_ragged_paged_jit",
    "_ragged_dense_jit",
    "_gather_finish_jit",
})


def _ids(seed: int, n: int, vocab: int) -> List[int]:
    """Deterministic token content: the same (seed, n) always yields the
    same ids, so a stored radix prefix is hit by the later sweep steps
    that rely on it."""
    lim = max(2, min(250, vocab - 2))
    return [(seed * 13 + i * 11) % lim + 1 for i in range(n)]


def _tail(seed: int, n: int, vocab: int) -> List[int]:
    lim = max(2, min(250, vocab - 2))
    return [(seed * 53 + j * 3) % lim + 1 for j in range(n)]


def warmup_plan(engine, full: bool = True) -> List[Dict[str, Any]]:
    """Enumerate the warmup REQUEST sweep for this engine's configuration:
    a list of ``{"prompt_ids": [...], "max_new_tokens": n}`` specs in the
    order they must run (earlier steps seed the radix runs later steps
    hit). ``full=False`` keeps only the per-bucket cold+hit pass — the
    cheap startup subset; the full sweep is what the zero-recompile
    certification runs."""
    vocab = max(engine._vocab, 8)
    buckets = list(engine._buckets)
    if buckets[-1] < engine.max_seq_len:
        # _bucket_for falls back to max_seq_len for prompts past the last
        # configured bucket — that implicit bucket is part of the compile
        # surface too (the sentry caught exactly this hole in testing)
        buckets.append(engine.max_seq_len)
    prefix = engine._prefix
    block = prefix.block if prefix is not None else 0
    paged = engine.paged_cache is not None
    plan: List[Dict[str, Any]] = []

    def req(ids: List[int], max_new: int = 2) -> None:
        if 0 < len(ids) < engine.max_seq_len:
            plan.append({"prompt_ids": ids, "max_new_tokens": max_new})

    def bucket_prefix_len(b: int) -> int:
        # largest block multiple that leaves room for a sub-block tail in
        # the same bucket (0 = no stored prefix at this bucket)
        return ((b - block) // block) * block if block and b > block else 0

    # 1) cold prefill per bucket + radix store/hit per bucket: the repeat
    # runs the hit path (gather/assemble + tail chunk) at that bucket
    for b in buckets:
        p = bucket_prefix_len(b)
        head = _ids(b, p, vocab)
        reps = 2 if (p and prefix is not None) else 1
        for rep in range(reps):
            tail = [
                (rep * 37 + j * 5 + b) % max(2, min(250, vocab - 2)) + 1
                for j in range(max(1, min(b - p, block or b) - 1))
            ]
            req(head + tail)
    if not full or prefix is None:
        return plan

    # 2) resume-commit tails, single-page: a preempted request's history
    # (and a partially evicted prefix) can resume with ANY final-segment
    # length 1..block, and the commit's tail slice/scatter compiles once
    # per (bucket, length-class) — the exact class PR 6 measured at
    # 100-200 ms per unwarmed length on the loop thread
    for b in buckets:
        p = bucket_prefix_len(b)
        if p < block:
            continue
        head = _ids(b, p, vocab)
        for t in range(1, block + 1):
            req(head + _tail(t, t, vocab))

    # 2b) resume-commit tails, multi-page: the commit slices the mini
    # cache with a DYNAMIC start and a PAGE-MULTIPLE static size
    # (engine._insert_prefill._tail), so its key space is (mini-cache
    # bucket, padded tail pages) — and eviction can shorten a stored run
    # to ANY block-multiple depth, which makes EVERY (bucket, k*page)
    # pair reachable at serve time (the strict sentry caught exactly the
    # missing (128, 2-page) pair during this sweep's own development).
    # A stored head's trie path contains all its block-aligned prefixes,
    # so head[:p'] + a fresh tail forces each pair deliberately.
    if paged:
        page = engine.paged_cache.pool.page_size
        for b in buckets:
            p_b = bucket_prefix_len(b)
            if p_b < block:
                continue
            head = _ids(b, p_b, vocab)
            for k in range(2, (b - block) // page + 1):
                p_prime = ((b - k * page) // block) * block
                if p_prime < block or p_prime > p_b:
                    continue
                tail_len = (k - 1) * page + 1
                req(head[:p_prime] + _tail(200 + b + k, tail_len, vocab))

    # 3) cold-commit scatter at every page count: the page-bucketed commit
    # write compiles once per page COUNT (kv_cache._scatter_pages)
    if paged:
        page = engine.paged_cache.pool.page_size
        for n_pages in range(1, engine.paged_cache.pool.pages_needed(
                buckets[-1]) + 1):
            n = n_pages * page - min(3, page - 1)
            req(_ids(67 + n_pages, n, vocab))

    # 4) multi-segment tails: when eviction shortened a stored run, a hit
    # replays a tail LONGER than one block — non-final chunk segments
    # (with_logits=False) are a distinct trace per bucket
    if block:
        seed_run = _ids(7, 2 * block - 1, vocab)
        req(seed_run)
        heads = [seed_run[:block]]
        heads += [
            _ids(b, bucket_prefix_len(b), vocab)
            for b in buckets
            if bucket_prefix_len(b) >= block
        ]
        for i, head in enumerate(heads):
            req(head + _tail(100 + i, block + 1, vocab))

    # 5) speculation: one longer greedy request so the spec draft/verify
    # chunk (and its commit bookkeeping) traces before the fence
    if engine._speculation is not None:
        k = engine._spec_k
        req(
            _ids(5, max(1, 2 * block or 8), vocab),
            max_new=max(4, 2 * engine.decode_steps * (k + 1)),
        )
    return plan


def warm_ragged_variants(engine) -> int:
    """Compile every (decode window, spec-row) ragged launch variant for
    this engine's configuration with null-row operands — see the call site
    in :func:`run_warmup`. Returns the number of launches run. Operand
    construction mirrors ``engine._dispatch_ragged_device_inner`` one for
    one (dtype-strong numpy uploads, same None-ness per variant); every
    scatter lands in the dead null page / a frozen dense position, so the
    pools/cache round-trip through the donated call value-unchanged."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    b = engine.max_batch
    k_ = engine._spec_k
    windows = []
    p = 1
    while p <= engine._ragged_steps_cap:
        windows.append(p)
        p *= 2
    spec_opts = [False] + ([True] if engine._speculation else [])
    sampling = engine._batch_sampling()
    lora = (
        jnp.asarray(np.zeros(b, np.int32)) if engine._lora_enabled else None
    )
    ran = 0

    def key():
        return engine._next_rng()

    def spec_args(on):
        if not on:
            return None
        return (
            jnp.asarray(np.zeros(b, bool)),
            jnp.asarray(np.zeros(b, bool)),
            jnp.asarray(np.zeros((b, k_), np.int32)),
            jnp.asarray(np.zeros((b, k_ + 1), np.int32)),
            key(),
        )

    if engine.paged_cache is not None:
        cache = engine.paged_cache
        tpad = engine._ragged_tpad
        nb = tpad // engine._ragged_qb
        page_table = jnp.asarray(
            np.zeros((b, engine._pages_per_seq), np.int32)
        )

        def tree_args(on):
            # draft-tree verify variant (docs/spec_decode_trees.md): the
            # tree arrays are FIXED-SHAPE ([B, k+1] topology + [tpad, k+1]
            # ancestor lists) so the whole topology space is ONE compile
            # key — warmed with the plain-causal sentinel (-2), which
            # drives the tree kernel variant over a null launch
            if not (on and getattr(engine, "_spec_tree", False)):
                return None
            anc = np.full((tpad, k_ + 1), -1, np.int32)
            anc[:, 0] = -2
            parents = np.zeros((b, k_ + 1), np.int32)
            parents[:, 0] = -1
            return (
                jnp.asarray(np.zeros((b, k_ + 1), np.int32)),
                jnp.asarray(parents),
                jnp.asarray(np.full(b, k_ + 1, np.int32)),
                jnp.asarray(anc),
            )
        blocks = (
            jnp.asarray(np.full(nb, -1, np.int32)),
            jnp.asarray(np.zeros(nb, np.int32)),
        ) if engine._ragged_on_tpu else (None, None)
        for steps in windows:
            for spec_on in spec_opts:
                chain = None
                if steps > 1:
                    chain = (
                        jnp.stack([key() for _ in range(steps - 1)]),
                        jnp.asarray(np.zeros((steps - 1, b), bool)),
                        jnp.asarray(np.zeros((steps - 1, b), np.int32)),
                        jnp.asarray(np.zeros((steps - 1, b), np.int32)),
                    )
                with cache.dispatch_lock:
                    (
                        sampled, _logits, cache.k, cache.v,
                        new_ks, new_vs, _counts, _lp, _gs, _sg, _sa,
                    ) = engine._ragged_paged_jit(
                        engine.params,
                        jnp.asarray(np.zeros(tpad, np.int32)),
                        jnp.asarray(np.zeros(tpad, np.int32)),
                        jnp.asarray(np.zeros(tpad, np.int32)),
                        jnp.asarray(np.zeros(tpad, bool)),
                        jnp.asarray(np.zeros(b, np.int32)),
                        cache.k, cache.v, cache.k_scale, cache.v_scale,
                        page_table,
                        jnp.asarray(np.zeros(b, np.int32)),
                        jnp.asarray(np.zeros(b, np.int32)),
                        jnp.asarray(np.zeros(b, np.int32)),
                        jnp.asarray(np.zeros(tpad, np.int32)),
                        jnp.asarray(np.zeros(tpad, np.int32)),
                        blocks[0], blocks[1],
                        jnp.asarray(np.zeros(b, bool)),
                        sampling, key(), lora,
                        None, None, None, None, None,
                        want_lp=False,
                        spec=spec_args(spec_on),
                        chain=chain,
                        tree=tree_args(spec_on),
                    )
                    if engine._paged_quant:
                        cache.k_scale = new_ks
                        cache.v_scale = new_vs
                jax.block_until_ready(sampled)
                ran += 1
    else:
        # dense ragged: the rectangular chunk width C is its own compile
        # key (pow2 of the widest row — admission takes up to the budget),
        # so the full certification sweeps every reachable width per
        # (window, spec) variant; spec variants start at the k+1-wide
        # chunks serve guarantees them
        from .shapes import pow2_bucket

        widths = []
        c = 1
        cap = pow2_bucket(engine._step_token_budget)
        while c <= cap:
            widths.append(c)
            c *= 2
        for steps in windows:
            for spec_on in spec_opts:
                chain = None
                if steps > 1:
                    chain = (
                        jnp.stack([key() for _ in range(steps - 1)]),
                        jnp.asarray(np.zeros((steps - 1, b), bool)),
                    )
                for c in widths:
                    if spec_on and c < k_ + 1:
                        continue
                    (
                        sampled, _logits, engine.cache,
                        _counts, _lp, _gs, _sg, _sa,
                    ) = engine._ragged_dense_jit(
                        engine.params,
                        jnp.asarray(np.zeros((b, c), np.int32)),
                        jnp.asarray(np.zeros(b, np.int32)),
                        jnp.asarray(np.zeros(b, np.int32)),
                        jnp.asarray(np.zeros(b, bool)),
                        engine.cache,
                        jnp.asarray(np.zeros(b, bool)),
                        sampling, key(), lora,
                        None, None, None, None, None,
                        want_lp=False,
                        spec=spec_args(spec_on),
                        chain=chain,
                    )
                    jax.block_until_ready(sampled)
                    ran += 1
    return ran


async def run_warmup(
    engine,
    full: bool = True,
    extra_prompts: Optional[List[List[int]]] = None,
    fence: bool = True,
) -> Dict[str, Any]:
    """Drive the warmup sweep against a live engine, then (optionally) set
    the compile sentry's warmup fence: every XLA compile after the fence
    is attributed to serving and — in strict mode — raises. Returns
    ``{"requests", "cow_buckets", "fenced"}``.

    ``extra_prompts`` lets a caller append workload-specific prompts (the
    loadtest replays its trace mix twice so production-shaped shared
    prefixes run warm); each is swept twice, cold then radix-hit.
    """
    import jax.numpy as jnp

    from . import compile_sentry
    from .engine import GenRequest

    plan = warmup_plan(engine, full=full)
    for spec in plan:
        request = GenRequest(
            prompt_ids=spec["prompt_ids"],
            max_new_tokens=spec["max_new_tokens"],
        )
        async for _ in engine.generate(request):
            pass
    if extra_prompts:
        for rep in range(2):  # second pass runs the warm radix path
            for ids in extra_prompts:
                request = GenRequest(
                    prompt_ids=list(ids), max_new_tokens=2
                )
                async for _ in engine.generate(request):
                    pass

    # copy-on-write program warmup: apply_pending_cow pads pair lists to
    # power-of-two buckets (llm/shapes.py) and each bucket is a distinct
    # DONATED program that would otherwise compile on the dispatch path
    # mid-run. Null-page self-copies are no-ops by construction. On int8
    # pools the scale pools CoW in the same batch — warm those programs too.
    cow = 0
    cache = engine.paged_cache
    if full and cache is not None:
        # bound by max_seq_len, not the last configured bucket: prompts in
        # the implicit fallback bucket hold pages_needed(max_seq_len)
        # pages, and their resumes can CoW-burst past a smaller bound
        max_pairs = 2 * cache.pool.pages_needed(engine.max_seq_len)
        p = 1
        while p <= max_pairs:
            zeros = jnp.zeros((p,), jnp.int32)
            with cache.dispatch_lock:
                cache.k = cache._copy_pages(cache.k, zeros, zeros)
                cache.v = cache._copy_pages(cache.v, zeros, zeros)
                if cache.k_scale is not None:
                    cache.k_scale = cache._copy_pages(
                        cache.k_scale, zeros, zeros
                    )
                    cache.v_scale = cache._copy_pages(
                        cache.v_scale, zeros, zeros
                    )
            cow += 1
            p *= 2

    # KV-transport movement programs (docs/disaggregation.md): a
    # disaggregated engine's serve path adds the ship export (the
    # host-tier demote gather verbatim, pow2-padded page lists) and the
    # receive import (the promotion staging scatter, pow2-padded slabs) —
    # both would otherwise compile at the first ship/receive mid-serve.
    # Null-page round trips are dead by construction: the gather reads
    # page 0 and the scatter writes it back, and the fence records reap
    # below so the drained audit stays clean.
    ship_buckets = 0
    if full and cache is not None and (
        getattr(engine, "_kv_transport", None) is not None
    ):
        max_pages = cache.pool.pages_needed(engine.max_seq_len)
        p = 1
        while True:
            pages = [0] * p
            slabs = cache.export_pages(pages)
            cache.import_pages(
                slabs["hk"], slabs["hv"], pages,
                slabs.get("hk_scale"), slabs.get("hv_scale"),
            )
            ship_buckets += 1
            if p >= max_pages:
                break
            p *= 2
        cache.reap_promotions(force=True)

    # ragged finish-row gather: retire reads back only finishing admission
    # rows, padded to a power of two — warm every pad size directly
    if full and engine._ragged and engine._gather_finish_jit is not None:
        logits = jnp.zeros((engine.max_batch, max(engine._vocab, 8)),
                           jnp.float32)
        p = 1
        while p <= engine.max_batch:
            engine._gather_finish_jit(logits, jnp.zeros((p,), jnp.int32))
            p *= 2

    # multi-step / spec-as-row ragged launch variants
    # (docs/ragged_attention.md): the per-launch decode window buckets to a
    # power of two (llm/shapes.decode_steps_bucket) and spec-verify rows
    # toggle the k+1 logit-gather + acceptance trace — each (window, spec)
    # pair is a distinct executable on the serve path. The traffic sweep
    # above only reliably drives the q=1 no-spec launch (sequential
    # requests rarely overlap), so the remaining variants warm DIRECTLY
    # with null-row operands: every write coordinate targets the dead null
    # page (page 0) / a dead position, every mask is False, and the pools
    # round-trip through the donated call like any dispatch.
    if full and engine._ragged:
        warm_ragged_variants(engine)

    await engine.wait_drained()
    fenced = False
    if fence and full and compile_sentry.enabled():
        # only the FULL sweep certifies: fencing after the reduced
        # startup pass would declare a knowingly-incomplete surface
        # warmed — resume tails and CoW programs would then count (and in
        # strict mode raise) as serve-time violations on a healthy engine.
        # Callers that deliberately fence a partial sweep (tests proving
        # the fence machinery) call compile_sentry.get().fence() directly.
        compile_sentry.get().fence()
        fenced = True
    return {
        "requests": len(plan) + 2 * len(extra_prompts or []),
        "cow_buckets": cow,
        "ship_buckets": ship_buckets,
        "fenced": fenced,
    }
