"""Model architecture registry for the JAX engine tier.

Each architecture module exposes ``build(config: dict) -> ModelBundle`` with
pure functional ``init`` / ``apply``. Model payloads on disk are "jax bundles":
a directory with ``model_config.json`` ({"arch": ..., "config": {...}}) and a
``params.msgpack`` flax-serialized parameter pytree — the TPU-native analog of
the reference's Triton model-repository folders (triton_helper.py:159-183).
"""

from types import SimpleNamespace
from typing import Any, Callable, Dict

_BUILDERS: Dict[str, Callable[[dict], Any]] = {}


def register_model(name: str):
    def _decorator(fn):
        _BUILDERS[name] = fn
        return fn

    return _decorator


def build_model(arch: str, config: dict) -> SimpleNamespace:
    try:
        builder = _BUILDERS[arch]
    except KeyError:
        raise ValueError(
            "unknown model arch {!r}; registered: {}".format(arch, sorted(_BUILDERS))
        ) from None
    return builder(config or {})


def registered_archs():
    return sorted(_BUILDERS)


from . import mlp  # noqa: E402,F401
from . import cnn  # noqa: E402,F401
from . import bert  # noqa: E402,F401
from . import llama  # noqa: E402,F401
from . import whisper  # noqa: E402,F401
