"""BERT encoder for token classification (BASELINE.md config 4).

Replaces the reference's Triton-hosted HuggingFace/ONNX path
(reference examples/huggingface) with a native JAX encoder: one big QKV matmul
per layer, fused GELU FFN, fp32 layernorm accumulation — all static-shape so a
single jit specialization serves each (batch-bucket, seq-bucket) pair.

HuggingFace `bert-base-*` checkpoints convert via
clearml_serving_tpu.engines.importers.convert_hf_bert.
"""

from __future__ import annotations

from types import SimpleNamespace
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from . import register_model

PRESETS: Dict[str, Dict[str, Any]] = {
    "bert-base": dict(
        vocab_size=30522, dim=768, n_layers=12, n_heads=12, ffn_dim=3072,
        max_seq_len=512, type_vocab_size=2, norm_eps=1e-12,
    ),
    "bert-tiny": dict(
        vocab_size=512, dim=64, n_layers=2, n_heads=2, ffn_dim=128,
        max_seq_len=128, type_vocab_size=2, norm_eps=1e-12,
    ),
}


def _layer_norm(x, scale, bias, eps):
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.var(x32, axis=-1, keepdims=True)
    out = (x32 - mean) * jax.lax.rsqrt(var + eps)
    return (out * scale.astype(jnp.float32) + bias.astype(jnp.float32)).astype(x.dtype)


@register_model("bert")
def build(config: dict) -> SimpleNamespace:
    cfg = dict(PRESETS.get(config.get("preset", ""), {}))
    cfg.update({k: v for k, v in config.items() if k != "preset"})
    cfg.setdefault("dtype", "bfloat16")
    cfg.setdefault("num_labels", 9)  # CoNLL-2003 NER default

    vocab = int(cfg["vocab_size"])
    dim = int(cfg["dim"])
    n_layers = int(cfg["n_layers"])
    n_heads = int(cfg["n_heads"])
    ffn_dim = int(cfg["ffn_dim"])
    max_len = int(cfg["max_seq_len"])
    eps = float(cfg["norm_eps"])
    num_labels = int(cfg["num_labels"])
    dtype = jnp.dtype(cfg["dtype"])
    head_dim = dim // n_heads

    def init(rng) -> Dict[str, Any]:
        def dense(key, shape, fan_in):
            return (
                jax.random.normal(key, shape, dtype=jnp.float32) * fan_in ** -0.5
            ).astype(dtype)

        keys = jax.random.split(rng, 4 + n_layers)
        params: Dict[str, Any] = {
            "word_embed": dense(keys[0], (vocab, dim), dim),
            "pos_embed": dense(keys[1], (max_len, dim), dim),
            "type_embed": dense(keys[2], (int(cfg["type_vocab_size"]), dim), dim),
            "embed_norm": {"scale": jnp.ones((dim,), dtype), "bias": jnp.zeros((dim,), dtype)},
            "layers": [],
            "classifier": {
                "w": dense(keys[3], (dim, num_labels), dim),
                "b": jnp.zeros((num_labels,), dtype),
            },
        }
        for i in range(n_layers):
            k = jax.random.split(keys[4 + i], 6)
            params["layers"].append(
                {
                    "wqkv": dense(k[0], (dim, 3 * dim), dim),
                    "bqkv": jnp.zeros((3 * dim,), dtype),
                    "wo": dense(k[1], (dim, dim), dim),
                    "bo": jnp.zeros((dim,), dtype),
                    "attn_norm": {"scale": jnp.ones((dim,), dtype), "bias": jnp.zeros((dim,), dtype)},
                    "w1": dense(k[2], (dim, ffn_dim), dim),
                    "b1": jnp.zeros((ffn_dim,), dtype),
                    "w2": dense(k[3], (ffn_dim, dim), ffn_dim),
                    "b2": jnp.zeros((dim,), dtype),
                    "ffn_norm": {"scale": jnp.ones((dim,), dtype), "bias": jnp.zeros((dim,), dtype)},
                }
            )
        return params

    def hidden(params, input_ids: jnp.ndarray, attention_mask: Optional[jnp.ndarray] = None):
        """input_ids [B, S] int32; attention_mask [B, S] (1 = keep) ->
        final-layer hidden states [B, S, dim] (pre-classifier). The encoder
        surface for embeddings/pooling/score routes (reference task-gated
        handlers, preprocess_service.py:711-808)."""
        b, s = input_ids.shape
        if attention_mask is None:
            attention_mask = jnp.ones((b, s), jnp.int32)
        pos = jnp.arange(s, dtype=jnp.int32)
        x = (
            params["word_embed"][input_ids]
            + params["pos_embed"][pos][None]
            + params["type_embed"][jnp.zeros((b, s), jnp.int32)]
        )
        x = _layer_norm(x, params["embed_norm"]["scale"], params["embed_norm"]["bias"], eps)
        bias = jnp.where(attention_mask[:, None, None, :] > 0, 0.0, -jnp.inf).astype(jnp.float32)
        for layer in params["layers"]:
            qkv = x @ layer["wqkv"] + layer["bqkv"]
            q, k, v = jnp.split(qkv, 3, axis=-1)
            q = q.reshape(b, s, n_heads, head_dim)
            k = k.reshape(b, s, n_heads, head_dim)
            v = v.reshape(b, s, n_heads, head_dim)
            scores = jnp.einsum(
                "bshd,bthd->bhst", q, k, preferred_element_type=jnp.float32
            ) * (head_dim ** -0.5)
            probs = jax.nn.softmax(scores + bias, axis=-1).astype(v.dtype)
            attn = jnp.einsum("bhst,bthd->bshd", probs, v).reshape(b, s, dim)
            x = _layer_norm(
                x + attn @ layer["wo"] + layer["bo"],
                layer["attn_norm"]["scale"], layer["attn_norm"]["bias"], eps,
            )
            h = jax.nn.gelu(x @ layer["w1"] + layer["b1"])
            x = _layer_norm(
                x + h @ layer["w2"] + layer["b2"],
                layer["ffn_norm"]["scale"], layer["ffn_norm"]["bias"], eps,
            )
        return x

    def apply(params, input_ids: jnp.ndarray, attention_mask: Optional[jnp.ndarray] = None):
        """Per-token label logits [B, S, num_labels] (token classification)."""
        x = hidden(params, input_ids, attention_mask)
        logits = x @ params["classifier"]["w"] + params["classifier"]["b"]
        return logits.astype(jnp.float32)

    return SimpleNamespace(init=init, apply=apply, hidden=hidden, config=cfg)
