"""Small conv net (MNIST-CNN class of workloads; BASELINE.md config 3).

NHWC layout (XLA's preferred TPU convolution layout) with
`lax.conv_general_dilated` so the convs tile onto the MXU.
"""

from __future__ import annotations

from types import SimpleNamespace

import jax
import jax.numpy as jnp
from jax import lax

from . import register_model


@register_model("cnn")
def build(config: dict) -> SimpleNamespace:
    in_hw = tuple(config.get("in_hw", (28, 28)))
    in_ch = int(config.get("in_ch", 1))
    channels = [int(c) for c in config.get("channels", [32, 64])]
    dense = int(config.get("dense", 128))
    out_dim = int(config.get("out_dim", 10))
    dtype = jnp.dtype(config.get("dtype", "float32"))

    def init(rng):
        params = {"conv": [], "dense": []}
        ch = in_ch
        for c in channels:
            rng, sub = jax.random.split(rng)
            k = jax.random.normal(sub, (3, 3, ch, c), dtype=jnp.float32)
            k = k * (2.0 / (9 * ch)) ** 0.5
            params["conv"].append({"k": k.astype(dtype), "b": jnp.zeros((c,), dtype)})
            ch = c
        # Each conv is followed by a 2x2 max-pool.
        h = in_hw[0] // (2 ** len(channels))
        w = in_hw[1] // (2 ** len(channels))
        flat = h * w * ch
        rng, s1, s2 = jax.random.split(rng, 3)
        params["dense"] = [
            {
                "w": (jax.random.normal(s1, (flat, dense)) * (2.0 / flat) ** 0.5).astype(dtype),
                "b": jnp.zeros((dense,), dtype),
            },
            {
                "w": (jax.random.normal(s2, (dense, out_dim)) * (2.0 / dense) ** 0.5).astype(dtype),
                "b": jnp.zeros((out_dim,), dtype),
            },
        ]
        return params

    def apply(params, x):
        # x: [B, H, W, C] (a [B, H, W] input gets a channel dim appended).
        if x.ndim == 3:
            x = x[..., None]
        x = x.astype(dtype)
        for layer in params["conv"]:
            x = lax.conv_general_dilated(
                x, layer["k"], window_strides=(1, 1), padding="SAME",
                dimension_numbers=("NHWC", "HWIO", "NHWC"),
            )
            x = jax.nn.relu(x + layer["b"])
            x = lax.reduce_window(
                x, -jnp.inf, lax.max, (1, 2, 2, 1), (1, 2, 2, 1), "VALID"
            )
        x = x.reshape((x.shape[0], -1))
        d1, d2 = params["dense"]
        x = jax.nn.relu(x @ d1["w"] + d1["b"])
        return x @ d2["w"] + d2["b"]

    return SimpleNamespace(init=init, apply=apply, config=config)
