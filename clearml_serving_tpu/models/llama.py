"""Llama-3-family decoder in pure functional JAX (flagship LLM architecture).

TPU-first design notes:
- bf16 params/activations by default (MXU-native), fp32 RMSNorm accumulation;
- GQA (n_kv_heads < n_heads) with head-batched einsums — no per-head Python
  loops, everything a single large matmul per projection so XLA tiles it onto
  the MXU;
- rotary embeddings precomputed per call from positions (static shapes under
  jit; positions are data, not shape);
- decode path takes a dense KV cache laid out [layers, batch, max_len, kv_heads,
  head_dim] so a TP mesh can shard kv_heads over the `tp` axis and the cache
  rides HBM untouched between steps. The paged-KV variant used by the LLM
  engine lives in clearml_serving_tpu/llm/kv_cache.py and reuses these weights.

Replaces the reference's vLLM model executor (CUDA) as the compute path behind
the OpenAI-compatible route surface (reference preprocess_service.py:619-1348).
"""

from __future__ import annotations

import math
from functools import partial
from types import SimpleNamespace
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from . import register_model

# Named configs: full Llama-3-8B plus scaled-down variants for tests/benches.
PRESETS: Dict[str, Dict[str, Any]] = {
    "llama3-8b": dict(
        vocab_size=128256, dim=4096, n_layers=32, n_heads=32, n_kv_heads=8,
        ffn_dim=14336, rope_theta=500000.0, norm_eps=1e-5, max_seq_len=8192,
    ),
    "llama3-1b": dict(  # llama-3.2-1B-shaped
        vocab_size=128256, dim=2048, n_layers=16, n_heads=32, n_kv_heads=8,
        ffn_dim=8192, rope_theta=500000.0, norm_eps=1e-5, max_seq_len=8192,
    ),
    "llama-tiny": dict(  # CI-sized
        vocab_size=512, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
        ffn_dim=128, rope_theta=10000.0, norm_eps=1e-5, max_seq_len=256,
    ),
}


def resolve_config(config: dict) -> dict:
    cfg = dict(PRESETS.get(config.get("preset", ""), {}))
    cfg.update({k: v for k, v in config.items() if k != "preset"})
    cfg.setdefault("dtype", "bfloat16")
    cfg.setdefault("tie_embeddings", False)
    cfg.setdefault("rope_theta", 10000.0)
    cfg.setdefault("norm_eps", 1e-5)
    cfg.setdefault("max_seq_len", 4096)
    return cfg


def _rms_norm(x, weight, eps, offset=0.0):
    # fp32 accumulation regardless of activation dtype. ``offset`` supports
    # the Gemma convention of zero-initialized weights applied as (1 + w).
    x32 = x.astype(jnp.float32)
    norm = x32 * jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + eps)
    return (norm * (offset + weight.astype(jnp.float32))).astype(x.dtype)


def _softcap(x, cap):
    """Gemma-2 logit soft-capping: cap * tanh(x / cap)."""
    return cap * jnp.tanh(x / cap)


def _rope_freqs(head_dim: int, theta: float, rope_scaling: Optional[dict]):
    freqs = 1.0 / (
        theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim)
    )
    if not rope_scaling:
        return freqs
    rope_type = rope_scaling.get("rope_type") or rope_scaling.get("type")
    if rope_type == "linear":
        # position-interpolation (Chen et al.): every frequency shrinks by
        # 1/factor, equivalent to scaling positions down
        return freqs / float(rope_scaling["factor"])
    if rope_type == "longrope":
        # position-dependent; applied in _rope — validate here (fail fast
        # at build instead of inside the first traced forward)
        hd2 = head_dim // 2
        for key in ("short_factor", "long_factor"):
            fac = rope_scaling.get(key)
            if fac is None or len(fac) != hd2:
                raise ValueError(
                    "rope_scaling.{} must list head_dim/2 = {} per-dim "
                    "factors".format(key, hd2)
                )
        if not rope_scaling.get("original_max_position_embeddings"):
            raise ValueError(
                "longrope rope_scaling needs original_max_position_embeddings"
            )
        return freqs
    if rope_type == "yarn":
        # YaRN (Peng et al.): NTK-by-parts — high frequencies extrapolate
        # (unscaled), low frequencies interpolate (1/factor), a linear ramp
        # between wavelength bands derived from beta_fast/beta_slow blends
        # the middle; the attention temperature rides cos/sin in _rope.
        # Mirrors transformers' _compute_yarn_parameters exactly: band
        # indices live in FULL head_dim space (clamped to head_dim-1, not
        # head_dim//2-1), truncate floors/ceils them (default on), missing
        # original_max_position_embeddings falls back to the deployed
        # length (injected by build from max_seq_len).
        factor = float(rope_scaling["factor"])
        orig = float(
            rope_scaling.get("original_max_position_embeddings")
            or rope_scaling.get("max_position_embeddings")
            or 4096
        )
        beta_fast = float(rope_scaling.get("beta_fast") or 32.0)
        beta_slow = float(rope_scaling.get("beta_slow") or 1.0)
        hd2 = head_dim // 2

        def band(beta):
            # dim index whose wavelength covers `beta` periods over orig
            return head_dim * math.log(orig / (beta * 2.0 * math.pi)) / (
                2.0 * math.log(theta)
            )

        low, high = band(beta_fast), band(beta_slow)
        if rope_scaling.get("truncate", True):
            low, high = math.floor(low), math.ceil(high)
        low = max(low, 0)
        high = min(high, head_dim - 1)
        if low == high:
            high += 0.001  # prevent singularity
        ramp = jnp.clip(
            (jnp.arange(hd2, dtype=jnp.float32) - low) / (high - low),
            0.0, 1.0,
        )
        extrap_w = 1.0 - ramp  # 1 = keep unscaled, 0 = fully interpolated
        return (freqs / factor) * (1.0 - extrap_w) + freqs * extrap_w
    if rope_type != "llama3":
        raise ValueError(
            "unsupported rope_scaling type {!r} (supported: llama3, "
            "linear, yarn, longrope)".format(rope_type)
        )
    # Llama-3.1 frequency-dependent scaling: long wavelengths scale by
    # 1/factor, short ones stay, the middle band interpolates smoothly.
    factor = float(rope_scaling["factor"])
    low = float(rope_scaling.get("low_freq_factor", 1.0))
    high = float(rope_scaling.get("high_freq_factor", 4.0))
    orig = float(rope_scaling.get("original_max_position_embeddings", 8192))
    wavelen = 2.0 * jnp.pi / freqs
    low_wavelen = orig / low
    high_wavelen = orig / high
    smooth = (orig / wavelen - low) / (high - low)
    smooth = jnp.clip(smooth, 0.0, 1.0)
    scaled = (1.0 - smooth) * freqs / factor + smooth * freqs
    return jnp.where(
        wavelen > low_wavelen, freqs / factor,
        jnp.where(wavelen < high_wavelen, freqs, scaled),
    )


def _yarn_attention_factor(rope_scaling: dict) -> float:
    """YaRN attention temperature on cos/sin: explicit attention_factor,
    else DeepSeek's mscale pair, else 0.1*ln(factor)+1 (the paper's
    default; HF _compute_yarn_parameters order)."""
    att = rope_scaling.get("attention_factor")
    if att is not None:
        return float(att)
    factor = float(rope_scaling["factor"])

    def get_mscale(scale, m=1.0):
        return 1.0 if scale <= 1.0 else 0.1 * m * math.log(scale) + 1.0

    mscale = rope_scaling.get("mscale")
    mscale_all_dim = rope_scaling.get("mscale_all_dim")
    # HF semantics: the DeepSeek pair applies only when BOTH are truthy
    if mscale and mscale_all_dim:
        return get_mscale(factor, float(mscale)) / get_mscale(
            factor, float(mscale_all_dim)
        )
    return get_mscale(factor)


def _rope(positions: jnp.ndarray, head_dim: int, theta: float,
          rope_scaling: Optional[dict] = None):
    """cos/sin tables for given positions: [..., head_dim//2]."""
    rope_type = (
        (rope_scaling.get("rope_type") or rope_scaling.get("type"))
        if rope_scaling
        else None
    )
    if rope_type == "yarn":
        freqs = _rope_freqs(head_dim, theta, rope_scaling)
        att = _yarn_attention_factor(rope_scaling)
        angles = positions.astype(jnp.float32)[..., None] * freqs
        return jnp.cos(angles) * att, jnp.sin(angles) * att
    if rope_type == "longrope":
        # Phi-3 LongRoPE (vLLM Phi3LongRoPEScaledRotaryEmbedding layout):
        # per-dim rescale factors — SHORT factors for positions inside the
        # original training window, LONG factors beyond it (a per-position
        # selection, so one table serves any mix of contexts) — plus a
        # global attention scale on cos/sin:
        # sqrt(1 + ln(max/orig)/ln(orig)) unless the checkpoint pins one.
        base = _rope_freqs(head_dim, theta, None)
        short = jnp.asarray(rope_scaling["short_factor"], jnp.float32)
        long = jnp.asarray(rope_scaling["long_factor"], jnp.float32)
        orig = float(rope_scaling["original_max_position_embeddings"])
        max_pos = float(
            rope_scaling.get("max_position_embeddings")
            or rope_scaling.get("max_seq_len")
            or orig
        )
        att = rope_scaling.get("attention_factor")
        if att is None:
            # plain-python math: this is a config constant, and _rope runs
            # under jit (jnp here would try to concretize a tracer)
            scale = max(max_pos / orig, 1.0)
            att = (
                1.0
                if scale <= 1.0
                else math.sqrt(1.0 + math.log(scale) / math.log(orig))
            )
        pos = positions.astype(jnp.float32)[..., None]            # [..., 1]
        freqs = jnp.where(pos < orig, base / short, base / long)  # [..., hd/2]
        angles = pos * freqs
        return jnp.cos(angles) * att, jnp.sin(angles) * att
    freqs = _rope_freqs(head_dim, theta, rope_scaling)
    angles = positions.astype(jnp.float32)[..., None] * freqs  # [..., hd/2]
    return jnp.cos(angles), jnp.sin(angles)


def _apply_rope(x: jnp.ndarray, cos: jnp.ndarray, sin: jnp.ndarray):
    """x: [B, S, H, D]; cos/sin: [B, S, D/2] -> broadcast over heads."""
    x1, x2 = jnp.split(x, 2, axis=-1)
    cos = cos[:, :, None, :]
    sin = sin[:, :, None, :]
    return jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1
    ).astype(x.dtype)


@register_model("llama")
def build(config: dict) -> SimpleNamespace:
    cfg = resolve_config(config)
    vocab = int(cfg["vocab_size"])
    dim = int(cfg["dim"])
    n_layers = int(cfg["n_layers"])
    n_heads = int(cfg["n_heads"])
    n_kv = int(cfg["n_kv_heads"])
    ffn_dim = int(cfg["ffn_dim"])
    theta = float(cfg["rope_theta"])
    rope_scaling = cfg.get("rope_scaling") or None
    eps = float(cfg["norm_eps"])
    dtype = jnp.dtype(cfg["dtype"])
    # head_dim may be decoupled from dim (Gemma-2: 16 heads x 256 > dim)
    head_dim = int(cfg.get("head_dim") or dim // n_heads)
    _rt = (
        (rope_scaling.get("rope_type") or rope_scaling.get("type"))
        if rope_scaling
        else None
    )
    if _rt == "longrope":
        # the attention scale needs the DEPLOYED context length; HF keeps it
        # outside the rope_scaling dict, so default it from the model's own
        # max_seq_len rather than silently degrading to scale 1.0
        rope_scaling = dict(rope_scaling)
        rope_scaling.setdefault(
            "max_position_embeddings", int(cfg.get("max_seq_len") or 0) or None
        )
    elif _rt == "yarn":
        # HF falls back to config.max_position_embeddings when the dict
        # omits the original window; a silent 4096 default would shift the
        # correction bands and diverge from the HF tables
        rope_scaling = dict(rope_scaling)
        rope_scaling.setdefault(
            "original_max_position_embeddings",
            int(cfg.get("max_seq_len") or 0) or None,
        )
    _rope_freqs(head_dim, theta, rope_scaling)  # fail fast on bad cfg
    assert n_heads % n_kv == 0, "n_heads must be divisible by n_kv_heads"
    group = n_heads // n_kv

    # Gemma-family deltas over the llama skeleton:
    # - norm_offset: RMSNorm weights stored zero-init, applied as (1 + w)
    # - hidden_act "gelu_tanh": GeGLU instead of SiLU-GLU
    # - embed_scale: embeddings multiplied by sqrt(dim) (converter supplies
    #   the numeric value)
    # - query_scale: attention score scale override (Gemma-2's
    #   query_pre_attn_scalar**-0.5 instead of head_dim**-0.5)
    # - attn/final logit softcap (Gemma-2)
    # - post_block_norms: extra norms on each sublayer OUTPUT before the
    #   residual add (Gemma-2's post_attention/post_feedforward norms)
    # - alt_window: per-layer local/global attention interleave (Gemma-2);
    #   each layer carries an ``attn_global`` scalar selecting its mask
    norm_offset = 1.0 if cfg.get("norm_offset") else 0.0
    hidden_act = str(cfg.get("hidden_act", "silu"))
    if hidden_act == "silu":
        _act = jax.nn.silu
    elif hidden_act in ("gelu_tanh", "gelu_pytorch_tanh"):
        _act = partial(jax.nn.gelu, approximate=True)
    elif hidden_act == "gelu":
        _act = partial(jax.nn.gelu, approximate=False)
    else:
        raise ValueError("unsupported hidden_act {!r}".format(hidden_act))
    embed_scale = float(cfg.get("embed_scale") or 0.0)
    query_scale = float(cfg.get("query_scale") or head_dim ** -0.5)
    attn_softcap = float(cfg.get("attn_logit_softcap") or 0.0)
    final_softcap = float(cfg.get("final_logit_softcap") or 0.0)
    post_block_norms = bool(cfg.get("post_block_norms"))
    alt_window = bool(cfg.get("alt_window"))

    # -- init ---------------------------------------------------------------

    # scan_layers: stack layer params [L, ...] and lax.scan over them — XLA
    # compiles ONE layer instead of n_layers unrolled copies. Essential for
    # deep models: the unrolled 32-layer 8B graph takes many minutes to
    # compile; the scanned one compiles like a 1-layer model.
    scan_layers = bool(cfg.get("scan_layers", False))

    # sparse MoE FFN (Mixtral-style): n_experts stacked expert FFNs behind a
    # top-k router; expert weights shard over the mesh's ``ep`` axis
    n_experts = int(cfg.get("n_experts", 0) or 0)
    moe = n_experts > 1
    moe_top_k = int(cfg.get("moe_top_k", 2))
    moe_capacity = float(cfg.get("moe_capacity_factor", 1.25))

    # family deltas over the llama skeleton:
    # - attn_bias: Qwen2-style additive QKV biases
    # - sliding_window: Mistral-style local attention — key t is visible to
    #   query position p iff p - W < t <= p (0 disables)
    attn_bias = bool(cfg.get("attn_bias", False))
    sliding_window = int(cfg.get("sliding_window", 0) or 0)

    # multi-LoRA serving (models/lora.py): stacked [A+1, in, r]/[A+1, r, out]
    # factors per targeted projection, gathered per batch slot by lora_idx
    # inside the layer body — one executable serves any adapter mix
    lora_rank, lora_targets, max_loras = 0, (), 0
    if cfg.get("lora_rank"):
        from . import lora as lora_lib

        lora_rank, lora_targets, max_loras = lora_lib.lora_spec(cfg)
        if moe and any(t in ("w_gate", "w_up", "w_down") for t in lora_targets):
            raise ValueError(
                "lora FFN targets are unsupported for MoE layers "
                "(expert-stacked weights); use attention targets"
            )

    if alt_window and not sliding_window:
        raise ValueError("alt_window needs a nonzero sliding_window")
    # per-layer global/full-attention flags for the Gemma-2 interleave:
    # default is the Gemma-2 pattern (odd layers global, even local)
    attn_global_layers = cfg.get("attn_global_layers")
    if alt_window and attn_global_layers is None:
        attn_global_layers = [1.0 if (i % 2 == 1) else 0.0 for i in range(n_layers)]
    norm_init = jnp.zeros if norm_offset else jnp.ones

    def _init_layer(key):
        def dense(k, shape, fan_in):
            return (
                jax.random.normal(k, shape, dtype=jnp.float32) * fan_in ** -0.5
            ).astype(dtype)

        k = jax.random.split(key, 8)
        out = {
            "attn_norm": norm_init((dim,), dtype),
            "wq": dense(k[0], (dim, n_heads * head_dim), dim),
            "wk": dense(k[1], (dim, n_kv * head_dim), dim),
            "wv": dense(k[2], (dim, n_kv * head_dim), dim),
            "wo": dense(k[3], (n_heads * head_dim, dim), n_heads * head_dim),
            "ffn_norm": norm_init((dim,), dtype),
        }
        if post_block_norms:
            out.update(
                post_attn_norm=norm_init((dim,), dtype),
                post_ffn_norm=norm_init((dim,), dtype),
            )
        if alt_window:
            out["attn_global"] = jnp.zeros((), jnp.float32)  # set by init()
        if attn_bias:
            out.update(
                bq=jnp.zeros((n_heads * head_dim,), dtype),
                bk=jnp.zeros((n_kv * head_dim,), dtype),
                bv=jnp.zeros((n_kv * head_dim,), dtype),
            )
        if moe:
            out.update(
                w_router=dense(k[7], (dim, n_experts), dim),
                w_gate_e=dense(k[4], (n_experts, dim, ffn_dim), dim),
                w_up_e=dense(k[5], (n_experts, dim, ffn_dim), dim),
                w_down_e=dense(k[6], (n_experts, ffn_dim, dim), ffn_dim),
            )
        else:
            out.update(
                w_gate=dense(k[4], (dim, ffn_dim), dim),
                w_up=dense(k[5], (dim, ffn_dim), dim),
                w_down=dense(k[6], (ffn_dim, dim), ffn_dim),
            )
        if lora_rank:
            from . import lora as lora_lib

            for t in lora_targets:
                d_in, d_out = lora_lib.target_dims(cfg, t)
                out["lora_a_" + t] = jnp.zeros(
                    (max_loras + 1, d_in, lora_rank), dtype
                )
                out["lora_b_" + t] = jnp.zeros(
                    (max_loras + 1, lora_rank, d_out), dtype
                )
        return out

    def init(rng) -> Dict[str, Any]:
        def dense(key, shape, fan_in):
            return (
                jax.random.normal(key, shape, dtype=jnp.float32) * fan_in ** -0.5
            ).astype(dtype)

        keys = jax.random.split(rng, 3)
        params: Dict[str, Any] = {
            "embed": dense(keys[0], (vocab, dim), dim),
            "final_norm": norm_init((dim,), dtype),
        }
        if not cfg["tie_embeddings"]:
            params["lm_head"] = dense(keys[1], (dim, vocab), dim)
        layer_keys = jax.random.split(keys[2], n_layers)
        if scan_layers:
            params["layers"] = jax.vmap(_init_layer)(layer_keys)
            if alt_window:
                params["layers"]["attn_global"] = jnp.asarray(
                    attn_global_layers, jnp.float32
                )
        else:
            params["layers"] = [_init_layer(k) for k in layer_keys]
            if alt_window:
                for i, layer in enumerate(params["layers"]):
                    layer["attn_global"] = jnp.asarray(
                        attn_global_layers[i], jnp.float32
                    )
        return params


    # -- shared layer math ----------------------------------------------------

    def _w(container, name):
        """Weight accessor with inline dequantization: a leaf may be a plain
        array, {"_q8": int8, "_scale": f32}, or {"_q4": packed uint8,
        "_scale4": f32} (ops/quant.py). Because this runs INSIDE the
        (possibly scanned) layer body, XLA dequantizes one layer at a time
        next to its consumer matmul — weights at rest stay quantized in HBM
        even under scan_layers."""
        w = container[name]
        if isinstance(w, dict) and "_q8" in w:
            from ..ops.quant import dequantize

            return dequantize(w["_q8"], w["_scale"], dtype)
        if isinstance(w, dict) and "_q4" in w:
            from ..ops.quant import dequantize_int4

            return dequantize_int4(w["_q4"], w["_scale4"], dtype)
        return w

    # w4a16 serving (docs/w4a16.md): decode-shaped matmuls on int4 leaves
    # route through the Pallas fused dequant-matmul — packed nibbles stream
    # HBM->VMEM and unpack next to the MXU, so the HBM weight read is
    # structurally 4-bit instead of fusion-dependent. cfg int4_fused=False
    # pins the XLA inline-dequant path (the A/B arm bench.py measures
    # against); misaligned shapes, prefill-sized M, and non-TPU backends
    # fall back to that same path inside the wrapper, byte-identically.
    int4_fused = bool(cfg.get("int4_fused", True))

    def _mm(container, name, x):
        """``x @ weight`` with quantization-aware routing. The ONE place a
        plain projection matmul touches its (possibly quantized) weight —
        MoE expert einsums and the tied-embedding lm_head keep the _w
        accessor (different contraction shapes; fallback matrix in
        docs/w4a16.md)."""
        w = container[name]
        if int4_fused and isinstance(w, dict) and "_q4" in w:
            from ..ops.fused_matmul import fused_int4_matmul

            return fused_int4_matmul(x, w["_q4"], w["_scale4"], dtype=dtype)
        return x @ _w(container, name)

    def _visible_w(q_pos, t_pos, window):
        """Causal visibility (key position t, query position q): t <= q,
        windowed to q - W < t when ``window`` is set. The ONE place the
        window semantics live — every attention path builds its mask here."""
        ok = t_pos <= q_pos
        if window:
            ok = ok & (t_pos > q_pos - window)
        return ok

    def _build_masks(build_fn):
        """``build_fn(window) -> mask``. Uniform models get one mask; under
        the Gemma-2 interleave (alt_window) BOTH masks build once per forward
        and each layer selects its own via ``attn_global`` (a scanned scalar,
        so lax.scan keeps one compiled layer body)."""
        if alt_window:
            return (build_fn(0), build_fn(sliding_window))
        return build_fn(sliding_window)

    def _layer_mask(layer, masks):
        if not alt_window:
            return masks
        mask_global, mask_local = masks
        return jnp.where(layer["attn_global"] != 0, mask_global, mask_local)

    def _lora_delta(layer, name, x, lora_idx):
        """Batched per-slot LoRA delta: x [B,S,in] -> [B,S,out]. The gather
        by lora_idx [B] selects each slot's adapter from the [A+1, ...]
        stacks (index 0 = zeros = base model); two rank-r matmuls with f32
        accumulation. Runs inside the (scanned) layer body so the stacks ride
        the same layout machinery as the base weights."""
        a = layer["lora_a_" + name][lora_idx]                  # [B, in, r]
        b = layer["lora_b_" + name][lora_idx]                  # [B, r, out]
        h = jnp.einsum("bsi,bir->bsr", x, a, preferred_element_type=jnp.float32)
        return jnp.einsum(
            "bsr,bro->bso", h, b, preferred_element_type=jnp.float32
        ).astype(x.dtype)

    def _with_lora(layer, name, x, y, lora_idx):
        if lora_idx is None or name not in lora_targets:
            return y
        return y + _lora_delta(layer, name, x, lora_idx)

    def _qkv(layer, x, cos, sin, lora_idx=None):
        b, s, _ = x.shape
        q = _with_lora(layer, "wq", x, _mm(layer, "wq", x), lora_idx)
        k = _with_lora(layer, "wk", x, _mm(layer, "wk", x), lora_idx)
        v = _with_lora(layer, "wv", x, _mm(layer, "wv", x), lora_idx)
        if attn_bias:  # Qwen2-style QKV biases (kept full precision)
            q = q + layer["bq"]
            k = k + layer["bk"]
            v = v + layer["bv"]
        q = q.reshape(b, s, n_heads, head_dim)
        k = k.reshape(b, s, n_kv, head_dim)
        v = v.reshape(b, s, n_kv, head_dim)
        return _apply_rope(q, cos, sin), _apply_rope(k, cos, sin), v

    def _oproj(layer, attn, lora_idx=None):
        return _with_lora(layer, "wo", attn, _mm(layer, "wo", attn), lora_idx)

    def _attend(q, k, v, mask):
        """q: [B,S,Hq,D]; k,v: [B,T,Hkv,D]; mask: [B,1,S,T] additive."""
        b, s, _, _ = q.shape
        t = k.shape[1]
        # Group query heads over their shared KV head: [B,S,Hkv,G,D].
        qg = q.reshape(b, s, n_kv, group, head_dim)
        scores = jnp.einsum(
            "bskgd,btkd->bkgst", qg, k, preferred_element_type=jnp.float32
        ) * query_scale
        if attn_softcap:
            scores = _softcap(scores, attn_softcap)  # before the mask (HF)
        scores = scores + mask[:, :, None, :, :]  # mask broadcast over groups
        probs = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
        out = jnp.einsum("bkgst,btkd->bskgd", probs, v)
        return out.reshape(b, s, n_heads * head_dim)

    def _ffn_dense(layer, x, lora_idx=None):
        gate = _with_lora(layer, "w_gate", x, _mm(layer, "w_gate", x), lora_idx)
        up = _with_lora(layer, "w_up", x, _mm(layer, "w_up", x), lora_idx)
        h = _act(gate) * up
        return _with_lora(layer, "w_down", h, _mm(layer, "w_down", h), lora_idx)

    def _moe_routing(layer, tokens):
        router_logits = (
            tokens.astype(jnp.float32) @ _w(layer, "w_router").astype(jnp.float32)
        )                                                         # [T, E]
        probs = jax.nn.softmax(router_logits, axis=-1)
        top_p, top_e = jax.lax.top_k(probs, moe_top_k)            # [T, k]
        # mixtral renormalizes the chosen experts' probabilities
        top_p = top_p / jnp.sum(top_p, axis=-1, keepdims=True)
        return top_p, top_e

    def _ffn_moe(layer, x, valid=None):
        """Mixtral-style sparse MoE FFN, GShard dispatch (TPU-first: the
        token->expert routing is expressed as one-hot einsums over a fixed
        capacity, so everything is static-shape batched matmuls — expert
        weights stack [E, ...] and shard over the mesh's ``ep`` axis, with
        XLA inserting the all-to-alls).

        ``valid`` [B, S] (bool) excludes right-padding from routing —
        without it one sequence's pad tokens would consume expert capacity
        and evict another sequence's REAL tokens. Exact w.r.t. top-k routing
        EXCEPT under overflow of valid tokens (capacity_factor * tokens * k
        / E per expert, standard GShard drop).
        """
        b, s, d_ = x.shape
        tokens = x.reshape(b * s, d_)
        n_tok = b * s
        top_p, top_e = _moe_routing(layer, tokens)

        capacity = max(1, int(moe_capacity * n_tok * moe_top_k / n_experts))
        # position of each (token, slot) within its expert's capacity buffer
        onehot = jax.nn.one_hot(top_e, n_experts, dtype=jnp.int32)  # [T,k,E]
        if valid is not None:
            onehot = onehot * valid.reshape(n_tok, 1, 1).astype(jnp.int32)
        # rank tokens per expert by arrival order across (slot-major) choices
        flat = onehot.reshape(n_tok * moe_top_k, n_experts)
        pos_in_expert = (jnp.cumsum(flat, axis=0) - 1).reshape(
            n_tok, moe_top_k, n_experts
        )
        within = (pos_in_expert < capacity) & (onehot > 0)
        # dispatch tensor [T, E, C]: one-hot of each kept (token, expert, pos)
        pos_oh = jax.nn.one_hot(
            jnp.where(within, pos_in_expert, capacity), capacity, dtype=x.dtype
        )                                                         # [T,k,E,C]
        dispatch = jnp.einsum("tke,tkec->tec", onehot.astype(x.dtype), pos_oh)
        combine = jnp.einsum(
            "tke,tkec->tec",
            (top_p.astype(jnp.float32)[:, :, None] * onehot).astype(x.dtype),
            pos_oh,
        )
        expert_in = jnp.einsum("tec,td->ecd", dispatch, tokens)   # [E,C,D]
        h = jax.nn.silu(
            jnp.einsum("ecd,edf->ecf", expert_in, _w(layer, "w_gate_e"))
        ) * jnp.einsum("ecd,edf->ecf", expert_in, _w(layer, "w_up_e"))
        expert_out = jnp.einsum("ecf,efd->ecd", h, _w(layer, "w_down_e"))
        out = jnp.einsum("tec,ecd->td", combine, expert_out)      # [T, D]
        return out.reshape(b, s, d_).astype(x.dtype)

    def _ffn_moe_dropless(layer, x):
        """Dropless MoE for decode: every token computes ALL experts and
        combines the top-k — no capacity, no cross-token interaction, so an
        inactive slot can never evict an active one and quality never
        depends on batch occupancy (inference references like vLLM apply no
        capacity either). E× FFN FLOPs on a [B, 1, D] decode step is cheap;
        the GShard dispatch path stays for prefill's long sequences."""
        b, s, d_ = x.shape
        tokens = x.reshape(b * s, d_)
        top_p, top_e = _moe_routing(layer, tokens)
        weights = jnp.zeros((b * s, n_experts), jnp.float32).at[
            jnp.arange(b * s)[:, None], top_e
        ].add(top_p)
        h = jax.nn.silu(
            jnp.einsum("td,edf->etf", tokens, _w(layer, "w_gate_e"))
        ) * jnp.einsum("td,edf->etf", tokens, _w(layer, "w_up_e"))
        expert_out = jnp.einsum("etf,efd->etd", h, _w(layer, "w_down_e"))
        out = jnp.einsum("te,etd->td", weights.astype(x.dtype), expert_out)
        return out.reshape(b, s, d_).astype(x.dtype)

    def _ffn(layer, x, valid=None, dropless=False, lora_idx=None):
        if moe:
            # decode and speculative verification must be dropless: capacity
            # dropping makes logits depend on batch occupancy, which would
            # break greedy-exactness (verify's argmax must equal decode's)
            if dropless or x.shape[1] == 1:
                return _ffn_moe_dropless(layer, x)
            return _ffn_moe(layer, x, valid)
        return _ffn_dense(layer, x, lora_idx)

    def _logits(params, x):
        x = _rms_norm(x, params["final_norm"], eps, norm_offset)
        if "lm_head" in params:
            out = _mm(params, "lm_head", x).astype(jnp.float32)
        else:
            out = (x @ params["embed"].T).astype(jnp.float32)
        if final_softcap:
            out = _softcap(out, final_softcap)
        return out

    def _embed(params, tokens):
        x = params["embed"][tokens]
        if embed_scale:
            # Gemma normalizer: applied in the ACTIVATION dtype like HF
            # (sqrt(dim) cast to bf16/f32 before the multiply)
            x = x * jnp.asarray(embed_scale, x.dtype)
        return x

    def _block(layer, x, attn_fn, lora_idx, ffn_kwargs=None):
        """One decoder block around pluggable attention: pre-norm ->
        attention -> (post-norm) -> residual -> pre-norm -> FFN ->
        (post-norm) -> residual. The ONE place the residual structure
        lives — every forward path (full, prefill, chunk, decode) runs
        through it, so family deltas (Gemma-2 post-block norms, norm
        offsets) apply everywhere by construction."""
        h = _rms_norm(x, layer["attn_norm"], eps, norm_offset)
        attn_out = _oproj(layer, attn_fn(layer, h), lora_idx)
        if post_block_norms:
            attn_out = _rms_norm(attn_out, layer["post_attn_norm"], eps, norm_offset)
        x = x + attn_out
        h = _rms_norm(x, layer["ffn_norm"], eps, norm_offset)
        ffn_out = _ffn(layer, h, lora_idx=lora_idx, **(ffn_kwargs or {}))
        if post_block_norms:
            ffn_out = _rms_norm(ffn_out, layer["post_ffn_norm"], eps, norm_offset)
        return x + ffn_out

    # -- full causal forward (training / no-cache prefill) -------------------

    def apply(params, tokens: jnp.ndarray, positions: Optional[jnp.ndarray] = None,
              lora_idx: Optional[jnp.ndarray] = None):
        """tokens: [B, S] int32 -> logits [B, S, vocab] (causal)."""
        b, s = tokens.shape
        if positions is None:
            positions = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32), (b, s))
        cos, sin = _rope(positions, head_dim, theta, rope_scaling)
        idx = jnp.arange(s)
        masks = _build_masks(
            lambda w: jnp.broadcast_to(
                jnp.where(
                    _visible_w(idx[:, None], idx[None, :], w), 0.0, -jnp.inf
                ).astype(jnp.float32)[None, None],
                (b, 1, s, s),
            )
        )
        x = _embed(params, tokens)

        def layer_body(x, layer):
            def attn(layer_, h):
                q, k, v = _qkv(layer_, h, cos, sin, lora_idx)
                return _attend(q, k, v, _layer_mask(layer_, masks))

            return _block(layer, x, attn, lora_idx)

        if scan_layers:
            x, _ = jax.lax.scan(
                lambda x, layer: (layer_body(x, layer), None), x, params["layers"]
            )
        else:
            for layer in params["layers"]:
                x = layer_body(x, layer)
        return _logits(params, x)

    # -- dense KV cache serving path -----------------------------------------

    # int8 KV cache (cfg kv_quant="int8"): K/V store as int8 with a per
    # (token, head) f32 scale — cache HBM roughly halves, which is what buys
    # the larger decode batches on a 16 GB chip (weights int8 + bf16 KV at
    # b=32/s=1024 for an 8B model would not fit). Dequant happens next to the
    # attention matmul (XLA fuses it into the HBM read).
    kv_quant = str(cfg.get("kv_quant") or "")
    if kv_quant not in ("", "int8"):
        raise ValueError("kv_quant must be 'int8' (got {!r})".format(kv_quant))

    def _kv_store(x):
        """bf16 [..., D] -> (stored, scale|None): per-vector symmetric int8."""
        if not kv_quant:
            return x.astype(dtype), None
        x32 = x.astype(jnp.float32)
        absmax = jnp.max(jnp.abs(x32), axis=-1)
        scale = jnp.where(absmax > 0, absmax / 127.0, 1.0)
        q = jnp.clip(
            jnp.round(x32 / scale[..., None]), -127, 127
        ).astype(jnp.int8)
        return q, scale.astype(jnp.float32)

    def _kv_load(stored, scale):
        if scale is None:
            return stored
        return (stored.astype(jnp.float32) * scale[..., None]).astype(dtype)

    def init_cache(batch: int, max_len: int) -> Dict[str, jnp.ndarray]:
        shape = (n_layers, batch, max_len, n_kv, head_dim)
        out = {
            "k": jnp.zeros(shape, jnp.int8 if kv_quant else dtype),
            "v": jnp.zeros(shape, jnp.int8 if kv_quant else dtype),
            "length": jnp.zeros((batch,), jnp.int32),
        }
        if kv_quant:
            out["k_scale"] = jnp.zeros(shape[:-1], jnp.float32)
            out["v_scale"] = jnp.zeros(shape[:-1], jnp.float32)
        return out

    def _prefill_impl(params, tokens, seq_lens, cache, attend_fn, lora_idx=None):
        """Shared prefill body: embed -> layers (attend_fn pluggable) ->
        last-token logits + freshly written cache. Only the LAST position's
        hidden state is projected to vocab — materializing [B, S, vocab] to
        keep one row would make throwaway logits the memory ceiling exactly
        on the long-S ring path."""
        b, s = tokens.shape
        positions = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32), (b, s))
        cos, sin = _rope(positions, head_dim, theta, rope_scaling)
        ffn_valid = positions < seq_lens[:, None]  # pads never route (MoE)
        x = _embed(params, tokens)

        def layer_body(x, layer):
            stash = []

            def attn(layer_, h):
                q, k, v = _qkv(layer_, h, cos, sin, lora_idx)
                stash.append((k, v))
                return attend_fn(layer_, q, k, v)

            x = _block(layer, x, attn, lora_idx, ffn_kwargs={"valid": ffn_valid})
            return x, stash[0]

        if scan_layers:
            x, (k_stack, v_stack) = jax.lax.scan(layer_body, x, params["layers"])
        else:
            new_k, new_v = [], []
            for layer in params["layers"]:
                x, (k, v) = layer_body(x, layer)
                new_k.append(k)
                new_v.append(v)
            k_stack = jnp.stack(new_k)                             # [L,B,S,Hkv,D]
            v_stack = jnp.stack(new_v)
        last_x = jnp.take_along_axis(
            x, (seq_lens - 1)[:, None, None].clip(0), axis=1
        )                                                          # [B, 1, D]
        last = _logits(params, last_x)[:, 0]                       # [B, vocab]
        max_len = cache["k"].shape[2]
        pad = max_len - s
        pad5 = ((0, 0), (0, 0), (0, pad), (0, 0), (0, 0))
        k_q, k_s = _kv_store(k_stack)
        v_q, v_s = _kv_store(v_stack)
        cache = {
            "k": jnp.pad(k_q, pad5),
            "v": jnp.pad(v_q, pad5),
            "length": seq_lens.astype(jnp.int32),
        }
        if kv_quant:
            cache["k_scale"] = jnp.pad(k_s, pad5[:-1])
            cache["v_scale"] = jnp.pad(v_s, pad5[:-1])
        return last, cache

    def prefill(params, tokens: jnp.ndarray, seq_lens: jnp.ndarray, cache,
                lora_idx: Optional[jnp.ndarray] = None):
        """Right-padded tokens [B, S]; seq_lens [B]. Writes the cache and
        returns (last-token logits [B, vocab], cache)."""
        b, s = tokens.shape
        positions = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32), (b, s))
        valid = positions < seq_lens[:, None]                      # [B, S]
        idx = jnp.arange(s)

        def build(w):
            causal = _visible_w(idx[:, None], idx[None, :], w)
            mask_b = causal[None] & valid[:, None, :]              # [B, S, T]
            return jnp.where(mask_b, 0.0, -jnp.inf).astype(jnp.float32)[:, None]

        masks = _build_masks(build)

        def attend(layer, q, k, v):
            return _attend(q, k, v, _layer_mask(layer, masks))

        return _prefill_impl(params, tokens, seq_lens, cache, attend, lora_idx)

    def _cached_chunk_layers(params, tokens, start, cache, ffn_kwargs,
                             lora_idx=None):
        """Shared layer loop for multi-token cached processing (chunked
        prefill AND speculative verification): embed ``tokens`` [B, C] at
        absolute positions ``start``..``start+C``, write their K/V into the
        cache at those positions (per-sequence dynamic_update_slice), attend
        causally over the whole sequence (cache beyond the chunk end is
        stale -> masked), and return (x [B,C,D], {"k","v"[,scales]})."""
        b, c = tokens.shape
        max_len = cache["k"].shape[2]
        positions = start[:, None] + jnp.arange(c, dtype=jnp.int32)[None]  # [B, C]
        cos, sin = _rope(positions, head_dim, theta, rope_scaling)
        x = _embed(params, tokens)
        t_idx = jnp.arange(max_len, dtype=jnp.int32)
        masks = _build_masks(
            lambda w: jnp.where(
                _visible_w(positions[:, :, None], t_idx[None, None, :], w),
                0.0,
                -jnp.inf,
            ).astype(jnp.float32)[:, None]                         # [B,1,C,T]
        )

        def _write_chunk(buf, values, width):
            """Per-sequence dynamic_update_slice of a [B, C, ...] chunk into
            a [B, T, ...] buffer at each row's start position."""
            zeros = (0,) * width
            return jax.vmap(
                lambda b_, v_, p: jax.lax.dynamic_update_slice(
                    b_, v_, (p,) + zeros
                )
            )(buf, values.astype(buf.dtype), start)

        def layer_body(carry, layer_and_kv):
            x = carry
            if kv_quant:
                layer, k_cache, v_cache, k_sc, v_sc = layer_and_kv
            else:
                layer, k_cache, v_cache = layer_and_kv
                k_sc = v_sc = None
            stash = []

            def attn(layer_, h):
                q, k, v = _qkv(layer_, h, cos, sin, lora_idx)
                k_q, k_s = _kv_store(k)
                v_q, v_s = _kv_store(v)
                k_c = _write_chunk(k_cache, k_q, 2)
                v_c = _write_chunk(v_cache, v_q, 2)
                if kv_quant:
                    k_s_c = _write_chunk(k_sc, k_s, 1)
                    v_s_c = _write_chunk(v_sc, v_s, 1)
                    stash.append((k_c, v_c, k_s_c, v_s_c))
                    k_full = _kv_load(k_c, k_s_c)
                    v_full = _kv_load(v_c, v_s_c)
                else:
                    stash.append((k_c, v_c))
                    k_full, v_full = k_c, v_c
                return _attend(q, k_full, v_full, _layer_mask(layer_, masks))

            x = _block(layer, x, attn, lora_idx, ffn_kwargs=ffn_kwargs)
            return x, stash[0]

        if kv_quant:
            xs = (params["layers"], cache["k"], cache["v"],
                  cache["k_scale"], cache["v_scale"])
        else:
            xs = (params["layers"], cache["k"], cache["v"])
        if scan_layers:
            x, new_bufs = jax.lax.scan(lambda x, t: layer_body(x, t), x, xs)
        else:
            per_layer = []
            for i, layer in enumerate(params["layers"]):
                tup = tuple(a[i] for a in xs[1:])
                x, bufs = layer_body(x, (layer,) + tup)
                per_layer.append(bufs)
            new_bufs = tuple(
                jnp.stack([bufs[j] for bufs in per_layer])
                for j in range(len(per_layer[0]))
            )
        out = {"k": new_bufs[0], "v": new_bufs[1]}
        if kv_quant:
            out["k_scale"] = new_bufs[2]
            out["v_scale"] = new_bufs[3]
        return x, out

    def prefill_chunk(params, tokens: jnp.ndarray, start: jnp.ndarray,
                      last_rel: jnp.ndarray, cache, *, with_logits: bool = True,
                      lora_idx: Optional[jnp.ndarray] = None):
        """Incremental (chunked) prefill: process ``tokens`` [B, C] at
        absolute positions ``start``..``start+C``, attending over everything
        already in ``cache`` plus the chunk itself (causal). Returns logits
        at relative index ``last_rel`` (the prompt's final real token in the
        — possibly right-padded — last chunk; [B, vocab]) and the extended
        cache. Pad positions write masked-out K/V exactly like plain
        prefill's bucket padding.

        Bounding each prefill dispatch to C tokens lets decode chunks
        interleave on the device stream between prompt segments — a full-
        prompt prefill would occupy the queue for the whole prompt (the
        chunked-prefill TTFT/TPOT smoothing from the serving literature).
        """
        b, c = tokens.shape
        ffn_valid = (
            jnp.arange(c, dtype=jnp.int32)[None] <= last_rel[:, None]
        )  # pad tail of the final chunk never routes (MoE)
        x, new_kv = _cached_chunk_layers(
            params, tokens, start, cache, ffn_kwargs={"valid": ffn_valid},
            lora_idx=lora_idx,
        )
        if with_logits:
            last_x = jnp.take_along_axis(
                x, last_rel[:, None, None].clip(0, c - 1), axis=1
            )                                                              # [B,1,D]
            last = _logits(params, last_x)[:, 0]                           # [B, vocab]
        else:
            # non-final chunks: skip final-norm + lm_head — for an 8B model
            # that matmul reads the whole vocab projection from HBM just to
            # be discarded
            last = jnp.zeros((b, 1), jnp.float32)
        cache = dict(
            new_kv,
            length=jnp.maximum(
                cache["length"], start + last_rel + 1
            ).astype(jnp.int32),
        )
        return last, cache

    def prefill_pipeline(params, tokens: jnp.ndarray, seq_lens: jnp.ndarray,
                         cache, *, stages: int, chunk: int):
        """Pipeline-parallel chunked prefill over the mesh's ``pp`` axis.

        TRUE pipeline parallelism (a GPipe-style inference schedule), not
        just weight-stack sharding: the scan-stacked layers reshape to
        [stages, L/stages] slabs (the pp-sharded layer axis splits
        contiguously, so each pp device group holds exactly one slab), the
        prompt splits into sequence chunks (the microbatches), and chunks
        flow through stages — at tick t stage s processes chunk t-s, so
        after the S-tick fill every pp group computes concurrently instead
        of idling while other groups' layers run. Activations hop stages
        through a shifted [stages, ...] buffer; XLA lowers the shift across
        the pp-sharded axis to a collective-permute on ICI. Causality makes
        sequence chunks valid microbatches: chunk c attends over its
        stage's cache slab holding chunks 0..c, which necessarily passed
        through that stage on earlier ticks.

        Scope (callers fall back to prefill_chunk): scan_layers stacked
        weights, dense KV (no kv_quant), dense FFN (no MoE), no LoRA.
        Reference parity: vLLM serves pipeline-parallel over NCCL P2P
        (--pipeline-parallel-size); this is the GSPMD equivalent.
        """
        if not scan_layers:
            raise ValueError("prefill_pipeline requires scan_layers")
        if kv_quant:
            raise ValueError("prefill_pipeline does not support kv_quant")
        if n_experts:
            raise ValueError("prefill_pipeline does not support MoE")
        if n_layers % stages:
            raise ValueError(
                "stages {} must divide n_layers {}".format(stages, n_layers)
            )
        b, s = tokens.shape
        if s % chunk:
            raise ValueError("padded length {} not a multiple of chunk {}".format(s, chunk))
        m = s // chunk
        lps = n_layers // stages
        layers_st = jax.tree.map(
            lambda a: a.reshape((stages, lps) + a.shape[1:]), params["layers"]
        )
        max_len = cache["k"].shape[2]
        kc = cache["k"].reshape(stages, lps, b, max_len, n_kv, head_dim)
        vc = cache["v"].reshape(stages, lps, b, max_len, n_kv, head_dim)
        emb_all = _embed(params, tokens)                        # [b, s, d]
        dim_model = emb_all.shape[-1]
        x_buf = jnp.zeros((stages, b, chunk, dim_model), emb_all.dtype)
        out = jnp.zeros((b, s, dim_model), emb_all.dtype)
        t_idx = jnp.arange(max_len, dtype=jnp.int32)

        def stage_apply(w_slab, x, kc_s, vc_s, c_idx):
            """One stage's layers over one chunk. c_idx: which chunk this
            stage holds this tick (may be out of range — the caller masks
            the cache commit, so clamped garbage writes are discarded)."""
            start = jnp.clip(c_idx, 0, m - 1) * chunk            # scalar
            rel = jnp.arange(chunk, dtype=jnp.int32)
            positions = jnp.broadcast_to(start + rel, (b, chunk))
            cos, sin = _rope(positions, head_dim, theta, rope_scaling)
            masks = _build_masks(
                lambda w: jnp.where(
                    _visible_w(positions[:, :, None], t_idx[None, None, :], w)
                    & (t_idx[None, None, :] < seq_lens[:, None, None]),
                    0.0,
                    -jnp.inf,
                ).astype(jnp.float32)[:, None]                   # [b,1,C,T]
            )

            def layer_body(x, wkv):
                w_l, k_l, v_l = wkv
                stash = []

                def attn(layer_, h):
                    q, k, v = _qkv(layer_, h, cos, sin, None)
                    k_c = jax.lax.dynamic_update_slice(
                        k_l, k.astype(k_l.dtype), (0, start, 0, 0)
                    )
                    v_c = jax.lax.dynamic_update_slice(
                        v_l, v.astype(v_l.dtype), (0, start, 0, 0)
                    )
                    stash.append((k_c, v_c))
                    return _attend(q, k_c, v_c, _layer_mask(layer_, masks))

                x = _block(w_l, x, attn, None)
                return x, stash[0]

            x, (kc_new, vc_new) = jax.lax.scan(
                layer_body, x, (w_slab, kc_s, vc_s)
            )
            return x, kc_new, vc_new

        def tick(t, carry):
            x_buf, kc, vc, out = carry
            inj = jax.lax.dynamic_slice(
                emb_all,
                (0, jnp.clip(t, 0, m - 1) * chunk, 0),
                (b, chunk, dim_model),
            )
            # stage hop expressed as roll+set rather than concat of slices:
            # concatenate along the pp-SHARDED stage axis has been observed
            # to miscompile on XLA:CPU (wrong values, not just reordering) —
            # roll lowers to a clean collective-permute on every backend
            x_in = jnp.roll(x_buf, 1, axis=0).at[0].set(inj)
            cs = t - jnp.arange(stages, dtype=jnp.int32)         # [stages]
            x_out, kc_new, vc_new = jax.vmap(stage_apply)(
                layers_st, x_in, kc, vc, cs
            )
            valid = (cs >= 0) & (cs < m)
            sel = valid[:, None, None, None, None, None]
            kc = jnp.where(sel, kc_new, kc)
            vc = jnp.where(sel, vc_new, vc)
            # drain: the LAST stage just finished chunk t-(stages-1)
            c_last = t - (stages - 1)
            drained = jax.lax.dynamic_update_slice(
                out,
                x_out[-1].astype(out.dtype),
                (0, jnp.clip(c_last, 0, m - 1) * chunk, 0),
            )
            out = jnp.where((c_last >= 0) & (c_last < m), drained, out)
            return x_out, kc, vc, out

        x_buf, kc, vc, out = jax.lax.fori_loop(
            0, m + stages - 1, lambda t, c: tick(t, c),
            (x_buf, kc, vc, out),
        )
        last_x = jnp.take_along_axis(
            out, (seq_lens - 1)[:, None, None].clip(0, s - 1), axis=1
        )                                                        # [b,1,d]
        last = _logits(params, last_x)[:, 0]
        new_cache = {
            "k": kc.reshape(n_layers, b, max_len, n_kv, head_dim),
            "v": vc.reshape(n_layers, b, max_len, n_kv, head_dim),
            "length": jnp.maximum(cache["length"], seq_lens).astype(jnp.int32),
        }
        return last, new_cache

    def verify(params, tokens: jnp.ndarray, cache,
               lora_idx: Optional[jnp.ndarray] = None):
        """Speculative verification: process ``tokens`` [B, S] (the pending
        token followed by S-1 draft tokens) at absolute positions
        ``length``..``length+S-1``, attending causally over the cache plus
        the chunk itself, and return logits at ALL S positions
        ([B, S, vocab]) plus the cache with the chunk's K/V written.

        ``length`` is deliberately NOT advanced: the caller accepts some
        prefix of the drafts (argmax match) and sets the new length itself —
        K/V written past the accepted point sit beyond ``length``, are
        masked by every later attention, and get overwritten by subsequent
        writes at the same positions. One weight read serves S positions,
        which is the entire speculative-decoding win on an HBM-bound decode
        (and amortizes the ~90 ms tunnel dispatch the same way the fused
        decode scan does).

        MoE routes DROPLESS here (like decode, unlike batched prefill):
        capacity dropping would make verify's argmax depend on batch
        occupancy and break the token-identical-to-plain-greedy guarantee.
        """
        start = cache["length"]                                    # [B]
        x, new_kv = _cached_chunk_layers(
            params, tokens, start, cache, ffn_kwargs={"dropless": True},
            lora_idx=lora_idx,
        )
        logits = _logits(params, x)                                # [B, S, vocab]
        return logits, dict(new_kv, length=start)

    def prefill_ring(params, tokens: jnp.ndarray, seq_lens: jnp.ndarray, cache,
                     mesh, lora_idx: Optional[jnp.ndarray] = None):
        """Sequence-parallel long-prompt prefill: exact ring attention over
        the mesh's ``sp`` axis (parallel/ring_attention.py shard_map +
        ppermute), so a single prompt's attention spreads across chips and
        context length is bounded by the SLICE's HBM, not one chip's.

        Same contract as :func:`prefill` (right-padded [B, S] tokens, S must
        divide the sp axis). Causal masking inside the ring keeps valid
        tokens from attending right-padding; padded positions' K/V land in
        the cache but sit beyond ``length`` and are masked by decode."""
        from ..parallel.ring_attention import ring_attention

        b, s = tokens.shape

        def attend_sp(layer, q, k, v):
            # GQA: repeat KV heads to query heads for the ring (activation
            # cost only; weights untouched)
            kf = jnp.repeat(k, group, axis=2)
            vf = jnp.repeat(v, group, axis=2)
            out = ring_attention(q, kf, vf, mesh, axis_name="sp", causal=True)
            return out.reshape(b, s, n_heads * head_dim).astype(q.dtype)

        return _prefill_impl(params, tokens, seq_lens, cache, attend_sp, lora_idx)

    def decode(params, tokens: jnp.ndarray, cache,
               lora_idx: Optional[jnp.ndarray] = None):
        """One decode step. tokens: [B] int32. Returns (logits [B, vocab], cache)."""
        b = tokens.shape[0]
        positions = cache["length"][:, None]                       # [B, 1]
        cos, sin = _rope(positions, head_dim, theta, rope_scaling)
        max_len = cache["k"].shape[2]
        t_idx = jnp.arange(max_len, dtype=jnp.int32)[None]         # [1, T]
        masks = _build_masks(
            lambda w: jnp.where(
                _visible_w(cache["length"][:, None], t_idx, w), 0.0, -jnp.inf
            ).astype(jnp.float32)[:, None, None]
        )
        x = _embed(params, tokens)[:, None]                        # [B, 1, dim]
        # Per-sequence scatter at each sequence's own length (overwrite, so
        # stale values from a recycled batch slot cannot leak through).
        write = (t_idx == cache["length"][:, None])[:, :, None, None]  # [B,T,1,1]
        write_s = write[..., 0]                                    # [B,T,1]

        def layer_body(x, xs):
            if kv_quant:
                layer, k_cache_l, v_cache_l, k_sc_l, v_sc_l = xs
            else:
                layer, k_cache_l, v_cache_l = xs
                k_sc_l = v_sc_l = None
            stash = []

            def attn(layer_, h):
                q, k, v = _qkv(layer_, h, cos, sin, lora_idx)      # k,v: [B,1,Hkv,D]
                # cast/quantize to the cache storage: params may be a
                # different precision than the cache
                k_q, k_s = _kv_store(k)
                v_q, v_s = _kv_store(v)
                k_cache = jnp.where(write, k_q.astype(k_cache_l.dtype), k_cache_l)
                v_cache = jnp.where(write, v_q.astype(v_cache_l.dtype), v_cache_l)
                if kv_quant:
                    k_sc = jnp.where(write_s, k_s, k_sc_l)
                    v_sc = jnp.where(write_s, v_s, v_sc_l)
                    stash.append((k_cache, v_cache, k_sc, v_sc))
                    k_full = _kv_load(k_cache, k_sc)
                    v_full = _kv_load(v_cache, v_sc)
                else:
                    stash.append((k_cache, v_cache))
                    k_full, v_full = k_cache, v_cache
                return _attend(q, k_full, v_full, _layer_mask(layer_, masks))

            x = _block(layer, x, attn, lora_idx)
            return x, stash[0]

        if kv_quant:
            xs_all = (params["layers"], cache["k"], cache["v"],
                      cache["k_scale"], cache["v_scale"])
        else:
            xs_all = (params["layers"], cache["k"], cache["v"])
        if scan_layers:
            x, new_bufs = jax.lax.scan(layer_body, x, xs_all)
        else:
            per_layer = []
            for li, layer in enumerate(params["layers"]):
                tup = tuple(a[li] for a in xs_all[1:])
                x, bufs = layer_body(x, (layer,) + tup)
                per_layer.append(bufs)
            new_bufs = tuple(
                jnp.stack([bufs[j] for bufs in per_layer])
                for j in range(len(per_layer[0]))
            )
        logits = _logits(params, x)[:, 0]
        cache = {
            "k": new_bufs[0],
            "v": new_bufs[1],
            "length": cache["length"] + 1,
        }
        if kv_quant:
            cache["k_scale"] = new_bufs[2]
            cache["v_scale"] = new_bufs[3]
        return logits, cache

    # -- paged KV serving path (pools from llm/kv_cache.PagedKVCache) --------

    def decode_paged(
        params,
        tokens,        # [B] int32
        k_pools,       # [L, Hkv, N, P, D] (int8 under kv_quant)
        v_pools,       # [L, Hkv, N, P, D]
        page_table,    # [B, PP] int32
        lengths,       # [B] int32 tokens present BEFORE this step
        write_page,    # [B] int32 page id for the new token
        write_offset,  # [B] int32 offset within that page
        lora_idx=None,  # [B] int32 adapter index per slot (None = base)
        *,
        k_scales=None,  # [L, Hkv, N, P] f32 scale pools (kv_quant only)
        v_scales=None,
    ):
        """One decode step over paged KV: writes the new token's K/V into the
        pools (scatter by (page, offset)), then attends via
        ops.paged_attention. Returns (logits [B, vocab], k_pools, v_pools) —
        plus the updated scale pools when ``kv_quant`` is on: the new
        token's K/V quantize through the dense path's _kv_store and the
        per-(token, head) scales scatter beside the int8 pages; dequant
        happens inside the attention kernel."""
        from ..ops.paged_attention import paged_attention

        if kv_quant and k_scales is None:
            raise ValueError("kv_quant decode_paged needs k_scales/v_scales")
        b = tokens.shape[0]
        positions = lengths[:, None]                               # [B, 1]
        cos, sin = _rope(positions, head_dim, theta, rope_scaling)
        x = _embed(params, tokens)[:, None]                        # [B, 1, dim]
        # the Pallas kernel scales scores by head_dim**-0.5 internally; a
        # family query_scale override folds into q before the kernel
        q_prescale = query_scale * (head_dim ** 0.5)

        def layer_body(x, layer, k_pool_l, v_pool_l, k_sc_l, v_sc_l):
            """One layer on its own pool slice [Hkv, N, P, D] (+ [Hkv, N, P]
            scale slices under kv_quant); returns the updated slices
            (scatter of the new token's K/V and scales)."""
            stash = []

            def attn_fn(layer_, h):
                q, k, v = _qkv(layer_, h, cos, sin, lora_idx)      # q [B,1,H,D]
                k_q, k_s = _kv_store(k)                            # [B,1,Hkv(,D)]
                v_q, v_s = _kv_store(v)
                # index tuple (:, wp, wo): the advanced indices are
                # CONTIGUOUS, so the broadcast dim [B] lands after the sliced
                # head dim -> set() takes [Hkv, B, D].
                k_hm = k_q[:, 0].transpose(1, 0, 2).astype(k_pool_l.dtype)
                v_hm = v_q[:, 0].transpose(1, 0, 2).astype(v_pool_l.dtype)
                k_p = k_pool_l.at[:, write_page, write_offset].set(k_hm)
                v_p = v_pool_l.at[:, write_page, write_offset].set(v_hm)
                scale_kw = {}
                if kv_quant:
                    # scale rows scatter at the same (page, offset) the int8
                    # values took — one lifecycle per page id
                    k_sp = k_sc_l.at[:, write_page, write_offset].set(
                        k_s[:, 0].transpose(1, 0)
                    )
                    v_sp = v_sc_l.at[:, write_page, write_offset].set(
                        v_s[:, 0].transpose(1, 0)
                    )
                    stash.append((k_p, v_p, k_sp, v_sp))
                    scale_kw = {"k_scale": k_sp, "v_scale": v_sp}
                else:
                    stash.append((k_p, v_p))
                q_grouped = q[:, 0].reshape(b, n_kv, group, head_dim)
                if q_prescale != 1.0:
                    q_grouped = q_grouped * jnp.asarray(q_prescale, q_grouped.dtype)
                attn = paged_attention(
                    q_grouped, k_p, v_p, page_table, lengths + 1, **scale_kw
                )                                                  # [B,Hkv,G,D]
                return attn.reshape(b, 1, n_heads * head_dim).astype(x.dtype)

            x = _block(layer, x, attn_fn, lora_idx)
            return (x,) + stash[0]

        if kv_quant:
            xs_all = (params["layers"], k_pools, v_pools, k_scales, v_scales)
        else:
            xs_all = (params["layers"], k_pools, v_pools)
        if scan_layers:
            def scan_body(x, xs):
                layer = xs[0]
                pools = xs[1:] if kv_quant else xs[1:] + (None, None)
                out = layer_body(x, layer, *pools)
                return out[0], out[1:]

            x, new_pools = jax.lax.scan(scan_body, x, xs_all)
        else:
            per_layer = []
            for li, layer in enumerate(params["layers"]):
                tup = tuple(a[li] for a in xs_all[1:])
                if not kv_quant:
                    tup = tup + (None, None)
                out = layer_body(x, layer, *tup)
                x = out[0]
                per_layer.append(out[1:])
            new_pools = tuple(
                jnp.stack([bufs[j] for bufs in per_layer])
                for j in range(len(per_layer[0]))
            )
        logits = _logits(params, x)[:, 0]
        return (logits,) + tuple(new_pools)

    def verify_paged(
        params,
        tokens,        # [B, S] int32: pending token + S-1 drafts
        k_pools,       # [L, Hkv, N, P, D] (int8 under kv_quant)
        v_pools,       # [L, Hkv, N, P, D]
        page_table,    # [B, PP] int32
        lengths,       # [B] int32 tokens present BEFORE this chunk
        lora_idx=None,
        *,
        k_scales=None,  # [L, Hkv, N, P] f32 scale pools (kv_quant only)
        v_scales=None,
    ):
        """Speculative verification over paged KV (vLLM spec-decode on a
        paged cache). Same contract as :func:`verify`: logits at ALL S
        positions, lengths NOT advanced — the caller accepts a draft
        prefix and sets pool lengths itself; K/V written past the accepted
        point sit beyond ``lengths`` and are overwritten by later writes
        at the same positions.

        The chunk's K/V scatter into the pools at coords derived from the
        page table (position p -> table[b, p // P], p % P), so the caller
        only pre-allocates pages; write coordinates stay dynamic, which a
        host-precomputed coord list could not be (accepted counts are a
        device-side value). Attention gathers each sequence's table to a
        dense [cap] run — capacity bandwidth, like the XLA-gather decode
        fallback — and reuses ``_attend`` so query_scale/softcap families
        verify exactly like they decode. Under ``kv_quant`` the chunk's K/V
        quantize before the scatter and the gather dequantizes with the
        scale pools (returned updated, like decode_paged)."""
        if kv_quant and k_scales is None:
            raise ValueError("kv_quant verify_paged needs k_scales/v_scales")
        b, s = tokens.shape
        pp = page_table.shape[1]
        page = k_pools.shape[3]
        cap = pp * page
        positions = lengths[:, None] + jnp.arange(s, dtype=jnp.int32)[None]
        cos, sin = _rope(positions, head_dim, theta, rope_scaling)
        x = _embed(params, tokens)                                 # [B, S, dim]
        wp = jnp.take_along_axis(page_table, positions // page, axis=1)
        wo = positions % page                                      # [B, S]
        # causal bound per query position; table slots past each row's
        # allocation hold page 0 (garbage) but always sit beyond the bound
        t_idx = jnp.arange(cap, dtype=jnp.int32)[None, None]       # [1,1,cap]
        mask = jnp.where(
            t_idx < (positions[:, :, None] + 1), 0.0, -jnp.inf
        ).astype(jnp.float32)[:, None]                             # [B,1,S,cap]

        def layer_body(x, layer, k_pool_l, v_pool_l, k_sc_l, v_sc_l):
            stash = []

            def attn_fn(layer_, h):
                q, k, v = _qkv(layer_, h, cos, sin, lora_idx)      # k,v [B,S,Hkv,D]
                k_q, k_s = _kv_store(k)
                v_q, v_s = _kv_store(v)
                k_hm = k_q.transpose(2, 0, 1, 3).astype(k_pool_l.dtype)
                v_hm = v_q.transpose(2, 0, 1, 3).astype(v_pool_l.dtype)
                k_p = k_pool_l.at[:, wp, wo].set(k_hm)
                v_p = v_pool_l.at[:, wp, wo].set(v_hm)
                if kv_quant:
                    k_sp = k_sc_l.at[:, wp, wo].set(k_s.transpose(2, 0, 1))
                    v_sp = v_sc_l.at[:, wp, wo].set(v_s.transpose(2, 0, 1))
                    stash.append((k_p, v_p, k_sp, v_sp))
                else:
                    stash.append((k_p, v_p))
                # [Hkv, B, PP, P, D] -> [B, cap, Hkv, D] (table order IS
                # sequence-position order)
                kg = k_p[:, page_table].transpose(1, 2, 3, 0, 4).reshape(
                    b, cap, n_kv, head_dim
                )
                vg = v_p[:, page_table].transpose(1, 2, 3, 0, 4).reshape(
                    b, cap, n_kv, head_dim
                )
                if kv_quant:
                    # dequant the gathered run with its scale rows ([B, cap,
                    # Hkv]), f32 math like the dense path's _kv_load
                    ksg = k_sp[:, page_table].transpose(1, 2, 3, 0).reshape(
                        b, cap, n_kv
                    )
                    vsg = v_sp[:, page_table].transpose(1, 2, 3, 0).reshape(
                        b, cap, n_kv
                    )
                    kg = kg.astype(jnp.float32) * ksg[..., None]
                    vg = vg.astype(jnp.float32) * vsg[..., None]
                return _attend(q, kg.astype(q.dtype), vg.astype(q.dtype), mask)

            # dropless MoE like verify(): capacity dropping would make the
            # accept chain depend on batch occupancy
            x = _block(layer, x, attn_fn, lora_idx,
                       ffn_kwargs={"dropless": True})
            return (x,) + stash[0]

        if kv_quant:
            xs_all = (params["layers"], k_pools, v_pools, k_scales, v_scales)
        else:
            xs_all = (params["layers"], k_pools, v_pools)
        if scan_layers:
            def scan_body(x, xs):
                layer = xs[0]
                pools = xs[1:] if kv_quant else xs[1:] + (None, None)
                out = layer_body(x, layer, *pools)
                return out[0], out[1:]

            x, new_pools = jax.lax.scan(scan_body, x, xs_all)
        else:
            per_layer = []
            for li, layer in enumerate(params["layers"]):
                tup = tuple(a[li] for a in xs_all[1:])
                if not kv_quant:
                    tup = tup + (None, None)
                out = layer_body(x, layer, *tup)
                x = out[0]
                per_layer.append(out[1:])
            new_pools = tuple(
                jnp.stack([bufs[j] for bufs in per_layer])
                for j in range(len(per_layer[0]))
            )
        return (_logits(params, x),) + tuple(new_pools)

    # -- ragged mixed prefill+decode step (docs/ragged_attention.md) ---------

    def forward_ragged(
        params,
        tokens,        # [T] int32 flattened ragged chunk (token-major)
        tok_pos,       # [T] int32 absolute position of each token in its row
        tok_row,       # [T] int32 owning batch row per token (pads -> 0)
        tok_valid,     # [T] bool real tokens (pads never route in MoE)
        row_last,      # [R] int32 flat index of each row's last real token
        k_pools,       # [L, Hkv, N, P, D] (int8 under kv_quant)
        v_pools,
        page_table,    # [R, PP] int32
        kv_lens,       # [R] int32 tokens present AFTER this chunk's writes
        row_starts,    # [R] int32 ragged row map (ops.ragged_layout)
        row_lens,      # [R] int32 query tokens per row (0 = idle row)
        write_page,    # [T] int32 per-token write coords (pads -> null page)
        write_offset,  # [T] int32
        block_rows=None,  # [T/QB] int32 kernel q-block map (host-built;
        block_q0=None,    #  None routes attention to the XLA reference)
        lora_idx=None,    # [R] int32 adapter index per row (None = base)
        *,
        k_scales=None,  # [L, Hkv, N, P] f32 scale pools (kv_quant only)
        v_scales=None,
        row_logit_idx=None,  # [R, W] int32 flat token indices to read
                             # logits at (None = row_last only)
        tree_anc=None,       # [T, DMAX] int32 per-token ancestor lists for
                             # draft-TREE verify rows (None = plain causal;
                             # ops.paged_attention.tree_ancestors layout)
    ):
        """ONE forward step over a ragged mixed batch: each row is at an
        arbitrary phase — decode rows contribute one query token (plus
        reserved multi-step pad positions), spec-verify rows a known
        draft chain of q=k+1 candidate tokens, prefill rows a prompt
        chunk — flattened into a token-major operand (PAPERS.md "Ragged
        Paged Attention"). Every token embeds at its own absolute
        position, writes its K/V into the paged pools at host-precomputed
        (page, offset) coords — the same scatter as decode_paged, with
        the chunk's quantized scales beside int8 pages — and attends
        through ops.ragged_paged_attention with per-row causal bounds.
        Returns (row logits [R, vocab] at each row's last real token,
        updated pools); when ``row_logit_idx`` [R, W] is given, the
        spec-verify gather ([R, W, vocab] logits at the W requested flat
        positions per row — a draft chain needs logits at EVERY candidate
        position, not just the last) is returned BESIDE the last-token
        logits, whose compute path stays byte-for-byte the default one:
        ((last, gathered), *pools). A decode row's logits are numerically
        the decode path's logits, which is what the engine's
        ragged-vs-two-dispatch byte-identity rests on."""
        from ..ops.paged_attention import ragged_paged_attention

        if kv_quant and k_scales is None:
            raise ValueError("kv_quant forward_ragged needs k_scales/v_scales")
        t = tokens.shape[0]
        positions = tok_pos[:, None]                               # [T, 1]
        cos, sin = _rope(positions, head_dim, theta, rope_scaling)
        x = _embed(params, tokens)[:, None]                        # [T, 1, dim]
        tok_lora = lora_idx[tok_row] if lora_idx is not None else None
        q_prescale = query_scale * (head_dim ** 0.5)

        def layer_body(x, layer, k_pool_l, v_pool_l, k_sc_l, v_sc_l):
            stash = []

            def attn_fn(layer_, h):
                q, k, v = _qkv(layer_, h, cos, sin, tok_lora)  # [T,1,H,D]
                k_q, k_s = _kv_store(k)
                v_q, v_s = _kv_store(v)
                k_hm = k_q[:, 0].transpose(1, 0, 2).astype(k_pool_l.dtype)
                v_hm = v_q[:, 0].transpose(1, 0, 2).astype(v_pool_l.dtype)
                k_p = k_pool_l.at[:, write_page, write_offset].set(k_hm)
                v_p = v_pool_l.at[:, write_page, write_offset].set(v_hm)
                scale_kw = {}
                if kv_quant:
                    k_sp = k_sc_l.at[:, write_page, write_offset].set(
                        k_s[:, 0].transpose(1, 0)
                    )
                    v_sp = v_sc_l.at[:, write_page, write_offset].set(
                        v_s[:, 0].transpose(1, 0)
                    )
                    stash.append((k_p, v_p, k_sp, v_sp))
                    scale_kw = {"k_scale": k_sp, "v_scale": v_sp}
                else:
                    stash.append((k_p, v_p))
                q_grouped = q[:, 0].reshape(t, n_kv, group, head_dim)
                if q_prescale != 1.0:
                    q_grouped = q_grouped * jnp.asarray(
                        q_prescale, q_grouped.dtype
                    )
                attn = ragged_paged_attention(
                    q_grouped, k_p, v_p, page_table, kv_lens,
                    row_starts, row_lens,
                    block_rows=block_rows, block_q0=block_q0,
                    tree_anc=tree_anc, **scale_kw,
                )                                                  # [T,Hkv,G,D]
                return attn.reshape(t, 1, n_heads * head_dim).astype(x.dtype)

            # dropless MoE: capacity dropping would make a row's tokens
            # depend on what the OTHER rows put in the launch — the ragged
            # scheduler requires per-row determinism (like verify)
            x = _block(layer, x, attn_fn, tok_lora,
                       ffn_kwargs={"valid": tok_valid[:, None],
                                   "dropless": True})
            return (x,) + stash[0]

        if kv_quant:
            xs_all = (params["layers"], k_pools, v_pools, k_scales, v_scales)
        else:
            xs_all = (params["layers"], k_pools, v_pools)
        if scan_layers:
            def scan_body(x, xs):
                layer = xs[0]
                pools = xs[1:] if kv_quant else xs[1:] + (None, None)
                out = layer_body(x, layer, *pools)
                return out[0], out[1:]

            x, new_pools = jax.lax.scan(scan_body, x, xs_all)
        else:
            per_layer = []
            for li, layer in enumerate(params["layers"]):
                tup = tuple(a[li] for a in xs_all[1:])
                if not kv_quant:
                    tup = tup + (None, None)
                out = layer_body(x, layer, *tup)
                x = out[0]
                per_layer.append(out[1:])
            new_pools = tuple(
                jnp.stack([bufs[j] for bufs in per_layer])
                for j in range(len(per_layer[0]))
            )
        last_x = x[:, 0][row_last][:, None]                    # [R, 1, dim]
        logits = _logits(params, last_x)[:, 0]                 # [R, vocab]
        if row_logit_idx is not None:
            # spec-verify gather: [R, W] flat indices -> [R, W, vocab].
            # W is small (k+1), so the extra lm_head rows cost R*W matvecs,
            # never a T-wide logits materialization. The last-token logits
            # keep their own (unchanged) compute path so every non-verify
            # consumer stays bitwise identical across spec/no-spec launches.
            sel_x = x[:, 0][row_logit_idx]                     # [R, W, dim]
            gathered = _logits(params, sel_x)                  # [R, W, vocab]
            return ((logits, gathered),) + tuple(new_pools)
        return (logits,) + tuple(new_pools)

    def forward_ragged_dense(params, tokens, start, last_rel, row_active,
                             cache, lora_idx=None, *, logit_rel=None):
        """Dense-cache ragged step (docs/ragged_attention.md): the mixed
        batch takes the RECTANGULAR chunk layout — tokens [B, C] where
        decode rows carry one real token, spec-verify rows a known
        draft chain (k+1 tokens), prefill rows a prompt chunk, and
        idle rows garbage their frozen length masks. Each row's chunk
        writes at its own absolute positions (the chunked-prefill layer
        loop) and attends causally over its slot's cache; logits return at
        ``last_rel`` — plus, when ``logit_rel`` [B, W] is given, the
        spec-verify gather at the W requested chunk-relative positions per
        row (``(last [B, vocab], gathered [B, W, vocab])``; the last-token
        path stays byte-for-byte the default one) — and lengths advance
        only where ``row_active`` (a spec caller re-clamps verify rows'
        lengths to the accepted prefix itself, the :func:`verify`
        contract)."""
        b, c = tokens.shape
        ffn_valid = (
            jnp.arange(c, dtype=jnp.int32)[None] <= last_rel[:, None]
        ) & row_active[:, None]
        x, new_kv = _cached_chunk_layers(
            params, tokens, start, cache, ffn_kwargs={"valid": ffn_valid},
            lora_idx=lora_idx,
        )
        new_len = jnp.maximum(
            cache["length"], start + last_rel + 1
        ).astype(jnp.int32)
        cache = dict(
            new_kv, length=jnp.where(row_active, new_len, cache["length"])
        )
        last_x = jnp.take_along_axis(
            x, last_rel[:, None, None].clip(0, c - 1), axis=1
        )                                                      # [B, 1, dim]
        last = _logits(params, last_x)[:, 0]                   # [B, vocab]
        if logit_rel is not None:
            sel_x = jnp.take_along_axis(
                x, logit_rel[:, :, None].clip(0, c - 1), axis=1
            )                                                  # [B, W, dim]
            return (last, _logits(params, sel_x)), cache
        return last, cache

    def prepare_params(params):
        """Adapt a loaded param pytree to this build's layout: under
        scan_layers, a list/tuple of per-layer dicts (e.g. from a checkpoint
        converter) is stacked into the [L, ...] pytree lax.scan consumes.
        When the build enables LoRA, checkpoints that predate it get zero
        adapter stacks backfilled (index 0 = base model)."""
        layers = params.get("layers")
        if scan_layers and isinstance(layers, (list, tuple)):
            params = dict(params)
            params["layers"] = jax.tree.map(lambda *xs: jnp.stack(xs), *layers)
        elif not scan_layers and isinstance(layers, dict) and "wq" in layers:
            params = dict(params)
            params["layers"] = [
                jax.tree.map(lambda x: x[i], layers) for i in range(n_layers)
            ]
        if lora_rank:
            from . import lora as lora_lib

            params = dict(params)
            layers = params["layers"]
            if isinstance(layers, dict):
                if "lora_a_" + lora_targets[0] not in layers:
                    layers = dict(layers)
                    for t in lora_targets:
                        d_in, d_out = lora_lib.target_dims(cfg, t)
                        layers["lora_a_" + t] = jnp.zeros(
                            (n_layers, max_loras + 1, d_in, lora_rank), dtype
                        )
                        layers["lora_b_" + t] = jnp.zeros(
                            (n_layers, max_loras + 1, lora_rank, d_out), dtype
                        )
                    params["layers"] = layers
            else:
                if layers and "lora_a_" + lora_targets[0] not in layers[0]:
                    new_layers = []
                    for layer in layers:
                        layer = dict(layer)
                        for t in lora_targets:
                            d_in, d_out = lora_lib.target_dims(cfg, t)
                            layer["lora_a_" + t] = jnp.zeros(
                                (max_loras + 1, d_in, lora_rank), dtype
                            )
                            layer["lora_b_" + t] = jnp.zeros(
                                (max_loras + 1, lora_rank, d_out), dtype
                            )
                        new_layers.append(layer)
                    params["layers"] = new_layers
        return params

    return SimpleNamespace(
        init=init,
        apply=apply,
        init_cache=init_cache,
        prefill=prefill,
        prefill_chunk=prefill_chunk,
        ffn=_ffn,
        # ring attention masks plain-causally inside the ring with the
        # default head_dim**-0.5 score scale and no soft-capping, so any
        # family that windows, rescales, or softcaps is unsupported on the
        # sp long-prefill path (engine falls back to plain prefill when
        # this is None)
        prefill_ring=(
            None
            if (
                sliding_window
                or attn_softcap
                or abs(query_scale - head_dim ** -0.5) > 1e-12
            )
            else prefill_ring
        ),
        decode=decode,
        verify=verify,
        decode_paged=decode_paged,
        verify_paged=verify_paged,
        # ragged mixed prefill+decode step (docs/ragged_attention.md): the
        # engine's token-budget scheduler drives one of these per iteration
        forward_ragged=forward_ragged,
        forward_ragged_dense=forward_ragged_dense,
        # pipeline-parallel prefill: gated to configs whose forward the
        # pipeline stage body reproduces exactly (see prefill_pipeline doc)
        prefill_pipeline=(
            prefill_pipeline
            if (scan_layers and not kv_quant and not n_experts)
            else None
        ),
        prepare_params=prepare_params,
        config=cfg,
        head_dim=head_dim,
        n_kv_heads=n_kv,
        n_heads=n_heads,
        n_layers=n_layers,
        lora_rank=lora_rank,
        max_loras=max_loras,
        # the paged kernel has no score soft-capping; the engine refuses
        # cache=paged for such models (alt_window is covered by the existing
        # sliding_window guard). kv_quant="int8" is supported on BOTH cache
        # backends since the int8 paged pools landed (docs/paged_kv_quant.md).
        paged_unsupported_reason=(
            "attention logit softcapping (Gemma-2) is not supported by the "
            "paged decode kernel; use engine.cache=dense"
            if attn_softcap
            else None
        ),
    )
