"""Multi-LoRA adapter serving for the llama-family decoder.

Reference capability: vLLM's ``lora_modules`` engine knob, surfaced by the
reference's vLLM preprocess config (reference
clearml_serving/serving/preprocess_service.py:740-767 wires
``lora_modules``/``LoRAModulePath`` into the OpenAI serving layer, and
examples/vllm/preprocess.py lists it among the model-config knobs). A served
endpoint exposes its base model plus N named adapters; each request picks one
by the OpenAI ``model`` field.

TPU-first design — *stacked adapters, gathered per slot inside the layer*:

- For every LoRA-targeted projection ``t`` each decoder layer carries two
  stacks ``lora_a_t`` [A+1, in, r] and ``lora_b_t`` [A+1, r, out] where A =
  ``max_loras``; index 0 is the base model (all-zero delta), adapters live at
  1..A. Under ``scan_layers`` the stacks gain the leading layer dim like
  every other layer weight and ride the same ``lax.scan``.
- The batch carries ``lora_idx`` [B] int32. Inside the (scanned) layer body
  the projection adds ``(x @ a[lora_idx]) @ b[lora_idx]`` — two small batched
  matmuls (rank r), so ONE compiled executable serves any mix of adapters in
  the same continuous batch; swapping adapters never recompiles. This is the
  standard batched-LoRA trick (vLLM's SGMV kernels do the gather on CUDA);
  on TPU the per-slot gather + einsum lowers to XLA gather + batched matmul
  with no custom kernel needed at serving ranks (r ≤ 64).
- Quantization composes: the int8 path (ops/quant.py) quantizes only the base
  projections; LoRA stacks stay in the model dtype (they are small and
  precision-critical).

PEFT checkpoints (adapter_model.safetensors / .bin + adapter_config.json)
convert via :func:`load_peft_adapter`; the ``alpha/r`` scaling folds into the
B factor at load time so the serving graph has no runtime scale multiply.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Any, Dict, Optional, Sequence, Tuple

import numpy as np

def _head_dim(c) -> int:
    # may be decoupled from dim (Gemma-2 heads)
    return int(c.get("head_dim") or c["dim"] // c["n_heads"])


# target name -> (in_dim, out_dim) resolver, given a resolved llama config
_TARGET_DIMS = {
    "wq": lambda c: (c["dim"], c["n_heads"] * _head_dim(c)),
    "wk": lambda c: (c["dim"], c["n_kv_heads"] * _head_dim(c)),
    "wv": lambda c: (c["dim"], c["n_kv_heads"] * _head_dim(c)),
    "wo": lambda c: (c["n_heads"] * _head_dim(c), c["dim"]),
    "w_gate": lambda c: (c["dim"], c["ffn_dim"]),
    "w_up": lambda c: (c["dim"], c["ffn_dim"]),
    "w_down": lambda c: (c["ffn_dim"], c["dim"]),
}

DEFAULT_TARGETS = ("wq", "wk", "wv", "wo")

# HF PEFT module names -> our projection names
_PEFT_NAME_MAP = {
    "q_proj": "wq",
    "k_proj": "wk",
    "v_proj": "wv",
    "o_proj": "wo",
    "gate_proj": "w_gate",
    "up_proj": "w_up",
    "down_proj": "w_down",
}


def lora_spec(cfg: dict) -> Tuple[int, Tuple[str, ...], int]:
    """(rank, targets, max_loras) from a resolved llama config; rank 0 = off."""
    rank = int(cfg.get("lora_rank", 0) or 0)
    targets = tuple(cfg.get("lora_targets") or DEFAULT_TARGETS)
    max_loras = int(cfg.get("max_loras", 4) or 4)
    for t in targets:
        if t not in _TARGET_DIMS:
            raise ValueError(
                "unknown lora target {!r} (supported: {})".format(
                    t, sorted(_TARGET_DIMS)
                )
            )
    return rank, targets, max_loras


def target_dims(cfg: dict, target: str) -> Tuple[int, int]:
    return tuple(int(x) for x in _TARGET_DIMS[target](cfg))


def zero_stacks(cfg: dict, dtype) -> Dict[str, np.ndarray]:
    """Per-layer zero LoRA stacks {lora_a_t: [A+1, in, r], lora_b_t: ...}.

    Returned as numpy so callers can install adapters host-side before the
    tree is placed on device."""
    import jax.numpy as jnp

    rank, targets, max_loras = lora_spec(cfg)
    out: Dict[str, Any] = {}
    for t in targets:
        d_in, d_out = target_dims(cfg, t)
        out["lora_a_" + t] = jnp.zeros((max_loras + 1, d_in, rank), dtype)
        out["lora_b_" + t] = jnp.zeros((max_loras + 1, rank, d_out), dtype)
    return out


def install_adapter(params: Dict[str, Any], index: int, adapter: Dict[str, Any]):
    """Write one adapter's factors into the param tree's LoRA stacks at
    ``index`` (1-based; 0 is reserved for the base model).

    ``adapter``: {target: {"a": [L, in, r], "b": [L, r, out]}} (layer-major,
    as produced by :func:`load_peft_adapter`). Handles both the scan_layers
    stacked layout (params["layers"] is a dict of [L, ...] arrays) and the
    per-layer list layout. Returns the updated tree (functional)."""
    if index < 1:
        raise ValueError("adapter index must be >= 1 (0 is the base model)")
    layers = params["layers"]
    stacked = isinstance(layers, dict)
    params = dict(params)
    if stacked:
        layers = dict(layers)
        for t, ab in adapter.items():
            a_key, b_key = "lora_a_" + t, "lora_b_" + t
            if a_key not in layers:
                raise ValueError(
                    "model was not built with lora target {!r} "
                    "(set lora_targets)".format(t)
                )
            if index >= layers[a_key].shape[1]:
                raise ValueError(
                    "adapter index {} exceeds max_loras {}".format(
                        index, layers[a_key].shape[1] - 1
                    )
                )
            r_have = layers[a_key].shape[-1]
            a = np.asarray(ab["a"], dtype=np.float32)
            b = np.asarray(ab["b"], dtype=np.float32)
            if a.shape[-1] > r_have:
                raise ValueError(
                    "adapter rank {} exceeds built lora_rank {}".format(
                        a.shape[-1], r_have
                    )
                )
            # lower-rank adapters zero-pad up to the built rank (the padded
            # columns contribute nothing: a's extra columns meet b's zero rows)
            if a.shape[-1] < r_have:
                pad = r_have - a.shape[-1]
                a = np.pad(a, ((0, 0), (0, 0), (0, pad)))
                b = np.pad(b, ((0, 0), (0, pad), (0, 0)))
            layers[a_key] = layers[a_key].at[:, index].set(
                a.astype(layers[a_key].dtype)
            )
            layers[b_key] = layers[b_key].at[:, index].set(
                b.astype(layers[b_key].dtype)
            )
        params["layers"] = layers
    else:
        new_layers = []
        for li, layer in enumerate(layers):
            layer = dict(layer)
            for t, ab in adapter.items():
                a_key, b_key = "lora_a_" + t, "lora_b_" + t
                if a_key not in layer:
                    raise ValueError(
                        "model was not built with lora target {!r}".format(t)
                    )
                if index >= layer[a_key].shape[0]:
                    raise ValueError(
                        "adapter index {} exceeds max_loras {}".format(
                            index, layer[a_key].shape[0] - 1
                        )
                    )
                r_have = layer[a_key].shape[-1]
                a = np.asarray(ab["a"][li], dtype=np.float32)
                b = np.asarray(ab["b"][li], dtype=np.float32)
                if a.shape[-1] > r_have:
                    raise ValueError(
                        "adapter rank {} exceeds built lora_rank {}".format(
                            a.shape[-1], r_have
                        )
                    )
                if a.shape[-1] < r_have:
                    pad = r_have - a.shape[-1]
                    a = np.pad(a, ((0, 0), (0, pad)))
                    b = np.pad(b, ((0, pad), (0, 0)))
                layer[a_key] = layer[a_key].at[index].set(
                    a.astype(layer[a_key].dtype)
                )
                layer[b_key] = layer[b_key].at[index].set(
                    b.astype(layer[b_key].dtype)
                )
            new_layers.append(layer)
        params["layers"] = new_layers
    return params


def merge_adapter_into_weights(params: Dict[str, Any], adapter: Dict[str, Any]):
    """Dense-merge an adapter into base weights (W + A @ B) — the classic
    offline merge, used by tests as the ground truth the batched path must
    match. Only supports the per-layer list layout with plain (unquantized)
    weights."""
    import jax.numpy as jnp

    params = dict(params)
    new_layers = []
    for li, layer in enumerate(params["layers"]):
        layer = dict(layer)
        for t, ab in adapter.items():
            delta = jnp.asarray(ab["a"][li], jnp.float32) @ jnp.asarray(
                ab["b"][li], jnp.float32
            )
            layer[t] = (layer[t].astype(jnp.float32) + delta).astype(layer[t].dtype)
        new_layers.append(layer)
    params["layers"] = new_layers
    return params


# -- adapter file formats -----------------------------------------------------

def load_adapter(path, n_layers: int) -> Dict[str, Any]:
    """Load an adapter directory in either supported format:

    - PEFT (HF): adapter_config.json + adapter_model.safetensors/.bin
    - native: lora_config.json + lora.msgpack ({target: {"a": [L,in,r], ...}})
    """
    path = Path(path)
    if (path / "adapter_config.json").exists():
        return load_peft_adapter(path, n_layers)
    if (path / "lora.msgpack").exists():
        from flax import serialization

        tree = serialization.msgpack_restore(
            bytearray((path / "lora.msgpack").read_bytes())
        )
        return {t: {"a": np.asarray(ab["a"]), "b": np.asarray(ab["b"])}
                for t, ab in tree.items()}
    raise ValueError(
        "not a LoRA adapter dir (no adapter_config.json or lora.msgpack): {}".format(
            path
        )
    )


def save_adapter(path, adapter: Dict[str, Any]) -> None:
    """Write the native adapter format."""
    from flax import serialization

    path = Path(path)
    path.mkdir(parents=True, exist_ok=True)
    tree = {t: {"a": np.asarray(ab["a"]), "b": np.asarray(ab["b"])}
            for t, ab in adapter.items()}
    (path / "lora.msgpack").write_bytes(serialization.msgpack_serialize(tree))
    (path / "lora_config.json").write_text(json.dumps(
        {t: {"rank": int(tree[t]["a"].shape[-1])} for t in tree}
    ))


def load_peft_adapter(path, n_layers: int) -> Dict[str, Any]:
    """HF PEFT LoRA checkpoint -> {target: {"a": [L, in, r], "b": [L, r, out]}}.

    PEFT stores per-module ``lora_A.weight`` [r, in] and ``lora_B.weight``
    [out, r] under keys like
    ``base_model.model.model.layers.{i}.self_attn.q_proj.lora_A.weight``.
    The delta is ``(alpha / r) * B @ A``; the scaling folds into B here so
    serving needs no extra multiply. Layers a checkpoint omits get zeros."""
    path = Path(path)
    cfg = json.loads((path / "adapter_config.json").read_text())
    alpha = float(cfg.get("lora_alpha", cfg.get("alpha", 1.0)))
    rank = int(cfg.get("r", cfg.get("rank", 0)) or 0)
    state = _load_peft_state_dict(path)
    if not state:
        raise ValueError("empty PEFT adapter state dict in {}".format(path))
    if not rank:
        rank = next(iter(state.values())).shape[0]
    scale = alpha / float(rank)

    # group keys: (layer_index, our_target) -> {"A": ..., "B": ...}
    grouped: Dict[Tuple[int, str], Dict[str, np.ndarray]] = {}
    for key, tensor in state.items():
        parts = key.split(".")
        if "lora_A" in parts:
            which = "A"
        elif "lora_B" in parts:
            which = "B"
        else:
            continue
        layer_idx = None
        target = None
        for i, p in enumerate(parts):
            if p == "layers" and i + 1 < len(parts) and parts[i + 1].isdigit():
                layer_idx = int(parts[i + 1])
            if p in _PEFT_NAME_MAP:
                target = _PEFT_NAME_MAP[p]
        if layer_idx is None or target is None:
            continue
        grouped.setdefault((layer_idx, target), {})[which] = np.asarray(
            tensor, dtype=np.float32
        )

    targets = sorted({t for (_l, t) in grouped})
    out: Dict[str, Any] = {}
    for t in targets:
        a_layers, b_layers = [], []
        # shapes from any present layer
        sample = next(v for (l, tt), v in grouped.items() if tt == t)
        d_in = sample["A"].shape[1]
        d_out = sample["B"].shape[0]
        for li in range(n_layers):
            entry = grouped.get((li, t))
            if entry is None or "A" not in entry or "B" not in entry:
                a_layers.append(np.zeros((d_in, rank), np.float32))
                b_layers.append(np.zeros((rank, d_out), np.float32))
            else:
                a_layers.append(entry["A"].T)                   # [in, r]
                b_layers.append(scale * entry["B"].T)           # [r, out]
        out[t] = {"a": np.stack(a_layers), "b": np.stack(b_layers)}
    return out


def _load_peft_state_dict(path: Path) -> Dict[str, np.ndarray]:
    st_file = path / "adapter_model.safetensors"
    if st_file.exists():
        try:
            from safetensors.numpy import load_file

            return dict(load_file(str(st_file)))
        except ImportError:
            # safetensors-without-library fallback: the format is a JSON
            # header + raw little-endian tensors; parse it directly
            return _read_safetensors(st_file)
    bin_file = path / "adapter_model.bin"
    if bin_file.exists():
        import torch

        sd = torch.load(str(bin_file), map_location="cpu", weights_only=True)
        return {k: v.float().numpy() for k, v in sd.items()}
    raise ValueError("no adapter_model.safetensors/.bin in {}".format(path))


_ST_DTYPES = {
    "F32": np.float32, "F16": np.float16, "BF16": None,  # bf16 special-cased
    "F64": np.float64, "I64": np.int64, "I32": np.int32,
}


def _read_safetensors(path: Path) -> Dict[str, np.ndarray]:
    raw = path.read_bytes()
    hdr_len = int.from_bytes(raw[:8], "little")
    header = json.loads(raw[8 : 8 + hdr_len].decode("utf-8"))
    base = 8 + hdr_len
    out = {}
    for name, meta in header.items():
        if name == "__metadata__":
            continue
        lo, hi = meta["data_offsets"]
        buf = raw[base + lo : base + hi]
        dt = meta["dtype"]
        if dt == "BF16":
            u16 = np.frombuffer(buf, np.uint16).astype(np.uint32) << 16
            arr = u16.view(np.float32)
        else:
            arr = np.frombuffer(buf, _ST_DTYPES[dt])
        out[name] = arr.reshape(meta["shape"]).astype(np.float32)
    return out
