"""Tabular MLP (iris-classifier class of workloads).

TPU-first: pure functional params pytree, bf16-friendly matmuls, batch-leading
shapes so the router's bucketed auto-batching maps straight onto the MXU.
Covers the reference's sklearn/xgboost/lightgbm tabular acceptance configs when
served through the `jax` engine (BASELINE.md configs 1-2).
"""

from __future__ import annotations

from types import SimpleNamespace

import jax
import jax.numpy as jnp

from . import register_model


def _dtype(name):
    return jnp.dtype(name) if name else jnp.float32


@register_model("mlp")
def build(config: dict) -> SimpleNamespace:
    in_dim = int(config.get("in_dim", 4))
    hidden = [int(h) for h in config.get("hidden", [64, 64])]
    out_dim = int(config.get("out_dim", 3))
    dtype = _dtype(config.get("dtype", "float32"))
    dims = [in_dim] + hidden + [out_dim]

    def init(rng):
        params = []
        for i, (a, b) in enumerate(zip(dims[:-1], dims[1:])):
            rng, sub = jax.random.split(rng)
            w = jax.random.normal(sub, (a, b), dtype=jnp.float32) * (2.0 / a) ** 0.5
            params.append({"w": w.astype(dtype), "b": jnp.zeros((b,), dtype=dtype)})
        return {"layers": params}

    def apply(params, x):
        x = x.astype(dtype)
        layers = params["layers"]
        for layer in layers[:-1]:
            x = jax.nn.relu(x @ layer["w"] + layer["b"])
        last = layers[-1]
        return x @ last["w"] + last["b"]

    return SimpleNamespace(init=init, apply=apply, config=config)
