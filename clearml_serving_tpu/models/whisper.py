"""Whisper-family speech-to-text encoder-decoder (audio routes' model).

Backs v1/audio/transcriptions + v1/audio/translations — the last two of the
reference's 13 vLLM route types (reference preprocess_service.py:1031-1075
delegates them to vLLM's transcription handlers; here the model is native
JAX and jit-compiles for TPU).

Architecture (OpenAI Whisper / HF WhisperForConditionalGeneration):
- encoder: conv1d(mels->d, k3) + gelu, conv1d(d->d, k3, stride 2) + gelu,
  + sinusoidal positions, pre-LN transformer self-attention stack, final LN;
- decoder: token embed + learned positions, pre-LN layers of causal
  self-attention, cross-attention over encoder states, GELU MLP, final LN;
  LM head tied to the token embedding;
- serving decode: self-attn KV cache + cross-attn KV precomputed once per
  utterance (same slot/cache discipline as the llama decode path).

Checkpoints convert via engines/importers/convert_hf_whisper.py; fidelity vs
transformers is pinned in tests/test_whisper.py.
"""

from __future__ import annotations

from types import SimpleNamespace
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from . import register_model

PRESETS: Dict[str, Dict[str, Any]] = {
    "whisper-tiny": dict(
        vocab_size=51865, d_model=384, n_audio_layers=4, n_text_layers=4,
        n_heads=6, ffn_dim=1536, n_mels=80, max_source_positions=1500,
        max_target_positions=448,
    ),
    "whisper-test": dict(  # CI-sized
        vocab_size=400, d_model=32, n_audio_layers=2, n_text_layers=2,
        n_heads=2, ffn_dim=64, n_mels=16, max_source_positions=64,
        max_target_positions=32,
    ),
}


def _layer_norm(x, p, eps=1e-5):
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.var(x32, axis=-1, keepdims=True)
    out = (x32 - mean) * jax.lax.rsqrt(var + eps)
    return (out * p["scale"].astype(jnp.float32) + p["bias"].astype(jnp.float32)).astype(x.dtype)


def _sinusoids(length: int, channels: int) -> jnp.ndarray:
    """Whisper's fixed sinusoidal encoder positions."""
    import numpy as np

    log_timescale = np.log(10000.0) / (channels // 2 - 1)
    inv = np.exp(-log_timescale * np.arange(channels // 2))
    scaled = np.arange(length)[:, None] * inv[None]
    return jnp.asarray(
        np.concatenate([np.sin(scaled), np.cos(scaled)], axis=1), jnp.float32
    )


@register_model("whisper")
def build(config: dict) -> SimpleNamespace:
    cfg = dict(PRESETS.get(config.get("preset", ""), {}))
    cfg.update({k: v for k, v in config.items() if k != "preset"})
    cfg.setdefault("dtype", "float32")

    vocab = int(cfg["vocab_size"])
    d = int(cfg["d_model"])
    n_audio = int(cfg["n_audio_layers"])
    n_text = int(cfg["n_text_layers"])
    n_heads = int(cfg["n_heads"])
    ffn = int(cfg["ffn_dim"])
    n_mels = int(cfg["n_mels"])
    src_pos = int(cfg["max_source_positions"])
    tgt_pos = int(cfg["max_target_positions"])
    dtype = jnp.dtype(cfg["dtype"])
    head_dim = d // n_heads

    def _dense_p(key, shape, fan_in, bias=True):
        w = (jax.random.normal(key, shape, jnp.float32) * fan_in ** -0.5).astype(dtype)
        out = {"w": w}
        if bias:
            out["b"] = jnp.zeros((shape[-1],), dtype)
        return out

    def _ln_p():
        return {"scale": jnp.ones((d,), dtype), "bias": jnp.zeros((d,), dtype)}

    def _attn_p(key):
        ks = jax.random.split(key, 4)
        return {
            # whisper quirk: k_proj carries no bias
            "q": _dense_p(ks[0], (d, d), d),
            "k": _dense_p(ks[1], (d, d), d, bias=False),
            "v": _dense_p(ks[2], (d, d), d),
            "o": _dense_p(ks[3], (d, d), d),
        }

    def init(rng) -> Dict[str, Any]:
        keys = jax.random.split(rng, 6 + n_audio + n_text)
        conv_scale = (3 * n_mels) ** -0.5
        params: Dict[str, Any] = {
            "conv1": {
                "w": (jax.random.normal(keys[0], (3, n_mels, d)) * conv_scale).astype(dtype),
                "b": jnp.zeros((d,), dtype),
            },
            "conv2": {
                "w": (jax.random.normal(keys[1], (3, d, d)) * (3 * d) ** -0.5).astype(dtype),
                "b": jnp.zeros((d,), dtype),
            },
            "enc_pos": _sinusoids(src_pos, d).astype(dtype),
            "enc_final_norm": _ln_p(),
            "embed": (jax.random.normal(keys[2], (vocab, d)) * 0.02).astype(dtype),
            "dec_pos": (jax.random.normal(keys[3], (tgt_pos, d)) * 0.02).astype(dtype),
            "dec_final_norm": _ln_p(),
            "enc_layers": [],
            "dec_layers": [],
        }
        for i in range(n_audio):
            k = jax.random.split(keys[4 + i], 2)
            params["enc_layers"].append(
                {
                    "attn_norm": _ln_p(),
                    "attn": _attn_p(k[0]),
                    "ffn_norm": _ln_p(),
                    "fc1": _dense_p(jax.random.split(k[1])[0], (d, ffn), d),
                    "fc2": _dense_p(jax.random.split(k[1])[1], (ffn, d), ffn),
                }
            )
        for i in range(n_text):
            k = jax.random.split(keys[4 + n_audio + i], 3)
            params["dec_layers"].append(
                {
                    "attn_norm": _ln_p(),
                    "attn": _attn_p(k[0]),
                    "cross_norm": _ln_p(),
                    "cross": _attn_p(k[1]),
                    "ffn_norm": _ln_p(),
                    "fc1": _dense_p(jax.random.split(k[2])[0], (d, ffn), d),
                    "fc2": _dense_p(jax.random.split(k[2])[1], (ffn, d), ffn),
                }
            )
        return params

    def _proj(p, x):
        out = x @ p["w"]
        if "b" in p:
            out = out + p["b"]
        return out

    def _heads(x, b, s):
        return x.reshape(b, s, n_heads, head_dim)

    def _mha(q, k, v, mask=None):
        """q [B,S,H,Dh]; k/v [B,T,H,Dh]; mask additive [B,1,S,T] or None."""
        scores = jnp.einsum(
            "bshd,bthd->bhst", q, k, preferred_element_type=jnp.float32
        ) * (head_dim ** -0.5)
        if mask is not None:
            scores = scores + mask
        probs = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
        return jnp.einsum("bhst,bthd->bshd", probs, v)

    def _self_attn(p, x, mask):
        b, s, _ = x.shape
        q = _heads(_proj(p["q"], x), b, s)
        k = _heads(_proj(p["k"], x), b, s)
        v = _heads(_proj(p["v"], x), b, s)
        out = _mha(q, k, v, mask).reshape(b, s, d)
        return _proj(p["o"], out)

    def _ffn_block(layer, x):
        h = jax.nn.gelu(_proj(layer["fc1"], x), approximate=False)
        return _proj(layer["fc2"], h)

    # -- encoder --------------------------------------------------------------

    def encode(params, mel: jnp.ndarray) -> jnp.ndarray:
        """mel [B, n_mels, T] -> encoder states [B, T//2, d]."""
        x = mel.astype(dtype).transpose(0, 2, 1)                  # [B, T, mels]
        x = jax.nn.gelu(
            jax.lax.conv_general_dilated(
                x, params["conv1"]["w"], (1,), [(1, 1)],
                dimension_numbers=("NWC", "WIO", "NWC"),
            )
            + params["conv1"]["b"],
            approximate=False,
        )
        x = jax.nn.gelu(
            jax.lax.conv_general_dilated(
                x, params["conv2"]["w"], (2,), [(1, 1)],
                dimension_numbers=("NWC", "WIO", "NWC"),
            )
            + params["conv2"]["b"],
            approximate=False,
        )
        s = x.shape[1]
        x = x + params["enc_pos"][:s].astype(x.dtype)[None]
        for layer in params["enc_layers"]:
            h = _layer_norm(x, layer["attn_norm"])
            x = x + _self_attn(layer["attn"], h, None)
            h = _layer_norm(x, layer["ffn_norm"])
            x = x + _ffn_block(layer, h)
        return _layer_norm(x, params["enc_final_norm"])

    # -- decoder (cached serving path) ----------------------------------------

    def init_cache(params, enc_out: jnp.ndarray, max_len: int) -> Dict[str, Any]:
        """Per-utterance decode state: empty self-attn KV + cross KV
        precomputed ONCE from the encoder states."""
        b, t, _ = enc_out.shape
        cross_k, cross_v = [], []
        for layer in params["dec_layers"]:
            cross_k.append(_heads(_proj(layer["cross"]["k"], enc_out), b, t))
            cross_v.append(_heads(_proj(layer["cross"]["v"], enc_out), b, t))
        return {
            "k": jnp.zeros((n_text, b, max_len, n_heads, head_dim), dtype),
            "v": jnp.zeros((n_text, b, max_len, n_heads, head_dim), dtype),
            "cross_k": jnp.stack(cross_k),
            "cross_v": jnp.stack(cross_v),
            "length": jnp.zeros((b,), jnp.int32),
        }

    def decode(params, tokens: jnp.ndarray, cache) -> Tuple[jnp.ndarray, Dict]:
        """One token per sequence: tokens [B] -> (logits [B, vocab], cache)."""
        b = tokens.shape[0]
        max_len = cache["k"].shape[2]
        pos = cache["length"]                                     # [B]
        x = params["embed"][tokens][:, None] + params["dec_pos"][pos][:, None]
        t_idx = jnp.arange(max_len, dtype=jnp.int32)[None]
        visible = t_idx <= pos[:, None]                           # [B, T]
        mask = jnp.where(visible, 0.0, -jnp.inf).astype(jnp.float32)[:, None, None]
        new_k, new_v = [], []
        for i, layer in enumerate(params["dec_layers"]):
            h = _layer_norm(x, layer["attn_norm"])
            q = _heads(_proj(layer["attn"]["q"], h), b, 1)
            k_new = _heads(_proj(layer["attn"]["k"], h), b, 1)
            v_new = _heads(_proj(layer["attn"]["v"], h), b, 1)
            k_all = jax.vmap(
                lambda buf, kn, p: jax.lax.dynamic_update_slice(buf, kn, (p, 0, 0))
            )(cache["k"][i], k_new, pos)
            v_all = jax.vmap(
                lambda buf, vn, p: jax.lax.dynamic_update_slice(buf, vn, (p, 0, 0))
            )(cache["v"][i], v_new, pos)
            new_k.append(k_all)
            new_v.append(v_all)
            attn = _mha(q, k_all, v_all, mask).reshape(b, 1, d)
            x = x + _proj(layer["attn"]["o"], attn)
            h = _layer_norm(x, layer["cross_norm"])
            qc = _heads(_proj(layer["cross"]["q"], h), b, 1)
            cross = _mha(qc, cache["cross_k"][i], cache["cross_v"][i]).reshape(b, 1, d)
            x = x + _proj(layer["cross"]["o"], cross)
            h = _layer_norm(x, layer["ffn_norm"])
            x = x + _ffn_block(layer, h)
        x = _layer_norm(x, params["dec_final_norm"])
        logits = (x[:, 0] @ params["embed"].T).astype(jnp.float32)
        cache = {
            "k": jnp.stack(new_k),
            "v": jnp.stack(new_v),
            "cross_k": cache["cross_k"],
            "cross_v": cache["cross_v"],
            "length": cache["length"] + 1,
        }
        return logits, cache

    def cross_attention_alignment(
        params, tokens: jnp.ndarray, enc_out: jnp.ndarray, heads: tuple,
        n_frames=None,
    ):
        """Teacher-forced decoder pass that returns the cross-attention
        PROBABILITIES of the selected alignment heads: tokens [B, S] ->
        [N, B, S, T] float32, N = len(heads), heads a static tuple of
        (layer, head) pairs (per-model alignment heads, or the generic
        top-half-of-decoder default — openai-whisper's fallback).

        ``n_frames`` (scalar, dynamic): encoder positions covering the REAL
        audio; the alignment softmax masks positions beyond it BEFORE
        normalizing (openai-whisper crops QK to num_frames//2 pre-softmax —
        window padding would otherwise siphon row mass non-uniformly and
        skew the DTW path for short audio). The decoder's own residual
        stream keeps the full-window attention the serving decode uses.

        Word-level timestamps DTW over these maps (reference delegates word
        timing to whisper's cross-attention DTW; preprocess_service.py
        verbose_json surface). Only the selected heads' probabilities leave
        the graph, so HBM cost stays ~N*S*T instead of L*H*S*T."""
        b, s = tokens.shape
        x = params["embed"][tokens] + params["dec_pos"][:s][None]
        causal = jnp.tril(jnp.ones((s, s), dtype=bool))
        mask = jnp.where(causal, 0.0, -jnp.inf).astype(jnp.float32)[None, None]
        t = enc_out.shape[1]
        frame_ok = None
        if n_frames is not None:
            frame_ok = (jnp.arange(t) < n_frames)[None, None, None, :]
        by_layer: Dict[int, list] = {}
        for li, hi in heads:
            by_layer.setdefault(int(li), []).append(int(hi))
        picked = []
        for i, layer in enumerate(params["dec_layers"]):
            h = _layer_norm(x, layer["attn_norm"])
            x = x + _self_attn(layer["attn"], h, mask)
            h = _layer_norm(x, layer["cross_norm"])
            qc = _heads(_proj(layer["cross"]["q"], h), b, s)
            kc = _heads(_proj(layer["cross"]["k"], enc_out), b, t)
            vc = _heads(_proj(layer["cross"]["v"], enc_out), b, t)
            scores = jnp.einsum(
                "bshd,bthd->bhst", qc, kc, preferred_element_type=jnp.float32
            ) * (head_dim ** -0.5)
            probs = jax.nn.softmax(scores, axis=-1)             # [B, H, S, T]
            if by_layer.get(i):
                a_scores = scores
                if frame_ok is not None:
                    a_scores = jnp.where(frame_ok, scores, -jnp.inf)
                a_probs = jax.nn.softmax(a_scores, axis=-1)
                for hi in by_layer[i]:
                    picked.append(a_probs[:, hi])
            cross = jnp.einsum(
                "bhst,bthd->bshd", probs.astype(vc.dtype), vc
            ).reshape(b, s, d)
            x = x + _proj(layer["cross"]["o"], cross)
            h = _layer_norm(x, layer["ffn_norm"])
            x = x + _ffn_block(layer, h)
        return jnp.stack(picked)                                 # [N, B, S, T]

    def decoder_forward(params, tokens: jnp.ndarray, enc_out: jnp.ndarray):
        """Full teacher-forced decoder pass: tokens [B, S] -> logits
        [B, S, vocab] (fidelity tests / scoring)."""
        b, s = tokens.shape
        x = params["embed"][tokens] + params["dec_pos"][:s][None]
        causal = jnp.tril(jnp.ones((s, s), dtype=bool))
        mask = jnp.where(causal, 0.0, -jnp.inf).astype(jnp.float32)[None, None]
        t = enc_out.shape[1]
        for layer in params["dec_layers"]:
            h = _layer_norm(x, layer["attn_norm"])
            x = x + _self_attn(layer["attn"], h, mask)
            h = _layer_norm(x, layer["cross_norm"])
            qc = _heads(_proj(layer["cross"]["q"], h), b, s)
            kc = _heads(_proj(layer["cross"]["k"], enc_out), b, t)
            vc = _heads(_proj(layer["cross"]["v"], enc_out), b, t)
            cross = _mha(qc, kc, vc).reshape(b, s, d)
            x = x + _proj(layer["cross"]["o"], cross)
            h = _layer_norm(x, layer["ffn_norm"])
            x = x + _ffn_block(layer, h)
        x = _layer_norm(x, params["dec_final_norm"])
        return (x @ params["embed"].T).astype(jnp.float32)

    return SimpleNamespace(
        init=init,
        encode=encode,
        init_cache=init_cache,
        decode=decode,
        decoder_forward=decoder_forward,
        cross_attention_alignment=cross_attention_alignment,
        apply=decoder_forward,  # generic-bundle surface (unused for serving)
        config=cfg,
        n_heads=n_heads,
        head_dim=head_dim,
    )
