"""ctypes bindings for the native runtime library (libtpuserve_native.so).

Builds lazily with the in-image toolchain (`make` + g++) on first use; every
consumer must degrade gracefully to its pure-Python path when the library is
unavailable (no compiler, read-only filesystem, exotic platform).
"""

from __future__ import annotations

import ctypes
import subprocess
from pathlib import Path
from typing import List, Optional

_NATIVE_DIR = Path(__file__).parent
_LIB_PATH = _NATIVE_DIR / "libtpuserve_native.so"
_lib = None
_lib_failed = False


def load_native() -> Optional[ctypes.CDLL]:
    """The shared library, building it if needed; None if unavailable."""
    global _lib, _lib_failed
    if _lib is not None or _lib_failed:
        return _lib
    try:
        if not _LIB_PATH.exists():
            subprocess.run(
                ["make", "-s", "libtpuserve_native.so"],
                cwd=str(_NATIVE_DIR), check=True, capture_output=True, timeout=120,
            )
        lib = ctypes.CDLL(str(_LIB_PATH))
        lib.tpuserve_queue_create.restype = ctypes.c_void_p
        lib.tpuserve_queue_create.argtypes = [ctypes.c_uint64, ctypes.c_uint64]
        lib.tpuserve_queue_destroy.argtypes = [ctypes.c_void_p]
        lib.tpuserve_queue_push.restype = ctypes.c_int
        lib.tpuserve_queue_push.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32,
        ]
        lib.tpuserve_queue_pop.restype = ctypes.c_int64
        lib.tpuserve_queue_pop.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64,
        ]
        lib.tpuserve_queue_size.restype = ctypes.c_uint64
        lib.tpuserve_queue_size.argtypes = [ctypes.c_void_p]
        lib.tpuserve_queue_dropped.restype = ctypes.c_uint64
        lib.tpuserve_queue_dropped.argtypes = [ctypes.c_void_p]
        lib.tpuserve_hist_create.restype = ctypes.c_void_p
        lib.tpuserve_hist_destroy.argtypes = [ctypes.c_void_p]
        lib.tpuserve_hist_observe.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.tpuserve_hist_snapshot.restype = ctypes.c_uint64
        lib.tpuserve_hist_snapshot.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint64),
        ]
        lib.tpuserve_hist_num_buckets.restype = ctypes.c_int
        lib.tpuserve_hist_bounds.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint64),
        ]
        lib.tpuserve_hist_total_us.restype = ctypes.c_uint64
        lib.tpuserve_hist_total_us.argtypes = [ctypes.c_void_p]
        _lib = lib
    except Exception:
        _lib_failed = True
        _lib = None
    return _lib


class NativeQueue:
    """Lock-free MPSC byte-message queue (raises RuntimeError if the native
    library is unavailable — callers pick the Python fallback instead)."""

    def __init__(self, capacity: int = 4096, cell_bytes: int = 4096):
        lib = load_native()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self._cell_bytes = cell_bytes
        self._q = lib.tpuserve_queue_create(capacity, cell_bytes)
        if not self._q:
            raise RuntimeError("native queue allocation failed")
        self._buf = ctypes.create_string_buffer(cell_bytes)

    def push(self, data: bytes) -> bool:
        return bool(self._lib.tpuserve_queue_push(self._q, data, len(data)))

    def pop(self) -> Optional[bytes]:
        n = self._lib.tpuserve_queue_pop(self._q, self._buf, self._cell_bytes)
        if n <= 0:
            return None
        return self._buf.raw[:n]

    def pop_all(self, limit: int = 100000) -> List[bytes]:
        out = []
        for _ in range(limit):
            item = self.pop()
            if item is None:
                break
            out.append(item)
        return out

    def __len__(self) -> int:
        return int(self._lib.tpuserve_queue_size(self._q))

    @property
    def rejected(self) -> int:
        """Count of pushes the ring refused (full/oversized). A rejected push
        is NOT necessarily a lost message — callers may retry or fall back."""
        return int(self._lib.tpuserve_queue_dropped(self._q))

    def __del__(self):
        try:
            if getattr(self, "_q", None):
                self._lib.tpuserve_queue_destroy(self._q)
                self._q = None
        except Exception:
            pass


class NativeHistogram:
    """Thread-safe microsecond latency histogram."""

    def __init__(self):
        lib = load_native()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self._h = lib.tpuserve_hist_create()
        if not self._h:
            raise RuntimeError("native histogram allocation failed")
        self._n = int(lib.tpuserve_hist_num_buckets())

    def observe_seconds(self, seconds: float) -> None:
        self._lib.tpuserve_hist_observe(self._h, int(seconds * 1e6))

    def snapshot(self):
        counts = (ctypes.c_uint64 * self._n)()
        total = self._lib.tpuserve_hist_snapshot(self._h, counts)
        bounds = (ctypes.c_uint64 * (self._n - 1))()
        self._lib.tpuserve_hist_bounds(self._h, bounds)
        return {
            "total": int(total),
            "bounds_us": list(bounds),
            "counts": list(counts),
            "total_us": int(self._lib.tpuserve_hist_total_us(self._h)),
        }

    def __del__(self):
        try:
            if getattr(self, "_h", None):
                self._lib.tpuserve_hist_destroy(self._h)
                self._h = None
        except Exception:
            pass
