// Lock-free bounded MPSC byte-message ring queue + latency histogram.
//
// Native backend for the serving runtime's statistics hot path: request
// handlers (multiple producers: gunicorn-style worker threads) push serialized
// stat packets without taking a lock; the single stats-sender thread drains
// batches. The reference achieves this in Python with GIL-atomic counters
// (clearml-serving model_request_processor.py FastWriteCounter/FastSimpleQueue);
// here the hot path is C++ with C11-atomic semantics, exposed through a plain
// C ABI for ctypes (no pybind11 dependency in the image).
//
// Layout: a ring of fixed-size cells. Each cell has a sequence number
// (Vyukov MPMC algorithm, specialised to MPSC drain) plus a length-prefixed
// payload buffer. Push is wait-free absent contention; a full queue drops the
// message (statistics are best-effort by contract).

#include <atomic>
#include <cstdint>
#include <cstring>
#include <new>

namespace {

struct Cell {
    std::atomic<uint64_t> seq;
    uint32_t len;
    // payload bytes follow the header in the arena
};

struct Queue {
    uint64_t capacity;       // number of cells (power of two)
    uint64_t mask;
    uint64_t cell_bytes;     // payload capacity per cell
    uint64_t stride;         // bytes between cell headers
    std::atomic<uint64_t> head;  // consumer position
    std::atomic<uint64_t> tail;  // producer position
    std::atomic<uint64_t> dropped;
    unsigned char* arena;

    Cell* cell(uint64_t idx) {
        return reinterpret_cast<Cell*>(arena + (idx & mask) * stride);
    }
};

struct Histogram {
    // fixed latency buckets in microseconds; last bucket = +inf
    static const int kBuckets = 16;
    uint64_t bounds_us[kBuckets - 1];
    std::atomic<uint64_t> counts[kBuckets];
    std::atomic<uint64_t> total_count;
    std::atomic<uint64_t> total_us;
};

}  // namespace

extern "C" {

Queue* tpuserve_queue_create(uint64_t capacity_pow2, uint64_t cell_bytes) {
    uint64_t cap = 1;
    while (cap < capacity_pow2) cap <<= 1;
    Queue* q = new (std::nothrow) Queue();
    if (!q) return nullptr;
    q->capacity = cap;
    q->mask = cap - 1;
    q->cell_bytes = cell_bytes;
    // align cell stride to 64 bytes (cache line) to avoid false sharing
    uint64_t stride = sizeof(Cell) + cell_bytes;
    q->stride = (stride + 63) & ~uint64_t(63);
    q->arena = new (std::nothrow) unsigned char[q->stride * cap];
    if (!q->arena) { delete q; return nullptr; }
    for (uint64_t i = 0; i < cap; ++i) {
        q->cell(i)->seq.store(i, std::memory_order_relaxed);
        q->cell(i)->len = 0;
    }
    q->head.store(0, std::memory_order_relaxed);
    q->tail.store(0, std::memory_order_relaxed);
    q->dropped.store(0, std::memory_order_relaxed);
    return q;
}

void tpuserve_queue_destroy(Queue* q) {
    if (!q) return;
    delete[] q->arena;
    delete q;
}

// Returns 1 on success, 0 when full (message dropped) or oversized.
int tpuserve_queue_push(Queue* q, const unsigned char* data, uint32_t len) {
    if (len > q->cell_bytes) return 0;
    uint64_t pos = q->tail.load(std::memory_order_relaxed);
    for (;;) {
        Cell* c = q->cell(pos);
        uint64_t seq = c->seq.load(std::memory_order_acquire);
        int64_t diff = (int64_t)seq - (int64_t)pos;
        if (diff == 0) {
            if (q->tail.compare_exchange_weak(
                    pos, pos + 1, std::memory_order_relaxed)) {
                std::memcpy(reinterpret_cast<unsigned char*>(c) + sizeof(Cell),
                            data, len);
                c->len = len;
                c->seq.store(pos + 1, std::memory_order_release);
                return 1;
            }
        } else if (diff < 0) {
            q->dropped.fetch_add(1, std::memory_order_relaxed);
            return 0;  // full
        } else {
            pos = q->tail.load(std::memory_order_relaxed);
        }
    }
}

// Single consumer: pops one message into out (size out_cap). Returns payload
// length, 0 if empty, or -1 if out_cap too small (message left in place).
int64_t tpuserve_queue_pop(Queue* q, unsigned char* out, uint64_t out_cap) {
    uint64_t pos = q->head.load(std::memory_order_relaxed);
    Cell* c = q->cell(pos);
    uint64_t seq = c->seq.load(std::memory_order_acquire);
    int64_t diff = (int64_t)seq - (int64_t)(pos + 1);
    if (diff < 0) return 0;  // empty
    if (c->len > out_cap) return -1;
    uint32_t len = c->len;
    std::memcpy(out, reinterpret_cast<unsigned char*>(c) + sizeof(Cell), len);
    c->seq.store(pos + q->capacity, std::memory_order_release);
    q->head.store(pos + 1, std::memory_order_relaxed);
    return (int64_t)len;
}

uint64_t tpuserve_queue_size(Queue* q) {
    uint64_t tail = q->tail.load(std::memory_order_relaxed);
    uint64_t head = q->head.load(std::memory_order_relaxed);
    return tail > head ? tail - head : 0;
}

uint64_t tpuserve_queue_dropped(Queue* q) {
    return q->dropped.load(std::memory_order_relaxed);
}

// ---------------------------------------------------------------- histogram

Histogram* tpuserve_hist_create() {
    Histogram* h = new (std::nothrow) Histogram();
    if (!h) return nullptr;
    // 5ms..5s-style default ladder, in microseconds (reference bucket range)
    static const uint64_t bounds[Histogram::kBuckets - 1] = {
        500, 1000, 2500, 5000, 10000, 25000, 50000, 75000, 100000,
        250000, 500000, 750000, 1000000, 2500000, 5000000,
    };
    std::memcpy(h->bounds_us, bounds, sizeof(bounds));
    for (int i = 0; i < Histogram::kBuckets; ++i)
        h->counts[i].store(0, std::memory_order_relaxed);
    h->total_count.store(0, std::memory_order_relaxed);
    h->total_us.store(0, std::memory_order_relaxed);
    return h;
}

void tpuserve_hist_destroy(Histogram* h) { delete h; }

void tpuserve_hist_observe(Histogram* h, uint64_t us) {
    int lo = 0, hi = Histogram::kBuckets - 1;
    while (lo < hi) {
        int mid = (lo + hi) / 2;
        if (us <= h->bounds_us[mid]) hi = mid; else lo = mid + 1;
    }
    h->counts[lo].fetch_add(1, std::memory_order_relaxed);
    h->total_count.fetch_add(1, std::memory_order_relaxed);
    h->total_us.fetch_add(us, std::memory_order_relaxed);
}

// Fills counts[kBuckets], returns total_count; bounds via tpuserve_hist_bounds.
uint64_t tpuserve_hist_snapshot(Histogram* h, uint64_t* counts_out) {
    for (int i = 0; i < Histogram::kBuckets; ++i)
        counts_out[i] = h->counts[i].load(std::memory_order_relaxed);
    return h->total_count.load(std::memory_order_relaxed);
}

int tpuserve_hist_num_buckets() { return Histogram::kBuckets; }

void tpuserve_hist_bounds(Histogram* h, uint64_t* bounds_out) {
    std::memcpy(bounds_out, h->bounds_us, sizeof(h->bounds_us));
}

uint64_t tpuserve_hist_total_us(Histogram* h) {
    return h->total_us.load(std::memory_order_relaxed);
}

}  // extern "C"
