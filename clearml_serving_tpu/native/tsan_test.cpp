// ThreadSanitizer stress for the MPSC queue: N producer threads push
// length-tagged messages while one consumer drains; verifies message
// integrity and total counts. Run via `make tsan` (SURVEY.md §5.2: add a TSAN
// job for any C++ engine code).

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <cstdlib>
#include <thread>
#include <vector>

extern "C" {
struct Queue;
Queue* tpuserve_queue_create(uint64_t, uint64_t);
void tpuserve_queue_destroy(Queue*);
int tpuserve_queue_push(Queue*, const unsigned char*, uint32_t);
int64_t tpuserve_queue_pop(Queue*, unsigned char*, uint64_t);
uint64_t tpuserve_queue_dropped(Queue*);
}

int main() {
    const int kProducers = 4;
    const int kPerProducer = 50000;
    Queue* q = tpuserve_queue_create(1024, 64);

    std::vector<std::thread> producers;
    for (int p = 0; p < kProducers; ++p) {
        producers.emplace_back([q, p] {
            unsigned char msg[64];
            for (int i = 0; i < kPerProducer; ++i) {
                std::memset(msg, 'a' + p, sizeof(msg));
                uint32_t len = 8 + (i % 56);
                while (!tpuserve_queue_push(q, msg, len)) {
                    std::this_thread::yield();  // queue full; retry
                }
            }
        });
    }

    uint64_t received = 0;
    std::thread consumer([&] {
        unsigned char buf[64];
        while (received < (uint64_t)kProducers * kPerProducer) {
            int64_t n = tpuserve_queue_pop(q, buf, sizeof(buf));
            if (n > 0) {
                // integrity: all bytes identical (single producer's fill char)
                for (int64_t i = 1; i < n; ++i) {
                    if (buf[i] != buf[0]) {
                        std::fprintf(stderr, "corrupt message!\n");
                        std::abort();
                    }
                }
                ++received;
            } else {
                std::this_thread::yield();
            }
        }
    });

    for (auto& t : producers) t.join();
    consumer.join();
    std::printf("tsan_test OK: %llu messages, %llu dropped\n",
                (unsigned long long)received,
                (unsigned long long)tpuserve_queue_dropped(q));
    tpuserve_queue_destroy(q);
    return 0;
}
