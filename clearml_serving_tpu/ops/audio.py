"""Audio frontend for the speech routes: WAV decode + Whisper log-mel.

Feature extraction runs on the host CPU (numpy) — same division of labor as
the reference's vLLM transcription path; the TPU sees only the fixed-shape
mel tensor. The mel filterbank normally ships inside the converted bundle
(engines/importers/convert_hf_whisper.py stores the checkpoint's own
filters); `mel_filter_bank` is the fallback for weightless demo bundles.
"""

from __future__ import annotations

import io
from typing import Optional, Tuple

import numpy as np


def _parse_riff_float_wav(data: bytes) -> Tuple[np.ndarray, int, int]:
    """Minimal RIFF parser for IEEE-float WAVs (format 3, or EXTENSIBLE with
    a float subformat) — the stdlib ``wave`` module rejects them before any
    sample-width heuristic can run. Returns (samples, n_channels, rate)."""
    import struct

    if len(data) < 12 or data[:4] != b"RIFF" or data[8:12] != b"WAVE":
        raise ValueError("not a RIFF/WAVE file")
    pos = 12
    fmt = None
    payload = None
    while pos + 8 <= len(data):
        chunk_id = data[pos : pos + 4]
        (size,) = struct.unpack_from("<I", data, pos + 4)
        body = data[pos + 8 : pos + 8 + size]
        if chunk_id == b"fmt ":
            fmt = struct.unpack_from("<HHIIHH", body, 0)
        elif chunk_id == b"data":
            payload = body
        pos += 8 + size + (size & 1)
    if fmt is None or payload is None:
        raise ValueError("WAV missing fmt/data chunks")
    audio_format, n_channels, rate, _, _, bits = fmt
    if audio_format == 0xFFFE and len(data) >= 2:  # WAVE_FORMAT_EXTENSIBLE
        # subformat GUID's leading u16 carries the real format code
        idx = data.find(b"fmt ")
        sub = struct.unpack_from("<H", data, idx + 8 + 24)[0] if idx >= 0 else 0
        audio_format = sub
    if audio_format != 3:
        raise ValueError("unsupported WAV format code {}".format(audio_format))
    dtype = np.float32 if bits == 32 else np.float64 if bits == 64 else None
    if dtype is None:
        raise ValueError("unsupported float WAV bit depth {}".format(bits))
    samples = np.frombuffer(payload, dtype).astype(np.float32)
    return samples, int(n_channels), int(rate)


def decode_wav(data: bytes, target_rate: int = 16000) -> np.ndarray:
    """WAV bytes -> mono float32 PCM in [-1, 1] at target_rate.

    Accepts PCM8/16/32 (stdlib wave) and IEEE-float32/64 WAVs (RIFF
    fallback — soundfile/librosa's default export), any channel count
    (averaged), any rate (linear resample)."""
    import wave

    try:
        with wave.open(io.BytesIO(data)) as wav:
            n_channels = wav.getnchannels()
            width = wav.getsampwidth()
            rate = wav.getframerate()
            raw = wav.readframes(wav.getnframes())
            comp = wav.getcomptype()
    except wave.Error as ex:
        try:  # stdlib wave rejects IEEE-float (format 3) outright
            pcm, n_channels, rate = _parse_riff_float_wav(data)
        except ValueError:
            raise ValueError("not a valid WAV file: {}".format(ex))
        width = comp = None
    if comp is not None and comp not in ("NONE",):
        raise ValueError("compressed WAV ({}) is not supported".format(comp))
    if width == 2:
        pcm = np.frombuffer(raw, np.int16).astype(np.float32) / 32768.0
    elif width == 4:
        pcm = np.frombuffer(raw, np.int32).astype(np.float32) / 2147483648.0
    elif width == 1:
        pcm = (np.frombuffer(raw, np.uint8).astype(np.float32) - 128.0) / 128.0
    elif width is not None:
        raise ValueError("unsupported WAV sample width {}".format(width))
    if n_channels > 1:
        pcm = pcm.reshape(-1, n_channels).mean(axis=1)
    if rate != target_rate and len(pcm):
        n_out = int(round(len(pcm) * target_rate / rate))
        pcm = np.interp(
            np.linspace(0.0, len(pcm) - 1, n_out), np.arange(len(pcm)), pcm
        ).astype(np.float32)
    return pcm.astype(np.float32)


def mel_filter_bank(n_mels: int, n_fft: int = 400, sampling_rate: int = 16000) -> np.ndarray:
    """[n_freq, n_mels] slaney-scale filterbank (Whisper's convention).
    Fallback only — converted bundles carry the checkpoint's own filters."""
    try:
        from transformers.audio_utils import mel_filter_bank as hf_bank

        return np.asarray(
            hf_bank(
                num_frequency_bins=1 + n_fft // 2,
                num_mel_filters=n_mels,
                min_frequency=0.0,
                max_frequency=sampling_rate / 2.0,
                sampling_rate=sampling_rate,
                norm="slaney",
                mel_scale="slaney",
            ),
            np.float32,
        )
    except Exception:
        # minimal slaney implementation (triangular filters, area-normalized)
        def hz_to_mel(f):
            f = np.asarray(f, np.float64)
            mel = 3.0 * f / 200.0
            log_region = f >= 1000.0
            mel = np.where(
                log_region, 15.0 + np.log(np.maximum(f, 1e-10) / 1000.0) * (27.0 / np.log(6.4)), mel
            )
            return mel

        def mel_to_hz(m):
            m = np.asarray(m, np.float64)
            f = 200.0 * m / 3.0
            log_region = m >= 15.0
            return np.where(log_region, 1000.0 * np.exp(np.log(6.4) / 27.0 * (m - 15.0)), f)

        n_freq = 1 + n_fft // 2
        freqs = np.linspace(0, sampling_rate / 2.0, n_freq)
        mel_pts = mel_to_hz(np.linspace(hz_to_mel(0.0), hz_to_mel(sampling_rate / 2.0), n_mels + 2))
        bank = np.zeros((n_freq, n_mels), np.float64)
        for i in range(n_mels):
            lo, ctr, hi = mel_pts[i], mel_pts[i + 1], mel_pts[i + 2]
            up = (freqs - lo) / max(ctr - lo, 1e-10)
            down = (hi - freqs) / max(hi - ctr, 1e-10)
            bank[:, i] = np.maximum(0.0, np.minimum(up, down)) * (2.0 / (hi - lo))
        return bank.astype(np.float32)


def log_mel_spectrogram(
    pcm: np.ndarray,
    mel_filters: np.ndarray,
    *,
    n_fft: int = 400,
    hop_length: int = 160,
    n_samples: Optional[int] = None,
) -> np.ndarray:
    """float32 PCM -> Whisper log-mel [n_mels, n_frames].

    Matches transformers' WhisperFeatureExtractor numerics: pad/trim to
    n_samples, centered reflect-padded STFT with a periodic Hann window,
    power spectrum, mel projection, log10 clamp to (max - 8), (x + 4) / 4.
    """
    pcm = np.asarray(pcm, np.float32).reshape(-1)
    if n_samples is not None:
        if len(pcm) < n_samples:
            pcm = np.pad(pcm, (0, n_samples - len(pcm)))
        else:
            pcm = pcm[:n_samples]
    window = np.hanning(n_fft + 1)[:-1].astype(np.float64)  # periodic hann
    half = n_fft // 2
    padded = np.pad(pcm.astype(np.float64), (half, half), mode="reflect")
    n_frames = 1 + (len(padded) - n_fft) // hop_length
    idx = np.arange(n_fft)[None] + hop_length * np.arange(n_frames)[:, None]
    frames = padded[idx] * window[None]
    spec = np.abs(np.fft.rfft(frames, axis=1)) ** 2                # [F, n_freq]
    spec = spec[:-1]                                               # whisper drops the final frame
    filters = np.asarray(mel_filters, np.float64)
    if filters.shape[0] != spec.shape[1]:
        filters = filters.T                                        # accept [n_mels, n_freq]
    mel = spec @ filters                                           # [F, n_mels]
    log_spec = np.log10(np.maximum(mel, 1e-10))
    log_spec = np.maximum(log_spec, log_spec.max() - 8.0)
    return (((log_spec + 4.0) / 4.0).T).astype(np.float32)         # [n_mels, F]
