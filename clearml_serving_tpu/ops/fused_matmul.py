"""w4a16 fused dequant-matmul: Pallas TPU kernel + XLA reference.

Decode is weight-read bound (ROOFLINE gap #3): every step streams the whole
projection stack out of HBM for a handful of activation rows. int4 group
quantization (ops/quant.py: packed ``_q4`` uint8 [K//2, N] + per-(group,
out-channel) ``_scale4`` f32 [K//group, N]) stores those bytes at a quarter
of bf16 — but the saving is only real if the *HBM read* is 4-bit. The
existing XLA path (``dequantize_int4`` inlined in the consumer matmul) keeps
weights at rest int4, yet XLA materializes a bf16 operand tile between the
unpack and the dot; whether the read stays 4-bit is fusion-dependent. This
kernel makes it structural, the same way the int8 KV path did for page reads
(ops/paged_attention.py, docs/paged_kv_quant.md):

- **Packed tiles stream HBM -> VMEM raw.** The uint8 ``_q4`` operand stays in
  HBM (``memory_space=ANY``); the kernel issues manual double-buffered async
  copies of one quantization group's packed rows per step — group g+1's DMA
  flies while group g's dot runs on the MXU. The bf16 weight never exists in
  HBM, so the weight-bytes term is exactly K/2 * N.
- **Group scales stay VMEM-resident.** The tiny ``_scale4`` rows ([G, BN] f32
  per grid step, ~1/64 of the packed bytes at group 128) ride the grid
  pipeline into VMEM once and are read per group from there — they never join
  the per-group DMA plan (an f32 row is not tile-alignable for Mosaic DMA,
  the same constraint that keeps KV scale rows out of the page DMAs).
- **Unpack + scale fuse into the MXU contraction.** Nibbles unpack by
  splitting the contraction over byte lanes instead of interleaving sublanes
  (Mosaic cannot cheaply re-interleave rows): byte row j of the packed tile
  holds unpacked rows 2j (low nibble) and 2j+1 (high), so with the activation
  columns pre-split XLA-side into x_even/x_odd the group's partial product is
  ``x_even @ (lo - 8) + x_odd @ (hi - 8)``. Within one quantization group the
  scale depends only on the output channel, so it folds into the f32
  accumulation *after* the dot — one multiply per output element per group,
  never a dequantized [rows, N] tile write.

Alignment gates (hardware; ``interpret=True`` runs any shape for parity
tests — misaligned/odd shapes fall back to the XLA reference, exactly like
the paged kernel's D%128 gate):

- N % 128 == 0 and a block width in {512, 256, 128} dividing N (lane tiling);
- packed rows per group % 32 == 0, i.e. group % 64 == 0 (uint8 sublane tile
  is 32 — INT4_GROUP=128 gives 64-row packed group tiles);
- groups must divide K evenly with an even group size (nibble pairs must not
  straddle a group boundary);
- flattened activation rows M <= 256 (x lives whole in VMEM — decode /
  speculative-verify shapes; prefill's M = B*S takes the XLA path, where the
  matmul is compute-bound and operand materialization is amortized anyway).

The XLA fallback is byte-identical to the pre-kernel path (``x @
dequantize_int4(...)``), so routing every int4 matmul through
:func:`fused_int4_matmul` changes nothing on ineligible shapes or backends.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from .quant import dequantize_int4

try:  # pallas is TPU-oriented; tolerate exotic builds without it
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _PALLAS_OK = True
except Exception:  # pragma: no cover
    _PALLAS_OK = False


# flattened activation rows the kernel accepts: x ([M, K] bf16) must sit
# whole in VMEM next to the double-buffered weight tiles. 256 rows x 14336
# (llama3-8b w_down) x 2B = 7 MB — the decode/verify shapes this kernel
# exists for are far below it.
MAX_FUSED_ROWS = 256

_BLOCK_N_CANDIDATES = (512, 256, 128)


def int4_matmul_xla(x, packed, scale, dtype=None):
    """Reference: the exact pre-kernel path (``models/llama._w`` inline
    dequant) — unpack+scale in XLA, fused into the consumer matmul by the
    compiler. Byte-identical to what routing through the fused wrapper
    replaces, so fallback shapes reproduce historical streams bit for bit."""
    return x @ dequantize_int4(packed, scale, dtype or x.dtype)


def int4_kernel_unsupported_reason(
    x, packed, scale, *, interpret: bool = False
) -> Optional[str]:
    """Why (x, packed, scale) cannot take the Pallas kernel — None if it can.

    Shape/layout gates only; the caller separately requires a TPU backend
    (or ``interpret=True``). Split out so tests can assert the routing
    matrix without touching a device."""
    if not _PALLAS_OK:
        return "pallas unavailable in this jax build"
    if packed.ndim != 2 or scale.ndim != 2:
        return "kernel takes 2-D packed/scale (got {}D/{}D); stacked trees " \
               "route per layer inside the scan".format(packed.ndim, scale.ndim)
    if packed.dtype != jnp.uint8:
        return "packed weights must be uint8 nibbles"
    if not jnp.issubdtype(x.dtype, jnp.floating):
        return "activations must be floating point"
    if x.shape[-1] != packed.shape[0] * 2:
        return "K mismatch: x has {} columns, packed holds {} rows".format(
            x.shape[-1], packed.shape[0] * 2
        )
    k2, n = packed.shape
    ng = scale.shape[0]
    if scale.shape[1] != n:
        return "scale output dim {} != weight output dim {}".format(
            scale.shape[1], n
        )
    k = 2 * k2
    if ng < 1 or k % ng:
        return "{} scale groups do not divide K={}".format(ng, k)
    group_k = k // ng
    if group_k % 2:
        return "odd group size {} (nibble pairs straddle groups)".format(group_k)
    m = 1
    for d in x.shape[:-1]:
        m *= int(d)
    if m == 0:
        return "empty activation batch"
    if m > MAX_FUSED_ROWS:
        return "M={} activation rows exceed the VMEM-resident cap {} " \
               "(prefill-shaped; XLA path)".format(m, MAX_FUSED_ROWS)
    if interpret:
        return None
    # hardware tiling gates (mirrors paged_attention's D%128/sublane gates)
    gp = group_k // 2
    if gp % 32:
        return "packed group tile {} rows is not sublane-aligned " \
               "(uint8 tile is 32; need group % 64 == 0)".format(gp)
    if n % 128 or not any(n % bn == 0 for bn in _BLOCK_N_CANDIDATES):
        return "N={} is not lane-tileable (need N % 128 == 0)".format(n)
    return None


def _pick_block_n(n: int, interpret: bool) -> int:
    for bn in _BLOCK_N_CANDIDATES:
        if n % bn == 0:
            return bn
    # interpret mode runs any shape: a single full-width block
    assert interpret
    return n


def _w4a16_kernel(
    # positionally (in_specs order):
    #   xe_ref     [M, K//2] VMEM   activation columns 0,2,4,... (low nibbles)
    #   xo_ref     [M, K//2] VMEM   activation columns 1,3,5,... (high nibbles)
    #   scale_ref  [G, BN] f32 VMEM resident group scales for this N block
    #   w_hbm      [K//2, N] uint8 ANY (stays in HBM; manual DMA)
    #   out_ref    [M, BN] VMEM
    # scratch:
    #   w_buf      [2, GP, BN] uint8 VMEM (double-buffered packed group tiles)
    #   sems       [2] DMA semaphores (one per slot)
    xe_ref,
    xo_ref,
    scale_ref,
    w_hbm,
    out_ref,
    w_buf,
    sems,
    *,
    gp: int,
    ng: int,
    bn: int,
):
    i = pl.program_id(0)
    m = xe_ref.shape[0]

    def _copy(g, slot):
        return pltpu.make_async_copy(
            w_hbm.at[pl.ds(g * gp, gp), pl.ds(i * bn, bn)],
            w_buf.at[slot],
            sems.at[slot],
        )

    _copy(0, 0).start()

    def body(g, acc):
        slot = jax.lax.rem(g, 2)

        @pl.when(g + 1 < ng)
        def _prefetch():
            _copy(g + 1, jax.lax.rem(g + 1, 2)).start()

        _copy(g, slot).wait()
        # Unpack next to the MXU: nibble -> signed level in [-8, 7], cast to
        # the compute dtype (exact: 4-bit ints are representable in bf16).
        # No scale multiply here — within a group the scale is per output
        # channel only, so it rides the f32 accumulation below instead of
        # touching every weight element.
        w = w_buf[slot].astype(jnp.int32)                    # [GP, BN]
        op_dtype = xe_ref.dtype
        lo = ((w & 0xF) - 8).astype(op_dtype)                # rows 2j
        hi = ((w >> 4) - 8).astype(op_dtype)                 # rows 2j+1
        xe_g = xe_ref[:, pl.ds(g * gp, gp)]                  # [M, GP]
        xo_g = xo_ref[:, pl.ds(g * gp, gp)]
        part = jax.lax.dot_general(
            xe_g, lo, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) + jax.lax.dot_general(
            xo_g, hi, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                                    # [M, BN] f32
        srow = scale_ref[pl.ds(g, 1), :]                     # [1, BN] f32
        return acc + part * srow

    acc = jax.lax.fori_loop(0, ng, body, jnp.zeros((m, bn), jnp.float32))
    out_ref[...] = acc.astype(out_ref.dtype)


def fused_int4_matmul(
    x, packed, scale, *, dtype=None, interpret: bool = False
):
    """``x [..., K] @ dequant(packed [K//2, N], scale [G, N]) -> [..., N]``.

    The Pallas fused path runs on TPU (or under ``interpret=True``) for
    aligned decode-shaped operands; everything else takes
    :func:`int4_matmul_xla`, which is byte-identical to the historical
    inline-dequant path. ``dtype`` pins the dequant/compute dtype for the
    fallback (the model's activation dtype); the kernel output is always
    ``x.dtype``, which equals it at every model call site.
    """
    reason = int4_kernel_unsupported_reason(x, packed, scale, interpret=interpret)
    if reason is not None:
        return int4_matmul_xla(x, packed, scale, dtype)
    if not interpret and jax.devices()[0].platform != "tpu":
        return int4_matmul_xla(x, packed, scale, dtype)

    k2, n = packed.shape
    k = 2 * k2
    ng = scale.shape[0]
    gp = (k // ng) // 2
    bn = _pick_block_n(n, interpret)

    x2 = x.reshape(-1, k)
    m = x2.shape[0]
    # pre-split activation columns by nibble position so the kernel's two
    # dots contract against the low/high planes without sublane interleaves
    xe = x2[:, 0::2]
    xo = x2[:, 1::2]
    # pad rows up to the f32 sublane tile; Mosaic would mask these anyway,
    # padding keeps the block shape conservative across toolchain versions
    m_pad = -(-m // 8) * 8
    if m_pad != m:
        pad = ((0, m_pad - m), (0, 0))
        xe = jnp.pad(xe, pad)
        xo = jnp.pad(xo, pad)

    kernel = functools.partial(_w4a16_kernel, gp=gp, ng=ng, bn=bn)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=0,
        grid=(n // bn,),
        in_specs=[
            pl.BlockSpec((m_pad, k2), lambda i: (0, 0)),
            pl.BlockSpec((m_pad, k2), lambda i: (0, 0)),
            pl.BlockSpec((ng, bn), lambda i: (0, i)),
            pl.BlockSpec(memory_space=pl.ANY),   # packed weight stays in HBM
        ],
        out_specs=pl.BlockSpec((m_pad, bn), lambda i: (0, i)),
        scratch_shapes=[
            pltpu.VMEM((2, gp, bn), jnp.uint8),
            pltpu.SemaphoreType.DMA((2,)),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((m_pad, n), x.dtype),
        interpret=interpret,
    )(xe, xo, scale.astype(jnp.float32), packed)
    return out[:m].reshape(x.shape[:-1] + (n,))
