"""Paged decode attention: Pallas TPU kernel + XLA reference.

The KV cache lives in fixed-size **pages** in HBM; each sequence owns a list of
pages (its page table row). Decode attention for one new token per sequence
gathers exactly the sequence's pages — HBM traffic scales with the tokens that
exist, not with a max-length dense cache. This is the kernel behind the
≥1500 tok/s/chip target (SURVEY.md §7 hard part 2; PAPERS.md "Ragged Paged
Attention").

Canonical layout (head-major pools — the TPU tiling wants the page's
[page_size, head_dim] plane to be the trailing block):
    q            [B, Hkv, G, D]    one new token per sequence, query heads
                                   grouped under their shared KV head (GQA)
    k/v pools    [Hkv, N_pages, P, D]
    page_table   [B, pages_per_seq] int32 page ids into the pool
    lengths      [B] int32         tokens currently in each sequence

Pallas design (decode): grid (B, Hkv, pages_per_seq) with
PrefetchScalarGridSpec — the page table IS the BlockSpec index map, so the
pipeline DMAs each sequence's next page from HBM to VMEM while the previous
page's flash-accumulation runs on the VPU/MXU. Output block revisits (b, h)
across the page dimension; running max / sum / accumulator live in VMEM
scratch.

Measured (v5e, b=16 hkv=8 g=4 d=64, 16-token pages, 64 pages/seq): kernel
matches the XLA gather reference to bf16 epsilon; at this size the gather is
~1.4x faster (3.1 vs 4.3 ms) because 16xD page blocks under-fill the tile
pipeline — but the gather materializes the whole [B,T,H,D] gathered cache,
which the paged kernel never does, so the kernel wins as contexts grow.
Tuning TODO: multiple pages per grid step + bf16 accumulation of V.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

try:  # pallas is TPU-oriented; tolerate exotic builds without it
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _PALLAS_OK = True
except Exception:  # pragma: no cover
    _PALLAS_OK = False


# ----------------------------------------------------------------- reference

def paged_attention_xla(q, k_pool, v_pool, page_table, lengths):
    """Reference implementation in plain XLA ops (also the CPU fallback).

    q: [B, Hkv, G, D]; pools: [Hkv, N, P, D]; page_table: [B, PP];
    lengths: [B] -> out [B, Hkv, G, D].
    """
    b, hkv, g, d = q.shape
    _, n, p, _ = k_pool.shape
    pp = page_table.shape[1]
    # gather pages -> [Hkv, B, PP, P, D] -> [B, T, Hkv, D]-equivalent einsum order
    k = k_pool[:, page_table].reshape(hkv, b, pp * p, d)
    v = v_pool[:, page_table].reshape(hkv, b, pp * p, d)
    t_idx = jnp.arange(pp * p, dtype=jnp.int32)[None]
    valid = t_idx < lengths[:, None]                          # [B, T]
    scores = jnp.einsum(
        "bkgd,kbtd->bkgt", q, k, preferred_element_type=jnp.float32
    ) * (d ** -0.5)
    scores = jnp.where(valid[:, None, None, :], scores, -jnp.inf)
    # manual stable softmax: zero-length rows (inactive batch slots) must
    # produce zeros, not NaN, matching the Pallas kernel
    row_max = jnp.maximum(jnp.max(scores, axis=-1, keepdims=True), -1e30)
    probs = jnp.exp(scores - row_max)
    probs = jnp.where(valid[:, None, None, :], probs, 0.0)
    denom = jnp.sum(probs, axis=-1, keepdims=True)
    probs = (probs / jnp.where(denom == 0.0, 1.0, denom)).astype(v.dtype)
    out = jnp.einsum("bkgt,kbtd->bkgd", probs, v)
    return out.astype(q.dtype)


# ----------------------------------------------------------------- pallas

def _paged_attention_kernel(
    # scalar prefetch
    page_table_ref,    # [B, PP] int32 (SMEM)
    lengths_ref,       # [B] int32 (SMEM)
    # blocks
    q_ref,             # [1, 1, G, D] VMEM
    k_ref,             # [1, 1, P, D] VMEM (page selected by index map)
    v_ref,             # [1, 1, P, D] VMEM
    out_ref,           # [1, 1, G, D] VMEM (revisited across the page grid dim)
    # scratch
    m_ref,             # [G, 1] f32
    l_ref,             # [G, 1] f32
    acc_ref,           # [G, D] f32
    *,
    page_size: int,
    pages_per_seq: int,
):
    b = pl.program_id(0)
    p_idx = pl.program_id(2)

    @pl.when(p_idx == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    length = lengths_ref[b]
    page_start = p_idx * page_size
    # tokens of this page that exist (ragged tail)
    valid_in_page = jnp.clip(length - page_start, 0, page_size)

    @pl.when(valid_in_page > 0)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)                    # [G, D]
        k = k_ref[0, 0].astype(jnp.float32)                    # [P, D]
        v = v_ref[0, 0].astype(jnp.float32)                    # [P, D]
        scores = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * (q.shape[-1] ** -0.5)                              # [G, P]
        token_ids = jax.lax.broadcasted_iota(jnp.int32, scores.shape, 1)
        scores = jnp.where(token_ids < valid_in_page, scores, -jnp.inf)

        m_prev = m_ref[...][:, 0]                              # [G]
        block_max = jnp.maximum(jnp.max(scores, axis=1), -1e30)
        m_new = jnp.maximum(m_prev, block_max)                 # [G]
        probs = jnp.exp(scores - m_new[:, None])               # [G, P]
        probs = jnp.where(token_ids < valid_in_page, probs, 0.0)
        correction = jnp.exp(m_prev - m_new)                   # [G]
        l_ref[...] = (l_ref[...][:, 0] * correction + jnp.sum(probs, axis=1))[:, None]
        acc_ref[...] = acc_ref[...] * correction[:, None] + jax.lax.dot_general(
            probs, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[...] = m_new[:, None]

    @pl.when(p_idx == pages_per_seq - 1)
    def _finalize():
        l = l_ref[...][:, 0]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        out_ref[0, 0] = (acc_ref[...] / safe_l[:, None]).astype(out_ref.dtype)


def paged_attention(
    q, k_pool, v_pool, page_table, lengths, *, interpret: bool = False
):
    """Pallas paged decode attention (falls back to XLA off-TPU).

    Shapes as in :func:`paged_attention_xla` (head-major pools).
    """
    if not _PALLAS_OK:
        return paged_attention_xla(q, k_pool, v_pool, page_table, lengths)
    on_tpu = jax.devices()[0].platform == "tpu"
    if not on_tpu and not interpret:
        return paged_attention_xla(q, k_pool, v_pool, page_table, lengths)

    b, hkv, g, d = q.shape
    _, n, page_size, _ = k_pool.shape
    pages_per_seq = page_table.shape[1]

    kernel = functools.partial(
        _paged_attention_kernel, page_size=page_size, pages_per_seq=pages_per_seq
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,  # page_table, lengths
        grid=(b, hkv, pages_per_seq),
        in_specs=[
            pl.BlockSpec((1, 1, g, d), lambda b, h, p, pt, ln: (b, h, 0, 0)),
            pl.BlockSpec((1, 1, page_size, d), lambda b, h, p, pt, ln: (h, pt[b, p], 0, 0)),
            pl.BlockSpec((1, 1, page_size, d), lambda b, h, p, pt, ln: (h, pt[b, p], 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, g, d), lambda b, h, p, pt, ln: (b, h, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((g, 1), jnp.float32),
            pltpu.VMEM((g, 1), jnp.float32),
            pltpu.VMEM((g, d), jnp.float32),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, hkv, g, d), q.dtype),
        interpret=interpret,
    )(page_table, lengths, q, k_pool, v_pool)
