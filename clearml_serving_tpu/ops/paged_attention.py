"""Paged decode attention: Pallas TPU kernel + XLA reference.

The KV cache lives in fixed-size **pages** in HBM; each sequence owns a list of
pages (its page table row). Decode attention for one new token per sequence
gathers exactly the sequence's pages — HBM traffic scales with the tokens that
exist, not with a max-length dense cache. This is the kernel behind the
≥1500 tok/s/chip target (SURVEY.md §7 hard part 2; PAPERS.md "Ragged Paged
Attention").

Canonical layout (head-major pools — the TPU tiling wants the page's
[page_size, head_dim] plane to be the trailing block):
    q            [B, Hkv, G, D]    one new token per sequence, query heads
                                   grouped under their shared KV head (GQA)
    k/v pools    [Hkv, N_pages, P, D]
    page_table   [B, pages_per_seq] int32 page ids into the pool
    lengths      [B] int32         tokens currently in each sequence

Pallas design (decode, r2 rewrite): grid (B, Hkv); the kernel owns the whole
sequence. K/V pools stay in HBM (memory_space=ANY); the kernel issues manual
double-buffered async copies of ``pages_per_block`` pages at a time into VMEM
scratch — block i+1's DMAs fly while block i's flash update runs on the MXU.
Three wins over the r1 BlockSpec-pipeline version (one page per grid step):

- **No dead traffic**: pages past a sequence's length are never copied. The
  r1 grid iterated all pages_per_seq steps, and the BlockSpec pipeline DMA'd
  every page before ``@pl.when`` skipped its compute — HBM traffic scaled
  with max capacity, not actual tokens, forfeiting paged attention's point.
- **MXU-sized blocks**: flash updates see [G, pages_per_block*P] score tiles
  (512 wide at the measured-best pb=32 default) instead of [G, 16] slivers.
- **bf16 operand feed**: K/V stream into the dot products in pool dtype
  (bf16) with f32 accumulation (preferred_element_type) — half the DMA bytes
  of the r1 kernel's eager f32 casts.

r1 measurement (v5e, b=16 hkv=8 g=4 d=64, 16-token pages, 64 pages/seq):
the one-page-per-step kernel matched the XLA gather to bf16 epsilon but ran
~1.4x slower (4.3 vs 3.1 ms). The rewrite flipped that.

r3 measurement (v5e via axon tunnel, 2026-07-29; benchmarks/paged_bench.py,
b=16 hkv=8 g=4 **d=128** — Llama-3's real head_dim; d=64 cannot lane-align
on Mosaic and takes the XLA fallback by construction — 16-token pages,
64 pages/seq, 512 live tokens):

    pallas pb=32   2.391 ms   <- default (1.15x faster than the gather)
    pallas pb=16   2.662 ms
    xla_gather     2.744 ms
    dense_fullcap  2.560 ms
    pallas pb=8    3.290 ms
    pallas pb=4    2.817 ms

Output matches the XLA reference to bf16 epsilon on hardware (maxdiff
0.002). Raw run lines live in benchmarks/TPU_RESULTS.jsonl (the
``post_fix_d128`` records; the errored pallas_pb* lines above them are this
same kernel BEFORE the fixes). Mosaic portability notes baked into the
kernel: never insert a
minor dim on an i1 vector (build masks via 2-D i32 iota compares), and DMA
slices must be lane-aligned (D % 128 == 0 gates the Pallas path).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

try:  # pallas is TPU-oriented; tolerate exotic builds without it
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _PALLAS_OK = True
except Exception:  # pragma: no cover
    _PALLAS_OK = False


# ----------------------------------------------------------------- reference

def paged_attention_xla(q, k_pool, v_pool, page_table, lengths):
    """Reference implementation in plain XLA ops (also the CPU fallback).

    q: [B, Hkv, G, D]; pools: [Hkv, N, P, D]; page_table: [B, PP];
    lengths: [B] -> out [B, Hkv, G, D].
    """
    b, hkv, g, d = q.shape
    _, n, p, _ = k_pool.shape
    pp = page_table.shape[1]
    # gather pages -> [Hkv, B, PP, P, D] -> [B, T, Hkv, D]-equivalent einsum order
    k = k_pool[:, page_table].reshape(hkv, b, pp * p, d)
    v = v_pool[:, page_table].reshape(hkv, b, pp * p, d)
    t_idx = jnp.arange(pp * p, dtype=jnp.int32)[None]
    valid = t_idx < lengths[:, None]                          # [B, T]
    scores = jnp.einsum(
        "bkgd,kbtd->bkgt", q, k, preferred_element_type=jnp.float32
    ) * (d ** -0.5)
    scores = jnp.where(valid[:, None, None, :], scores, -jnp.inf)
    # manual stable softmax: zero-length rows (inactive batch slots) must
    # produce zeros, not NaN, matching the Pallas kernel
    row_max = jnp.maximum(jnp.max(scores, axis=-1, keepdims=True), -1e30)
    probs = jnp.exp(scores - row_max)
    probs = jnp.where(valid[:, None, None, :], probs, 0.0)
    denom = jnp.sum(probs, axis=-1, keepdims=True)
    probs = (probs / jnp.where(denom == 0.0, 1.0, denom)).astype(v.dtype)
    out = jnp.einsum("bkgt,kbtd->bkgd", probs, v)
    return out.astype(q.dtype)


# ----------------------------------------------------------------- pallas

def _paged_attention_kernel(
    # scalar prefetch
    page_table_ref,    # [B, PP] int32 (SMEM)
    lengths_ref,       # [B] int32 (SMEM)
    # blocks
    q_ref,             # [1, 1, G, D] VMEM
    k_hbm,             # [Hkv, N, P, D] ANY (stays in HBM)
    v_hbm,             # [Hkv, N, P, D] ANY
    out_ref,           # [1, 1, G, D] VMEM
    # scratch
    k_buf,             # [2, PB*P, D] VMEM (double-buffered page blocks)
    v_buf,             # [2, PB*P, D] VMEM
    sems,              # [2, PB, 2] DMA semaphores (slot, page-in-block, k/v)
    *,
    page_size: int,
    pages_per_block: int,
):
    b = pl.program_id(0)
    h = pl.program_id(1)
    g, d = q_ref.shape[2], q_ref.shape[3]
    p = page_size
    pb = pages_per_block
    length = lengths_ref[b]
    block_tokens = pb * p
    # blocks that contain live tokens; DMA never touches pages past length
    n_blocks = (length + block_tokens - 1) // block_tokens

    def _copies(block_idx, slot, j):
        page_idx = block_idx * pb + j
        page = page_table_ref[b, page_idx]
        dst = pl.ds(j * p, p)
        return (
            pltpu.make_async_copy(
                k_hbm.at[h, page], k_buf.at[slot, dst], sems.at[slot, j, 0]
            ),
            pltpu.make_async_copy(
                v_hbm.at[h, page], v_buf.at[slot, dst], sems.at[slot, j, 1]
            ),
        )

    def start_block(block_idx, slot):
        for j in range(pb):  # static unroll; ragged tail gated per page
            @pl.when((block_idx * pb + j) * p < length)
            def _start(j=j):
                ck, cv = _copies(block_idx, slot, j)
                ck.start()
                cv.start()

    def wait_block(block_idx, slot):
        for j in range(pb):
            @pl.when((block_idx * pb + j) * p < length)
            def _wait(j=j):
                ck, cv = _copies(block_idx, slot, j)
                ck.wait()
                cv.wait()

    @pl.when(n_blocks > 0)
    def _run():
        start_block(0, 0)

        def body(i, carry):
            m_prev, l_prev, acc_prev = carry
            slot = jax.lax.rem(i, 2)

            @pl.when(i + 1 < n_blocks)
            def _prefetch():
                start_block(i + 1, jax.lax.rem(i + 1, 2))

            wait_block(i, slot)
            # K/V feed the MXU in pool dtype (bf16) with f32 accumulation
            q = q_ref[0, 0]                                     # [G, D]
            k = k_buf[slot]                                     # [PB*P, D]
            v = v_buf[slot]
            scores = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * (d ** -0.5)                                     # [G, PB*P]
            token_ids = (
                i * block_tokens
                + jax.lax.broadcasted_iota(jnp.int32, scores.shape, 1)
            )
            valid = token_ids < length
            scores = jnp.where(valid, scores, -jnp.inf)
            # rows past length were never DMA'd: their buffer bytes are
            # arbitrary (NaN/inf poisons 0*v), so zero them before the matmul.
            # Mask built as a 2-D i32 iota compare: Mosaic cannot insert a
            # minor dim on an i1 vector (bool[:, None] fails to compile).
            row_ids = i * block_tokens + jax.lax.broadcasted_iota(
                jnp.int32, (block_tokens, 1), 0
            )
            v = jnp.where(row_ids < length, v, jnp.zeros_like(v))

            block_max = jnp.maximum(jnp.max(scores, axis=1), -1e30)
            m_new = jnp.maximum(m_prev, block_max)              # [G]
            probs = jnp.exp(scores - m_new[:, None])            # [G, PB*P]
            probs = jnp.where(valid, probs, 0.0)
            correction = jnp.exp(m_prev - m_new)                # [G]
            l_new = l_prev * correction + jnp.sum(probs, axis=1)
            acc_new = acc_prev * correction[:, None] + jax.lax.dot_general(
                probs.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            return m_new, l_new, acc_new

        m0 = jnp.full((g,), -jnp.inf, jnp.float32)
        l0 = jnp.zeros((g,), jnp.float32)
        acc0 = jnp.zeros((g, d), jnp.float32)
        _, l, acc = jax.lax.fori_loop(0, n_blocks, body, (m0, l0, acc0))
        safe_l = jnp.where(l == 0.0, 1.0, l)
        out_ref[0, 0] = (acc / safe_l[:, None]).astype(out_ref.dtype)

    @pl.when(n_blocks == 0)
    def _empty():
        out_ref[0, 0] = jnp.zeros((g, d), out_ref.dtype)


def paged_attention(
    q, k_pool, v_pool, page_table, lengths, *,
    pages_per_block: int = 32, interpret: bool = False,
):
    """Pallas paged decode attention (falls back to XLA off-TPU).

    Shapes as in :func:`paged_attention_xla` (head-major pools).
    ``pages_per_block``: pages flash-processed per MXU block (DMA'd together,
    double-buffered against the previous block's compute).
    """
    if not _PALLAS_OK:
        return paged_attention_xla(q, k_pool, v_pool, page_table, lengths)
    on_tpu = jax.devices()[0].platform == "tpu"
    if not on_tpu and not interpret:
        return paged_attention_xla(q, k_pool, v_pool, page_table, lengths)
    if on_tpu and not interpret and (
        q.shape[-1] % 128 != 0 or k_pool.shape[2] % 16 != 0
    ):
        # Mosaic requires DMA slices tile-aligned: a [P, D] page plane with
        # D < 128 cannot be sliced out of the pool (measured on v5e: D=64
        # fails "slice shape along dimension 3 must be aligned to tiling"),
        # and a page_size off the 16-sublane bf16 tile would misalign the
        # k_buf/v_buf destination offsets (j*P). Known-misaligned shapes
        # route to the XLA gather instead of failing at compile time;
        # Llama-class heads (D=128, 16-token pages) take the kernel.
        return paged_attention_xla(q, k_pool, v_pool, page_table, lengths)

    b, hkv, g, d = q.shape
    _, n, page_size, _ = k_pool.shape
    pages_per_seq = page_table.shape[1]
    pb = max(1, min(pages_per_block, pages_per_seq))

    kernel = functools.partial(
        _paged_attention_kernel,
        page_size=page_size,
        pages_per_block=pb,
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,  # page_table, lengths
        grid=(b, hkv),
        in_specs=[
            pl.BlockSpec((1, 1, g, d), lambda b, h, pt, ln: (b, h, 0, 0)),
            pl.BlockSpec(memory_space=pl.ANY),   # K pool stays in HBM
            pl.BlockSpec(memory_space=pl.ANY),   # V pool stays in HBM
        ],
        out_specs=pl.BlockSpec((1, 1, g, d), lambda b, h, pt, ln: (b, h, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((2, pb * page_size, d), k_pool.dtype),
            pltpu.VMEM((2, pb * page_size, d), v_pool.dtype),
            pltpu.SemaphoreType.DMA((2, pb, 2)),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, hkv, g, d), q.dtype),
        interpret=interpret,
    )(page_table, lengths, q, k_pool, v_pool)
