"""Paged decode attention: Pallas TPU kernel + XLA reference.

The KV cache lives in fixed-size **pages** in HBM; each sequence owns a list of
pages (its page table row). Decode attention for one new token per sequence
gathers exactly the sequence's pages — HBM traffic scales with the tokens that
exist, not with a max-length dense cache. This is the kernel behind the
≥1500 tok/s/chip target (SURVEY.md §7 hard part 2; PAPERS.md "Ragged Paged
Attention").

Canonical layout (head-major pools — the TPU tiling wants the page's
[page_size, head_dim] plane to be the trailing block):
    q            [B, Hkv, G, D]    one new token per sequence, query heads
                                   grouped under their shared KV head (GQA)
    k/v pools    [Hkv, N_pages, P, D]
    page_table   [B, pages_per_seq] int32 page ids into the pool
    lengths      [B] int32         tokens currently in each sequence

Pallas design (decode, r2 rewrite): grid (B, Hkv); the kernel owns the whole
sequence. K/V pools stay in HBM (memory_space=ANY); the kernel issues manual
double-buffered async copies of ``pages_per_block`` pages at a time into VMEM
scratch — block i+1's DMAs fly while block i's flash update runs on the MXU.
Three wins over the r1 BlockSpec-pipeline version (one page per grid step):

- **No dead traffic**: pages past a sequence's length are never copied. The
  r1 grid iterated all pages_per_seq steps, and the BlockSpec pipeline DMA'd
  every page before ``@pl.when`` skipped its compute — HBM traffic scaled
  with max capacity, not actual tokens, forfeiting paged attention's point.
- **MXU-sized blocks**: flash updates see [G, pages_per_block*P] score tiles
  (512 wide at the measured-best pb=32 default) instead of [G, 16] slivers.
- **bf16 operand feed**: K/V stream into the dot products in pool dtype
  (bf16) with f32 accumulation (preferred_element_type) — half the DMA bytes
  of the r1 kernel's eager f32 casts.

r1 measurement (v5e, b=16 hkv=8 g=4 d=64, 16-token pages, 64 pages/seq):
the one-page-per-step kernel matched the XLA gather to bf16 epsilon but ran
~1.4x slower (4.3 vs 3.1 ms). The rewrite flipped that.

r3 measurement (v5e via axon tunnel, 2026-07-29; benchmarks/paged_bench.py,
b=16 hkv=8 g=4 **d=128** — Llama-3's real head_dim; d=64 cannot lane-align
on Mosaic and takes the XLA fallback by construction — 16-token pages,
64 pages/seq, 512 live tokens):

    pallas pb=32   2.391 ms   <- default (1.15x faster than the gather)
    pallas pb=16   2.662 ms
    xla_gather     2.744 ms
    dense_fullcap  2.560 ms
    pallas pb=8    3.290 ms
    pallas pb=4    2.817 ms

Output matches the XLA reference to bf16 epsilon on hardware (maxdiff
0.002). Raw run lines live in benchmarks/TPU_RESULTS.jsonl (the
``post_fix_d128`` records; the errored pallas_pb* lines above them are this
same kernel BEFORE the fixes). Mosaic portability notes baked into the
kernel: never insert a
minor dim on an i1 vector (build masks via 2-D i32 iota compares), and DMA
slices must be lane-aligned (D % 128 == 0 gates the Pallas path).

int8 paged KV (r4, docs/paged_kv_quant.md): pools may store int8 with a
per-(token, head) f32 scale pool ``[Hkv, N, P]`` beside each side —
``k_scale``/``v_scale`` operands. The kernel streams the int8 pages through
the SAME manual double-buffered DMA plan (half the bytes of bf16: the
dominant decode DMA term), and dequantization fuses into the flash update
next to the MXU:

- K side: the dot runs on the raw int8 block cast to the compute dtype
  (int8 -> bf16 is LOSSLESS: 8-bit mantissa covers [-127, 127]) and the
  f32 scores multiply by ``k_scale`` per key column — algebraically the
  dequantized matmul, without materializing a dequantized [PB*P, D] tile.
- V side: the f32 probs multiply by ``v_scale`` per value row before the
  PV dot — same fusion.

Scales do NOT ride the per-page DMA plan: an f32 scale row is [P] (16-64
lanes), and Mosaic requires DMA slices tile-aligned — the same constraint
that gates D % 128 would reject every scale-row copy. Instead the tiny
scale vectors (4 bytes per token-head vs 128+ data bytes) are pre-gathered
by XLA into a lane-aligned [B, Hkv, 1, PP*P] operand that the grid
pipeline DMAs into VMEM like any blocked input. The gather reads scale
rows at table capacity rather than live length; that dead traffic is
bounded by scale_bytes/kv_bytes = 4/D of the int8 stream (~3% at D=128).

Alignment gates for the int8 path: D % 128 == 0 (unchanged) and
page_size % 32 == 0 on hardware — the int8 tile is (32, 128), so a 16-row
page plane cannot be sliced out of an int8 pool (bf16's 16-sublane tile
could). Misaligned int8 shapes (including the default 16-token pages)
route to the XLA gather, exactly like D=64 does today; interpret=True
exercises the kernel on any shape.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

try:  # pallas is TPU-oriented; tolerate exotic builds without it
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _PALLAS_OK = True
except Exception:  # pragma: no cover
    _PALLAS_OK = False


# ----------------------------------------------------------------- reference

def paged_attention_xla(q, k_pool, v_pool, page_table, lengths,
                        k_scale=None, v_scale=None):
    """Reference implementation in plain XLA ops (also the CPU fallback).

    q: [B, Hkv, G, D]; pools: [Hkv, N, P, D]; page_table: [B, PP];
    lengths: [B] -> out [B, Hkv, G, D].

    ``k_scale``/``v_scale`` ([Hkv, N, P] f32) dequantize int8 pools: the
    per-(token, head) symmetric scales of models/llama._kv_store. Dequant
    happens in f32 and casts to the query dtype before the attention math,
    mirroring the dense path's _kv_load, so XLA fuses it into the gather.
    """
    b, hkv, g, d = q.shape
    _, n, p, _ = k_pool.shape
    pp = page_table.shape[1]
    # gather pages -> [Hkv, B, PP, P, D] -> [B, T, Hkv, D]-equivalent einsum order
    k = k_pool[:, page_table].reshape(hkv, b, pp * p, d)
    v = v_pool[:, page_table].reshape(hkv, b, pp * p, d)
    if k_scale is not None:
        ks = k_scale[:, page_table].reshape(hkv, b, pp * p, 1)
        vs = v_scale[:, page_table].reshape(hkv, b, pp * p, 1)
        k = (k.astype(jnp.float32) * ks).astype(q.dtype)
        v = (v.astype(jnp.float32) * vs).astype(q.dtype)
    t_idx = jnp.arange(pp * p, dtype=jnp.int32)[None]
    valid = t_idx < lengths[:, None]                          # [B, T]
    scores = jnp.einsum(
        "bkgd,kbtd->bkgt", q, k, preferred_element_type=jnp.float32
    ) * (d ** -0.5)
    scores = jnp.where(valid[:, None, None, :], scores, -jnp.inf)
    # manual stable softmax: zero-length rows (inactive batch slots) must
    # produce zeros, not NaN, matching the Pallas kernel
    row_max = jnp.maximum(jnp.max(scores, axis=-1, keepdims=True), -1e30)
    probs = jnp.exp(scores - row_max)
    probs = jnp.where(valid[:, None, None, :], probs, 0.0)
    denom = jnp.sum(probs, axis=-1, keepdims=True)
    probs = (probs / jnp.where(denom == 0.0, 1.0, denom)).astype(v.dtype)
    out = jnp.einsum("bkgt,kbtd->bkgd", probs, v)
    return out.astype(q.dtype)


# ----------------------------------------------------------------- pallas

def _paged_attention_kernel(
    # scalar prefetch
    page_table_ref,    # [B, PP] int32 (SMEM)
    lengths_ref,       # [B] int32 (SMEM)
    # then, positionally (in_specs order):
    #   q_ref            [1, 1, G, D] VMEM
    #   k_hbm            [Hkv, N, P, D] ANY (stays in HBM)
    #   v_hbm            [Hkv, N, P, D] ANY
    #   k_scale_ref      [1, 1, 1, PP*P] f32 VMEM   (quantized=True only:
    #   v_scale_ref      [1, 1, 1, PP*P] f32 VMEM    pre-gathered per-token
    #                    scales in sequence order — module docstring)
    #   out_ref          [1, 1, G, D] VMEM
    # scratch:
    #   k_buf            [2, PB*P, D] VMEM (double-buffered page blocks)
    #   v_buf            [2, PB*P, D] VMEM
    #   sems             [2, PB, 2] DMA semaphores (slot, page-in-block, k/v)
    *refs,
    page_size: int,
    pages_per_block: int,
    quantized: bool = False,
):
    if quantized:
        (q_ref, k_hbm, v_hbm, k_scale_ref, v_scale_ref,
         out_ref, k_buf, v_buf, sems) = refs
    else:
        q_ref, k_hbm, v_hbm, out_ref, k_buf, v_buf, sems = refs
        k_scale_ref = v_scale_ref = None
    b = pl.program_id(0)
    h = pl.program_id(1)
    g, d = q_ref.shape[2], q_ref.shape[3]
    p = page_size
    pb = pages_per_block
    length = lengths_ref[b]
    block_tokens = pb * p
    # blocks that contain live tokens; DMA never touches pages past length
    n_blocks = (length + block_tokens - 1) // block_tokens

    def _copies(block_idx, slot, j):
        page_idx = block_idx * pb + j
        page = page_table_ref[b, page_idx]
        dst = pl.ds(j * p, p)
        return (
            pltpu.make_async_copy(
                k_hbm.at[h, page], k_buf.at[slot, dst], sems.at[slot, j, 0]
            ),
            pltpu.make_async_copy(
                v_hbm.at[h, page], v_buf.at[slot, dst], sems.at[slot, j, 1]
            ),
        )

    def start_block(block_idx, slot):
        for j in range(pb):  # static unroll; ragged tail gated per page
            @pl.when((block_idx * pb + j) * p < length)
            def _start(j=j):
                ck, cv = _copies(block_idx, slot, j)
                ck.start()
                cv.start()

    def wait_block(block_idx, slot):
        for j in range(pb):
            @pl.when((block_idx * pb + j) * p < length)
            def _wait(j=j):
                ck, cv = _copies(block_idx, slot, j)
                ck.wait()
                cv.wait()

    @pl.when(n_blocks > 0)
    def _run():
        start_block(0, 0)

        def body(i, carry):
            m_prev, l_prev, acc_prev = carry
            slot = jax.lax.rem(i, 2)

            @pl.when(i + 1 < n_blocks)
            def _prefetch():
                start_block(i + 1, jax.lax.rem(i + 1, 2))

            wait_block(i, slot)
            # K/V feed the MXU in pool dtype (bf16) with f32 accumulation.
            # int8 pools (quantized): the block feeds the dot as raw int8
            # cast to the output compute dtype — int8 -> bf16 is lossless —
            # and the per-token scales fold into the f32 scores/probs, so
            # dequant fuses into the flash update without materializing a
            # dequantized tile (module docstring).
            q = q_ref[0, 0]                                     # [G, D]
            k = k_buf[slot]                                     # [PB*P, D]
            v = v_buf[slot]
            if quantized:
                op_dtype = out_ref.dtype
                k = k.astype(op_dtype)
                v = v.astype(op_dtype)
            scores = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * (d ** -0.5)                                     # [G, PB*P]
            if quantized:
                # scale rows of pages past length come from the gathered
                # null-page padding: finite garbage, masked right below
                k_s = k_scale_ref[0, 0, :, pl.ds(i * block_tokens,
                                                 block_tokens)]  # [1, PB*P]
                scores = scores * k_s
            token_ids = (
                i * block_tokens
                + jax.lax.broadcasted_iota(jnp.int32, scores.shape, 1)
            )
            valid = token_ids < length
            scores = jnp.where(valid, scores, -jnp.inf)
            # rows past length were never DMA'd: their buffer bytes are
            # arbitrary (NaN/inf poisons 0*v), so zero them before the matmul.
            # (int8 garbage is always finite, but the zeroing also keeps the
            # masked rows from polluting the scaled-probs matmul below.)
            # Mask built as a 2-D i32 iota compare: Mosaic cannot insert a
            # minor dim on an i1 vector (bool[:, None] fails to compile).
            row_ids = i * block_tokens + jax.lax.broadcasted_iota(
                jnp.int32, (block_tokens, 1), 0
            )
            v = jnp.where(row_ids < length, v, jnp.zeros_like(v))

            block_max = jnp.maximum(jnp.max(scores, axis=1), -1e30)
            m_new = jnp.maximum(m_prev, block_max)              # [G]
            probs = jnp.exp(scores - m_new[:, None])            # [G, PB*P]
            probs = jnp.where(valid, probs, 0.0)
            correction = jnp.exp(m_prev - m_new)                # [G]
            # the softmax denominator sums the UNSCALED probs; v_scale
            # belongs only to the PV product
            l_new = l_prev * correction + jnp.sum(probs, axis=1)
            pv = probs
            if quantized:
                # V dequant folded into the probs (per value row); probs are
                # zero past length, so garbage scales multiply into zeros
                v_s = v_scale_ref[0, 0, :, pl.ds(i * block_tokens,
                                                 block_tokens)]  # [1, PB*P]
                pv = probs * v_s
            acc_new = acc_prev * correction[:, None] + jax.lax.dot_general(
                pv.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            return m_new, l_new, acc_new

        m0 = jnp.full((g,), -jnp.inf, jnp.float32)
        l0 = jnp.zeros((g,), jnp.float32)
        acc0 = jnp.zeros((g, d), jnp.float32)
        _, l, acc = jax.lax.fori_loop(0, n_blocks, body, (m0, l0, acc0))
        safe_l = jnp.where(l == 0.0, 1.0, l)
        out_ref[0, 0] = (acc / safe_l[:, None]).astype(out_ref.dtype)

    @pl.when(n_blocks == 0)
    def _empty():
        out_ref[0, 0] = jnp.zeros((g, d), out_ref.dtype)


def paged_attention(
    q, k_pool, v_pool, page_table, lengths, *,
    k_scale=None, v_scale=None,
    pages_per_block: int = 32, interpret: bool = False,
):
    """Pallas paged decode attention (falls back to XLA off-TPU).

    Shapes as in :func:`paged_attention_xla` (head-major pools).
    ``pages_per_block``: pages flash-processed per MXU block (DMA'd together,
    double-buffered against the previous block's compute).
    ``k_scale``/``v_scale`` ([Hkv, N, P] f32): per-(token, head) dequant
    scales for int8 pools (required when the pools are int8); dequant fuses
    into the in-kernel flash update (module docstring).
    """
    quantized = k_scale is not None
    if jnp.issubdtype(k_pool.dtype, jnp.signedinteger) and not quantized:
        raise ValueError(
            "int8 KV pools need k_scale/v_scale operands (per-token dequant)"
        )
    if not _PALLAS_OK:
        return paged_attention_xla(
            q, k_pool, v_pool, page_table, lengths, k_scale, v_scale
        )
    on_tpu = jax.devices()[0].platform == "tpu"
    if not on_tpu and not interpret:
        return paged_attention_xla(
            q, k_pool, v_pool, page_table, lengths, k_scale, v_scale
        )
    # Mosaic requires DMA slices tile-aligned: a [P, D] page plane with
    # D < 128 cannot be sliced out of the pool (measured on v5e: D=64
    # fails "slice shape along dimension 3 must be aligned to tiling"),
    # and a page_size off the sublane tile would misalign the k_buf/v_buf
    # destination offsets (j*P). The sublane tile is dtype-dependent: 16
    # for bf16 pools, 32 for int8 (module docstring) — so the int8 path
    # needs 32-token pages on hardware. Known-misaligned shapes route to
    # the XLA gather instead of failing at compile time; Llama-class heads
    # (D=128) take the kernel.
    min_sublane = 32 if k_pool.dtype.itemsize == 1 else 16
    if on_tpu and not interpret and (
        q.shape[-1] % 128 != 0 or k_pool.shape[2] % min_sublane != 0
    ):
        return paged_attention_xla(
            q, k_pool, v_pool, page_table, lengths, k_scale, v_scale
        )

    b, hkv, g, d = q.shape
    _, n, page_size, _ = k_pool.shape
    pages_per_seq = page_table.shape[1]
    pb = max(1, min(pages_per_block, pages_per_seq))
    cap = pages_per_seq * page_size

    kernel = functools.partial(
        _paged_attention_kernel,
        page_size=page_size,
        pages_per_block=pb,
        quantized=quantized,
    )
    in_specs = [
        pl.BlockSpec((1, 1, g, d), lambda b, h, pt, ln: (b, h, 0, 0)),
        pl.BlockSpec(memory_space=pl.ANY),   # K pool stays in HBM
        pl.BlockSpec(memory_space=pl.ANY),   # V pool stays in HBM
    ]
    inputs = [q, k_pool, v_pool]
    if quantized:
        # pre-gather the tiny scale vectors into sequence order (XLA-side:
        # scale rows are not tile-aligned for the per-page DMA plan — see
        # module docstring); the grid pipeline DMAs each row into VMEM.
        # [Hkv, N, P] -> [Hkv, B, PP, P] -> [B, Hkv, 1, PP*P], padded up to
        # a block-token multiple: the kernel slices fixed block_tokens-wide
        # windows, and when pages_per_seq % pb != 0 the last window would
        # run past cap — dynamic-slice CLAMPING would then silently feed
        # valid tokens the wrong rows' scales.
        block_tokens = pb * page_size
        cap_pad = -(-cap // block_tokens) * block_tokens
        pad = ((0, 0), (0, 0), (0, 0), (0, cap_pad - cap))

        def gather(scale):
            seq = jnp.moveaxis(
                scale[:, page_table].reshape(hkv, b, cap), 0, 1
            ).reshape(b, hkv, 1, cap)
            return jnp.pad(seq, pad)

        in_specs += [
            pl.BlockSpec((1, 1, 1, cap_pad), lambda b, h, pt, ln: (b, h, 0, 0)),
            pl.BlockSpec((1, 1, 1, cap_pad), lambda b, h, pt, ln: (b, h, 0, 0)),
        ]
        inputs += [gather(k_scale), gather(v_scale)]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,  # page_table, lengths
        grid=(b, hkv),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, g, d), lambda b, h, pt, ln: (b, h, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((2, pb * page_size, d), k_pool.dtype),
            pltpu.VMEM((2, pb * page_size, d), v_pool.dtype),
            pltpu.SemaphoreType.DMA((2, pb, 2)),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, hkv, g, d), q.dtype),
        interpret=interpret,
    )(page_table, lengths, *inputs)


# ----------------------------------------------------------- ragged (mixed)

# Ragged paged attention (PAPERS.md "Ragged Paged Attention",
# docs/ragged_attention.md): ONE kernel over a batch whose rows sit at
# arbitrary phases — a decode row contributes one query token, a prefill row
# contributes a whole prompt chunk. The queries of all rows flatten into one
# token-major operand; per-row offsets/lengths ride in SMEM. This is what
# lets the engine's token-budget scheduler put chunked prefill and decode in
# a single launch instead of two dispatches (llm/engine.py ragged mode).
#
# Layout:
#     q           [T, Hkv, G, D]   flattened ragged queries: row r occupies
#                                  q[row_starts[r] : row_starts[r]+row_lens[r]]
#     page_table  [R, PP]          one row per batch row (same pools/ids as
#                                  the decode kernel above)
#     kv_lens     [R]              tokens present per row INCLUDING this
#                                  step's chunk (K/V are written before the
#                                  attention call, like decode_paged)
#     row_starts  [R], row_lens [R]  the ragged row map (row_lens 0 = idle)
#
# Causality: query i of row r sits at absolute position
# kv_lens[r] - row_lens[r] + i and attends KV positions <= its own — decode
# rows (row_lens 1) degenerate to exactly the decode kernel's masking,
# prefill rows get the standard causal triangle against their own history.
#
# Pallas design: the grid runs (T/QB, Hkv) where QB (`q_block`) is a small
# static query block. The flattened layout is Q-BLOCK ALIGNED — every row's
# segment starts at a QB boundary (ragged_layout below builds it), so each
# q block belongs to exactly ONE row and the host passes that mapping as two
# scalar-prefetch vectors (block_rows / block_q0). Each grid step re-uses the
# decode kernel's manual double-buffered page-DMA plan against its row's
# pages — including the int8 path's pre-gathered per-row scale operands,
# which pipeline per BLOCK via an index map that reads block_rows — and runs
# the flash update on a [QB*G, pages_per_block*P] score tile. Pages past the
# block's causal bound are never copied: a prefill chunk's early q blocks
# stop their DMA train at their own triangle's edge.

_RAGGED_QB = 8  # default query block (sublane-friendly; decode rows pad to it)


def ragged_layout(row_lens, q_block: int = _RAGGED_QB, total: int | None = None):
    """Host-side layout of a ragged batch: returns (row_starts [R],
    block_rows [NB], block_q0 [NB], t_pad) as numpy int32, with every row's
    flat segment aligned to ``q_block`` (the kernel's one-row-per-q-block
    contract). ``total`` pads the flat token axis to a fixed static size so
    engine traces stay bucketed; blocks not owned by any row carry -1."""
    import numpy as np

    lens = np.asarray(row_lens, np.int32)
    starts = np.zeros(lens.shape[0], np.int32)
    off = 0
    for r, n in enumerate(lens):
        starts[r] = off
        if n > 0:
            off += -(-int(n) // q_block) * q_block
    t_pad = -(-max(off, 1) // q_block) * q_block
    if total is not None:
        if total < t_pad:
            raise ValueError(
                "ragged layout needs {} tokens but total={}".format(t_pad, total)
            )
        t_pad = -(-int(total) // q_block) * q_block
    nb = t_pad // q_block
    block_rows = np.full(nb, -1, np.int32)
    block_q0 = np.zeros(nb, np.int32)
    for r, n in enumerate(lens):
        if n <= 0:
            continue
        b0 = int(starts[r]) // q_block
        for j in range(-(-int(n) // q_block)):
            block_rows[b0 + j] = r
            block_q0[b0 + j] = j * q_block
    return starts, block_rows, block_q0, int(t_pad)


def tree_ancestors(parents, n_nodes=None, *, width=None):
    """Host-side tree-topology mask metadata for a verify row
    (docs/spec_decode_trees.md): per-node ancestor lists.

    ``parents`` [N] int32 with ``parents[0] == -1`` and
    ``parents[j] < j`` (spec_proposer.DraftForest layout). Returns
    ``[N, width]`` int32 where row j lists the in-row indices of node
    j's root-to-node path INCLUDING itself, -1 padded. ``width``
    defaults to N (the deepest possible chain). Dead nodes (>=
    ``n_nodes``) get all -1 rows — they still mask causally but match
    no ancestor, so their (garbage) outputs attend history only.

    The kernels treat ``anc[t, 0] == -2`` as the PLAIN-CAUSAL sentinel
    (non-tree rows); this builder never emits it — the engine stamps it
    on every token outside a tree row."""
    import numpy as np

    parents = np.asarray(parents, np.int32)
    n = parents.shape[0]
    live = n if n_nodes is None else int(n_nodes)
    w = n if width is None else int(width)
    out = np.full((n, w), -1, np.int32)
    for j in range(live):
        chain = []
        node = j
        while node >= 0:
            chain.append(node)
            node = int(parents[node])
        if len(chain) > w:
            raise ValueError(
                "tree depth {} exceeds ancestor width {}".format(
                    len(chain), w))
        out[j, : len(chain)] = chain[::-1]
    return out


def ragged_paged_attention_xla(q, k_pool, v_pool, page_table, kv_lens,
                               row_starts, row_lens,
                               k_scale=None, v_scale=None,
                               tree_anc=None):
    """Reference ragged paged attention in plain XLA ops (CPU fallback).

    Shapes per the module's ragged section; returns [T, Hkv, G, D] with
    zeros at tokens no row owns. Per-token math mirrors
    :func:`paged_attention_xla` exactly (same contraction order, f32
    softmax, probs cast to the value dtype before the PV product) so a
    decode row's output is the decode reference's output — the engine's
    byte-identity A/B rests on that.

    The pool gather runs per ROW ([Hkv, R, cap, D]) and fans out to
    tokens by row index — the per-token [T, cap] operand still
    materializes for the score/PV einsums (acceptable at the fallback's
    test/smoke scale; the Pallas kernel is the capacity-scale path), but
    HBM gather traffic stays R*cap, not T*cap."""
    t, hkv, g, d = q.shape
    _, n, p, _ = k_pool.shape
    pp = page_table.shape[1]
    cap = pp * p
    t_idx = jnp.arange(t, dtype=jnp.int32)
    ends = row_starts + row_lens
    in_row = (t_idx[None, :] >= row_starts[:, None]) & (
        t_idx[None, :] < ends[:, None]
    )                                                       # [R, T]
    tok_valid = jnp.any(in_row, axis=0)                     # [T]
    tok_row = jnp.argmax(in_row, axis=0).astype(jnp.int32)  # [T]
    qi = t_idx - row_starts[tok_row]
    base = (kv_lens - row_lens)[tok_row]
    bound = jnp.where(
        tok_valid, jnp.minimum(base + qi + 1, kv_lens[tok_row]), 0
    )                                                       # [T]
    k_rows = k_pool[:, page_table].reshape(hkv, -1, cap, d)  # [Hkv, R, cap, D]
    v_rows = v_pool[:, page_table].reshape(hkv, -1, cap, d)
    if k_scale is not None:
        ks = k_scale[:, page_table].reshape(hkv, -1, cap, 1)
        vs = v_scale[:, page_table].reshape(hkv, -1, cap, 1)
        k_rows = (k_rows.astype(jnp.float32) * ks).astype(q.dtype)
        v_rows = (v_rows.astype(jnp.float32) * vs).astype(q.dtype)
    k = k_rows[:, tok_row]                                  # [Hkv, T, cap, D]
    v = v_rows[:, tok_row]
    scores = jnp.einsum(
        "thgd,htcd->thgc", q, k, preferred_element_type=jnp.float32
    ) * (d ** -0.5)
    valid = jnp.arange(cap, dtype=jnp.int32)[None, :] < bound[:, None]
    if tree_anc is not None:
        # tree-topology pruning INSIDE the causal bound
        # (docs/spec_decode_trees.md): a tree row's query attends its
        # history plus its own root-to-node ancestor path only. In-row
        # offsets compare against the per-token ancestor list;
        # anc[t, 0] == -2 marks plain-causal tokens (mask unchanged).
        off = jnp.arange(cap, dtype=jnp.int32)[None, :] - base[:, None]
        anc = jnp.any(
            off[:, :, None] == tree_anc[:, None, :], axis=-1
        )                                                   # [T, cap]
        plain = (tree_anc[:, 0] == -2)[:, None]
        valid = valid & (plain | (off < 0) | anc)
    scores = jnp.where(valid[:, None, None, :], scores, -jnp.inf)
    row_max = jnp.maximum(jnp.max(scores, axis=-1, keepdims=True), -1e30)
    probs = jnp.exp(scores - row_max)
    probs = jnp.where(valid[:, None, None, :], probs, 0.0)
    denom = jnp.sum(probs, axis=-1, keepdims=True)
    probs = (probs / jnp.where(denom == 0.0, 1.0, denom)).astype(v.dtype)
    out = jnp.einsum("thgc,htcd->thgd", probs, v)
    return out.astype(q.dtype)


def _ragged_attention_kernel(
    # scalar prefetch (SMEM): block_rows [NB], block_q0 [NB],
    # page_table [R, PP], kv_lens [R], row_lens [R],
    # tree only: tree_anc [T, DMAX] (per flat token: in-row ancestor
    # indices incl. self, -1 padded; anc[t, 0] == -2 => plain causal)
    *refs,
    page_size: int,
    pages_per_block: int,
    q_block: int,
    quantized: bool = False,
    tree: bool = False,
):
    # then positionally: q_ref [QB,1,G,D]; k_hbm/v_hbm [Hkv,N,P,D] (ANY);
    # quantized only: k_scale_ref/v_scale_ref [1,1,1,cap_pad] (per-ROW
    # pre-gathered scales, pipelined by the block_rows index map);
    # out_ref [QB,1,G,D]; scratch k_buf/v_buf [2, PB*P, D], sems [2, PB, 2]
    (block_rows_ref, block_q0_ref, page_table_ref, kv_lens_ref,
     row_lens_ref) = refs[:5]
    refs = refs[5:]
    if tree:
        tree_anc_ref, refs = refs[0], refs[1:]
    else:
        tree_anc_ref = None
    if quantized:
        (q_ref, k_hbm, v_hbm, k_scale_ref, v_scale_ref,
         out_ref, k_buf, v_buf, sems) = refs
    else:
        q_ref, k_hbm, v_hbm, out_ref, k_buf, v_buf, sems = refs
        k_scale_ref = v_scale_ref = None
    bi = pl.program_id(0)
    h = pl.program_id(1)
    g, d = q_ref.shape[2], q_ref.shape[3]
    p = page_size
    pb = pages_per_block
    qb = q_block
    row_raw = block_rows_ref[bi]
    live = row_raw >= 0
    row = jnp.maximum(row_raw, 0)
    q0 = block_q0_ref[bi]
    kv_len = kv_lens_ref[row]
    row_len = row_lens_ref[row]
    base = kv_len - row_len          # absolute position of the row's query 0
    # causal bound of this block's LAST query — pages past it never DMA
    bound = jnp.where(live, jnp.minimum(kv_len, base + q0 + qb), 0)
    block_tokens = pb * p
    n_blocks = (bound + block_tokens - 1) // block_tokens

    def _copies(block_idx, slot, j):
        page_idx = block_idx * pb + j
        page = page_table_ref[row, page_idx]
        dst = pl.ds(j * p, p)
        return (
            pltpu.make_async_copy(
                k_hbm.at[h, page], k_buf.at[slot, dst], sems.at[slot, j, 0]
            ),
            pltpu.make_async_copy(
                v_hbm.at[h, page], v_buf.at[slot, dst], sems.at[slot, j, 1]
            ),
        )

    def start_block(block_idx, slot):
        for j in range(pb):  # static unroll; ragged tail gated per page
            @pl.when((block_idx * pb + j) * p < bound)
            def _start(j=j):
                ck, cv = _copies(block_idx, slot, j)
                ck.start()
                cv.start()

    def wait_block(block_idx, slot):
        for j in range(pb):
            @pl.when((block_idx * pb + j) * p < bound)
            def _wait(j=j):
                ck, cv = _copies(block_idx, slot, j)
                ck.wait()
                cv.wait()

    @pl.when(n_blocks > 0)
    def _run():
        start_block(0, 0)

        def body(i, carry):
            m_prev, l_prev, acc_prev = carry
            slot = jax.lax.rem(i, 2)

            @pl.when(i + 1 < n_blocks)
            def _prefetch():
                start_block(i + 1, jax.lax.rem(i + 1, 2))

            wait_block(i, slot)
            # queries flatten to [QB*G, D]: query-in-block index = ri // G
            q = q_ref[:, 0].reshape(qb * g, d)                  # [QB*G, D]
            k = k_buf[slot]                                     # [PB*P, D]
            v = v_buf[slot]
            if quantized:
                op_dtype = out_ref.dtype
                k = k.astype(op_dtype)
                v = v.astype(op_dtype)
            scores = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * (d ** -0.5)                                     # [QB*G, PB*P]
            if quantized:
                k_s = k_scale_ref[0, 0, :, pl.ds(i * block_tokens,
                                                 block_tokens)]  # [1, PB*P]
                scores = scores * k_s
            # per-query causal masking: query q0+qi attends KV positions
            # <= base+q0+qi; 2-D i32 iota compares (Mosaic: no i1 minor dim)
            token_ids = (
                i * block_tokens
                + jax.lax.broadcasted_iota(jnp.int32, scores.shape, 1)
            )
            qi = jax.lax.broadcasted_iota(jnp.int32, scores.shape, 0) // g
            q_live = (q0 + qi) < row_len                        # query exists
            valid = (token_ids < base + q0 + qi + 1) & q_live
            if tree:
                # tree-topology pruning inside the unchanged causal
                # bound (docs/spec_decode_trees.md): the DMA plan above
                # is untouched — parent-before-child node order keeps
                # base+q0+qi+1 a valid upper bound, so trees only MASK
                # within the pages already copied. Ancestor lists live
                # in SMEM (scalar prefetch); the per-query unroll is
                # static (q_block x DMAX scalar reads, equality
                # compares only — no i1 minor dims, no vector shifts).
                tok_off = token_ids - base          # in-row kv offset
                allow = tok_off < 0                 # history always
                for qs in range(qb):
                    t_flat = bi * qb + qs
                    plain = tree_anc_ref[t_flat, 0] == -2
                    match = tok_off < 0
                    for a in range(tree_anc_ref.shape[1]):
                        av = tree_anc_ref[t_flat, a]
                        match = match | ((tok_off == av) & (av >= 0))
                    allow = jnp.where(
                        qi == qs, jnp.logical_or(plain, match), allow
                    )
                valid = valid & allow
            scores = jnp.where(valid, scores, -jnp.inf)
            # rows past the bound were never DMA'd: zero before the matmul
            row_ids = i * block_tokens + jax.lax.broadcasted_iota(
                jnp.int32, (block_tokens, 1), 0
            )
            v = jnp.where(row_ids < bound, v, jnp.zeros_like(v))

            block_max = jnp.maximum(jnp.max(scores, axis=1), -1e30)
            m_new = jnp.maximum(m_prev, block_max)              # [QB*G]
            probs = jnp.exp(scores - m_new[:, None])
            probs = jnp.where(valid, probs, 0.0)
            correction = jnp.exp(m_prev - m_new)
            l_new = l_prev * correction + jnp.sum(probs, axis=1)
            pv = probs
            if quantized:
                v_s = v_scale_ref[0, 0, :, pl.ds(i * block_tokens,
                                                 block_tokens)]  # [1, PB*P]
                pv = probs * v_s
            acc_new = acc_prev * correction[:, None] + jax.lax.dot_general(
                pv.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            return m_new, l_new, acc_new

        m0 = jnp.full((qb * g,), -jnp.inf, jnp.float32)
        l0 = jnp.zeros((qb * g,), jnp.float32)
        acc0 = jnp.zeros((qb * g, d), jnp.float32)
        _, l, acc = jax.lax.fori_loop(0, n_blocks, body, (m0, l0, acc0))
        safe_l = jnp.where(l == 0.0, 1.0, l)
        out_ref[:, 0] = (acc / safe_l[:, None]).reshape(qb, g, d).astype(
            out_ref.dtype
        )

    @pl.when(n_blocks == 0)
    def _empty():
        out_ref[:, 0] = jnp.zeros((qb, g, d), out_ref.dtype)


def ragged_paged_attention(
    q, k_pool, v_pool, page_table, kv_lens, row_starts, row_lens, *,
    block_rows=None, block_q0=None,
    k_scale=None, v_scale=None, tree_anc=None,
    pages_per_block: int = 32, q_block: int = _RAGGED_QB,
    interpret: bool = False,
):
    """Ragged paged attention over mixed prefill+decode rows (falls back to
    :func:`ragged_paged_attention_xla` off-TPU and on misaligned shapes —
    the SAME gates as the decode kernel: D % 128, dtype-dependent page
    sublane tile).

    ``block_rows``/``block_q0`` ([T/q_block] int32) are the host-computed
    q-block -> row map (:func:`ragged_layout`); the Pallas path REQUIRES
    them (they cannot be derived from traced row metadata on device) and
    the flat layout must be q_block-aligned per row. Without them every
    call routes to the XLA reference.

    ``tree_anc`` ([T, DMAX] int32, optional) turns spec-verify rows into
    draft-TREE rows (docs/spec_decode_trees.md): per flat token, the
    in-row indices of its root-to-node ancestor path (self included, -1
    padded); ``tree_anc[t, 0] == -2`` keeps token t plain-causal. Only
    the mask changes — the page DMA plan is topology-blind."""
    quantized = k_scale is not None
    if jnp.issubdtype(k_pool.dtype, jnp.signedinteger) and not quantized:
        raise ValueError(
            "int8 KV pools need k_scale/v_scale operands (per-token dequant)"
        )

    def _xla():
        return ragged_paged_attention_xla(
            q, k_pool, v_pool, page_table, kv_lens, row_starts, row_lens,
            k_scale, v_scale, tree_anc,
        )

    if not _PALLAS_OK or block_rows is None or block_q0 is None:
        return _xla()
    on_tpu = jax.devices()[0].platform == "tpu"
    if not on_tpu and not interpret:
        return _xla()
    min_sublane = 32 if k_pool.dtype.itemsize == 1 else 16
    if on_tpu and not interpret and (
        q.shape[-1] % 128 != 0 or k_pool.shape[2] % min_sublane != 0
    ):
        return _xla()

    t, hkv, g, d = q.shape
    _, n, page_size, _ = k_pool.shape
    pages_per_seq = page_table.shape[1]
    if t % q_block:
        raise ValueError(
            "ragged q length {} must be a multiple of q_block {}".format(
                t, q_block
            )
        )
    pb = max(1, min(pages_per_block, pages_per_seq))
    cap = pages_per_seq * page_size

    kernel = functools.partial(
        _ragged_attention_kernel,
        page_size=page_size,
        pages_per_block=pb,
        q_block=q_block,
        quantized=quantized,
        tree=tree_anc is not None,
    )
    nb = t // q_block
    # index maps take *_ for the scalar-prefetch refs: their count is 5
    # or 6 (tree_anc) and the maps never read beyond block_rows
    in_specs = [
        pl.BlockSpec(
            (q_block, 1, g, d), lambda b, h, *_: (b, h, 0, 0)
        ),
        pl.BlockSpec(memory_space=pl.ANY),   # K pool stays in HBM
        pl.BlockSpec(memory_space=pl.ANY),   # V pool stays in HBM
    ]
    inputs = [q, k_pool, v_pool]
    if quantized:
        # per-ROW pre-gathered scales (same rationale/padding as the decode
        # kernel's: f32 scale rows are not tile-alignable for the page DMA
        # plan); the grid pipeline picks each q block's row via block_rows
        block_tokens = pb * page_size
        cap_pad = -(-cap // block_tokens) * block_tokens
        pad = ((0, 0), (0, 0), (0, 0), (0, cap_pad - cap))
        r = page_table.shape[0]

        def gather(scale):
            seq = jnp.moveaxis(
                scale[:, page_table].reshape(hkv, r, cap), 0, 1
            ).reshape(r, hkv, 1, cap)
            return jnp.pad(seq, pad)

        def scale_idx(b, h, br, *_):
            return (jnp.maximum(br[b], 0), h, 0, 0)

        in_specs += [
            pl.BlockSpec((1, 1, 1, cap_pad), scale_idx),
            pl.BlockSpec((1, 1, 1, cap_pad), scale_idx),
        ]
        inputs += [gather(k_scale), gather(v_scale)]
    prefetch = [block_rows, block_q0, page_table, kv_lens, row_lens]
    if tree_anc is not None:
        if tree_anc.shape[0] != t:
            raise ValueError(
                "tree_anc rows {} != flat token count {}".format(
                    tree_anc.shape[0], t))
        prefetch.append(tree_anc.astype(jnp.int32))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=len(prefetch),  # block/row map + tables (+ tree)
        grid=(nb, hkv),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (q_block, 1, g, d), lambda b, h, *_: (b, h, 0, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM((2, pb * page_size, d), k_pool.dtype),
            pltpu.VMEM((2, pb * page_size, d), v_pool.dtype),
            pltpu.SemaphoreType.DMA((2, pb, 2)),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((t, hkv, g, d), q.dtype),
        interpret=interpret,
    )(*prefetch, *inputs)
