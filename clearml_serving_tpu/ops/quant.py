"""Weight quantization for HBM-constrained serving.

An 8B-param model in bf16 (16 GB) does not fit one v5e chip's HBM next to a KV
cache — int8 weights (8 GB) do. Symmetric per-output-channel int8 with an f32
scale; dequantization happens in VMEM fused into the matmul by XLA, so HBM
traffic (the decode bottleneck) halves.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax.numpy as jnp


def quantize_int8(w: jnp.ndarray, axis: int = 0) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """w (float) -> (w_int8, scale_f32). `axis` is the reduction (input) axis;
    scales are per-output-channel."""
    w32 = w.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(w32), axis=axis, keepdims=True)
    scale = jnp.where(absmax > 0, absmax / 127.0, 1.0)
    q = jnp.clip(jnp.round(w32 / scale), -127, 127).astype(jnp.int8)
    return q, scale.astype(jnp.float32)


def dequantize(q: jnp.ndarray, scale: jnp.ndarray, dtype=jnp.bfloat16) -> jnp.ndarray:
    return (q.astype(jnp.float32) * scale).astype(dtype)


def int8_matmul(x: jnp.ndarray, q: jnp.ndarray, scale: jnp.ndarray) -> jnp.ndarray:
    """x [..., K] @ dequant(q [K, N]) — dequant fuses into the matmul."""
    return (x @ dequantize(q, scale, x.dtype)).astype(x.dtype)


def quantize_llama_params(params: Dict[str, Any]) -> Dict[str, Any]:
    """Quantize every projection matrix of a llama param pytree to int8;
    norms/embeddings stay bf16. Serve by calling `dequant_llama_params`
    INSIDE the jitted step function (see llm/engine.py) — XLA then fuses each
    dequant next to its consumer matmul and frees the bf16 buffer after use,
    so weights at rest stay int8. Calling dequant eagerly (outside jit)
    materializes a full bf16 copy and defeats the purpose."""
    quant_keys = {
        "wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down", "lm_head",
        # MoE expert stacks [E, in, out] quantize the same way (axis=-2 is
        # still the reduction dim); the small router stays full precision
        "w_gate_e", "w_up_e", "w_down_e",
    }

    def _q(tree):
        if isinstance(tree, dict):
            out = {}
            for key, value in tree.items():
                if key in quant_keys:
                    # axis=-2 is the input (reduction) dim for both plain
                    # [in, out] matrices and scan_layers-stacked [L, in, out]
                    qv, s = quantize_int8(value, axis=-2)
                    out[key] = {"_q8": qv, "_scale": s}
                else:
                    out[key] = _q(value)
            return out
        if isinstance(tree, list):
            return [_q(v) for v in tree]
        return tree

    return _q(params)


def random_quantized_llama(config: dict, seed: int = 0):
    """(bundle, params) with the int8 tree built DIRECTLY — full-precision
    weights are never materialized, so an 8B model initializes inside a single
    chip's HBM. For benchmarks and weightless demo endpoints (throughput is
    weight-value-independent); real checkpoints go through
    quantize_llama_params instead."""
    import jax

    from ..models import llama

    cfg = llama.resolve_config(dict(config, scan_layers=True))
    bundle = llama.build(dict(config, scan_layers=True))
    dim = int(cfg["dim"])
    n_layers = int(cfg["n_layers"])
    heads_dim = dim  # wq output
    n_kv_dim = int(cfg["n_kv_heads"]) * (dim // int(cfg["n_heads"]))
    ffn = int(cfg["ffn_dim"])
    vocab = int(cfg["vocab_size"])
    dtype = jnp.dtype(cfg["dtype"])

    def qstack(key, shape):
        return {
            "_q8": jax.random.randint(key, (n_layers,) + shape, -127, 128, jnp.int8),
            "_scale": jnp.full((n_layers, 1, shape[1]), 0.01, jnp.float32),
        }

    ks = jax.random.split(jax.random.PRNGKey(seed), 9)
    params = {
        "embed": (jax.random.normal(ks[0], (vocab, dim)) * 0.02).astype(dtype),
        "lm_head": {
            "_q8": jax.random.randint(ks[1], (dim, vocab), -127, 128, jnp.int8),
            "_scale": jnp.full((1, vocab), 0.01, jnp.float32),
        },
        "final_norm": jnp.ones((dim,), dtype),
        "layers": {
            "attn_norm": jnp.ones((n_layers, dim), dtype),
            "wq": qstack(ks[2], (dim, heads_dim)),
            "wk": qstack(ks[3], (dim, n_kv_dim)),
            "wv": qstack(ks[4], (dim, n_kv_dim)),
            "wo": qstack(ks[5], (heads_dim, dim)),
            "ffn_norm": jnp.ones((n_layers, dim), dtype),
            "w_gate": qstack(ks[6], (dim, ffn)),
            "w_up": qstack(ks[7], (dim, ffn)),
            "w_down": qstack(ks[8], (ffn, dim)),
        },
    }
    return bundle, params


def dequant_llama_params(params: Dict[str, Any], dtype=jnp.bfloat16) -> Dict[str, Any]:
    """Inverse transform (inside jit: XLA fuses dequant into consumers)."""

    def _dq(tree):
        if isinstance(tree, dict):
            if "_q8" in tree:
                return dequantize(tree["_q8"], tree["_scale"], dtype)
            return {k: _dq(v) for k, v in tree.items()}
        if isinstance(tree, list):
            return [_dq(v) for v in tree]
        return tree

    return _dq(params)
