"""Weight quantization for HBM-constrained serving.

An 8B-param model in bf16 (16 GB) does not fit one v5e chip's HBM next to a KV
cache — int8 weights (8 GB) do. Symmetric per-output-channel int8 with an f32
scale; dequantization happens in VMEM fused into the matmul by XLA, so HBM
traffic (the decode bottleneck) halves.

int4 halves it again (8B -> ~4 GB): symmetric **group-quantized** 4-bit
(AWQ/GPTQ-style w4a16 — per-(128-input-row group, output channel) scales
recover most of the quality a single per-channel scale loses at 4 bits), two
nibbles packed per uint8 byte so the HBM win is real on every backend rather
than depending on XLA s4 packing. Unpack (mask/shift) + dequant fuse into the
consumer matmul's operand pipeline exactly like the int8 path.

Leaf formats (pytree leaves produced by quantize_llama_params):
    int8: {"_q8": int8 [..., K, N],     "_scale":  f32 [..., 1, N]}
    int4: {"_q4": uint8 [..., K//2, N], "_scale4": f32 [..., K//g, N]}
_scale4 has the same rank as the weight (groups axis in the K slot), so TP
sharding specs transfer unchanged (parallel/sharding.py).
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax.numpy as jnp


def quantize_int8(w: jnp.ndarray, axis: int = 0) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """w (float) -> (w_int8, scale_f32). `axis` is the reduction (input) axis;
    scales are per-output-channel."""
    w32 = w.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(w32), axis=axis, keepdims=True)
    scale = jnp.where(absmax > 0, absmax / 127.0, 1.0)
    q = jnp.clip(jnp.round(w32 / scale), -127, 127).astype(jnp.int8)
    return q, scale.astype(jnp.float32)


def dequantize(q: jnp.ndarray, scale: jnp.ndarray, dtype=jnp.bfloat16) -> jnp.ndarray:
    return (q.astype(jnp.float32) * scale).astype(dtype)


def int8_matmul(x: jnp.ndarray, q: jnp.ndarray, scale: jnp.ndarray) -> jnp.ndarray:
    """x [..., K] @ dequant(q [K, N]) — dequant fuses into the matmul."""
    return (x @ dequantize(q, scale, x.dtype)).astype(x.dtype)


INT4_GROUP = 128  # input rows per scale group (AWQ/GPTQ convention)


def int4_groups(k: int, group: int = INT4_GROUP) -> int:
    """Number of scale groups for a K-row input dim: K//group, falling back
    to one per-channel group when K doesn't divide (the single source of
    truth for the fallback rule — quantize_int4 and random tree builders
    must agree or benchmark trees diverge from real-checkpoint trees)."""
    return k // group if group and k % group == 0 else 1


def quantize_int4(
    w: jnp.ndarray, axis: int = -2, group: int = INT4_GROUP
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """w float [..., K, N] -> (packed uint8 [..., K//2, N], scale f32
    [..., K//group, N]). Symmetric signed 4-bit in [-8, 7], stored as
    unsigned nibbles (q+8); rows 2i/2i+1 pack into byte i's low/high nibble.
    K not divisible by ``group`` falls back to one group (per-channel)."""
    if axis not in (-2, w.ndim - 2):
        raise ValueError("int4 quantization packs along axis -2")
    k, n = w.shape[-2], w.shape[-1]
    if k % 2:
        raise ValueError("int4 packing needs an even input dim, got {}".format(k))
    g = k // int4_groups(k, group)
    w32 = w.astype(jnp.float32)
    shaped = w32.reshape(*w.shape[:-2], k // g, g, n)
    absmax = jnp.max(jnp.abs(shaped), axis=-2, keepdims=True)   # [.., K//g, 1, N]
    scale = jnp.where(absmax > 0, absmax / 7.0, 1.0)
    q = jnp.clip(jnp.round(shaped / scale), -8, 7)
    u = (q + 8).astype(jnp.uint8).reshape(*w.shape[:-2], k, n)
    packed = u[..., 0::2, :] | (u[..., 1::2, :] << 4)           # [.., K//2, N]
    return packed, jnp.squeeze(scale, -2).astype(jnp.float32)


def dequantize_int4(
    packed: jnp.ndarray, scale: jnp.ndarray, dtype=jnp.bfloat16
) -> jnp.ndarray:
    """Inverse of quantize_int4 (run INSIDE jit: XLA fuses unpack + scale
    into the consumer matmul, weights at rest stay 4-bit in HBM)."""
    k2, n = packed.shape[-2], packed.shape[-1]
    lo = (packed & 0xF).astype(jnp.int32)
    hi = (packed >> 4).astype(jnp.int32)
    q = jnp.stack([lo, hi], axis=-2)                            # [.., K//2, 2, N]
    qf = q.reshape(*packed.shape[:-2], k2 * 2, n).astype(jnp.float32) - 8.0
    ng = scale.shape[-2]
    g = (k2 * 2) // ng
    shaped = qf.reshape(*qf.shape[:-2], ng, g, n) * scale[..., :, None, :]
    return shaped.reshape(qf.shape).astype(dtype)


def detect_weight_quant(params: Any) -> str:
    """"int4"/"int8" when the pytree already holds packed quantized leaves
    (e.g. a bundle written by scripts/quantize_ckpt.py), else "". Lets the
    engine pick the quantized TP sharding specs and report the right
    weight_quant without re-deriving it from config."""
    if isinstance(params, dict):
        if "_q4" in params:
            return "int4"
        if "_q8" in params:
            return "int8"
        for value in params.values():
            found = detect_weight_quant(value)
            if found:
                return found
        return ""
    if isinstance(params, (list, tuple)):
        for value in params:
            found = detect_weight_quant(value)
            if found:
                return found
    return ""


def quantize_llama_params(
    params: Dict[str, Any], bits: int = 8, group: int = INT4_GROUP
) -> Dict[str, Any]:
    """Quantize every projection matrix of a llama param pytree to int8 (or
    group-int4 with ``bits=4``); norms/embeddings stay bf16. Serve by calling
    `dequant_llama_params` INSIDE the jitted step function (see
    llm/engine.py) — XLA then fuses each dequant next to its consumer matmul
    and frees the bf16 buffer after use, so weights at rest stay quantized.
    Calling dequant eagerly (outside jit) materializes a full bf16 copy and
    defeats the purpose."""
    if bits not in (4, 8):
        raise ValueError("bits must be 4 or 8, got {}".format(bits))
    quant_keys = {
        "wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down", "lm_head",
        # MoE expert stacks [E, in, out] quantize the same way (axis=-2 is
        # still the reduction dim); the small router stays full precision
        "w_gate_e", "w_up_e", "w_down_e",
    }

    def _q(tree):
        if isinstance(tree, dict):
            out = {}
            for key, value in tree.items():
                if key in quant_keys:
                    # axis=-2 is the input (reduction) dim for both plain
                    # [in, out] matrices and scan_layers-stacked [L, in, out]
                    if bits == 4:
                        qv, s = quantize_int4(value, axis=-2, group=group)
                        out[key] = {"_q4": qv, "_scale4": s}
                    else:
                        qv, s = quantize_int8(value, axis=-2)
                        out[key] = {"_q8": qv, "_scale": s}
                else:
                    out[key] = _q(value)
            return out
        if isinstance(tree, list):
            return [_q(v) for v in tree]
        return tree

    return _q(params)


def random_quantized_llama(config: dict, seed: int = 0, bits: int = 8):
    """(bundle, params) with the int8/int4 tree built DIRECTLY — full-precision
    weights are never materialized, so an 8B model initializes inside a single
    chip's HBM. For benchmarks and weightless demo endpoints (throughput is
    weight-value-independent); real checkpoints go through
    quantize_llama_params instead."""
    import jax

    from ..models import llama

    cfg = llama.resolve_config(dict(config, scan_layers=True))
    bundle = llama.build(dict(config, scan_layers=True))
    dim = int(cfg["dim"])
    n_layers = int(cfg["n_layers"])
    heads_dim = dim  # wq output
    n_kv_dim = int(cfg["n_kv_heads"]) * (dim // int(cfg["n_heads"]))
    ffn = int(cfg["ffn_dim"])
    vocab = int(cfg["vocab_size"])
    dtype = jnp.dtype(cfg["dtype"])

    def _qleaf(key, shape):  # shape = (K, N), possibly under a leading stack
        k_in = shape[-2]
        if bits == 4:
            groups = int4_groups(k_in)
            return {
                "_q4": jax.random.randint(
                    key, shape[:-2] + (k_in // 2, shape[-1]), 0, 256, jnp.uint8
                ),
                "_scale4": jnp.full(
                    shape[:-2] + (groups, shape[-1]), 0.01, jnp.float32
                ),
            }
        return {
            "_q8": jax.random.randint(key, shape, -127, 128, jnp.int8),
            "_scale": jnp.full(shape[:-2] + (1, shape[-1]), 0.01, jnp.float32),
        }

    def qstack(key, shape):
        return _qleaf(key, (n_layers,) + shape)

    ks = jax.random.split(jax.random.PRNGKey(seed), 9)
    params = {
        "embed": (jax.random.normal(ks[0], (vocab, dim)) * 0.02).astype(dtype),
        "lm_head": _qleaf(ks[1], (dim, vocab)),
        "final_norm": jnp.ones((dim,), dtype),
        "layers": {
            "attn_norm": jnp.ones((n_layers, dim), dtype),
            "wq": qstack(ks[2], (dim, heads_dim)),
            "wk": qstack(ks[3], (dim, n_kv_dim)),
            "wv": qstack(ks[4], (dim, n_kv_dim)),
            "wo": qstack(ks[5], (heads_dim, dim)),
            "ffn_norm": jnp.ones((n_layers, dim), dtype),
            "w_gate": qstack(ks[6], (dim, ffn)),
            "w_up": qstack(ks[7], (dim, ffn)),
            "w_down": qstack(ks[8], (ffn, dim)),
        },
    }
    return bundle, params


def dequant_llama_params(params: Dict[str, Any], dtype=jnp.bfloat16) -> Dict[str, Any]:
    """Inverse transform (inside jit: XLA fuses dequant into consumers)."""

    def _dq(tree):
        if isinstance(tree, dict):
            if "_q8" in tree:
                return dequantize(tree["_q8"], tree["_scale"], dtype)
            if "_q4" in tree:
                return dequantize_int4(tree["_q4"], tree["_scale4"], dtype)
            return {k: _dq(v) for k, v in tree.items()}
        if isinstance(tree, list):
            return [_dq(v) for v in tree]
        return tree

    return _dq(params)
