from .mesh import make_mesh, mesh_from_aux_cfg
from .sharding import (
    llama_param_sharding,
    llama_quantized_param_sharding,
    llama_cache_sharding,
    shard_params,
)
from .distributed import global_mesh, initialize_distributed, is_primary_host

__all__ = [
    "make_mesh",
    "mesh_from_aux_cfg",
    "llama_param_sharding",
    "llama_quantized_param_sharding",
    "llama_cache_sharding",
    "shard_params",
    "global_mesh",
    "initialize_distributed",
    "is_primary_host",
]
