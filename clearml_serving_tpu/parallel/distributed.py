"""Multi-host distributed bootstrap (SURVEY.md §5.8 build obligation).

Topology for multi-host TPU slices (e.g. v5e-16 = 2 hosts x 8 chips):

- one **engine-server process per host**, all calling
  :func:`initialize_distributed` so jax sees the global device set;
- pjit/GSPMD shardings span the global mesh — XLA routes collectives over ICI
  within the slice and DCN across slices; no NCCL/MPI analog is written here
  (the compiler inserts all collectives);
- the **router targets only host 0's gRPC endpoint** (the process whose
  ``jax.process_index() == 0``); other hosts participate purely through the
  collectives their compiled executables contain — they run the same
  executables triggered by host 0's dispatch (multi-controller SPMD);
- across replicas (independent slices), scale-out stays plain HTTP/gRPC load
  balancing, exactly like the reference's replica containers.

Single-process usage is a no-op, so every entrypoint can call
:func:`initialize_distributed` unconditionally.
"""

from __future__ import annotations

import os
from typing import Dict, Optional


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> int:
    """Initialize jax.distributed from args or TPUSERVE_* / default envs.

    Returns the process index (0 for single-process). Safe to call twice.
    """
    import jax

    coordinator_address = coordinator_address or os.environ.get("TPUSERVE_COORDINATOR")
    if num_processes is None:
        num_processes = int(os.environ.get("TPUSERVE_NUM_HOSTS", 0)) or None
    if process_id is None:
        pid_env = os.environ.get("TPUSERVE_HOST_ID")
        process_id = int(pid_env) if pid_env is not None else None

    if not coordinator_address and not num_processes:
        return 0  # single process
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    except RuntimeError as ex:
        if "already initialized" not in str(ex):
            raise
    return jax.process_index()


def global_mesh(axis_sizes: Optional[Dict[str, int]] = None):
    """Mesh over the GLOBAL device set (all hosts). Axis sizes default to
    pure tensor-parallel over every chip in the slice."""
    import jax

    from .mesh import make_mesh

    return make_mesh(axis_sizes or {"tp": -1}, devices=jax.devices())


def is_primary_host() -> bool:
    """True on the process that should expose the service port (host 0)."""
    import jax

    return jax.process_index() == 0
