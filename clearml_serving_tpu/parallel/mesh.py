"""Device-mesh construction for the serving engines.

The reference scales out with replica containers + vLLM-internal TP configured
opaquely through engine-args JSON (SURVEY.md §2.9 "Parallelism strategies").
Here parallelism is first-class: every tensor engine accepts a per-endpoint
``aux_config["mesh"]`` block (e.g. ``{"dp": 2, "tp": 4}``) that maps onto a
`jax.sharding.Mesh` whose collectives ride ICI within a slice.

Axis vocabulary (used consistently across sharding rules and kernels):
  dp — data/batch parallel     tp — tensor parallel (heads / ffn)
  sp — sequence/context parallel (ring attention)   ep — expert parallel (MoE)
  pp — layer-stage parallel: the stacked (scan_layers) layer dim shards over
       pp, so each chip holds L/pp layers' weights and XLA gathers one
       layer per scan step — the serving-side memory-scaling form of
       pipeline parallelism (no microbatch schedule; latency trades for HBM)
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import numpy as np

# mesh-axis closed world (tpuserve-analyze TPU801): THE axis registry. Every
# axis literal in a PartitionSpec/collective anywhere in the tree must come
# from this literal — the analyzer parses it from source (no jax import), so
# keep it a literal tuple and document new axes in the docstring above.
__mesh_axes__ = ("dp", "tp", "sp", "ep", "pp")

AXES = __mesh_axes__


def make_mesh(
    axis_sizes: Optional[Dict[str, int]] = None,
    devices: Optional[Sequence] = None,
):
    """Build a Mesh over `devices` (default: all local devices).

    ``axis_sizes`` maps axis name -> size; a single axis may be -1 meaning
    "whatever is left". Axes of size 1 are kept (so sharding rules can always
    reference every axis name).
    """
    import jax
    from jax.experimental import mesh_utils
    from jax.sharding import Mesh

    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    sizes = dict(axis_sizes or {})
    for ax in AXES:
        sizes.setdefault(ax, 1)
    # resolve a single -1
    unknown = [ax for ax, s in sizes.items() if s == -1]
    if len(unknown) > 1:
        raise ValueError("at most one mesh axis may be -1")
    known = int(np.prod([s for s in sizes.values() if s != -1]))
    if unknown:
        if n % known:
            raise ValueError(
                "cannot infer {}: {} devices not divisible by {}".format(unknown[0], n, known)
            )
        sizes[unknown[0]] = n // known
    total = int(np.prod(list(sizes.values())))
    if total != n:
        raise ValueError(
            "mesh {} needs {} devices, have {}".format(sizes, total, n)
        )
    shape = tuple(sizes[ax] for ax in AXES)
    try:
        dev_array = mesh_utils.create_device_mesh(shape, devices=devices)
    except Exception:
        dev_array = np.array(devices).reshape(shape)
    return Mesh(dev_array, AXES)


def mesh_from_aux_cfg(aux_cfg: Optional[dict]):
    """Per-endpoint mesh from the aux-config block (None -> single-device-style
    mesh over all local devices with tp=-1 if >1 device and no spec given)."""
    spec = {}
    if isinstance(aux_cfg, dict):
        spec = dict(aux_cfg.get("mesh") or {})
    if not spec:
        spec = {"tp": -1}  # default: pure tensor-parallel over the local slice
    return make_mesh(spec)
