"""Host-0 broadcast dispatch for multi-controller SPMD serving.

In a multi-host slice every controller must enter the SAME compiled
computation in the same order, or the collectives deadlock. Requests only
arrive at host 0 (the router targets its gRPC port alone), so host 0
**broadcasts each step** — which model to run and the batch bytes — to the
secondary controllers, which replay it against their own copy of the model
repo (synced from the same control plane). This replaces the reference
topology's single tritonserver process with one engine process per host
(SURVEY.md §7 hard part 6).

Transport: ``jax.experimental.multihost_utils.broadcast_one_to_all`` — itself
one compiled psum over the global device set, so the control channel rides
the same ICI/DCN fabric as the data. Two rounds per step: a fixed-shape
header [op, nbytes], then the payload padded to the broadcast length every
host now knows.

No NCCL/MPI analog is hand-written; inside the jitted model executable XLA
inserts all collectives from shardings, and this module only sequences WHICH
executable runs.
"""

from __future__ import annotations

import os
import pickle
from typing import Any, Callable, Optional, Tuple

import numpy as np

OP_NOOP = 0
OP_RUN = 1
OP_STOP = 2

# op-code closed world: the declared registry of every step op a follower
# can replay. recv() validates against it, so an op this module cannot name
# (version skew between host 0 and a follower, or header corruption) raises
# UnknownBroadcastOp instead of silently desyncing the follower loop — a
# follower that skips a step host 0 executed deadlocks the slice on the
# next cross-host collective with no diagnostic.
_OP_NAMES = {0: "noop", 1: "run", 2: "stop"}


class UnknownBroadcastOp(RuntimeError):
    """Host 0 broadcast an op code outside the declared closed world."""


def _check_op(op: int) -> int:
    if op not in _OP_NAMES:
        raise UnknownBroadcastOp(
            "broadcast op {} is not in the declared op registry {} — "
            "host 0 and this follower disagree on the step protocol "
            "(version skew?); refusing to guess (a silently skipped step "
            "deadlocks the slice on the next collective)".format(
                op, _OP_NAMES
            )
        )
    return op


class BroadcastChannel:
    """Host-0 -> all-hosts step channel over the global device set."""

    def __init__(self):
        import threading

        import jax

        self._is_source = jax.process_index() == 0
        self.process_count = jax.process_count()
        # host-0 sends come from batcher worker threads AND the reconcile
        # loop; interleaved broadcasts would corrupt the header/payload
        # pairing, so sends serialize
        self._send_lock = threading.Lock()

    @staticmethod
    def _bucket(nbytes: int) -> int:
        """Pad payload broadcasts to power-of-two sizes: broadcast_one_to_all
        jit-compiles per shape, so raw pickle lengths would compile a fresh
        collective for nearly every request; bucketing bounds the cache to
        ~log2(max_payload) executables."""
        size = 64
        while size < nbytes:
            size *= 2
        return size

    def send(self, op: int, payload: bytes = b"") -> None:
        """Host 0 only. Secondary hosts MUST be in recv() concurrently."""
        from jax.experimental import multihost_utils

        with self._send_lock:
            header = np.asarray([op, len(payload)], np.int64)
            multihost_utils.broadcast_one_to_all(header, is_source=self._is_source)
            if payload:
                bucket = self._bucket(len(payload))
                buf = np.zeros(bucket, np.uint8)
                buf[: len(payload)] = np.frombuffer(payload, np.uint8)
                multihost_utils.broadcast_one_to_all(buf, is_source=self._is_source)

    def recv(self) -> Tuple[int, bytes]:
        """Secondary hosts: blocks until host 0 sends the next step."""
        from jax.experimental import multihost_utils

        header = multihost_utils.broadcast_one_to_all(
            np.zeros(2, np.int64), is_source=self._is_source
        )
        # broadcast_one_to_all returns a fully-replicated global value —
        # every host holds the identical header/payload, so the host reads
        # below are multihost-safe by construction
        op, nbytes = int(header[0]), int(header[1])  # tpuserve: ignore[TPU803] header is replicated (broadcast result)
        op = _check_op(op)
        payload = b""
        if nbytes:
            buf = multihost_utils.broadcast_one_to_all(
                np.zeros(self._bucket(nbytes), np.uint8), is_source=self._is_source
            )
            payload = np.asarray(buf, np.uint8)[:nbytes].tobytes()  # tpuserve: ignore[TPU803] buf is replicated (broadcast result)
        return op, payload


class HostZeroDispatcher:
    """Wraps host-0's per-request execution so every step is mirrored to the
    followers BEFORE the local dispatch enters the executable."""

    def __init__(self, channel: Optional[BroadcastChannel] = None):
        import threading

        self.channel = channel or BroadcastChannel()
        self._multi = self.channel.process_count > 1
        # broadcast order MUST equal local execution order: followers replay
        # in broadcast order, and two executables entered in different orders
        # on different hosts deadlock the slice if they contain cross-host
        # collectives — so send+dispatch are one critical section
        self._order_lock = threading.Lock()

    def run(self, key: str, fn: Callable, inputs) -> Any:
        """Broadcast (key, inputs) then execute fn(inputs) locally, atomically
        with respect to other dispatches."""
        if not self._multi:
            return fn(inputs)
        with self._order_lock:
            self.channel.send(OP_RUN, pickle.dumps((key, inputs)))
            return fn(inputs)

    def noop(self) -> None:
        """Heartbeat broadcast, ordered with respect to run()/stop().

        run() releases the channel's send lock before entering the executable
        (still inside _order_lock), so a raw ``channel.send(OP_NOOP)`` from
        another thread could slot its psum between a RUN broadcast and the
        executable's own collectives — host 0 and the followers would then
        enqueue device work in different orders and deadlock the slice.
        """
        if self._multi:
            with self._order_lock:
                self.channel.send(OP_NOOP)

    def stop(self) -> None:
        if self._multi:
            # under the order lock: a queued dispatch must not broadcast
            # AFTER followers exit, or its collective hangs host 0 forever
            with self._order_lock:
                self.channel.send(OP_STOP)


def follower_loop(
    resolve: Callable[[str], Optional[Callable]],
    channel: Optional[BroadcastChannel] = None,
    on_error: Optional[Callable[[str, BaseException], None]] = None,
) -> None:
    """Secondary-controller main loop: replay host-0's steps until OP_STOP.

    ``resolve(key)`` returns the callable for a broadcast step (e.g. the
    repo model's run_batch) or None if this host could not materialize the
    model even after a re-sync. None is a FATAL desync: host 0 is already
    entering the executable, and if it contains cross-host collectives a
    silently-skipping follower hangs the whole slice with no diagnostic.
    We fail loudly instead — raise, crash this controller, and let the
    supervisor restart it into a fresh sync (same crash-and-restart policy
    as HBM OOM; the hang becomes a visible, attributable failure).
    """
    chan = channel or BroadcastChannel()
    while True:
        op, payload = chan.recv()  # raises UnknownBroadcastOp on skew
        if op == OP_STOP:
            return
        if op == OP_NOOP:
            continue
        key, inputs = pickle.loads(payload)
        fn = resolve(key)
        if fn is None:
            raise RuntimeError(
                "follower desync: host 0 dispatched model {!r} but this host "
                "cannot resolve it after re-sync; refusing to silently skip a "
                "broadcast step (slice would deadlock on any cross-host "
                "collective). Restart this controller to re-join.".format(key)
            )
        try:
            fn(inputs)
        except BaseException as ex:  # a follower must never desync the loop
            if on_error is not None:
                on_error(key, ex)


def configure_process_devices(devices: Optional[dict]) -> None:
    """Apply a worker spec's device block before the first jax device use.

    Process-backend replicas (serving/process_replica.py,
    docs/replication.md) run one engine per OS process, each owning its own
    device mesh. On a real slice that partitioning comes from the platform
    (each controller process sees its local chips); on CPU hosts it has to
    be conjured — ``cpu_devices`` forces ``jax_num_cpu_devices`` so a worker
    gets the same N-device mesh the in-process test fixtures configure.

    Must run before anything touches ``jax.devices()``: the XLA CPU client
    is created once per process and never re-reads the flag. Call it first
    thing in the worker main, before the engine module is imported.
    """
    block = devices or {}
    n = int(block.get("cpu_devices") or 0)
    if n > 0:
        # env first: it works even on jax builds without the explicit
        # config knob (same fallback ladder as tests/conftest.py), and the
        # worker main calls this before jax is ever imported
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count={}".format(n)
            ).strip()
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        import jax

        try:
            jax.config.update("jax_num_cpu_devices", n)
        except AttributeError:
            pass
