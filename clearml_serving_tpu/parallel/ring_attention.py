"""Ring attention: sequence-parallel exact attention for long context.

The reference has no long-context story (SURVEY.md §5.7 "Absent in the
reference") — this is a new TPU-first design obligation. Sequences are sharded
over the ``sp`` mesh axis; each device holds a [B, S/sp, H, D] slice of q/k/v.
KV blocks rotate around the ring with ``ppermute`` (ICI neighbor exchange,
overlappable with compute by XLA) while each device accumulates its queries'
attention with a numerically-stable streaming softmax (flash-attention style
running max / denominator). Peak memory is O(S/sp) per device instead of O(S),
so context length scales linearly with the ring size.

Causal mode uses block-level structure: a KV block strictly in the future is
skipped wholesale; the diagonal block applies the intra-block causal mask;
past blocks attend densely.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

# jax moved shard_map out of experimental in newer releases and removed the
# experimental alias; older jaxlibs (this image: 0.4.x) only have the
# experimental one. Resolve once, newest spelling first.
_shard_map = getattr(jax, "shard_map", None)
if _shard_map is None:  # pragma: no cover - depends on installed jax
    from jax.experimental.shard_map import shard_map as _shard_map


def _stream_block(q, k, v, o, m, l, mask):
    """One flash-style accumulation step.

    q: [B,Sq,H,D]  k,v: [B,Sk,H,D]  o: [B,Sq,H,D]  m,l: [B,Sq,H]
    mask: additive [Sq,Sk] or None.
    """
    scores = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * (q.shape[-1] ** -0.5)
    if mask is not None:
        scores = scores + mask[None, None]
    block_max = jnp.max(scores, axis=-1)                     # [B,H,Sq]
    block_max = jnp.maximum(block_max, -1e30)                # guard all-masked rows
    m_bhq = jnp.moveaxis(m, -1, 1)                           # [B,H,Sq]
    m_new = jnp.maximum(m_bhq, block_max)
    probs = jnp.exp(scores - m_new[..., None])               # [B,H,Sq,Sk]
    correction = jnp.exp(m_bhq - m_new)                      # [B,H,Sq]
    l_new = jnp.moveaxis(l, -1, 1) * correction + jnp.sum(probs, axis=-1)
    pv = jnp.einsum("bhqk,bkhd->bqhd", probs.astype(v.dtype), v)
    corr_bqh = jnp.moveaxis(correction, 1, -1)               # [B,Sq,H]
    o_new = o * corr_bqh[..., None] + pv.astype(jnp.float32)
    return o_new, jnp.moveaxis(m_new, 1, -1), jnp.moveaxis(l_new, 1, -1)


def _ring_attention_local(q, k, v, axis_name: str, causal: bool):
    """Per-device body (runs under shard_map). q,k,v: [B, S_local, H, D]."""
    axis_size = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    b, s_q, h, d = q.shape

    # accumulators start as constants; mark them device-varying over the ring
    # axis so the fori_loop carry type matches the body outputs (JAX vma
    # rules). Older jax has no pvary (and no vma typing either) — identity.
    pvary = getattr(lax, "pvary", lambda x, _axis: x)
    o = pvary(jnp.zeros((b, s_q, h, d), jnp.float32), axis_name)
    m = pvary(jnp.full((b, s_q, h), -jnp.inf, jnp.float32), axis_name)
    l = pvary(jnp.zeros((b, s_q, h), jnp.float32), axis_name)

    causal_mask = jnp.where(
        jnp.tril(jnp.ones((s_q, s_q), dtype=bool)), 0.0, -jnp.inf
    ).astype(jnp.float32)

    zeros_mask = jnp.zeros((s_q, s_q), jnp.float32)
    neginf_mask = jnp.full((s_q, s_q), -jnp.inf, jnp.float32)

    def _mask_for(step):
        if not causal:
            return zeros_mask
        # which global block the current k/v came from: future blocks are
        # fully masked, the diagonal block gets the intra-block causal mask,
        # past blocks attend densely. Additive-mask select keeps the traced
        # structure identical across ring steps (shard_map-friendly).
        kv_idx = (my_idx - step) % axis_size
        return jnp.where(
            kv_idx == my_idx,
            causal_mask,
            jnp.where(kv_idx > my_idx, neginf_mask, zeros_mask),
        )

    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    def body(step, carry):
        k_cur, v_cur, o, m, l = carry
        o, m, l = _stream_block(q, k_cur, v_cur, o, m, l, _mask_for(step))
        # rotate kv to the next device (ring neighbor exchange over ICI)
        k_next = lax.ppermute(k_cur, axis_name, perm)
        v_next = lax.ppermute(v_cur, axis_name, perm)
        return k_next, v_next, o, m, l

    # last block computes without the (discarded) final rotation
    k, v, o, m, l = lax.fori_loop(0, axis_size - 1, body, (k, v, o, m, l))
    o, m, l = _stream_block(q, k, v, o, m, l, _mask_for(axis_size - 1))
    # all-masked rows (can happen only if s_q rows saw nothing) -> zero output
    safe_l = jnp.where(l == 0.0, 1.0, l)
    return (o / safe_l[..., None]).astype(q.dtype)


def ring_attention(
    q, k, v, mesh, axis_name: str = "sp", causal: bool = True,
):
    """Exact attention over sequence shards.

    q, k, v: [B, S, H, D] global arrays (sharded/shardable over `axis_name` on
    dim 1). Returns [B, S, H, D] with the same sharding.
    """
    from jax.sharding import PartitionSpec as P

    spec = P(None, axis_name, None, None)
    fn = _shard_map(
        functools.partial(_ring_attention_local, axis_name=axis_name, causal=causal),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    return fn(q, k, v)
