"""Sharding rules: parameter/cache PartitionSpecs for the model zoo.

Megatron-style tensor parallelism for the Llama decoder, expressed as GSPMD
sharding annotations — XLA inserts the all-reduces over ICI; no hand-written
collectives (SURVEY.md §5.8 "TPU-native equivalent"):

- wq/wk/wv: shard the head (output) dimension over `tp`;
- wo: shard the input dimension over `tp` (row-parallel; XLA emits one
  all-reduce per layer after the attention output matmul);
- w_gate/w_up column-parallel, w_down row-parallel (second all-reduce);
- embed/lm_head: shard the vocab dimension;
- KV cache: shard the kv-head dimension over `tp`, batch over `dp`.

Weights replicate over `dp`; activations shard batch over `dp` via the data
layout (requests land on their dp shard).
"""

from __future__ import annotations

from typing import Any, Dict

# sharding-builder registry (tpuserve-analyze TPU802): the closed world of
# functions allowed to produce shardings for engine operand families. Every
# name a `__shardings__` class annotation cites must appear here, and every
# name here must be defined in this module — the analyzer parses the literal
# from source and tests/test_analyze_sharding.py round-trips it both ways.
__sharding_builders__ = (
    "llama_param_sharding",
    "llama_cache_sharding",
    "llama_quantized_param_sharding",
    "shard_params",
    "replicated",
    "batch_sharding",
)


def llama_param_sharding(
    mesh, params: Dict[str, Any], n_kv_heads: int = None, n_heads: int = None
) -> Dict[str, Any]:
    """NamedSharding pytree matching a llama param pytree.

    ``n_kv_heads``/``n_heads`` (optional): when given, attention projections
    shard over ``tp`` only if the head count divides evenly — a shard
    boundary INSIDE a head would split the rotate-half RoPE halves across
    chips (collectives inside rope, and an observed XLA:CPU miscompile of
    concat-over-a-sharded-axis). Misaligned projections replicate instead.
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    def ns(*spec):
        return NamedSharding(mesh, P(*spec))

    tp = int(dict(mesh.shape).get("tp", 1))

    def head_tp(heads):
        # None (caller didn't say) keeps the historical always-shard rule
        if heads is None or tp <= 1 or int(heads) % tp == 0:
            return "tp"
        return None  # tpuserve: ignore[TPU804] a tp boundary inside a head would split the RoPE rotate-half across chips (and hit the XLA:CPU concat-over-sharded-axis miscompile); misaligned projections replicate by design

    q_tp = head_tp(n_heads)
    kv_tp = head_tp(n_kv_heads)

    stacked = isinstance(params["layers"], dict)  # scan_layers: [L, ...] arrays
    # pp: shard the stacked layer dim — each chip stores L/pp layers and XLA
    # gathers one layer's weights per scan step (memory-scaling PP)
    pp = int(dict(mesh.shape).get("pp", 1)) if stacked else 1
    layer_axis = "pp" if pp > 1 else None

    def col(*spec):
        # stacked layer params carry a leading layer dim (pp-sharded if the
        # mesh has a pp axis)
        return ns(layer_axis, *spec) if stacked else ns(*spec)

    layer_spec = {
        "attn_norm": col(),
        "wq": col(None, q_tp),
        "wk": col(None, kv_tp),
        "wv": col(None, kv_tp),
        # Qwen2-style QKV biases: 1-D over the tp-sharded output dim
        "bq": col(q_tp),
        "bk": col(kv_tp),
        "bv": col(kv_tp),
        "wo": col(q_tp, None),
        "ffn_norm": col(),
        # Gemma-2 extras: post-sublayer norms replicate like the other
        # norms; the per-layer global/local flag is a scalar
        "post_attn_norm": col(),
        "post_ffn_norm": col(),
        "attn_global": col(),
        "w_gate": col(None, "tp"),
        "w_up": col(None, "tp"),
        "w_down": col("tp", None),
        # MoE variant: experts shard over ep, each expert's ffn over tp
        # (XLA inserts the dispatch/combine all-to-alls across ep)
        "w_router": col(),
        "w_gate_e": col("ep", None, "tp"),
        "w_up_e": col("ep", None, "tp"),
        "w_down_e": col("ep", "tp", None),
        # LoRA stacks [A+1, in, r]/[A+1, r, out] (models/lora.py): the B
        # factor shards its output dim like the base weight (column-parallel
        # targets) and the A factor shards its input dim for the
        # row-parallel targets (wo/w_down); the rank dim never shards
        "lora_a_wq": col(), "lora_b_wq": col(None, None, q_tp),
        "lora_a_wk": col(), "lora_b_wk": col(None, None, kv_tp),
        "lora_a_wv": col(), "lora_b_wv": col(None, None, kv_tp),
        "lora_a_wo": col(None, q_tp, None), "lora_b_wo": col(),
        "lora_a_w_gate": col(), "lora_b_w_gate": col(None, None, "tp"),
        "lora_a_w_up": col(), "lora_b_w_up": col(None, None, "tp"),
        "lora_a_w_down": col(None, "tp", None), "lora_b_w_down": col(),
    }
    # spec structure must mirror the actual param keys (dense layers carry
    # w_gate/..., MoE layers carry w_router/w_*_e)
    sample = params["layers"] if stacked else params["layers"][0]
    layer_spec = {k: v for k, v in layer_spec.items() if k in sample}
    out: Dict[str, Any] = {
        "embed": ns("tp", None),        # vocab-sharded lookup; gathered by XLA
        "final_norm": ns(),
        "layers": (
            dict(layer_spec) if stacked
            else [dict(layer_spec) for _ in params["layers"]]
        ),
    }
    if "lm_head" in params:
        out["lm_head"] = ns(None, "tp")
    return out


def llama_cache_sharding(mesh, quantized: bool = False) -> Dict[str, Any]:
    """Dense KV cache [L, B, T, Hkv, D]: batch over dp, kv heads over tp.
    The int8 variant adds per-(token, head) scale buffers [L, B, T, Hkv]."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    kv = NamedSharding(mesh, P(None, "dp", None, "tp", None))
    out = {"k": kv, "v": kv, "length": NamedSharding(mesh, P("dp"))}
    if quantized:
        sc = NamedSharding(mesh, P(None, "dp", None, "tp"))
        out["k_scale"] = sc
        out["v_scale"] = sc
    return out


def shard_params(mesh, params: Dict[str, Any], shardings: Dict[str, Any]):
    """Place a param pytree onto the mesh per the sharding pytree."""
    import jax

    return jax.tree.map(
        lambda p, s: jax.device_put(p, s), params, shardings,
        is_leaf=lambda x: not isinstance(x, (dict, list)),
    )


def replicated(mesh):
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(mesh, P())


def batch_sharding(mesh):
    """Activations/tokens: shard the leading batch dim over dp."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(mesh, P("dp"))


def llama_quantized_param_sharding(
    mesh, params: Dict[str, Any], n_kv_heads: int = None, n_heads: int = None
) -> Dict[str, Any]:
    """NamedSharding pytree for a quantized llama tree (ops/quant.py layouts:
    int8 {"_q8": [..., in, out], "_scale": [..., 1, out]} or int4
    {"_q4": [..., in//2, out], "_scale4": [..., in//group, out]}).

    The _q8/_q4 tensor takes the bf16 weight's TP spec unchanged (int4's
    packed input dim and _scale4's group dim both divide the input axis
    contiguously, so input-axis sharding remains valid); the int8 _scale
    takes the same spec with the input (reduction, -2) axis entry cleared —
    its input dim is 1 and cannot shard. Without this the whole quantized
    tree replicates on every chip (r1 VERDICT weak #2), defeating TP memory
    scaling exactly in the 8B-on-8-chip case.
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    base = llama_param_sharding(
        mesh, params, n_kv_heads=n_kv_heads, n_heads=n_heads
    )

    def _scale_spec(weight_sharding: "NamedSharding", ndim: int) -> "NamedSharding":
        spec = list(weight_sharding.spec)
        # quantize_int8 reduces over axis -2 relative to the weight rank; pad
        # to the WEIGHT's rank first (PartitionSpec legally omits trailing
        # None entries, so -2 on the raw spec could hit the wrong axis)
        spec = spec + [None] * (ndim - len(spec))
        spec[-2] = None
        return NamedSharding(mesh, P(*spec))

    def _walk(param_node, shard_node):
        if isinstance(param_node, dict):
            if "_q8" in param_node:
                return {
                    "_q8": shard_node,
                    "_scale": _scale_spec(shard_node, param_node["_q8"].ndim),
                }
            if "_q4" in param_node:
                # both tensors keep the weight spec: packed K//2 and the
                # K//group scale rows shard along the input axis the same
                # way the unpacked K rows do (contiguous division). The
                # scale's group count can be too coarse to split: the
                # single-group K<group fallback replicates its input axis
                # (exact — one per-channel scale serves every shard), but a
                # MULTI-group scale that doesn't divide means some shard
                # boundary lands INSIDE a 128-row quantization group — each
                # shard would dequantize part of that group with the wrong
                # scale row. That must be a loud config error, not silently
                # wrong logits.
                scale4 = param_node["_scale4"]
                spec = list(shard_node.spec)
                spec += [None] * (scale4.ndim - len(spec))
                ent = spec[-2]
                axes = ent if isinstance(ent, tuple) else (ent,)
                ways = 1
                for ax in axes:
                    if ax is not None:
                        ways *= mesh.shape[ax]
                ng = int(scale4.shape[-2])
                if ent is not None and ng == 1:
                    sspec = _scale_spec(shard_node, scale4.ndim)
                elif ent is not None and ng % ways != 0:
                    k_rows = int(param_node["_q4"].shape[-2]) * 2
                    raise ValueError(
                        "mesh axis {axes} (degree {ways}) splits the int4 "
                        "quantization groups of a {k}-row weight ({ng} "
                        "groups of {gk} rows) across shards — per-shard "
                        "dequant would apply the wrong scale rows. Set the "
                        "aux mesh.tp (parallel/mesh.py) to a divisor of "
                        "{ng}, or serve this model with "
                        "engine.weight_quant=int8 (per-channel scales shard "
                        "at any degree).".format(
                            axes=[a for a in axes if a is not None],
                            ways=ways, k=k_rows, ng=ng, gk=k_rows // ng,
                        )
                    )
                else:
                    sspec = shard_node
                return {"_q4": shard_node, "_scale4": sspec}
            return {k: _walk(param_node[k], shard_node[k]) for k in param_node}
        if isinstance(param_node, list):
            return [_walk(p, s) for p, s in zip(param_node, shard_node)]
        return shard_node

    return _walk(params, base)
