"""User pre/post-processing contract (duck-typed).

This file documents — and is importable as a starting point for — the class a
user attaches to an endpoint with ``--preprocess``. Capability parity with the
reference contract (clearml_serving/preprocess/preprocess_template.py:6-168):
the serving runtime hot-loads this code per endpoint, instantiates ``Preprocess``
once per endpoint per process, and calls the hooks below around every request.

Thread-safety contract (same as the reference): a single instance may serve many
concurrent requests — keep per-request state in the ``state`` dict passed to the
hooks, never on ``self``.

Every method below is optional; async variants (``async def``) are honored for
engines that declare async phases (custom_async, llm).
"""

from typing import Any, Callable, Optional


class Preprocess(object):
    """Example/default implementation: identity passthrough."""

    serving_config = None  # set by the runtime before load()

    def __init__(self):
        # No arguments. Runs inside the serving process at endpoint load time.
        pass

    def load(self, local_file_name: str) -> Any:
        """Optionally load the model payload yourself. Return value replaces the
        engine's default model object (for the `custom` engines this is the only
        model-loading path; for `jax`/`llm` engines returning None keeps the
        engine's native loader). ``local_file_name`` is the local copy of the
        registered model file/directory."""
        return None

    def unload(self) -> None:
        """Called when the endpoint is removed or the process exits."""
        pass

    def preprocess(
        self,
        body: Any,
        state: dict,
        collect_custom_statistics_fn: Optional[Callable[[dict], None]],
    ) -> Any:
        """Raw request body -> model input. ``state`` is per-request scratch
        shared with postprocess. ``collect_custom_statistics_fn({"name": val})``
        feeds the statistics pipeline."""
        return body

    # def process(self, data, state, collect_custom_statistics_fn):
    #     """UNCOMMENT ONLY IF NEEDED. Overrides the engine's inference call —
    #     required for the `custom`/`custom_async` engines, optional elsewhere.
    #     NOTE: if present on a tensor engine (sklearn/jax/...), YOUR code is
    #     the inference; the engine's native predict/compiled path is skipped.
    #     """
    #     return data

    def postprocess(
        self,
        data: Any,
        state: dict,
        collect_custom_statistics_fn: Optional[Callable[[dict], None]],
    ) -> Any:
        """Model output -> response body."""
        return data

    # Injected by the runtime (do not implement):
    #   self.send_request(endpoint: str, version: Optional[str], data: Any) -> Any
    # POSTs to another endpoint on this serving service (pipeline composition).
