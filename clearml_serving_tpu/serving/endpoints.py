"""Typed endpoint / canary / monitoring / metric-logging records.

Capability parity with the reference's endpoint schemas
(clearml_serving/serving/endpoints.py:1-124): engine-type validation against the
engine registry, numpy-dtype validation of I/O specs with scalar auto-wrapping,
and dict round-tripping for the control-plane state store. Implemented as plain
dataclasses (no attrs) with explicit validation so the records stay trivially
JSON-serializable.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Union

import numpy as np

# Engine implementations register their names here at import time (see
# clearml_serving_tpu/engines/base.py). Seeded with the full engine surface so
# schema validation works even before engine modules are imported.
KNOWN_ENGINES: set = {
    "sklearn",
    "xgboost",
    "lightgbm",
    "custom",
    "custom_async",
    "jax",          # in-process JAX/XLA engine (Triton-equivalent, local)
    "jax_grpc",     # remote JAX engine server over gRPC (Triton-equivalent)
    "llm",          # continuous-batching TPU LLM engine (vLLM-equivalent)
}


def register_engine_name(name: str) -> None:
    KNOWN_ENGINES.add(name)


def _validate_engine_type(value: Optional[str]) -> None:
    if value is not None and value not in KNOWN_ENGINES:
        raise ValueError(
            "engine_type={!r} is not a registered engine (known: {})".format(
                value, sorted(KNOWN_ENGINES)
            )
        )


def _as_list(value):
    """Scalars auto-wrap into single-element lists (reference endpoints.py:21-33)."""
    if value is None:
        return None
    if isinstance(value, (list, tuple)):
        return list(value)
    return [value]


def _validate_dtypes(value: Optional[List[str]]) -> None:
    for v in value or []:
        try:
            np.dtype(v)
        except TypeError as ex:
            raise ValueError("invalid numpy dtype {!r}: {}".format(v, ex)) from ex


def _normalize_io_spec(record) -> None:
    """Shared I/O-spec normalization: scalar entries auto-wrap to lists, single
    shapes wrap to a list-of-shapes, dtypes validated against numpy."""
    for attr_name in ("input_type", "input_name", "output_type", "output_name"):
        setattr(record, attr_name, _as_list(getattr(record, attr_name)))
    for attr_name in ("input_size", "output_size"):
        v = getattr(record, attr_name)
        if v is not None:
            v = list(v)
            if v and not isinstance(v[0], (list, tuple)):
                v = [v]
            setattr(record, attr_name, [list(s) for s in v])
    _validate_dtypes(record.input_type)
    _validate_dtypes(record.output_type)


class _Record:
    """Shared dict round-trip for all control-plane records."""

    def as_dict(self, remove_null_entries: bool = False) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        if remove_null_entries:
            d = {k: v for k, v in d.items() if v is not None}
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]):
        names = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in names})


@dataclass
class ModelEndpoint(_Record):
    """A single served model version (reference endpoints.py:64-78)."""

    engine_type: str = "custom"
    serving_url: str = ""
    model_id: Optional[str] = None
    version: Optional[str] = None
    preprocess_artifact: Optional[str] = None
    input_size: Optional[List[Any]] = None   # list of shapes (or one shape)
    input_type: Optional[List[str]] = None   # numpy dtype names
    input_name: Optional[List[str]] = None
    output_size: Optional[List[Any]] = None
    output_type: Optional[List[str]] = None
    output_name: Optional[List[str]] = None
    # Engine-specific tuning block (reference: Triton pbtxt aux config). Here: a
    # dict/str with batching buckets, mesh spec, dtype policy, compile options.
    auxiliary_cfg: Optional[Union[str, dict]] = None

    def __post_init__(self):
        _validate_engine_type(self.engine_type)
        if not self.serving_url:
            raise ValueError("serving_url is required")
        _normalize_io_spec(self)


@dataclass
class ModelMonitoring(_Record):
    """Auto-deployment query: newly published models matching the query become
    versioned endpoints (reference endpoints.py:44-61)."""

    base_serving_url: str = ""
    engine_type: str = "custom"
    monitor_project: Optional[str] = None
    monitor_name: Optional[str] = None
    monitor_tags: Optional[List[str]] = None
    only_published: bool = False
    max_versions: Optional[int] = None
    preprocess_artifact: Optional[str] = None
    input_size: Optional[List[Any]] = None
    input_type: Optional[List[str]] = None
    input_name: Optional[List[str]] = None
    output_size: Optional[List[Any]] = None
    output_type: Optional[List[str]] = None
    output_name: Optional[List[str]] = None
    auxiliary_cfg: Optional[Union[str, dict]] = None

    def __post_init__(self):
        _validate_engine_type(self.engine_type)
        if not self.base_serving_url:
            raise ValueError("base_serving_url is required")
        _normalize_io_spec(self)


@dataclass
class CanaryEP(_Record):
    """Weighted A/B routing entry (reference endpoints.py:81-88)."""

    endpoint: str = ""
    weights: List[float] = field(default_factory=list)
    load_endpoints: List[str] = field(default_factory=list)
    load_endpoint_prefix: Optional[str] = None

    def __post_init__(self):
        if not self.endpoint:
            raise ValueError("endpoint is required")
        if self.load_endpoints and self.load_endpoint_prefix:
            raise ValueError(
                "load_endpoints and load_endpoint_prefix are mutually exclusive"
            )
        if not self.load_endpoints and not self.load_endpoint_prefix:
            raise ValueError(
                "one of load_endpoints / load_endpoint_prefix is required"
            )


@dataclass
class MetricType(_Record):
    """One logged variable: scalar (bucketed histogram) | enum | value | counter
    (reference endpoints.py:93-96)."""

    type: str = "scalar"
    buckets: Optional[List[Any]] = None

    _TYPES = ("scalar", "enum", "value", "counter")

    def __post_init__(self):
        if self.type not in self._TYPES:
            raise ValueError(
                "metric type must be one of {}, got {!r}".format(self._TYPES, self.type)
            )
        if self.type in ("scalar", "enum") and not self.buckets:
            raise ValueError("metric type {!r} requires buckets".format(self.type))


@dataclass
class EndpointMetricLogging(_Record):
    """Per-endpoint logged variables + sampling frequency
    (reference endpoints.py:91-124)."""

    endpoint: str = ""
    log_frequency: Optional[float] = None  # 0..1 fraction of requests sampled
    metrics: Dict[str, MetricType] = field(default_factory=dict)

    def __post_init__(self):
        if not self.endpoint:
            raise ValueError("endpoint is required")
        if self.log_frequency is not None and not (0.0 <= float(self.log_frequency) <= 1.0):
            raise ValueError("log_frequency must be within [0, 1]")
        self.metrics = {
            k: (v if isinstance(v, MetricType) else MetricType.from_dict(v))
            for k, v in (self.metrics or {}).items()
        }

    def as_dict(self, remove_null_entries: bool = False) -> Dict[str, Any]:
        d = super().as_dict(remove_null_entries=remove_null_entries)
        d["metrics"] = {
            k: v.as_dict(remove_null_entries) if isinstance(v, MetricType) else v
            for k, v in (self.metrics or {}).items()
        }
        return d
