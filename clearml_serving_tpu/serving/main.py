"""Serving HTTP router (aiohttp).

Route surface parity with the reference FastAPI app
(clearml_serving/serving/main.py:1-233):

- ``POST /serve/{endpoint}``, ``/serve/{endpoint}/{version}``;
- OpenAI-compatible ``POST|GET /serve/openai/{endpoint_type...}`` where the
  path tail (e.g. ``v1/chat/completions``) becomes the serve type and
  ``body["model"]`` names the endpoint;
- transparent gzip request decompression;
- error taxonomy: 404 endpoint-not-found, 422 model/backend/value errors,
  500 internal (with the instance id in the payload);
- hardware-OOM policy: crash-and-restart (``os._exit(1)``) unless dev mode
  (reference main.py:111-123 for CUDA; here RESOURCE_EXHAUSTED / HBM OOM);
- streaming: engines may return a ``StreamingOutput`` (async generator) which
  is forwarded as an SSE response through the router unchanged — preserving the
  pre/process/post hook contract the same way the reference passes vLLM's
  StreamingResponse through.

The route prefix is configurable via ``TPUSERVE_DEFAULT_SERVE_SUFFIX``
(default "serve"). Process model: single process, or ``TPUSERVE_NUM_PROCESS``
forked workers sharing the port via SO_REUSEPORT (gunicorn-equivalent).
"""

from __future__ import annotations

import asyncio
import gzip
import json
import os
import signal
import traceback
from typing import Any, AsyncIterator, Optional

from aiohttp import web

from .model_request_processor import (
    EndpointBackendError,
    EndpointNotFoundException,
    ModelRequestProcessor,
    ServingInitializationError,
)
from .responses import JSONOutput, StreamingOutput, TextOutput
from ..engines.base import EndpointModelError
from ..errors import RequestError, is_hbm_oom as _is_hbm_oom


def _instance_id(processor: Optional[ModelRequestProcessor]) -> str:
    return getattr(processor, "_instance_id", "unknown") if processor else "unknown"


def _request_error_response(
    ex: RequestError, processor: Optional[ModelRequestProcessor]
) -> web.Response:
    """Structured lifecycle errors (errors.RequestError) map to their own
    status (408 deadline, 429/503 shed, 503/504 upstream) with a
    ``Retry-After`` hint so clients back off instead of hammering."""
    payload = ex.payload()
    payload["instance"] = _instance_id(processor)
    headers = {}
    if ex.retry_after is not None:
        headers["Retry-After"] = str(max(1, int(round(ex.retry_after))))
    return web.json_response(payload, status=ex.status, headers=headers)


async def _read_body(request: web.Request) -> Any:
    content_type = request.headers.get("Content-Type", "")
    if content_type.startswith("multipart/form-data"):
        # OpenAI audio API shape: file upload + form fields (model, language,
        # response_format, ...) — fields land in a dict, the upload's bytes
        # under its field name (usually "file")
        fields: dict = {}
        async for part in await request.multipart():
            if part.name is None:
                continue
            data = await part.read(decode=True)
            if part.filename is not None:
                fields[part.name] = data
            else:
                fields[part.name] = data.decode("utf-8", "replace")
        return fields
    raw = await request.read()
    # aiohttp transparently decompresses Content-Encoding: gzip; only
    # decompress here if the payload still carries the gzip magic (e.g. a
    # proxy stripped the header, or double-compressed clients).
    if raw[:2] == b"\x1f\x8b" and (
        request.headers.get("Content-Encoding", "").lower() == "gzip"
        or "gzip" in request.headers.get("Content-Type", "")
    ):
        raw = gzip.decompress(raw)
    if not raw:
        return None
    content_type = request.headers.get("Content-Type", "")
    if content_type and "application/json" not in content_type and "text/" not in content_type:
        return raw  # binary passthrough (e.g. image payloads, reference pytorch example)
    try:
        return json.loads(raw)
    except (json.JSONDecodeError, UnicodeDecodeError):
        return raw


def _engine_health(processor: ModelRequestProcessor) -> dict:
    """Per-endpoint engine health for /ready: any loaded processor exposing
    an ``engine`` with a ``health()`` surface (the LLM engine core, or a
    replica group's fleet aggregate) contributes; plain CPU/gRPC engines
    are stateless and always ready."""
    out = {}
    for url, proc in getattr(processor, "_engine_processor_lookup", {}).items():
        engine = getattr(proc, "engine", None)
        health = getattr(engine, "health", None)
        if callable(health):
            try:
                out[url] = health()
            except Exception as ex:
                out[url] = {"ready": False, "error": str(ex)}
    return out


def _fleet_health(processor: ModelRequestProcessor) -> dict:
    """Health of REPLICA-GROUP engines only (those exposing a ``router``):
    /health is a liveness probe and must not pay every plain engine's
    full health snapshot — nor run fleet ring sweeps it then discards —
    on each kubelet poll."""
    out = {}
    for url, proc in getattr(processor, "_engine_processor_lookup", {}).items():
        engine = getattr(proc, "engine", None)
        if getattr(engine, "router", None) is None:
            continue
        try:
            out[url] = engine.health()
        except Exception as ex:
            out[url] = {"ready": False, "error": str(ex)}
    return out


def _fleet_summary(engines: dict) -> dict:
    """Replica-fleet view of the engine healths (docs/replication.md):
    endpoints backed by a replica group report per-replica state and the
    router's ring — an endpoint is READY iff its ring has >= 1 member
    (the group's own ``ready`` aggregate), so one tripped replica never
    flips /ready while its siblings still serve."""
    out = {}
    for url, h in engines.items():
        router = h.get("router")
        if not isinstance(router, dict):
            continue  # single-engine endpoint: no fleet block
        out[url] = {
            "replicas": router.get("replicas"),
            "ring_size": router.get("ring_size"),
            "ring": router.get("ring"),
            "ready": bool(h.get("ready")),
            "failovers": h.get("failovers", 0),
            "fleet_brownout": router.get("fleet_brownout"),
            "per_replica": {
                name: {
                    "ready": bool(rh.get("ready")),
                    "ring_state": rh.get("ring_state"),
                    "brownout_stage": (rh.get("brownout") or {}).get(
                        "stage", 0
                    ),
                    "queue_depth": rh.get("queue_depth", 0),
                }
                for name, rh in (h.get("replicas") or {}).items()
            },
        }
    return out


def build_app(processor: ModelRequestProcessor) -> web.Application:
    app = web.Application(client_max_size=int(os.environ.get("TPUSERVE_MAX_BODY", 64 * 1024 * 1024)))
    app["processor"] = processor
    # SIGTERM drain state: once draining, new requests shed with 503 while
    # in-flight ones (inflight counter) finish up to the drain timeout.
    # A plain mutable dict: aiohttp deprecates reassigning app keys after
    # startup, so the handlers mutate THIS object, never the app mapping.
    app["lifecycle"] = {"draining": False, "inflight": 0}
    serve_suffix = os.environ.get("TPUSERVE_DEFAULT_SERVE_SUFFIX", "serve").strip("/")
    dev_mode = bool(os.environ.get("TPUSERVE_DEV_MODE"))

    async def process_with_exceptions(
        base_url: str, version: Optional[str], body: Any, serve_type: str
    ) -> web.StreamResponse:
        try:
            out = await processor.process_request(
                base_url=base_url, version=version, request_body=body, serve_type=serve_type
            )
        except EndpointNotFoundException as ex:
            return web.json_response(
                {"detail": "Error processing request: {}".format(ex)}, status=404
            )
        except RequestError as ex:
            return _request_error_response(ex, processor)
        except (EndpointModelError, EndpointBackendError, ValueError) as ex:
            return web.json_response(
                {
                    "detail": "Error processing request: {} {}".format(
                        type(ex).__name__, ex
                    ),
                    "instance": _instance_id(processor),
                },
                status=422,
            )
        except ServingInitializationError as ex:
            return web.json_response(
                {"detail": "Service not ready: {}".format(ex)}, status=500
            )
        except Exception as ex:
            if _is_hbm_oom(ex):
                # HBM OOM: the compiled state may be poisoned — crash so the
                # container restart loop brings up a clean process
                # (reference CUDA-OOM policy, main.py:111-123).
                if not dev_mode:
                    traceback.print_exc()
                    os._exit(1)
            traceback.print_exc()
            return web.json_response(
                {
                    "detail": "Internal error: {} {}".format(type(ex).__name__, ex),
                    "instance": _instance_id(processor),
                },
                status=500,
            )
        if isinstance(out, StreamingOutput):
            resp = web.StreamResponse(
                status=200,
                headers={
                    "Content-Type": out.content_type,
                    "Cache-Control": "no-cache",
                },
            )
            return resp, out  # handled by caller (needs the request to prepare)
        if isinstance(out, JSONOutput):
            return web.json_response(out.payload, status=out.status)
        if isinstance(out, TextOutput):
            return web.Response(text=out.text, content_type=out.content_type)
        if isinstance(out, (bytes, bytearray)):
            return web.Response(body=bytes(out), content_type="application/octet-stream")
        try:
            return web.json_response(out)
        except (TypeError, ValueError) as ex:
            return web.json_response(
                {
                    "detail": "Endpoint returned a non-JSON-serializable response "
                    "({}); return bytes or JSON-compatible types".format(ex),
                    "instance": _instance_id(processor),
                },
                status=500,
            )

    async def _respond(request: web.Request, result) -> web.StreamResponse:
        if isinstance(result, tuple):  # streaming
            resp, out = result
            try:
                try:
                    # prepare inside the guard: a disconnect racing the 200
                    # headers must still close the generator + emit stats
                    await resp.prepare(request)
                    async for chunk in out.generator:
                        if isinstance(chunk, str):
                            chunk = chunk.encode("utf-8")
                        await resp.write(chunk)
                except ConnectionResetError:
                    pass
            finally:
                # deliver GeneratorExit into the engine's SSE body NOW (frees
                # the decode slot on disconnect), then emit deferred stats
                aclose = getattr(out.generator, "aclose", None)
                if aclose is not None:
                    try:
                        await aclose()
                    except Exception:  # tpuserve: ignore[TPU401] client is gone; generator cleanup has no receiver
                        pass
                if out.on_complete is not None:
                    out.on_complete()
            try:
                await resp.write_eof()
            except ConnectionResetError:
                pass
            return resp
        return result

    async def serve_model(request: web.Request) -> web.StreamResponse:
        state = app["lifecycle"]
        if state["draining"]:
            # graceful shutdown: stop admitting, let in-flight work finish
            return web.json_response(
                {"detail": "server is draining", "code": "draining"},
                status=503,
                headers={"Retry-After": "5"},
            )
        state["inflight"] += 1
        try:
            return await _serve_model_inner(request)
        finally:
            state["inflight"] -= 1

    async def _serve_model_inner(request: web.Request) -> web.StreamResponse:
        tail = request.match_info["tail"].strip("/")
        try:
            body = await _read_body(request)
        except Exception as ex:
            # malformed multipart/body must follow the 422 JSON error
            # contract, not aiohttp's default 500 page
            return web.json_response(
                {"detail": "unreadable request body: {}".format(ex)}, status=422
            )
        if tail.startswith("openai/"):
            # OpenAI-compatible: serve type is the path, endpoint is body.model
            serve_type = tail[len("openai/"):]
            if serve_type == "version":
                # model-independent (reference show_version): answer without
                # requiring a body/model so plain GET works
                from ..version import __version__

                return web.json_response({"version": __version__})
            if not isinstance(body, dict) or not body.get("model"):
                return web.json_response(
                    {"detail": "OpenAI route requires a JSON body with a 'model' field"},
                    status=422,
                )
            result = await process_with_exceptions(
                base_url=str(body["model"]), version=None, body=body, serve_type=serve_type
            )
            return await _respond(request, result)
        parts = tail.split("/")
        # longest-match: try full tail as endpoint, else endpoint/version split
        version = None
        base_url = tail
        if len(parts) > 1:
            # membership-only check on the live dicts (no per-request copies)
            if tail not in processor._endpoints and tail not in processor._model_monitoring_endpoints:
                base_url, version = "/".join(parts[:-1]), parts[-1]
        result = await process_with_exceptions(
            base_url=base_url, version=version, body=body, serve_type="process"
        )
        return await _respond(request, result)

    async def health(request: web.Request) -> web.Response:
        payload = {
            "status": "ok",
            "instance": _instance_id(processor),
            "endpoints": sorted(processor.list_endpoints()),
        }
        # replica-fleet endpoints surface per-replica liveness here too
        # (docs/replication.md) — /health stays liveness (200 while the
        # process serves anything), /ready below is the routing signal
        fleet = _fleet_summary(_fleet_health(processor))
        if fleet:
            payload["fleet"] = fleet
        return web.json_response(payload)

    async def dashboard(request: web.Request) -> web.Response:
        return web.json_response(processor.get_serving_layout())

    async def ready(request: web.Request) -> web.Response:
        """Readiness (distinct from /health liveness): 503 while draining or
        while any loaded engine reports not-ready (stopped / watchdog
        recovery in progress) — so load balancers stop routing here while
        /health keeps the container from being killed."""
        engines = _engine_health(processor)
        # a replica-group endpoint aggregates its own readiness (ready iff
        # >= 1 ring member, docs/replication.md); the fleet block carries
        # the per-replica detail either way
        fleet = _fleet_summary(engines)
        not_ready = sorted(
            url for url, h in engines.items() if not h.get("ready")
        )
        # brownout summary (docs/slo_scheduling.md): a browned-out engine is
        # still READY — it is shedding load by policy, not failing — but
        # operators and load balancers watching /ready should see the stage
        brownout = {
            url: (h.get("brownout") or {}).get("stage", 0)
            for url, h in engines.items()
            if (h.get("brownout") or {}).get("stage")
        }
        draining = app["lifecycle"]["draining"]
        if draining or not_ready:
            return web.json_response(
                {
                    "status": "draining" if draining else "not_ready",
                    "instance": _instance_id(processor),
                    "not_ready": not_ready,
                    "brownout": brownout,
                    "fleet": fleet,
                    "engines": engines,
                },
                status=503,
                headers={"Retry-After": "5"},
            )
        return web.json_response(
            {
                "status": "ready",
                "instance": _instance_id(processor),
                "brownout": brownout,
                "fleet": fleet,
                "engines": engines,
            }
        )

    app.router.add_post("/{}/{{tail:.+}}".format(serve_suffix), serve_model)
    app.router.add_get("/{}/{{tail:openai/.+}}".format(serve_suffix), serve_model)
    app.router.add_get("/health", health)
    app.router.add_get("/ready", ready)
    app.router.add_get("/dashboard", dashboard)
    app.router.add_get("/", health)
    return app


async def drain_app(
    app: web.Application,
    processor: Optional[ModelRequestProcessor],
    timeout: Optional[float] = None,
) -> None:
    """Graceful drain: stop admitting (serve_model starts answering 503 the
    moment ``draining`` flips), wait for in-flight requests up to
    ``timeout`` seconds, then stop the engines and daemons cleanly. Called
    from the SIGTERM handler; exposed separately so tests can drive it."""
    state = app["lifecycle"]
    state["draining"] = True
    if timeout is None:
        timeout = float(os.environ.get("TPUSERVE_DRAIN_TIMEOUT", 30.0))
    deadline = asyncio.get_running_loop().time() + timeout
    while state["inflight"] > 0 and asyncio.get_running_loop().time() < deadline:
        await asyncio.sleep(0.05)
    if processor is not None:
        for proc in list(
            getattr(processor, "_engine_processor_lookup", {}).values()
        ):
            engine = getattr(proc, "engine", None)
            stop = getattr(engine, "stop", None)
            if callable(stop):
                try:
                    stop()
                except Exception:
                    traceback.print_exc()
        try:
            processor.stop()
        except Exception:
            traceback.print_exc()


def install_graceful_drain(app: web.Application) -> None:
    """SIGTERM -> drain -> exit. aiohttp's run_app exits on SIGINT; after
    the drain completes we re-raise SIGINT against ourselves so its normal
    graceful-shutdown path (connection close, cleanup hooks) runs."""

    async def _on_startup(app: web.Application) -> None:
        loop = asyncio.get_running_loop()

        def _begin_drain() -> None:
            state = app["lifecycle"]
            if state["draining"]:
                return  # second SIGTERM: drain already in progress
            # flip synchronously: the guard above must close the window
            # BEFORE the drain task gets scheduled, or back-to-back SIGTERMs
            # would spawn duplicate drains (and duplicate exit SIGINTs)
            state["draining"] = True

            async def _drain_then_exit() -> None:
                await drain_app(app, app.get("processor"))
                os.kill(os.getpid(), signal.SIGINT)

            loop.create_task(_drain_then_exit())

        try:
            loop.add_signal_handler(signal.SIGTERM, _begin_drain)
        except (NotImplementedError, RuntimeError):
            pass  # non-unix / nested-loop environments keep default handling

    app.on_startup.append(_on_startup)


def maybe_start_profiler() -> None:
    """XLA profiler capture server (SURVEY.md §5.1): set
    TPUSERVE_PROFILER_PORT and connect TensorBoard / `jax.profiler` tooling to
    capture device traces from the live service."""
    port = os.environ.get("TPUSERVE_PROFILER_PORT")
    if port:
        try:
            import jax

            jax.profiler.start_server(int(port))
            print("jax profiler server on :{}".format(port))
        except Exception as ex:
            print("profiler server failed: {}".format(ex))


def setup_processor() -> ModelRequestProcessor:
    """Resolve the control-plane service (env TPUSERVE_SERVICE_ID, or the most
    recent service) and launch the sync/stats daemons
    (reference init.py setup_task + startup_event)."""
    from ..engines import load_engine_modules

    load_engine_modules()
    maybe_start_profiler()
    service_id = os.environ.get("TPUSERVE_SERVICE_ID") or os.environ.get(
        "CLEARML_SERVING_TASK_ID"
    )
    processor = ModelRequestProcessor(service_id=service_id or None)
    poll_freq_min = float(os.environ.get("TPUSERVE_POLL_FREQ", 5.0))
    processor.launch(poll_frequency_sec=poll_freq_min * 60.0)
    return processor


def main() -> None:
    port = int(os.environ.get("TPUSERVE_PORT", 8080))
    host = os.environ.get("TPUSERVE_HOST", "0.0.0.0")
    num_proc = int(os.environ.get("TPUSERVE_NUM_PROCESS", 1))

    if num_proc > 1:
        # gunicorn-equivalent pre-fork model: N workers share the port via
        # SO_REUSEPORT; each builds its own processor post-fork.
        import multiprocessing

        def _worker():
            processor = setup_processor()
            app = build_app(processor)
            install_graceful_drain(app)
            web.run_app(
                app, host=host, port=port, reuse_port=True,
                print=None,
            )

        procs = [multiprocessing.Process(target=_worker) for _ in range(num_proc)]
        for p in procs:
            p.start()

        def _forward_term(signum, frame):
            # pre-fork mode: SIGTERM lands on THIS parent (pid 1 in a
            # container) — forward it so every worker runs its graceful
            # drain instead of dying with the parent
            for p in procs:
                if p.is_alive():
                    p.terminate()

        signal.signal(signal.SIGTERM, _forward_term)
        for p in procs:
            p.join()
    else:
        processor = setup_processor()
        app = build_app(processor)
        install_graceful_drain(app)
        web.run_app(app, host=host, port=port)


if __name__ == "__main__":
    main()
