"""Request orchestration core.

Capability parity with the reference's ModelRequestProcessor
(clearml_serving/serving/model_request_processor.py, 1569 LoC):

- endpoint registry (static + monitoring-generated), lazy per-endpoint engine
  construction with cache eviction after config sync;
- **zero-downtime config updates**: an inflight-request counter (GIL-atomic two
  `itertools.count` design, reference :58-70) lets `deserialize` drain inflight
  requests, atomically swap every endpoint dict, and release — requests arriving
  mid-swap async-sleep briefly and retry;
- config-hash change detection so a poll with no changes is a no-op;
- canary routing: weighted choice over resolved routes, fixed lists (weight
  renormalization, missing-endpoint skip) and prefix mode (numeric-version-desc
  resolution);
- auto-deployment: model-registry queries materialize versioned endpoints with
  monotone version numbers and publish them to the `model_monitoring_eps`
  config object for engine sidecars;
- background sync daemon (heartbeat ping + reload + monitored query) and a
  batched stats queue drained to the statistics broker;
- per-request sampled statistics with reserved `_latency`/`_count`/`_url` keys.

The control plane is a ServingService document (state/store.py) instead of a
ClearML Task; the mechanism (poll + reconcile, serialize/deserialize) is the
same.
"""

from __future__ import annotations

import asyncio
import gc
import itertools
import os
import random
import re
import threading
import time
from typing import Any, Dict, List, Optional, Tuple, Union

import numpy as np

from .endpoints import (
    CanaryEP,
    EndpointMetricLogging,
    ModelEndpoint,
    ModelMonitoring,
)
from ..engines import get_engine_cls
from ..engines.base import BaseEngineRequest
from .responses import StreamingOutput
from ..state import ModelRegistry, ServingService, StateStore
from ..utils.files import sha256_obj
from ..version import __version__


# serve-type dispatch allowlist: v1_chat_completions, v2_embeddings, ...
# versioned API handler names, plus the bare "version" route (the reference's
# show_version, preprocess_service.py:890 / :1218)
_SERVE_TYPE_RE = re.compile(r"^(v\d+_[a-z][a-z0-9_]*|version)$")


class EndpointNotFoundException(Exception):
    pass


class EndpointBackendError(Exception):
    pass


class ServingInitializationError(Exception):
    pass


class FastWriteCounter:
    """Lock-free inflight counter: two GIL-atomic itertools counters
    (reference model_request_processor.py:58-70)."""

    def __init__(self):
        self._inc = itertools.count()
        self._dec = itertools.count()

    def inc(self) -> None:
        next(self._inc)

    def dec(self) -> None:
        next(self._dec)

    def value(self) -> int:
        # next() returns the number of prior calls; advancing both counters by
        # one each keeps the inc-dec difference invariant across reads.
        return next(self._inc) - next(self._dec)


class FastSimpleQueue:
    """Stats queue with batched wakeups: the notifier only fires the Event
    every `_notify_every` seconds, trading latency for throughput on the hot
    path (reference :73-101).

    Backend: a plain deque by default (GIL-atomic append/popleft — fastest in
    CPython). Setting ``TPUSERVE_NATIVE_QUEUE=1`` switches to the native
    lock-free MPSC ring (clearml_serving_tpu/native) for free-threaded /
    subinterpreter builds where the deque path contends; packets are JSON on
    the wire either way."""

    _notify_every = 10.0

    def __init__(self):
        import json as _json
        from collections import deque

        self._json = _json
        self._native = None
        if os.environ.get("TPUSERVE_NATIVE_QUEUE"):
            try:
                from ..native import NativeQueue

                self._native = NativeQueue(capacity=1024, cell_bytes=4096)
            except Exception:  # tpuserve: ignore[TPU401] optional native accel; deque fallback below
                pass
        self._q = deque()
        self._event = threading.Event()
        self._last_notify = time.time()

    def put(self, item) -> None:
        if self._native is not None:
            try:
                if self._native.push(self._json.dumps(item).encode("utf-8")):
                    self._maybe_notify()
                    return
            except (TypeError, ValueError):
                pass  # non-JSON stat packet: deque fallback below
        self._q.append(item)
        self._maybe_notify()

    def _maybe_notify(self) -> None:
        if time.time() - self._last_notify > self._notify_every:
            self._last_notify = time.time()
            self._event.set()

    def get_all(self, timeout: float) -> List[Any]:
        self._event.wait(timeout=timeout)
        self._event.clear()
        out: List[Any] = []
        if self._native is not None:
            for raw in self._native.pop_all():
                try:
                    out.append(self._json.loads(raw))
                except ValueError:
                    pass
        while True:
            try:
                out.append(self._q.popleft())
            except IndexError:
                break
        return out


class ModelRequestProcessor:
    _config_key_serving_base_url = "serving_base_url"
    _config_key_engine_grpc_addr = "engine_grpc_server"
    _config_key_stats_broker = "stats_broker"
    _config_key_metric_log_freq = "metric_logging_freq"

    # thread-affinity registry (tpuserve-analyze TPU501,
    # docs/static_analysis.md): the endpoint/canary/metric registries and
    # telemetry counters are read lock-free on the serving event loop. The
    # sync daemon (_sync_daemon_loop) may REPLACE them, but only through
    # the zero-downtime swap protocol — atomic dict rebinds under
    # _update_lock_guard after the inflight-request drain — and every
    # daemon-side mutator is annotated with that reason at its def line.
    # Any new cross-thread mutation must either go through the same
    # protocol (and say so) or move onto the event loop.
    __affine_to__ = {
        "loop": (
            "_endpoints", "_model_monitoring", "_model_monitoring_endpoints",
            "_model_monitoring_versions", "_canary_endpoints",
            "_canary_route", "_metric_logging", "_engine_processor_lookup",
            "_telemetry",
        ),
    }

    def __init__(
        self,
        service_id: Optional[str] = None,
        state_root: Optional[str] = None,
        force_create: bool = False,
        name: Optional[str] = None,
        project: Optional[str] = None,
        tags: Optional[List[str]] = None,
        update_lock_guard: Optional[threading.Lock] = None,
    ):
        self._store = StateStore(state_root)
        self._registry = ModelRegistry(self._store.root)
        if force_create:
            self._service = self._store.create_service(
                name or "tpu-serving", project=project or "DevOps", tags=tags
            )
        elif service_id:
            self._service = self._store.get_service(service_id)
        else:
            svc = self._store.find_service(name)
            if svc is None:
                raise ServingInitializationError(
                    "no serving service found (create one with `tpu-serving create`)"
                )
            self._service = svc

        self._endpoints: Dict[str, ModelEndpoint] = {}
        self._model_monitoring: Dict[str, ModelMonitoring] = {}
        self._model_monitoring_endpoints: Dict[str, ModelEndpoint] = {}
        self._model_monitoring_versions: Dict[str, Dict[str, int]] = {}
        self._canary_endpoints: Dict[str, CanaryEP] = {}
        self._canary_route: Dict[str, dict] = {}
        self._metric_logging: Dict[str, EndpointMetricLogging] = {}
        self._engine_processor_lookup: Dict[str, BaseEngineRequest] = {}
        self._last_update_hash: Optional[str] = None
        self._sync_daemon: Optional[threading.Thread] = None
        self._stats_sender: Optional[threading.Thread] = None
        self._stats_queue = FastSimpleQueue()
        self._inflight = FastWriteCounter()
        self._update_lock_flag = False
        self._update_lock_guard = update_lock_guard or threading.Lock()
        self._stop_event = threading.Event()
        self._poll_frequency_sec = 300.0
        self._serving_base_url: Optional[str] = None
        self._metric_log_freq: float = 0.0
        self._stats_broker_url: Optional[str] = None
        self._stats_producer = None
        self._stats_producer_url: Optional[str] = None
        self._instance_id = "inst_{:x}".format(random.getrandbits(48))
        # per-endpoint telemetry counters (reference endpoint_telemetry,
        # :165-251): request/error counts + cumulative latency, surfaced via
        # /dashboard. Plain dicts mutated GIL-atomically per key.
        self._telemetry: Dict[str, Dict[str, float]] = {}

    # ------------------------------------------------------------------ API

    def get_id(self) -> str:
        return self._service.id

    @property
    def service(self) -> ServingService:
        return self._service

    @property
    def registry(self) -> ModelRegistry:
        return self._registry

    def get_version(self) -> str:
        props = self._service.get_runtime_properties()
        return str(props.get("version") or __version__)

    # -- endpoint management (CLI surface) ----------------------------------

    def add_endpoint(
        self,
        endpoint: Union[ModelEndpoint, dict],
        preprocess_code: Optional[str] = None,
    ) -> str:
        if isinstance(endpoint, dict):
            endpoint = ModelEndpoint.from_dict(endpoint)
        self._validate_endpoint(endpoint)
        endpoint.serving_url = endpoint.serving_url.strip("/")
        url = self._normalize_endpoint_url(endpoint.serving_url, endpoint.version)
        if url in self._endpoints and not self._endpoints[url] == endpoint:
            print("Warning: overwriting endpoint {}".format(url))
        if endpoint.model_id is None and not preprocess_code and endpoint.engine_type not in (
            "custom", "custom_async", "llm",
        ):
            raise ValueError(
                "endpoint {!r} requires a model_id for engine {!r}".format(
                    url, endpoint.engine_type
                )
            )
        if preprocess_code:
            endpoint.preprocess_artifact = self._upload_preprocess_code(url, preprocess_code)
        self._endpoints[url] = endpoint
        return url

    def remove_endpoint(self, endpoint_url: str) -> bool:
        endpoint_url = endpoint_url.strip("/")
        for d in (self._endpoints, self._model_monitoring, self._canary_endpoints):
            if endpoint_url in d:
                d.pop(endpoint_url, None)
                return True
        return False

    def add_model_monitoring(
        self,
        monitoring: Union[ModelMonitoring, dict],
        preprocess_code: Optional[str] = None,
    ) -> str:
        if isinstance(monitoring, dict):
            monitoring = ModelMonitoring.from_dict(monitoring)
        name = monitoring.base_serving_url.strip("/")
        monitoring.base_serving_url = name
        if preprocess_code:
            monitoring.preprocess_artifact = self._upload_preprocess_code(name, preprocess_code)
        self._model_monitoring[name] = monitoring
        return name

    def remove_model_monitoring(self, base_url: str) -> bool:
        return self._model_monitoring.pop(base_url.strip("/"), None) is not None

    def add_canary_endpoint(self, canary: Union[CanaryEP, dict]) -> str:
        if isinstance(canary, dict):
            canary = CanaryEP.from_dict(canary)
        self._canary_endpoints[canary.endpoint.strip("/")] = canary
        return canary.endpoint

    def remove_canary_endpoint(self, endpoint_url: str) -> bool:
        return self._canary_endpoints.pop(endpoint_url.strip("/"), None) is not None

    def add_metric_logging(self, metric: Union[EndpointMetricLogging, dict]) -> bool:
        if isinstance(metric, dict):
            metric = EndpointMetricLogging.from_dict(metric)
        name = str(metric.endpoint).strip("/")
        metric.endpoint = name
        if "*" not in name and name not in self._endpoints and name.rsplit("/", 1)[0] not in (
            list(self._model_monitoring) + [u.rsplit("/", 1)[0] for u in self._endpoints]
        ):
            # wildcard-less metric on an unknown endpoint is allowed but noted
            print("Warning: metric logging for unknown endpoint {!r}".format(name))
        existing = self._metric_logging.get(name)
        if existing:
            existing.metrics.update(metric.metrics)
            if metric.log_frequency is not None:
                existing.log_frequency = metric.log_frequency
        else:
            self._metric_logging[name] = metric
        return True

    def remove_metric_logging(self, endpoint: str, variable: Optional[str] = None) -> bool:
        name = endpoint.strip("/")
        if name not in self._metric_logging:
            return False
        if variable is None:
            self._metric_logging.pop(name)
            return True
        return self._metric_logging[name].metrics.pop(variable, None) is not None

    def list_endpoints(self) -> Dict[str, ModelEndpoint]:
        return dict(self._endpoints)

    def list_model_monitoring(self) -> Dict[str, ModelMonitoring]:
        return dict(self._model_monitoring)

    def list_canary_endpoints(self) -> Dict[str, CanaryEP]:
        return dict(self._canary_endpoints)

    def list_endpoint_logging(self) -> Dict[str, EndpointMetricLogging]:
        return dict(self._metric_logging)

    def get_endpoint_metric_logging(self, endpoint: str) -> Optional[EndpointMetricLogging]:
        """Resolve a concrete endpoint url against specs incl. `model/*`
        wildcards (reference :925-949)."""
        endpoint = endpoint.strip("/")
        direct = self._metric_logging.get(endpoint)
        if direct:
            return direct
        for name, spec in self._metric_logging.items():
            # "model/*" matches "model/..." only — not "model2/..."
            if name.endswith("/*") and endpoint.startswith(name[:-1]):
                return spec
        return None

    # -- serialization (control-plane sync) ---------------------------------

    def serialize(self) -> None:
        config = {
            "endpoints": {k: v.as_dict() for k, v in self._endpoints.items()},
            "model_monitoring": {k: v.as_dict() for k, v in self._model_monitoring.items()},
            "canary": {k: v.as_dict() for k, v in self._canary_endpoints.items()},
            "metric_logging": {k: v.as_dict() for k, v in self._metric_logging.items()},
            "model_monitoring_eps": {
                k: v.as_dict() for k, v in self._model_monitoring_endpoints.items()
            },
            "model_monitoring_versions": self._model_monitoring_versions,
        }
        self._service.set_configuration_objects(config)
        self._service.set_runtime_properties({"version": __version__})

    def deserialize(  # tpuserve: ignore[TPU501] zero-downtime swap: the sync daemon rebinds the registries atomically under _update_lock_guard after draining inflight requests (skip_sync callers own the processor exclusively)
        self,
        skip_sync: bool = False,
        prefetch_artifacts: bool = False,
    ) -> bool:
        """Reload state from the service document. Returns True if anything
        changed. When not `skip_sync`, performs the zero-downtime swap: set the
        update flag, drain inflight requests, swap dicts, release."""
        # One consistent snapshot — config objects, params, and artifact hashes
        # all come from a single atomic document read so a concurrent writer
        # can never produce a torn config (e.g. new canary + old endpoints).
        snapshot = self._service.get_snapshot()
        configuration = snapshot.get("configuration") or {}
        config = {
            name: configuration.get(name) or {}
            for name in (
                "endpoints",
                "model_monitoring",
                "canary",
                "metric_logging",
                "model_monitoring_eps",
                "model_monitoring_versions",
            )
        }
        artifact_hashes = {
            name: (meta or {}).get("hash")
            for name, meta in (snapshot.get("artifacts") or {}).items()
        }
        params = snapshot.get("parameters") or {}
        new_hash = sha256_obj(
            {"config": config, "artifacts": artifact_hashes, "params": params}
        )
        if new_hash == self._last_update_hash:
            return False

        endpoints = {
            k: ModelEndpoint.from_dict(v) for k, v in config["endpoints"].items()
        }
        monitoring = {
            k: ModelMonitoring.from_dict(v) for k, v in config["model_monitoring"].items()
        }
        monitoring_eps = {
            k: ModelEndpoint.from_dict(v) for k, v in config["model_monitoring_eps"].items()
        }
        canary = {k: CanaryEP.from_dict(v) for k, v in config["canary"].items()}
        metrics = {
            k: EndpointMetricLogging.from_dict(v)
            for k, v in config["metric_logging"].items()
        }
        self._deserialize_conf_params(params)

        if skip_sync:
            self._endpoints = endpoints
            self._model_monitoring = monitoring
            self._model_monitoring_endpoints = monitoring_eps
            self._model_monitoring_versions = dict(config["model_monitoring_versions"])
            self._canary_endpoints = canary
            self._metric_logging = metrics
            self._update_canary_lookup()
            self._last_update_hash = new_hash
            return True

        with self._update_lock_guard:
            self._update_lock_flag = True
            try:
                # Drain inflight requests (zero-downtime swap, reference :700-717).
                t0 = time.time()
                while self._inflight.value() > 0 and time.time() - t0 < 60:
                    time.sleep(0.05)
                self._endpoints = endpoints
                self._model_monitoring = monitoring
                self._model_monitoring_endpoints = monitoring_eps
                self._model_monitoring_versions = dict(config["model_monitoring_versions"])
                self._canary_endpoints = canary
                self._metric_logging = metrics
                self._update_canary_lookup()
                self._last_update_hash = new_hash
            finally:
                self._update_lock_flag = False

        # Evict engine processors whose endpoint disappeared or changed.
        self._cleanup_processor_cache()
        self._prune_telemetry()
        if prefetch_artifacts:
            for url in list(self._endpoints) + list(self._model_monitoring_endpoints):
                try:
                    self._get_processor(url)
                except Exception:  # tpuserve: ignore[TPU401] prefetch only warms the cache; the request path re-raises properly
                    pass
        return True

    def _prune_telemetry(self) -> None:  # tpuserve: ignore[TPU501] GIL-atomic per-key pops over a snapshot key list; the loop only inserts, so a lost insert-after-prune is re-created on the next request
        """Drop counters for endpoints that no longer exist (bounded growth
        across removed endpoints / churned monitored versions)."""
        live = set(self._endpoints) | set(self._model_monitoring_endpoints)
        for url in [u for u in list(self._telemetry) if u not in live]:
            self._telemetry.pop(url, None)

    def _cleanup_processor_cache(self) -> None:  # tpuserve: ignore[TPU501] GIL-atomic pops over a snapshot; inflight requests keep their processor instance alive by reference (docstring protocol)
        """Evict processors whose endpoint disappeared, changed, or whose
        preprocess artifact content changed (hot reload of re-uploaded user
        code). Runs on the sync thread while the event loop serves requests:
        iterate a snapshot, and do NOT call unload() — an inflight request may
        still hold the instance; GC finalizes it via __del__ once the last
        reference drops."""
        all_eps = {**self._model_monitoring_endpoints, **self._endpoints}
        stale = []
        for url, proc in list(self._engine_processor_lookup.items()):
            ep = all_eps.get(url)
            if ep is None or ep != proc.endpoint:
                stale.append(url)
                continue
            art = ep.preprocess_artifact
            if art and proc._preprocess_hash != self._service.artifact_hash(art):
                stale.append(url)
        for url in stale:
            self._engine_processor_lookup.pop(url, None)
        if stale:
            gc.collect()

    def _deserialize_conf_params(self, params: Optional[Dict[str, Any]] = None) -> None:
        if params is None:
            params = self._service.get_parameters()
        self._serving_base_url = params.get(self._config_key_serving_base_url) or os.environ.get(
            "TPUSERVE_DEFAULT_BASE_SERVE_URL", "http://127.0.0.1:8080/serve"
        )
        self._stats_broker_url = params.get(self._config_key_stats_broker) or os.environ.get(
            "TPUSERVE_STATS_BROKER", ""
        )
        try:
            self._metric_log_freq = float(
                params.get(self._config_key_metric_log_freq)
                if params.get(self._config_key_metric_log_freq) is not None
                else os.environ.get("TPUSERVE_DEFAULT_METRIC_LOG_FREQ", 0.0)
            )
        except (TypeError, ValueError):
            self._metric_log_freq = 0.0
        BaseEngineRequest.set_server_config(
            {
                "serving_base_url": self._serving_base_url,
                "engine_grpc_server": params.get(self._config_key_engine_grpc_addr)
                or os.environ.get("TPUSERVE_DEFAULT_ENGINE_GRPC_ADDR"),
                "stats_broker": self._stats_broker_url,
            }
        )

    def configure(
        self,
        external_serving_base_url: Optional[str] = None,
        external_engine_grpc_address: Optional[str] = None,
        external_stats_broker: Optional[str] = None,
        default_metric_log_freq: Optional[float] = None,
    ) -> None:
        params = {}
        if external_serving_base_url is not None:
            params[self._config_key_serving_base_url] = external_serving_base_url
        if external_engine_grpc_address is not None:
            params[self._config_key_engine_grpc_addr] = external_engine_grpc_address
        if external_stats_broker is not None:
            params[self._config_key_stats_broker] = external_stats_broker
        if default_metric_log_freq is not None:
            params[self._config_key_metric_log_freq] = float(default_metric_log_freq)
        if params:
            self._service.update_parameters(params)

    # -- canary --------------------------------------------------------------

    def _update_canary_lookup(self) -> None:  # tpuserve: ignore[TPU501] builds a fresh dict and rebinds atomically (readers see old or new route table, never a torn one); daemon callers sit inside the deserialize swap protocol
        canary_route = {}
        for name, canary in self._canary_endpoints.items():
            if canary.load_endpoint_prefix:
                prefix = canary.load_endpoint_prefix.strip("/")
                # match on name boundaries only: prefix "ep" must match
                # "ep" and "ep/2" but NOT "ep2/1"
                matches = [
                    u for u in list(self._endpoints) + list(self._model_monitoring_endpoints)
                    if u == prefix or u.startswith(prefix + "/")
                ]
                # sort by zero-padded numeric version suffix, descending
                def _version_key(u):
                    tail = u.rsplit("/", 1)[-1]
                    return tail.zfill(12) if tail.isdigit() else tail
                matches = sorted(matches, key=_version_key, reverse=True)
                matches = matches[: len(canary.weights)]
                weights = canary.weights[: len(matches)]
            else:
                matches, weights = [], []
                for ep, w in zip(canary.load_endpoints, canary.weights):
                    ep = ep.strip("/")
                    if ep in self._endpoints or ep in self._model_monitoring_endpoints:
                        matches.append(ep)
                        weights.append(w)
            if not matches:
                continue
            total = sum(weights)
            if total <= 0:
                continue
            canary_route[name] = {
                "endpoints": matches,
                "weights": [w / total for w in weights],
            }
        self._canary_route = canary_route

    def _process_canary(self, base_url: str) -> Optional[str]:
        route = self._canary_route.get(base_url)
        if not route:
            return None
        return str(np.random.choice(route["endpoints"], p=route["weights"]))

    # -- monitoring auto-deployment ------------------------------------------

    def _update_monitored_models(self) -> bool:  # tpuserve: ignore[TPU501] daemon-side auto-deployment: materialized endpoints rebind atomically and version assignments only grow; the loop never mutates these maps concurrently (CLI mutators run out-of-process)
        """Run each monitoring query; assign monotone versions to newly seen
        model ids; (de)materialize versioned endpoints (reference :816-923)."""
        changed = False
        new_eps: Dict[str, ModelEndpoint] = {}
        for name, mon in self._model_monitoring.items():
            records = self._registry.query(
                project=mon.monitor_project or None,
                name=mon.monitor_name or None,
                tags=mon.monitor_tags or None,
                only_published=mon.only_published,
                max_results=mon.max_versions or None,
            )
            versions = self._model_monitoring_versions.setdefault(name, {})
            next_version = (max(versions.values()) + 1) if versions else 1
            # oldest-first so version numbers increase with recency
            for record in sorted(records, key=lambda r: r.created):
                if record.id not in versions:
                    versions[record.id] = next_version
                    next_version += 1
                    changed = True
            keep_ids = {r.id for r in records}
            for model_id in keep_ids:
                version = versions[model_id]
                url = "{}/{}".format(name, version)
                ep = ModelEndpoint(
                    engine_type=mon.engine_type,
                    serving_url=url,
                    model_id=model_id,
                    version=str(version),
                    preprocess_artifact=mon.preprocess_artifact,
                    input_size=mon.input_size,
                    input_type=mon.input_type,
                    input_name=mon.input_name,
                    output_size=mon.output_size,
                    output_type=mon.output_type,
                    output_name=mon.output_name,
                    auxiliary_cfg=mon.auxiliary_cfg,
                )
                if new_eps.get(url) != ep:
                    new_eps[url] = ep
        if new_eps != self._model_monitoring_endpoints:
            changed = True
        if changed:
            self._model_monitoring_endpoints = new_eps
            self._update_canary_lookup()
            # publish for sidecars + persistence of version assignments
            self._service.set_configuration_objects(
                {
                    "model_monitoring_eps": {
                        k: v.as_dict() for k, v in new_eps.items()
                    },
                    "model_monitoring_versions": self._model_monitoring_versions,
                }
            )
            self._last_update_hash = None  # force re-hash next poll
        return changed

    # -- request processing ---------------------------------------------------

    def _normalize_endpoint_url(self, endpoint: str, version: Optional[str] = None) -> str:
        return "{}/{}".format(endpoint.rstrip("/"), version) if version else endpoint.strip("/")

    def _resolve_lora_alias(self, name: str) -> Optional[str]:
        """Endpoint whose aux ``engine.lora.modules`` declares adapter
        ``name`` (config-driven, so it works before the endpoint's engine has
        ever been constructed). None if nothing claims the name."""
        for registry in (self._endpoints, self._model_monitoring_endpoints):
            for url, ep in registry.items():
                aux = ep.auxiliary_cfg if isinstance(ep.auxiliary_cfg, dict) else {}
                modules = ((aux.get("engine") or {}).get("lora") or {}).get(
                    "modules"
                ) or {}
                if name in modules:
                    return url
        return None

    def _get_processor(self, url: str) -> BaseEngineRequest:  # tpuserve: ignore[TPU501] GIL-atomic lazy-cache insert; the daemon only reaches this through launch-time prefetch (before serving) and a double construction is wasteful, not unsound
        processor = self._engine_processor_lookup.get(url)
        if processor is None:
            ep = self._endpoints.get(url) or self._model_monitoring_endpoints.get(url)
            if ep is None:
                raise EndpointNotFoundException("endpoint {!r} not found".format(url))
            processor_cls = get_engine_cls(ep.engine_type)
            processor = processor_cls(ep, service=self._service, registry=self._registry)
            self._engine_processor_lookup[url] = processor
        return processor

    async def process_request(
        self, base_url: str, version: Optional[str], request_body: Any,
        serve_type: str = "process",
    ) -> Any:
        """The hot path (reference :253-304)."""
        self._inflight.inc()
        try:
            # stall-free update: wait out an in-progress config swap
            while self._update_lock_flag:
                self._inflight.dec()
                await asyncio.sleep(0.5 + 1.0 * random.random())
                self._inflight.inc()
            url = self._normalize_endpoint_url(base_url, version)
            canary_url = self._process_canary(url)
            if canary_url:
                url = canary_url
            if url not in self._endpoints and url not in self._model_monitoring_endpoints:
                # OpenAI multi-LoRA: an adapter name declared in some llm
                # endpoint's aux engine.lora.modules serves as a top-level
                # model name (vLLM-compatible); route it to that endpoint —
                # the engine applies the adapter per the body's `model` field
                alias = self._resolve_lora_alias(url)
                if alias is None:
                    raise EndpointNotFoundException(
                        "endpoint {!r} not found (have: {})".format(
                            url,
                            sorted(list(self._endpoints) + list(self._model_monitoring_endpoints)),
                        )
                    )
                url = alias
            processor = self._get_processor(url)
            tic = time.monotonic()
            entry = self._telemetry.setdefault(
                url, {"requests": 0, "errors": 0, "latency_sum": 0.0}
            )
            # "requests" counts every attempt (errors included), so
            # errors/requests is a true error rate
            entry["requests"] += 1
            try:
                result = await self._process_request(
                    processor, url, request_body, serve_type
                )
            except Exception:
                entry["errors"] += 1
                raise
            entry["latency_sum"] += time.monotonic() - tic
            return result
        finally:
            self._inflight.dec()

    async def _process_request(
        self, processor: BaseEngineRequest, url: str, body: Any, serve_type: str
    ) -> Any:
        # sampling decision (reference :1316-1323)
        metric_spec = self.get_endpoint_metric_logging(url)
        freq = (
            metric_spec.log_frequency
            if metric_spec is not None and metric_spec.log_frequency is not None
            else self._metric_log_freq
        )
        collect = freq and random.random() <= freq
        custom_stats: Dict[str, Any] = {}
        collect_fn = custom_stats.update if collect else None
        state: Dict[str, Any] = {}

        tic = time.time()
        if serve_type == "process":
            if processor.is_preprocess_async:
                data = await processor.preprocess(body, state, collect_fn)
            else:
                data = processor.preprocess(body, state, collect_fn)
            if processor.is_process_async:
                out = await processor.process(data, state, collect_fn)
            else:
                out = processor.process(data, state, collect_fn)
        else:
            # OpenAI-style serve types dispatch to a named engine method,
            # e.g. "v1/chat/completions" -> processor.v1_chat_completions
            # (reference :1327-1339).
            method_name = serve_type.replace("/", "_").replace(".", "_")
            # Allowlist: only versioned API handler names (v1_*, v2_* ...) are
            # dispatchable — a URL-derived name must never reach lifecycle or
            # dunder attributes (e.g. /serve/openai/__class__ or /unload).
            if not _SERVE_TYPE_RE.match(method_name):
                raise EndpointBackendError(
                    "invalid serve type {!r}".format(serve_type)
                )
            method = getattr(processor, method_name, None)
            if method is None and processor._preprocess is not None:
                # user Preprocess code may implement the OpenAI-style handler
                method = getattr(processor._preprocess, method_name, None)
            if method is None:
                raise EndpointBackendError(
                    "endpoint engine {!r} does not support serve type {!r}".format(
                        processor.engine_name, serve_type
                    )
                )
            if processor.is_preprocess_async:
                data = await processor.preprocess(body, state, collect_fn)
            else:
                data = processor.preprocess(body, state, collect_fn)
            out = method(data, state, collect_fn)
            if asyncio.iscoroutine(out):
                out = await out
        if processor.is_postprocess_async:
            result = await processor.postprocess(out, state, collect_fn)
        else:
            result = processor.postprocess(out, state, collect_fn)

        if collect:

            def _emit_stats() -> None:
                stats = {
                    "_url": url,
                    "_latency": round(time.time() - tic, 6),
                    "_count": int(1.0 / freq) if freq else 1,
                }
                # whitelisted request/response fields per the metric spec
                if metric_spec is not None:
                    for key in metric_spec.metrics:
                        if key.startswith("_"):
                            continue
                        if isinstance(body, dict) and key in body:
                            stats[key] = body[key]
                        elif isinstance(result, dict) and key in result:
                            stats[key] = result[key]
                stats.update(custom_stats)
                self._stats_queue.put(stats)

            if isinstance(result, StreamingOutput):
                # streaming: defer the packet to stream completion so
                # _latency covers the whole stream and the engine's
                # end-of-stream TTFT/token stats (written through collect_fn
                # during the body) are included — streaming chat is THE LLM
                # workload; its TTFT is the BASELINE.md headline metric
                result.on_complete = _emit_stats
            else:
                _emit_stats()
        return result

    # -- daemons --------------------------------------------------------------

    def launch(self, poll_frequency_sec: float = 300.0) -> None:
        """Initial sync + background sync daemon + stats sender
        (reference :951-1047)."""
        self._poll_frequency_sec = poll_frequency_sec
        # Prefetch at startup: engine construction (model load + jit compile)
        # must happen here, not lazily on the event loop's first request.
        self.deserialize(prefetch_artifacts=True)
        self._update_monitored_models()
        self._stop_event.clear()
        self._sync_daemon = threading.Thread(target=self._sync_daemon_loop, daemon=True)
        self._sync_daemon.start()
        self._stats_sender = threading.Thread(target=self._stats_send_loop, daemon=True)
        self._stats_sender.start()

    def stop(self) -> None:
        self._stop_event.set()

    def _sync_daemon_loop(self) -> None:
        while not self._stop_event.wait(timeout=self._poll_frequency_sec):
            try:
                self._service.ping(instance_id=self._instance_id)
                self.deserialize()
                self._update_monitored_models()
                self._service.set_runtime_properties(
                    {"layout": self.get_serving_layout()}
                )
            except Exception as ex:
                print("sync daemon error: {}".format(ex))

    def _get_stats_producer(self):
        # Rebuild when the broker URL changes at runtime (configure + poll).
        if self._stats_producer_url != self._stats_broker_url:
            self._stats_producer = None
            self._stats_producer_url = self._stats_broker_url
        if self._stats_producer is None and self._stats_broker_url:
            from ..statistics.broker import make_producer

            self._stats_producer = make_producer(self._stats_broker_url)
        return self._stats_producer

    def _stats_send_loop(self) -> None:
        while not self._stop_event.is_set():
            batch = self._stats_queue.get_all(timeout=5.0)
            if not batch:
                continue
            try:
                producer = self._get_stats_producer()
                if producer is not None:
                    producer.send_batch(batch)
            except Exception as ex:
                print("stats send error: {}".format(ex))
                time.sleep(5.0)

    # -- observability ---------------------------------------------------------

    def get_serving_layout(self) -> Dict[str, Any]:
        """Endpoint table + routing graph — the reference's endpoint-table /
        Sankey plot data (reference :1141-1278) as a JSON document. Exposed by
        the router's /dashboard route; the sync daemon also persists it to the
        service document's runtime properties each poll."""
        table = []
        for url, ep in sorted({**self._model_monitoring_endpoints, **self._endpoints}.items()):
            table.append(
                {
                    "endpoint": url,
                    "engine": ep.engine_type,
                    "model_id": ep.model_id,
                    "version": ep.version,
                    "preprocess": ep.preprocess_artifact,
                    "monitored": url in self._model_monitoring_endpoints,
                    "loaded": url in self._engine_processor_lookup,
                }
            )
        # routing graph: external -> canary -> versions, monitoring -> versions
        edges = []
        for name, route in self._canary_route.items():
            for target, weight in zip(route["endpoints"], route["weights"]):
                edges.append({"from": "canary:{}".format(name), "to": target,
                              "weight": round(weight, 4)})
        for name in self._model_monitoring:
            for url in self._model_monitoring_endpoints:
                if url.startswith(name + "/"):
                    edges.append({"from": "monitor:{}".format(name), "to": url, "weight": 1.0})
        telemetry = {}
        # snapshot: the event-loop thread inserts keys while the sync daemon
        # may be iterating from its own thread
        for url, entry in list(self._telemetry.items()):
            ok = entry["requests"] - entry["errors"]
            telemetry[url] = {
                "requests": entry["requests"],
                "errors": entry["errors"],
                "mean_latency_ms": round(entry["latency_sum"] / ok * 1000, 3) if ok else None,
            }
        return {
            "service_id": self._service.id,
            "instance": self._instance_id,
            "endpoints": table,
            "routing": edges,
            "metrics": {k: v.as_dict() for k, v in self._metric_logging.items()},
            "telemetry": telemetry,
        }

    # -- validation ------------------------------------------------------------

    def _validate_endpoint(self, endpoint: ModelEndpoint) -> None:
        """Tensor engines require a full I/O spec so compiled signatures are
        static (reference :1459-1535 enforces the same for Triton)."""
        if endpoint.engine_type in ("jax_grpc",):
            if not (endpoint.input_type and endpoint.output_type):
                raise ValueError(
                    "engine {!r} endpoints require --input-type/--output-type "
                    "(and matching sizes/names) so the engine server can compile "
                    "a static signature".format(endpoint.engine_type)
                )

    def _upload_preprocess_code(self, url: str, code_path: str) -> str:
        name = "py_code_{}".format(url.replace("/", "_"))
        self._service.upload_artifact(name, code_path)
        return name

    # -- service discovery (CLI) ----------------------------------------------

    @classmethod
    def list_control_plane_services(cls, state_root: Optional[str] = None) -> List[dict]:
        return StateStore(state_root).list_services()
