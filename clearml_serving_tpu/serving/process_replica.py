"""Process-backend engine replicas: one worker OS process per ring member.

The in-process replica fleet (llm/replica.py, docs/replication.md) runs N
``LLMEngineCore`` instances on one Python heap — honest enough for routing
and failover semantics, but every replica shares one GIL, one XLA client,
and one blast radius: a wedged C++ callback or a heap corruption takes the
whole fleet down. This module is the production shape: each replica is a
**supervised worker subprocess** owning its own engine on its own device
mesh (``parallel.multihost.configure_process_devices`` — on CPU hosts each
worker gets a private ``jax_num_cpu_devices`` mesh; on a real slice the
platform hands each controller process its local chips).

``ProcessEngineReplica`` satisfies the exact ``EngineReplica`` surface the
router and group consume — begin_warm/health/generate/stop/wait_drained,
streamed tokens and lifecycle stats — by proxying over a length-prefixed
JSON control channel on a UNIX socket:

- an **async channel**: id-multiplexed request frames; ``generate`` streams
  ``{"id", "tok"}`` frames back, ``warmup``/``drain``/``ping`` are single
  request/reply exchanges. The parent side demuxes on a reader thread into
  per-call queues, so streams survive being consumed from different event
  loops (tests run one ``asyncio.run`` per request).
- a **sync channel**: blocking request/reply for the engine's synchronous
  surface (check_admission, validate, receive_shipment, health, lifecycle
  stats, score_prompt). One outstanding call at a time under
  ``_sync_lock``; loop-affine ops are re-dispatched onto the worker's own
  event loop via ``run_coroutine_threadsafe`` so the engine's declared
  thread discipline (docs/static_analysis.md TPU5xx) holds inside the
  worker too.

Liveness is a supervisor THREAD per replica: heartbeat pings on the async
channel feed ``is_ready``; a missed-heartbeat budget or a dead process
marks the proxy not-ready — the router's next sweep ejects it, streams in
flight fail with ``EngineUnavailableError`` and the group resumes them
history-as-prompt on a sibling, exactly like the in-process watchdog path.
A crashed worker gets a bounded **restart-with-rewarm**: respawn, fresh
handshake, and ``invalidate_warm()`` so the ring-entry warmup gate
(llm/warmup.py) re-certifies before the router re-admits it.

Errors cross the boundary BY NAME: the worker serializes
``type(ex).__name__`` + message + the structured fields (retry_after,
stage, shed_class) and the parent reconstructs the class from
``clearml_serving_tpu.errors`` — a 429 stays a 429 with its Retry-After
across the process hop.

Chaos seam: ``replica.proc.crash`` (llm/faults.py) fires in the supervisor
tick with the replica INDEX as the shim prompt — ``match_token: 1`` SIGKILLs
exactly worker r1, the real-signal version of the in-process kill tests.

Known limits (validated with named errors, queued in ROADMAP.md): guided
decoding (the grammar compiler needs the tokenizer, which stays in the
parent) and LoRA adapter registries are not yet shipped to workers.
"""

from __future__ import annotations

import asyncio
import itertools
import json
import logging
import os
import shutil
import socket
import struct
import subprocess
import sys
import tempfile
import threading
import time
import queue as _queue
from typing import Any, AsyncIterator, Dict, List, Optional, Tuple

from .. import errors as _errors
from ..errors import EngineUnavailableError
from ..llm import faults
from ..llm import lifecycle_ledger as _ledger

logger = logging.getLogger(__name__)

# handshake budget: a worker imports jax, builds the model, and constructs
# the engine before it can connect — minutes on a busy 1-core CI host
_DEFAULT_STARTUP_TIMEOUT = 300.0
_SYNC_CALL_TIMEOUT = 60.0


# -- framing (shared by both sides) -----------------------------------------
#
# [u32 little-endian frame length][UTF-8 JSON payload] — the same length-
# prefixed discipline as the KV wire (llm/kv_wire.py), minus the binary
# body: control frames are small and structured, JSON keeps them
# debuggable with strace alone.


def _send_frame_sock(sock: socket.socket, obj: dict) -> None:
    payload = json.dumps(obj, separators=(",", ":")).encode("utf-8")
    sock.sendall(struct.pack("<I", len(payload)) + payload)


def _read_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = bytearray()
    while len(buf) < n:
        try:
            chunk = sock.recv(n - len(buf))
        except OSError:
            return None
        if not chunk:
            return None
        buf += chunk
    return bytes(buf)


def _recv_frame_sock(sock: socket.socket) -> Optional[dict]:
    """One frame, or None on EOF/timeout/closed socket (a truncated frame
    is a dead peer, not a protocol state worth distinguishing)."""
    head = _read_exact(sock, 4)
    if head is None:
        return None
    (length,) = struct.unpack("<I", head)
    body = _read_exact(sock, length)
    if body is None:
        return None
    try:
        return json.loads(body.decode("utf-8"))
    except (UnicodeDecodeError, ValueError):
        return None


def _jsonable(obj: Any) -> Any:
    """Best-effort JSON sanitizer for health/lifecycle payloads: numpy
    scalars/arrays, bytes, sets, and non-string dict keys all appear in
    engine snapshots and must not kill the control channel."""
    if isinstance(obj, dict):
        return {str(k): _jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple, set, frozenset)):
        return [_jsonable(v) for v in obj]
    if isinstance(obj, (bytes, bytearray)):
        return bytes(obj).hex()
    if isinstance(obj, (str, int, float, bool)) or obj is None:
        return obj
    item = getattr(obj, "item", None)
    if callable(item):
        try:
            return _jsonable(item())
        except (TypeError, ValueError):
            pass
    tolist = getattr(obj, "tolist", None)
    if callable(tolist):
        try:
            return _jsonable(tolist())
        except (TypeError, ValueError):
            pass
    return repr(obj)


# -- errors over the wire ---------------------------------------------------


def _err_to_dict(ex: BaseException) -> dict:
    out = {"name": type(ex).__name__, "message": str(ex)}
    for field in ("retry_after", "stage", "shed_class"):
        val = getattr(ex, field, None)
        if val is not None:
            out[field] = val
    return out


def _err_from_dict(d: dict) -> BaseException:
    """Reconstruct a worker-side error by class name against the project's
    error module — the structured fields (Retry-After, deadline stage,
    shed class) survive the hop, so the front's HTTP mapping is identical
    to the in-process backend. Unknown names degrade to RuntimeError."""
    name = str(d.get("name", ""))
    message = str(d.get("message", ""))
    cls = getattr(_errors, name, None)
    if isinstance(cls, type) and issubclass(cls, Exception):
        kwargs = {}
        if issubclass(cls, _errors.RequestError) and d.get("retry_after") is not None:
            kwargs["retry_after"] = d["retry_after"]
        if name == "DeadlineExceededError" and d.get("stage"):
            kwargs["stage"] = d["stage"]
        if name == "EngineOverloadedError" and d.get("shed_class"):
            kwargs["shed_class"] = d["shed_class"]
        try:
            return cls(message, **kwargs)
        except TypeError:
            try:
                return cls(message)
            except TypeError:
                pass
    if name in ("InjectedFault", "MemoryError", "ValueError"):
        # receive/admission fault classes the group's degradation paths
        # catch by type: preserve the category even without the module
        return {"MemoryError": MemoryError, "ValueError": ValueError}.get(
            name, RuntimeError
        )(message)
    return RuntimeError("{}: {}".format(name, message) if name else message)


# -- request serialization --------------------------------------------------

_REQ_FIELDS = (
    "max_new_tokens", "temperature", "top_k", "top_p", "stop_token_ids",
    "presence_penalty", "frequency_penalty", "repetition_penalty", "seed",
    "logprobs", "adapter", "min_tokens", "priority",
)


def _req_to_wire(request) -> dict:
    """A GenRequest as a JSON dict of REMAINING budgets (the group's
    ``_resume_clone`` deadline convention: resolved monotonic deadlines do
    not cross process clocks, so the wire carries what is left of each)."""
    if getattr(request, "guided", None) is not None:
        raise ValueError(
            "guided decoding is not supported on process-backend replicas "
            "yet (the grammar compiler needs the tokenizer, which lives in "
            "the parent; docs/replication.md)"
        )
    d = {f: getattr(request, f) for f in _REQ_FIELDS}
    d["prompt_ids"] = [int(t) for t in request.prompt_ids]
    if request.logit_bias:
        d["logit_bias"] = {str(k): float(v) for k, v in request.logit_bias.items()}
    now = time.monotonic()

    def _remaining(deadline, fallback):
        if deadline is not None:
            return max(0.05, deadline - now)
        return fallback

    d["queue_timeout"] = _remaining(request._queue_deadline, request.queue_timeout)
    d["ttft_timeout"] = _remaining(request._ttft_deadline, request.ttft_timeout)
    d["total_timeout"] = _remaining(request._deadline, request.total_timeout)
    d["ship_to"] = request._ship_to
    # the group's post-ship marker: the decode worker's admission judges
    # the ship outcome (hit vs recompute) from it, so the hit-rate
    # headline survives the process boundary
    d["shipped"] = bool(request._shipped)
    return d


def _req_from_wire(d: dict):
    from ..llm.engine import GenRequest

    bias = d.get("logit_bias")
    request = GenRequest(
        prompt_ids=[int(t) for t in d["prompt_ids"]],
        logit_bias=(
            {int(k): float(v) for k, v in bias.items()} if bias else None
        ),
        **{f: d.get(f) for f in _REQ_FIELDS if f in d},
    )
    request._ship_to = d.get("ship_to")
    request._shipped = bool(d.get("shipped"))
    return request


# -- parent-side channels ---------------------------------------------------


class _AsyncChannel:
    """Parent half of the id-multiplexed channel. A daemon reader thread
    demuxes reply frames into per-call queues; consumers poll those from
    whatever event loop is current (``asyncio.to_thread``), so one stream
    is not pinned to the loop that opened the channel. Channel death fails
    every outstanding call with ``EngineUnavailableError`` — the group's
    failover then resumes streams history-as-prompt on a sibling."""

    def __init__(self, sock: socket.socket, name: str):
        self._sock = sock
        self._name = name
        self._send_lock = threading.Lock()
        self._calls_lock = threading.Lock()
        self._calls: Dict[int, "_queue.Queue"] = {}
        self._ids = itertools.count(1)
        self.dead = False
        self._reader = threading.Thread(
            target=self._read_loop, daemon=True,
            name="proc-replica-{}-reader".format(name),
        )
        self._reader.start()

    def _read_loop(self) -> None:
        while True:
            frame = _recv_frame_sock(self._sock)
            if frame is None:
                break
            with self._calls_lock:
                q = self._calls.get(frame.get("id"))
            if q is not None:
                q.put(frame)
        self.dead = True
        with self._calls_lock:
            pending = list(self._calls.values())
        fail = {"err": {"name": "EngineUnavailableError",
                        "message": "worker control channel lost"}}
        for q in pending:
            q.put(dict(fail))

    def submit(self, op: str, **fields) -> Tuple[int, "_queue.Queue"]:
        if self.dead:
            raise EngineUnavailableError(
                "replica {} worker control channel lost".format(self._name)
            )
        fid = next(self._ids)
        q: "_queue.Queue" = _queue.Queue()
        with self._calls_lock:
            self._calls[fid] = q
        try:
            with self._send_lock:
                _send_frame_sock(self._sock, {"id": fid, "op": op, **fields})
        except OSError:
            self.dead = True
            with self._calls_lock:
                self._calls.pop(fid, None)
            raise EngineUnavailableError(
                "replica {} worker control channel lost".format(self._name)
            )
        return fid, q

    def finish(self, fid: int) -> None:
        with self._calls_lock:
            self._calls.pop(fid, None)

    def notify(self, op: str, **fields) -> None:
        """Fire-and-forget (cancel/exit): send errors only mark the channel
        dead — the supervisor owns escalation."""
        try:
            with self._send_lock:
                _send_frame_sock(self._sock, {"op": op, **fields})
        except OSError:
            self.dead = True

    def call_blocking(self, op: str, timeout: float, **fields) -> dict:
        fid, q = self.submit(op, **fields)
        try:
            frame = q.get(True, timeout)
        except _queue.Empty:
            raise EngineUnavailableError(
                "replica {} worker {} timed out after {:.1f}s".format(
                    self._name, op, timeout
                )
            )
        finally:
            self.finish(fid)
        if "err" in frame:
            raise _err_from_dict(frame["err"])
        return frame

    async def call(self, op: str, timeout: float, **fields) -> dict:
        return await asyncio.to_thread(self.call_blocking, op, timeout, **fields)

    def close(self) -> None:
        self.dead = True
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass


class _SyncChannel:
    """Parent half of the blocking request/reply channel: one outstanding
    call at a time — the serving loop's pre-admission checks, to_thread
    receive workers, and the Prometheus scrape thread all share it."""

    __guarded_by__ = {"_sync_lock": ("_sync_sock",)}

    def __init__(self, sock: socket.socket, name: str):
        self._sync_lock = threading.Lock()
        self._sync_sock: Optional[socket.socket] = sock
        self._name = name
        self.dead = False

    def call(self, op: str, timeout: float = _SYNC_CALL_TIMEOUT, **fields) -> dict:
        with self._sync_lock:
            sock = self._sync_sock
            if self.dead or sock is None:
                raise EngineUnavailableError(
                    "replica {} worker sync channel lost".format(self._name)
                )
            try:
                sock.settimeout(timeout)
                _send_frame_sock(sock, {"id": 0, "op": op, **fields})
                frame = _recv_frame_sock(sock)
            except OSError:
                frame = None
            if frame is None:
                self.dead = True
                try:
                    sock.close()
                except OSError:
                    pass
                self._sync_sock = None
                raise EngineUnavailableError(
                    "replica {} worker sync channel lost during {}".format(
                        self._name, op
                    )
                )
        if "err" in frame:
            raise _err_from_dict(frame["err"])
        return frame

    def close(self) -> None:
        with self._sync_lock:
            self.dead = True
            if self._sync_sock is not None:
                try:
                    self._sync_sock.close()
                except OSError:
                    pass
                self._sync_sock = None


class ProcessFleetControl:
    """The parent's control listener: workers connect back to it twice
    (async + sync channel), identify themselves with one handshake frame,
    and ``wait_for`` hands the paired sockets to the owning replica. The
    accept loop keeps running for the fleet's lifetime — a restarted
    worker re-handshakes through the same path."""

    def __init__(self, base_dir: str):
        self.path = os.path.join(base_dir, "control.sock")
        self._listener = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._listener.bind(self.path)
        self._listener.listen(32)
        self._cond = threading.Condition()
        self._pending: Dict[str, Dict[str, Tuple[socket.socket, dict]]] = {}
        self._closing = False
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="proc-fleet-accept"
        )
        self._accept_thread.start()

    def _accept_loop(self) -> None:
        while True:
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            # the handshake frame is read inline: it is the first thing a
            # worker writes, and a worker that connects without one is
            # broken anyway (short timeout keeps a dead accept cheap)
            conn.settimeout(30.0)
            frame = _recv_frame_sock(conn)
            if (
                not frame
                or frame.get("channel") not in ("sync", "async")
                or not frame.get("name")
            ):
                try:
                    conn.close()
                except OSError:
                    pass
                continue
            conn.settimeout(None)
            with self._cond:
                slot = self._pending.setdefault(str(frame["name"]), {})
                slot[str(frame["channel"])] = (conn, frame)
                self._cond.notify_all()

    def wait_for(self, name: str, timeout: float) -> Dict[str, Tuple[socket.socket, dict]]:
        deadline = time.monotonic() + timeout
        with self._cond:
            while True:
                slot = self._pending.get(name)
                if slot and "sync" in slot and "async" in slot:
                    return self._pending.pop(name)
                remaining = deadline - time.monotonic()
                if remaining <= 0 or self._closing:
                    raise EngineUnavailableError(
                        "replica {} worker did not handshake within "
                        "{:.0f}s".format(name, timeout)
                    )
                self._cond.wait(min(remaining, 1.0))

    def close(self) -> None:
        with self._cond:
            self._closing = True
            self._cond.notify_all()
            leftovers = list(self._pending.values())
            self._pending.clear()
        try:
            self._listener.close()
        except OSError:
            pass
        try:
            os.unlink(self.path)
        except OSError:
            pass
        for slot in leftovers:
            for sock, _ in slot.values():
                try:
                    sock.close()
                except OSError:
                    pass


# -- the engine proxy -------------------------------------------------------


class _QueueDepthShim:
    """Duck-typed ``engine._pending``: the router reads ``qsize()`` only."""

    def __init__(self, proxy: "ProcessEngineProxy"):
        self._proxy = proxy

    def qsize(self) -> int:
        return int(self._proxy._stats.get("queue_depth", 0))


class _PrefixProbe:
    """Duck-typed ``engine._prefix`` for the group's disaggregation
    preamble: ``block``/``longest_prefix_len`` are pure config math
    (mirroring RadixPrefixCache), ``match_len`` is a sync RPC — a lost
    channel reads as a cold cache (0), which degrades to recompute."""

    def __init__(self, proxy: "ProcessEngineProxy", block: int):
        self._proxy = proxy
        self.block = int(block)

    def longest_prefix_len(self, n_tokens: int) -> int:
        return ((int(n_tokens) - 1) // self.block) * self.block

    def match_len(self, ids, lora: int = 0) -> int:
        try:
            frame = self._proxy._require_sync().call(
                "match_len", ids=[int(t) for t in ids], lora=int(lora)
            )
            return int(frame.get("n", 0))
        except Exception:  # tpuserve: ignore[TPU401] cold-cache degradation: an unreachable worker ships nothing and recomputes
            return 0


class _BundleShim:
    """The slice of ``engine.bundle`` the serving front reads through the
    group facade (vocab-size range checks); the real bundle stays in the
    worker."""

    def __init__(self, config: dict):
        self.config = dict(config)


class _PagedMarker:
    """Truthy stand-in for ``engine.paged_cache`` on the parent side: the
    group/router only None-check it; the real pool lives in the worker."""

    pool = None

    def __bool__(self) -> bool:
        return True


class ProcessEngineProxy:
    """The engine surface ``EngineReplica``/group/router consume, served
    over the worker's control channels. Constructed cold; ``attach``
    wires the channels + hello config after the worker handshakes."""

    def __init__(self, name: str, spec: dict):
        self.replica_id = name
        self._name = name
        self._spec = spec
        self._sync: Optional[_SyncChannel] = None
        self._async: Optional[_AsyncChannel] = None
        self._hello: dict = {}
        self._stats: dict = {}
        self._alive = False
        self._stopped = False
        self._pending = _QueueDepthShim(self)
        self._prefix: Optional[_PrefixProbe] = None
        self.paged_cache = None
        self._adapter_index: Dict[str, int] = {}
        self.adapter_names: List[str] = []
        self.max_seq_len = 0
        self.max_batch = 0
        self.logprobs_k = 0
        self.max_pending: Optional[int] = None
        self.pid: Optional[int] = None
        self.bundle: Optional[_BundleShim] = None

    # -- wiring -------------------------------------------------------------

    def attach(self, sync_chan: _SyncChannel, async_chan: _AsyncChannel,
               hello: dict) -> None:
        self._sync = sync_chan
        self._async = async_chan
        self._hello = dict(hello)
        self.max_seq_len = int(hello.get("max_seq_len", 0))
        self.max_batch = int(hello.get("max_batch", 0))
        self.logprobs_k = int(hello.get("logprobs_k", 0))
        self.max_pending = hello.get("max_pending")
        self._adapter_index = {
            str(k): int(v) for k, v in (hello.get("adapter_index") or {}).items()
        }
        self.adapter_names = [str(n) for n in (hello.get("adapters") or [])]
        block = hello.get("prefix_block")
        self._prefix = _PrefixProbe(self, int(block)) if block else None
        self.paged_cache = _PagedMarker() if hello.get("paged") else None
        self.bundle = _BundleShim(
            {"vocab_size": int(hello.get("vocab_size", 0))}
        )
        self.pid = hello.get("pid")
        self._stats = {}
        self._alive = True

    def _require_sync(self) -> _SyncChannel:
        chan = self._sync
        if chan is None:
            raise EngineUnavailableError(
                "replica {} worker is not connected".format(self._name)
            )
        return chan

    def _require_async(self) -> _AsyncChannel:
        chan = self._async
        if chan is None:
            raise EngineUnavailableError(
                "replica {} worker is not connected".format(self._name)
            )
        return chan

    def _note_pong(self, pong: dict) -> None:
        self._stats = dict(pong)
        self._alive = True

    # -- readiness + router-consumed state ----------------------------------

    @property
    def is_ready(self) -> bool:
        chan = self._async
        return (
            not self._stopped
            and self._alive
            and chan is not None
            and not chan.dead
        )

    @property
    def active_slots(self) -> int:
        return int(self._stats.get("active_slots", 0))

    def _brownout_snapshot(self) -> dict:
        return {"stage": int(self._stats.get("brownout_stage", 0))}

    def _slot_lora(self, request) -> int:
        # mirror of LLMEngineCore._slot_lora against the hello's registry
        return self._adapter_index.get(request.adapter or "", 0)

    # -- request path -------------------------------------------------------

    def validate(self, request) -> None:
        payload = _req_to_wire(request)  # raises the named guided error
        self._require_sync().call("validate", req=payload)

    def check_admission(self, request, reserve: int = 0) -> None:
        payload = _req_to_wire(request)
        self._require_sync().call(
            "check_admission", req=payload, reserve=int(reserve)
        )

    async def generate(self, request) -> AsyncIterator[int]:
        payload = _req_to_wire(request)
        chan = self._require_async()
        fid, q = chan.submit("generate", req=payload)
        request.prompt_len = len(request.prompt_ids)
        cancel_sent = False
        finished = False
        try:
            while True:
                try:
                    frame = await asyncio.to_thread(q.get, True, 0.5)
                except _queue.Empty:
                    if request.cancelled and not cancel_sent:
                        chan.notify("cancel", gen=fid)
                        cancel_sent = True
                    if chan.dead:
                        finished = True
                        raise EngineUnavailableError(
                            "replica {} worker lost mid-stream".format(
                                self._name
                            )
                        )
                    continue
                if "tok" in frame:
                    if frame.get("first"):
                        request.first_token_at = time.time()
                    request.produced += 1
                    yield int(frame["tok"])
                elif "end" in frame:
                    end = frame.get("end") or {}
                    request.produced = int(end.get("produced", request.produced))
                    if request.logprobs is not None:
                        request.logprob_entries.extend(
                            end.get("logprob_entries") or []
                        )
                    finished = True
                    return
                elif "err" in frame:
                    finished = True
                    raise _err_from_dict(frame["err"])
        finally:
            chan.finish(fid)
            if not finished and not cancel_sent:
                # consumer stopped early (GeneratorExit): free the worker's
                # slot + KV pages promptly, same contract as request.cancel
                chan.notify("cancel", gen=fid)

    def receive_shipment(self, prompt_ids, lora: int = 0) -> dict:
        try:
            frame = self._require_sync().call(
                "receive_shipment",
                ids=[int(t) for t in prompt_ids],
                lora=int(lora),
            )
            return dict(frame.get("result") or {})
        except EngineUnavailableError as ex:
            # the group treats a failed receive as re-route-or-recompute;
            # a dead worker must degrade the same way, not raise
            return {"status": "failed", "reason": str(ex)}

    def score_prompt(self, prompt_ids, adapter: Optional[str] = None):
        frame = self._require_sync().call(
            "score_prompt",
            ids=[int(t) for t in prompt_ids],
            adapter=adapter,
        )
        return frame.get("result")

    # -- lifecycle ----------------------------------------------------------

    async def warmup_rpc(self, full: bool) -> dict:
        frame = await self._require_async().call(
            "warmup", timeout=900.0, full=bool(full)
        )
        return dict(frame.get("result") or {})

    async def wait_drained(self, timeout: float = 30.0) -> None:
        chan = self._async
        if chan is None or chan.dead:
            return
        try:
            await chan.call("drain", timeout=timeout + 10.0, timeout_s=timeout)
        except EngineUnavailableError:
            return

    def stop(self) -> None:
        self._stopped = True
        chan = self._async
        if chan is not None and not chan.dead:
            chan.notify("exit")

    # -- observability ------------------------------------------------------

    def _process_block(self) -> dict:
        return {
            "backend": "process",
            "pid": self.pid,
            "alive": self._alive,
            "heartbeat": dict(self._stats),
        }

    def health(self) -> dict:
        try:
            frame = self._require_sync().call("health")
            out = dict(frame.get("health") or {})
        except Exception as ex:  # tpuserve: ignore[TPU401] a dead worker still gets a health row — that row IS the diagnostic
            out = {"ready": False, "error": str(ex)}
        out["process"] = self._process_block()
        return out

    def lifecycle_stats(self) -> dict:
        try:
            frame = self._require_sync().call("lifecycle")
            out = dict(frame.get("stats") or {})
        except Exception:  # tpuserve: ignore[TPU401] scrape path: a dead worker exports an empty block, not a scrape failure
            out = {}
        out["process"] = self._process_block()
        return out


# -- the supervised replica -------------------------------------------------


class _ReplicaShim:
    """Fault-match carrier for the ``replica.proc.crash`` seam: the
    supervisor has no request in hand, so the replica INDEX rides as the
    shim prompt (the router ejection seam's convention) — ``match_token:
    1`` kills exactly worker r1."""

    def __init__(self, index: int):
        self.prompt_ids = [int(index)]


class ProcessEngineReplica:
    """An ``EngineReplica``-shaped ring member whose engine is a supervised
    worker subprocess. Import note: this class deliberately does NOT
    subclass ``EngineReplica`` — importing llm.replica pulls the engine
    (and jax) into the worker bootstrap path before
    ``configure_process_devices`` can run; the replica surface is small
    and duck-typed everywhere (router + group consume properties only).
    ``tests/test_process_replica.py`` pins the shared surface."""

    __guarded_by__ = {"_lock": ("_proc", "_restarts_left")}
    __affine_to__ = {"worker": ("_hb_misses",)}
    __acquires__ = {
        "_spawn": {
            "resource": "replica.worker_proc",
            "releases": ("_reap", "stop"),
            "drops": (),
            "static": False,
            "receivers": ("self", "replica", "supervisor"),
        },
    }

    def __init__(
        self,
        index: int,
        spec: dict,
        control: ProcessFleetControl,
        *,
        warmup_mode: str = "off",
        heartbeat_interval: float = 0.5,
        heartbeat_misses: int = 4,
        max_restarts: int = 1,
        startup_timeout: float = _DEFAULT_STARTUP_TIMEOUT,
    ):
        if warmup_mode not in ("off", "startup", "full"):
            raise ValueError(
                "replica warmup mode must be off/startup/full: got {!r}"
                .format(warmup_mode)
            )
        self.index = int(index)
        self.name = "r{}".format(index)
        if spec.get("name") != self.name:
            raise ValueError(
                "worker spec name {!r} does not match ring slot {!r}"
                .format(spec.get("name"), self.name)
            )
        self._spec = dict(spec)
        self._control = control
        self._warmup_mode = warmup_mode
        self.warmed = warmup_mode == "off"
        self.warmed_full = False
        self.warm_result = {"requests": 0, "cow_buckets": 0}
        self._warm_task: Optional[asyncio.Task] = None
        self._lock = threading.Lock()
        self._proc: Optional[subprocess.Popen] = None
        self._restarts_left = int(max_restarts)
        self._hb_misses = 0
        self._hb_interval = float(heartbeat_interval)
        self._hb_limit = int(heartbeat_misses)
        self._startup_timeout = float(startup_timeout)
        self.restarts = 0
        self.engine = ProcessEngineProxy(self.name, self._spec)
        self._supervisor: Optional[threading.Thread] = None
        self._spawn()

    # -- process lifecycle --------------------------------------------------

    def _spawn(self) -> None:
        env = dict(os.environ)
        env.update({str(k): str(v) for k, v in (self._spec.get("env") or {}).items()})
        proc = subprocess.Popen(
            [
                sys.executable, "-m",
                "clearml_serving_tpu.serving.process_replica",
                "--spec", self._spec["spec_path"],
            ],
            env=env,
        )
        if _ledger.armed():
            _ledger.acquire("replica.worker_proc", key=self.name, domain=self)
        with self._lock:
            self._proc = proc

    def complete_startup(self) -> None:
        """Block until the worker handshakes, then start supervision.
        Separate from ``__init__`` so a fleet builder spawns every worker
        first and overlaps their (expensive) engine bootstraps."""
        self._attach_worker()
        self._supervisor = threading.Thread(
            target=self._supervise, daemon=True,
            name="proc-replica-{}-supervisor".format(self.name),
        )
        self._supervisor.start()

    def _attach_worker(self) -> None:
        # chunked wait so a worker that dies during bootstrap (bad preset,
        # import error) fails the builder in ~1s, not after the full
        # startup timeout
        deadline = time.monotonic() + self._startup_timeout
        while True:
            with self._lock:
                proc = self._proc
            if proc is not None and proc.poll() is not None:
                raise EngineUnavailableError(
                    "replica {} worker exited with rc={} before "
                    "handshaking".format(self.name, proc.returncode)
                )
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                # one last zero-ish wait so wait_for raises the named error
                remaining = 0.001
            try:
                slot = self._control.wait_for(
                    self.name, min(1.0, max(0.001, remaining))
                )
                break
            except EngineUnavailableError:
                if deadline - time.monotonic() <= 0:
                    raise
        sync_sock, _ = slot["sync"]
        async_sock, aframe = slot["async"]
        self.engine.attach(
            _SyncChannel(sync_sock, self.name),
            _AsyncChannel(async_sock, self.name),
            aframe.get("hello") or {},
        )

    def _reap(self) -> None:
        with self._lock:
            proc = self._proc
            self._proc = None
        for chan in (self.engine._sync, self.engine._async):
            if chan is not None:
                chan.close()
        if proc is None:
            return
        try:
            proc.wait(timeout=10.0)
        except subprocess.TimeoutExpired:
            proc.kill()
            try:
                proc.wait(timeout=10.0)
            except subprocess.TimeoutExpired:
                pass
        if _ledger.armed():
            _ledger.release("replica.worker_proc", key=self.name, domain=self)

    def _supervise(self) -> None:
        """Heartbeat + crash supervision (dedicated daemon thread):
        liveness feeds ``is_ready`` (the router's ejection input), a dead
        or wedged worker gets the bounded restart-with-rewarm, and the
        ``replica.proc.crash`` chaos seam SIGKILLs for real."""
        while True:
            time.sleep(self._hb_interval)
            if self.engine._stopped:
                self._shutdown_worker()
                return
            with self._lock:
                proc = self._proc
            if proc is not None and proc.poll() is not None:
                if not self._maybe_restart(
                    "exit code {}".format(proc.returncode)
                ):
                    return
                continue
            try:
                faults.fire("replica.proc.crash", _ReplicaShim(self.index))
            except faults.InjectedFault:
                logger.warning(
                    "replica %s: injected crash — SIGKILLing worker pid %s",
                    self.name, self.engine.pid,
                )
                if proc is not None and proc.poll() is None:
                    proc.kill()
                continue  # next tick takes the dead-process branch
            chan = self.engine._async
            if chan is None or chan.dead:
                self._hb_misses += 1
            else:
                try:
                    frame = chan.call_blocking(
                        "ping", timeout=max(2.0, 4 * self._hb_interval)
                    )
                except Exception:  # tpuserve: ignore[TPU401] a failed ping IS the signal — counted against the miss budget below
                    self._hb_misses += 1
                else:
                    self.engine._note_pong(frame.get("pong") or {})
                    self._hb_misses = 0
                    continue
            if self._hb_misses >= self._hb_limit:
                self.engine._alive = False
                if proc is not None and proc.poll() is None:
                    logger.error(
                        "replica %s: %d missed heartbeats — killing wedged "
                        "worker pid %s", self.name, self._hb_misses,
                        self.engine.pid,
                    )
                    proc.kill()
                if not self._maybe_restart("missed heartbeats"):
                    return

    def _maybe_restart(self, why: str) -> bool:
        """Bounded restart-with-rewarm. Returns False when supervision
        should end (budget exhausted, stop requested, restart failed) —
        the proxy stays not-ready and the router keeps the slot ejected."""
        self.engine._alive = False
        self._reap()
        if self.engine._stopped:
            return False
        with self._lock:
            budget = self._restarts_left
            if budget > 0:
                self._restarts_left = budget - 1
        if budget <= 0:
            logger.error(
                "replica %s worker died (%s); restart budget exhausted — "
                "ejected for good", self.name, why,
            )
            return False
        logger.warning(
            "replica %s worker died (%s); restarting (%d restart(s) left)",
            self.name, why, budget - 1,
        )
        # the warmup gate closes BEFORE the new worker serves: re-admission
        # to the ring re-runs the same run_warmup gate as first entry
        self.invalidate_warm()
        try:
            self._spawn()
            self._attach_worker()
        except Exception as ex:  # tpuserve: ignore[TPU401] a failed restart ends supervision with the slot ejected; the error is the log line
            logger.error("replica %s restart failed: %s", self.name, ex)
            return False
        self._hb_misses = 0
        self.restarts += 1
        return True

    def _shutdown_worker(self) -> None:
        with self._lock:
            proc = self._proc
        if proc is not None:
            try:
                proc.wait(timeout=15.0)
            except subprocess.TimeoutExpired:
                proc.terminate()
        self._reap()

    # -- EngineReplica surface (router + group consume) ---------------------

    @property
    def engine_ready(self) -> bool:
        return bool(self.engine.is_ready)

    @property
    def serving_ready(self) -> bool:
        return self.engine_ready and self.warmed

    @property
    def warming(self) -> bool:
        return self._warm_task is not None and not self._warm_task.done()

    @property
    def queue_depth(self) -> int:
        return int(self.engine._pending.qsize())

    @property
    def brownout_stage(self) -> int:
        snap = self.engine._brownout_snapshot()
        return int((snap or {}).get("stage", 0))

    def invalidate_warm(self) -> None:
        if self._warmup_mode != "off":
            self.warmed = False
            self.warmed_full = False

    def begin_warm(self) -> None:
        if self.warmed or self.warming or not self.engine_ready:
            return
        if self._warmup_mode == "off":
            self.warmed = True
            return
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            return
        self._warm_task = loop.create_task(self.ensure_warm())

    async def ensure_warm(self, full: Optional[bool] = None) -> None:
        """The warmup gate, RPC'd: the worker runs the exact same
        ``run_warmup`` sweep (and fences its own compile sentry on a full
        pass); the gate state machine up here is verbatim EngineReplica."""
        if full is None:
            full = self._warmup_mode == "full"
        try:
            self.warm_result = await self.engine.warmup_rpc(full=bool(full))
        except Exception as ex:  # tpuserve: ignore[TPU401] warmup is best-effort by contract; failure falls back to lazy compiles and is logged
            logger.warning(
                "replica %s process warmup failed (will serve with lazy "
                "compiles): %s", self.name, ex,
            )
        self.warmed = True
        self.warmed_full = self.warmed_full or bool(full)

    def health(self) -> dict:
        out = self.engine.health()
        out["replica"] = self.name
        out["ring_state"] = (
            "ready" if self.serving_ready
            else ("warming" if self.warming else "ejected")
        )
        return out


# -- fleet construction -----------------------------------------------------


class _FleetRuntime:
    """What the parent must tear down after the workers: the control
    listener, the supervisor threads, and the socket/spec directory."""

    def __init__(self, base_dir: str, control: ProcessFleetControl,
                 replicas: List[ProcessEngineReplica]):
        self.base_dir = base_dir
        self.control = control
        self.replicas = replicas

    def close(self) -> None:
        deadline = time.monotonic() + 20.0
        for replica in self.replicas:
            thread = replica._supervisor
            if thread is not None and thread.is_alive():
                thread.join(timeout=max(0.1, deadline - time.monotonic()))
            # a supervisor that already exited (restart budget burned)
            # leaves the reap to us
            replica._reap()
        self.control.close()
        shutil.rmtree(self.base_dir, ignore_errors=True)


def build_process_fleet(
    model: dict,
    engine_cfg: dict,
    n_replicas: int,
    *,
    roles: Optional[List[str]] = None,
    warmup_mode: str = "startup",
    affinity_blocks: int = 4,
    spill_queue_depth: Optional[int] = None,
    spill_brownout_stage: int = 2,
    fleet_shed_stage: int = 3,
    kv_transport_pages: Optional[int] = None,
    cpu_devices: Optional[int] = None,
    heartbeat_interval: float = 0.5,
    heartbeat_misses: int = 4,
    max_restarts: int = 1,
    startup_timeout: float = _DEFAULT_STARTUP_TIMEOUT,
    env: Optional[dict] = None,
):
    """Build a ``ReplicaGroup`` whose members are worker subprocesses.

    ``model`` is the preset spec workers rebuild from (``{"arch",
    "config", "seed"}`` — config must include ``preset``; identical params
    everywhere follows from the identical seed). ``engine_cfg`` is the
    JSON-safe ``LLMEngineCore`` kwargs dict. Disaggregated ``roles`` wire
    the workers' KV endpoints together over the socket slab transport
    (llm/kv_wire.py) — ``engine.kv.ship``/``engine.kv.receive`` seams and
    mailbox semantics are identical to the in-process fleet, so the chaos
    suite runs unchanged against this backend."""
    from ..llm.replica import ReplicaGroup

    n_replicas = int(n_replicas)
    if n_replicas < 1:
        raise ValueError("a process fleet needs at least one replica")
    if roles is not None and len(roles) != n_replicas:
        raise ValueError(
            "engine.replica_roles lists {} roles for {} replicas".format(
                len(roles), n_replicas
            )
        )
    names = ["r{}".format(i) for i in range(n_replicas)]
    base_dir = tempfile.mkdtemp(prefix="tpuserve-proc-")
    control = ProcessFleetControl(base_dir)
    disaggregated = roles is not None and any(r != "hybrid" for r in roles)
    wire_addrs: Dict[str, str] = {}
    wire_capacity = 0
    if disaggregated:
        page_size = int(engine_cfg.get("page_size") or 16)
        per_seq = -(-int(engine_cfg.get("max_seq_len", 2048)) // page_size)
        wire_capacity = int(kv_transport_pages or max(64, 4 * per_seq))
        wire_addrs = {
            name: "unix:{}".format(os.path.join(base_dir, name + ".kv.sock"))
            for name in names
        }
    replicas: List[ProcessEngineReplica] = []
    try:
        for i, name in enumerate(names):
            spec = {
                "name": name,
                "index": i,
                "role": roles[i] if roles is not None else "hybrid",
                "control": control.path,
                "cohosted_procs": n_replicas,
                "model": dict(model),
                "engine": dict(engine_cfg),
                "devices": (
                    {"cpu_devices": int(cpu_devices)} if cpu_devices else {}
                ),
                "kv_wire": (
                    {
                        "bind": wire_addrs[name],
                        "peers": wire_addrs,
                        "capacity_pages": wire_capacity,
                    }
                    if disaggregated else None
                ),
                "env": dict(env or {}),
            }
            path = os.path.join(base_dir, name + ".spec.json")
            with open(path, "w", encoding="utf-8") as fh:
                json.dump(spec, fh)
            spec["spec_path"] = path
            replicas.append(
                ProcessEngineReplica(
                    i, spec, control,
                    warmup_mode=warmup_mode,
                    heartbeat_interval=heartbeat_interval,
                    heartbeat_misses=heartbeat_misses,
                    max_restarts=max_restarts,
                    startup_timeout=startup_timeout,
                )
            )
        # all workers boot in parallel; handshakes complete in ring order
        for replica in replicas:
            replica.complete_startup()
    except BaseException:
        for replica in replicas:
            replica.engine._stopped = True
            replica._reap()
        control.close()
        shutil.rmtree(base_dir, ignore_errors=True)
        raise
    hello = replicas[0].engine._hello
    role_map = (
        {name: role for name, role in zip(names, roles)}
        if roles is not None else None
    )
    group = ReplicaGroup.__new__(ReplicaGroup)
    group._finish_init(
        replicas,
        block=int(hello.get("prefix_block") or 64),
        role_map=role_map,
        disaggregated=disaggregated,
        transport=None,  # worker-owned socket endpoints; stats via workers
        spill_queue_depth=spill_queue_depth,
        spill_brownout_stage=spill_brownout_stage,
        fleet_shed_stage=fleet_shed_stage,
        affinity_blocks=affinity_blocks,
        replica_backend="process",
        max_pending_hint=hello.get("max_pending"),
        runtime=_FleetRuntime(base_dir, control, replicas),
    )
    return group


# ===========================================================================
# worker side
# ===========================================================================


def _worker_hello(engine) -> dict:
    prefix = getattr(engine, "_prefix", None)
    return {
        "pid": os.getpid(),
        "vocab_size": int(engine.bundle.config.get("vocab_size", 0)),
        "max_seq_len": int(engine.max_seq_len),
        "max_batch": int(engine.max_batch),
        "logprobs_k": int(engine.logprobs_k),
        "max_pending": engine.max_pending,
        "prefix_block": int(prefix.block) if prefix is not None else None,
        "paged": engine.paged_cache is not None,
        "adapters": list(engine.adapter_names),
        "adapter_index": dict(getattr(engine, "_adapter_index", {})),
    }


def _sync_dispatch(engine, frame: dict, loop) -> dict:
    """One sync-channel op against the live engine. Loop-affine entry
    points (admission, validation) are re-dispatched onto the worker's
    event loop; the rest are the engine's documented any-thread surface
    (receive_shipment, the scrape-path snapshots)."""
    op = frame.get("op")
    if op == "check_admission":
        request = _req_from_wire(frame["req"])

        async def _admit():
            engine.check_admission(request, reserve=int(frame.get("reserve", 0)))

        asyncio.run_coroutine_threadsafe(_admit(), loop).result(
            timeout=_SYNC_CALL_TIMEOUT
        )
        return {"ok": 1}
    if op == "validate":
        request = _req_from_wire(frame["req"])

        async def _validate():
            engine.validate(request)

        asyncio.run_coroutine_threadsafe(_validate(), loop).result(
            timeout=_SYNC_CALL_TIMEOUT
        )
        return {"ok": 1}
    if op == "match_len":
        prefix = getattr(engine, "_prefix", None)
        n = 0
        if prefix is not None:
            n = prefix.match_len(
                [int(t) for t in frame.get("ids") or []],
                int(frame.get("lora", 0)),
            )
        return {"n": int(n)}
    if op == "receive_shipment":
        res = engine.receive_shipment(
            [int(t) for t in frame.get("ids") or []],
            int(frame.get("lora", 0)),
        )
        return {"result": _jsonable(res)}
    if op == "health":
        return {"health": _jsonable(engine.health())}
    if op == "lifecycle":
        return {"stats": _jsonable(engine.lifecycle_stats())}
    if op == "score_prompt":
        res = engine.score_prompt(
            [int(t) for t in frame.get("ids") or []], frame.get("adapter")
        )
        return {"result": _jsonable(res)}
    raise ValueError("unknown sync op {!r}".format(op))


def _sync_serve(engine, sock: socket.socket, loop) -> None:
    while True:
        frame = _recv_frame_sock(sock)
        if frame is None:
            return
        try:
            out = _sync_dispatch(engine, frame, loop)
        except BaseException as ex:  # noqa: BLE001 - every error crosses the wire by name
            out = {"err": _err_to_dict(ex)}
        out["id"] = frame.get("id", 0)
        try:
            _send_frame_sock(sock, out)
        except OSError:
            return


async def _recv_frame_stream(reader: asyncio.StreamReader) -> Optional[dict]:
    try:
        head = await reader.readexactly(4)
        (length,) = struct.unpack("<I", head)
        body = await reader.readexactly(length)
    except (asyncio.IncompleteReadError, ConnectionError, OSError):
        return None
    try:
        return json.loads(body.decode("utf-8"))
    except (UnicodeDecodeError, ValueError):
        return None


async def _send_frame_stream(writer: asyncio.StreamWriter, wlock: asyncio.Lock,
                             obj: dict) -> None:
    payload = json.dumps(obj, separators=(",", ":")).encode("utf-8")
    async with wlock:
        writer.write(struct.pack("<I", len(payload)) + payload)
        await writer.drain()


async def _gen_task(engine, writer, wlock, fid: int, payload: dict,
                    live: dict) -> None:
    try:
        request = _req_from_wire(payload)
    except Exception as ex:  # noqa: BLE001 - a bad frame is the caller's error, reported on its id
        await _send_frame_stream(writer, wlock, {"id": fid, "err": _err_to_dict(ex)})
        return
    live[fid] = request
    try:
        first = True
        async for token in engine.generate(request):
            await _send_frame_stream(
                writer, wlock,
                {"id": fid, "tok": int(token), "first": first},
            )
            first = False
        end = {"produced": request.produced, "prompt_len": request.prompt_len}
        if request.logprobs is not None:
            end["logprob_entries"] = _jsonable(request.logprob_entries)
        await _send_frame_stream(writer, wlock, {"id": fid, "end": end})
    except BaseException as ex:  # noqa: BLE001 - stream errors cross the wire by name
        try:
            await _send_frame_stream(
                writer, wlock, {"id": fid, "err": _err_to_dict(ex)}
            )
        except (ConnectionError, OSError):
            pass
    finally:
        live.pop(fid, None)


async def _warmup_task(engine, writer, wlock, fid: int, full: bool) -> None:
    from ..llm import compile_sentry
    from ..llm.warmup import run_warmup

    try:
        result = await run_warmup(engine, full=full, fence=False)
        fenced = False
        if full and compile_sentry.enabled():
            # each worker fences its OWN process-wide sentry — the group's
            # single-fence contract, scoped to the process that compiled
            compile_sentry.get().fence()
            fenced = True
        result = dict(result)
        result["fenced"] = fenced
        await _send_frame_stream(
            writer, wlock, {"id": fid, "result": _jsonable(result)}
        )
    except BaseException as ex:  # noqa: BLE001 - warmup failures report to the parent's gate, which logs + degrades
        await _send_frame_stream(
            writer, wlock, {"id": fid, "err": _err_to_dict(ex)}
        )


async def _drain_task(engine, writer, wlock, fid: int, timeout: float) -> None:
    try:
        await engine.wait_drained(timeout=timeout)
        await _send_frame_stream(writer, wlock, {"id": fid, "ok": 1})
    except BaseException as ex:  # noqa: BLE001
        await _send_frame_stream(
            writer, wlock, {"id": fid, "err": _err_to_dict(ex)}
        )


async def _worker_serve(engine, spec: dict) -> None:
    loop = asyncio.get_running_loop()
    control_path = spec["control"]
    reader, writer = await asyncio.open_unix_connection(control_path)
    wlock = asyncio.Lock()
    await _send_frame_stream(
        writer, wlock,
        {
            "channel": "async",
            "name": spec["name"],
            "hello": _worker_hello(engine),
        },
    )
    sync_sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    sync_sock.connect(control_path)
    _send_frame_sock(sync_sock, {"channel": "sync", "name": spec["name"]})
    threading.Thread(
        target=_sync_serve, args=(engine, sync_sock, loop), daemon=True,
        name="worker-sync-serve",
    ).start()
    live: Dict[int, Any] = {}
    while True:
        frame = await _recv_frame_stream(reader)
        if frame is None:
            break  # parent died: no orphaned decode loops
        op = frame.get("op")
        fid = frame.get("id")
        if op == "ping":
            snap = engine._brownout_snapshot()
            pong = {
                "ready": bool(engine.is_ready),
                "queue_depth": int(engine._pending.qsize()),
                "brownout_stage": int((snap or {}).get("stage", 0)),
                "active_slots": int(engine.active_slots),
            }
            await _send_frame_stream(writer, wlock, {"id": fid, "pong": pong})
        elif op == "generate":
            asyncio.ensure_future(
                _gen_task(engine, writer, wlock, fid, frame.get("req") or {}, live)
            )
        elif op == "cancel":
            request = live.get(frame.get("gen"))
            if request is not None:
                request.cancel()
        elif op == "warmup":
            asyncio.ensure_future(
                _warmup_task(engine, writer, wlock, fid, bool(frame.get("full")))
            )
        elif op == "drain":
            asyncio.ensure_future(
                _drain_task(
                    engine, writer, wlock, fid,
                    float(frame.get("timeout_s", 30.0)),
                )
            )
        elif op == "exit":
            break
        elif fid is not None:
            await _send_frame_stream(
                writer, wlock,
                {"id": fid, "err": {"name": "ValueError",
                                    "message": "unknown op {!r}".format(op)}},
            )
    engine.stop()
    try:
        writer.close()
    except OSError:
        pass


def _worker_main(spec_path: str) -> int:
    with open(spec_path, "r", encoding="utf-8") as fh:
        spec = json.load(fh)
    for key, value in (spec.get("env") or {}).items():
        os.environ[str(key)] = str(value)
    # host-tier "auto" sizing divides MemAvailable by the co-hosted worker
    # count (docs/kv_tiering.md) — the fleet builder knows how many of us
    # share this host
    os.environ.setdefault(
        "TPUSERVE_COHOSTED_PROCS", str(spec.get("cohosted_procs", 1))
    )
    # device mesh BEFORE anything touches jax.devices()
    from ..parallel.multihost import configure_process_devices

    configure_process_devices(spec.get("devices"))
    import jax

    from .. import models
    from ..llm.engine import LLMEngineCore

    model = spec["model"]
    bundle = models.build_model(
        model.get("arch", "llama"), dict(model.get("config") or {})
    )
    params = bundle.init(jax.random.PRNGKey(int(model.get("seed", 0))))
    engine = LLMEngineCore(
        bundle, params, replica=spec["name"], **dict(spec.get("engine") or {})
    )
    wire = spec.get("kv_wire")
    role = spec.get("role", "hybrid")
    if wire:
        from ..llm.kv_wire import SocketSlabTransport

        endpoint = SocketSlabTransport(
            spec["name"], wire["bind"], dict(wire["peers"]),
            capacity_pages=int(wire.get("capacity_pages", 1024)),
        )
        engine.attach_kv_transport(endpoint, role=role)
    elif role != "hybrid":
        engine.attach_kv_transport(None, role=role)
    asyncio.run(_worker_serve(engine, spec))
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        description="tpu-serving process-replica worker (internal entry "
        "point: spawned by ProcessEngineReplica)"
    )
    parser.add_argument("--spec", required=True, help="worker spec JSON path")
    args = parser.parse_args(argv)
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s worker %(name)s %(levelname)s %(message)s",
    )
    return _worker_main(args.spec)


if __name__ == "__main__":
    sys.exit(main())
