"""Prefix-affine replica router (docs/replication.md).

The scale-out spine of the serving layer: N engine replicas (in-process
``LLMEngineCore`` instances today, per-mesh process groups behind the same
interface later) sit behind one rendezvous-hashed ring, and every request
routes by the BLOCK-ALIGNED radix prefix of its prompt — the same block
math ``llm/prefix_cache.py`` keys its trie on — so repeated conversations
land on the replica whose device+host KV tier already holds their pages
(PR 10's host tier only pays off fleet-wide if routing is prefix-affine).

Routing contract, in order:

1. **Affinity**: HRW/rendezvous order of the ring by
   ``blake2b(affinity_key || replica_name)`` — deterministic, minimally
   disruptive (removing a member only moves that member's keys).
2. **Health**: a replica that is not serving-ready (engine stopped,
   watchdog recovery in progress, warmup gate still closed, or
   fault-forced ejection via the ``router.eject`` seam) is not in the
   ring; its keys fall to their next HRW choice (route ``rebalance``).
3. **Load**: when the affine member is overloaded (queue depth or
   brownout stage over the spill bounds) and its next choice is strictly
   less pressured, the request spills (route ``spill``) — prefix warmth
   loses to a meaningful pressure gap, never to a tie.
4. **Fleet brownout**: when EVERY ring member is at the shed stage,
   best-effort work sheds at the router door (structured 429) before any
   replica queues it — one replica's stage-3 pressure already redirected
   its admissions at step 3; this is the whole fleet saying no.

This module is jax-free on purpose: routing math must import from the
CLI/router process without pulling an accelerator runtime.
"""

from __future__ import annotations

import hashlib
import struct
import threading
from typing import Any, Dict, List, Optional, Sequence

from ..errors import EngineOverloadedError, EngineUnavailableError
from ..llm import faults

# conversation anchor depth: the affinity key hashes at most this many
# prefix blocks, so a growing conversation (each turn appends to its
# history) keeps ONE key for its whole life instead of re-keying per turn
DEFAULT_AFFINITY_BLOCKS = 4


def affinity_key(prompt_ids: Sequence[int], block: int,
                 max_blocks: int = DEFAULT_AFFINITY_BLOCKS) -> bytes:
    """Stable conversation anchor for a prompt: a digest of its first
    block-aligned prefix blocks (``block`` = the radix cache's block size,
    so the key space is exactly the trie's top levels). The final token
    never contributes (mirroring ``RadixPrefixCache.longest_prefix_len``:
    it always computes live), and prompts shorter than one block hash
    whole — short one-shot work spreads uniformly over the ring."""
    ids = list(prompt_ids)
    depth = ((len(ids) - 1) // max(1, int(block))) * max(1, int(block))
    depth = min(depth, max(1, int(max_blocks)) * max(1, int(block)))
    head = ids[:depth] if depth > 0 else ids
    digest = hashlib.blake2b(digest_size=8)
    digest.update(struct.pack("<I", len(head)))
    for token in head:
        digest.update(struct.pack("<q", int(token)))
    return digest.digest()


def hrw_order(key: bytes, names: Sequence[str]) -> List[int]:
    """Rendezvous (highest-random-weight) ranking of ``names`` for ``key``:
    indices sorted by score descending. Deterministic across processes
    (blake2b, not the seeded builtin hash)."""
    scored = []
    for i, name in enumerate(names):
        h = hashlib.blake2b(key, digest_size=8)
        h.update(str(name).encode("utf-8"))
        scored.append((h.digest(), i))
    scored.sort(reverse=True)
    return [i for _, i in scored]


class _ReplicaShim:
    """Carrier for fault matching on router seams: ``match_token`` against
    a replica INDEX selects which replica a ``router.eject`` spec forces
    out of the ring (the fault machinery matches on ``prompt_ids``)."""

    def __init__(self, index: int):
        self.prompt_ids = [int(index)]


class ReplicaRouter:
    """Prefix-affine HRW ring over replica handles.

    ``replicas``: objects exposing ``name``/``index``, liveness
    (``engine_ready``), the warmup gate (``warmed``/``warming``/
    ``begin_warm()``/``invalidate_warm()``), and pressure signals
    (``queue_depth``/``brownout_stage``) — ``llm/replica.py``'s
    ``EngineReplica`` in production, light stubs in tests.
    """

    # lock-discipline registry (tpuserve-analyze TPU301): the route/event
    # counter maps are written on the serving event loop and read by the
    # Prometheus scrape thread (statistics/metrics.py ReplicaRouterCollector)
    __guarded_by__ = {
        "_lock": ("_route_counts", "_router_events"),
    }

    # thread-affinity registry (tpuserve-analyze TPU501): ring membership is
    # event-loop-owned — sweeps and picks run on the serving loop and
    # REBIND an immutable frozenset (never mutate in place), so the scrape
    # thread's stats() reads a torn-free snapshot by reference
    __affine_to__ = {
        "loop": ("_ring_members",),
    }

    def __init__(
        self,
        replicas: Sequence[Any],
        *,
        block: int = 64,
        affinity_blocks: int = DEFAULT_AFFINITY_BLOCKS,
        # spill when the affine member's queue depth reaches this bound
        # (None = queue depth never spills) ...
        spill_queue_depth: Optional[int] = None,
        # ... or its brownout stage reaches this bound — stage >= 2 means
        # the member is already degrading batch work; redirect BEFORE it
        # has to shed (docs/slo_scheduling.md)
        spill_brownout_stage: int = 2,
        # fleet-wide brownout: every ring member at this stage sheds
        # best-effort at the router door
        fleet_shed_stage: int = 3,
        # replica roles (docs/disaggregation.md): name -> "prefill" |
        # "decode" | "hybrid" (missing = hybrid). Streams route to
        # decode-capable members; the group's ship leg asks pick_prefill
        # for a prefill-capable one. An empty role class degrades to
        # hybrid routing (any ring member serves) instead of failing.
        roles: Optional[Dict[str, str]] = None,
        # which replica backend the fleet runs on ("inprocess" = N engines
        # on this heap, "process" = supervised worker subprocesses —
        # serving/process_replica.py); exported in stats() for the
        # router_replica_backend info gauge (docs/replication.md)
        replica_backend: str = "inprocess",
    ):
        self.replica_backend = str(replica_backend)
        self._replicas = list(replicas)
        self._names = [r.name for r in self._replicas]
        if len(set(self._names)) != len(self._names):
            raise ValueError("replica names must be unique: {}".format(self._names))
        self._roles = {name: "hybrid" for name in self._names}
        for name, role in (roles or {}).items():
            if name not in self._roles:
                raise ValueError(
                    "role for unknown replica {!r} (replicas: {})".format(
                        name, self._names
                    )
                )
            if role not in ("prefill", "decode", "hybrid"):
                raise ValueError(
                    "replica role must be prefill/decode/hybrid: got {!r} "
                    "for {}".format(role, name)
                )
            self._roles[name] = role
        self.block = int(block)
        self.affinity_blocks = int(affinity_blocks)
        self.spill_queue_depth = spill_queue_depth
        self.spill_brownout_stage = int(spill_brownout_stage)
        self.fleet_shed_stage = int(fleet_shed_stage)
        self._lock = threading.Lock()
        self._ring_members: frozenset = frozenset()
        self._route_counts: Dict[str, Dict[str, int]] = {
            name: {"affine": 0, "spill": 0, "rebalance": 0}
            for name in self._names
        }
        self._router_events: Dict[str, Dict[str, int]] = {
            "ejections": {name: 0 for name in self._names},
            "readmissions": {name: 0 for name in self._names},
            "fleet_sheds": {"best_effort": 0},
        }
        self.sweep()

    # -- ring maintenance ---------------------------------------------------

    def _force_ejected(self, replica) -> bool:
        """``router.eject`` fault seam: an armed spec whose ``match_token``
        equals the replica INDEX forces that replica out of the ring — the
        chaos suite's handle for ejection without a real engine failure."""
        try:
            faults.fire("router.eject", request=_ReplicaShim(replica.index))
        except faults.InjectedFault:
            return True
        return False

    def sweep(self) -> None:
        """Refresh ring membership from live replica state. Runs on the
        serving event loop (every pick, cheap) and from tests.

        Ejection: a member that stops being serving-ready (engine not
        ready, or fault-forced) leaves the ring immediately and its warmup
        gate closes — re-admission must re-warm (a recovered engine's
        caches survive, so the re-warm is a fast no-compile pass, but a
        replaced process would compile here instead of under traffic).
        Re-admission: a non-member whose engine is ready re-enters only
        once the warmup gate reopens; ``begin_warm()`` schedules the gate's
        shared warmup task when one is needed."""
        for replica in self._replicas:
            forced = self._force_ejected(replica)
            healthy = bool(replica.engine_ready) and not forced
            member = replica.name in self._ring_members
            if member and not (healthy and replica.warmed):
                self._ring_members = self._ring_members - {replica.name}
                replica.invalidate_warm()
                with self._lock:
                    self._router_events["ejections"][replica.name] += 1
            elif not member and healthy:
                if replica.warmed:
                    self._ring_members = self._ring_members | {replica.name}
                    with self._lock:
                        # cold-start entry is not a READ-mission: only a
                        # previously ejected member counts here
                        if self._router_events["ejections"][replica.name]:
                            self._router_events["readmissions"][replica.name] += 1
                else:
                    replica.begin_warm()

    def ring(self) -> List[str]:
        return sorted(self._ring_members)

    @property
    def ring_size(self) -> int:
        return len(self._ring_members)

    # -- pressure -----------------------------------------------------------

    def _overloaded(self, replica) -> bool:
        if (
            self.spill_queue_depth is not None
            and replica.queue_depth >= self.spill_queue_depth
        ):
            return True
        return replica.brownout_stage >= self.spill_brownout_stage

    @staticmethod
    def _pressure(replica) -> tuple:
        return (replica.brownout_stage, replica.queue_depth)

    def fleet_stage(self) -> int:
        """Fleet brownout stage: the MINIMUM stage over ring members — the
        least-pressured member defines what the fleet can still absorb
        (one healthy replica at stage 0 means redirect, not shed)."""
        stages = [
            r.brownout_stage
            for r in self._replicas
            if r.name in self._ring_members
        ]
        return min(stages) if stages else 0

    # -- roles (docs/disaggregation.md) -------------------------------------

    def role_of(self, name: str) -> str:
        return self._roles.get(name, "hybrid")

    def _decode_capable(self, replica) -> bool:
        return self.role_of(replica.name) in ("decode", "hybrid")

    def _prefill_capable(self, replica) -> bool:
        return self.role_of(replica.name) in ("prefill", "hybrid")

    def pick_prefill(self, request,
                     exclude: Optional[str] = None) -> Optional[Any]:
        """The prefill replica for a disaggregated request's ship leg:
        prefill-ROLE ring members first (specialization is the point),
        then hybrids, each set in HRW order for the prompt; browned-out
        members (stage >= the spill bound) are skipped — a degrading
        prefill replica must not slow every stream's TTFT. Returns None
        when nothing suitable remains (the caller degrades to hybrid:
        the decode replica prefills for itself)."""
        self.sweep()
        order = [
            r for r in self.order_for(request.prompt_ids)
            if r.name in self._ring_members
            and r.name != exclude
            and self._prefill_capable(r)
            and r.brownout_stage < self.spill_brownout_stage
        ]
        dedicated = [r for r in order if self.role_of(r.name) == "prefill"]
        return (dedicated or order or [None])[0]

    # -- routing ------------------------------------------------------------

    def order_for(self, prompt_ids: Sequence[int]) -> List[Any]:
        """Full HRW preference order (healthy or not) for a prompt."""
        key = affinity_key(prompt_ids, self.block, self.affinity_blocks)
        return [self._replicas[i] for i in hrw_order(key, self._names)]

    def pick(self, request) -> tuple:
        """Route one request: returns ``(replica, route)`` with ``route``
        in ``affine`` (HRW first choice), ``rebalance`` (first choice out
        of the ring — health/eject reroute), ``spill`` (first choice
        overloaded, second strictly less pressured). With replica roles,
        streams prefer DECODE-capable members (decode/hybrid); an empty
        decode class degrades to any ring member (route ``rebalance``).
        Raises structured errors when the fleet itself cannot take the
        request."""
        self.sweep()
        order = self.order_for(request.prompt_ids)
        candidates = [r for r in order if self._decode_capable(r)]
        ring = [r for r in candidates if r.name in self._ring_members]
        if not ring:
            # decode class empty/ejected: hybrid degradation — any ring
            # member takes the stream rather than shedding it
            candidates = order
            ring = [r for r in order if r.name in self._ring_members]
        if not ring:
            if any(r.warming for r in self._replicas):
                raise EngineUnavailableError(
                    "all replicas are warming up", retry_after=1.0
                )
            raise EngineUnavailableError("no ready replicas in the ring")
        if (
            getattr(request, "priority", "interactive") == "best_effort"
            and self.fleet_stage() >= self.fleet_shed_stage
        ):
            with self._lock:
                self._router_events["fleet_sheds"]["best_effort"] += 1
            raise EngineOverloadedError(
                "fleet brownout (every ring member at stage >= {}): "
                "best-effort shed at the router".format(self.fleet_shed_stage),
                shed_class="best_effort",
            )
        # "affine" = HRW first choice AMONG role-eligible members: on a
        # role-split fleet every stream would otherwise count rebalance
        affine = candidates[0] if candidates else order[0]
        chosen = ring[0]
        route = "affine" if chosen is affine else "rebalance"
        if route == "affine" and len(ring) > 1:
            alt = ring[1]
            if self._overloaded(chosen) and (
                self._pressure(alt) < self._pressure(chosen)
            ):
                chosen, route = alt, "spill"
        try:
            faults.fire("router.pick", request=request)
        except faults.InjectedFault:
            # injected pick failure: structured fallback to the next ring
            # member (never a 500) — counted as a rebalance
            if len(ring) > 1:
                chosen = ring[(ring.index(chosen) + 1) % len(ring)]
            route = "rebalance"
        with self._lock:
            self._route_counts[chosen.name][route] += 1
        return chosen, route

    # -- observability ------------------------------------------------------

    def stats(self) -> Dict[str, Any]:
        """Scrape-time snapshot (statistics/metrics.py
        ReplicaRouterCollector; mirrored in the group's health() /
        lifecycle_stats())."""
        with self._lock:
            requests = {
                name: dict(routes) for name, routes in self._route_counts.items()
            }
            events = {
                kind: dict(per) for kind, per in self._router_events.items()
            }
        stages = {r.name: r.brownout_stage for r in self._replicas}
        return {
            "replicas": len(self._replicas),
            "replica_backend": self.replica_backend,
            "ring_size": len(self._ring_members),
            "ring": self.ring(),
            "roles": dict(self._roles),
            "requests": requests,
            "ejections": events["ejections"],
            "readmissions": events["readmissions"],
            "fleet_sheds": events["fleet_sheds"],
            "fleet_brownout": {
                "stage": self.fleet_stage(),
                "stages": stages,
            },
        }
