"""Response wrapper types engines can return through the router.

Leaf module (no intra-package imports) so engines and the HTTP app can both
depend on it without cycles.
"""

from __future__ import annotations

from typing import Any, AsyncIterator


class StreamingOutput:
    """Engine phases may return this to stream SSE chunks through the router.

    ``generator`` yields str (already SSE-framed or raw data lines) or bytes.
    """

    def __init__(self, generator: AsyncIterator, content_type: str = "text/event-stream"):
        self.generator = generator
        self.content_type = content_type
        # set by the orchestrator: called once after the stream body finishes
        # (or the client disconnects) — used to emit the stats packet with the
        # real stream latency/TTFT instead of time-to-headers
        self.on_complete = None


class JSONOutput:
    """Engine phases may return this to control the HTTP status code."""

    def __init__(self, payload: Any, status: int = 200):
        self.payload = payload
        self.status = status


class TextOutput:
    """Engine phases may return this for a raw text body (e.g. the OpenAI
    transcription API's response_format=text, which expects text/plain — a
    JSON-encoded string would arrive wrapped in literal quotes)."""

    def __init__(self, text: str, content_type: str = "text/plain"):
        self.text = text
        self.content_type = content_type
