from .store import ServingService, StateStore, default_state_root
from .registry import ModelRecord, ModelRegistry

__all__ = [
    "ServingService",
    "StateStore",
    "default_state_root",
    "ModelRecord",
    "ModelRegistry",
]
